"""Full transaction lifecycle, every plane of the framework in one run:

  client proposal -> 2 endorsing orgs simulate + sign (ESCC)
  -> client assembles the tx -> orderer broadcast (admission filters)
  -> solo chain cuts blocks -> deliver stream to the peer
  -> orderer-signature check + verify-then-gate block validation
  -> MVCC -> ledger commit.

Run: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
       PYTHONPATH=. python examples/e2e_tx_lifecycle.py
"""

from fabric_tpu.bccsp.factory import FactoryOpts, init_factories
from fabric_tpu.chaincode import (
    ChaincodeDefinition,
    ChaincodeRegistry,
    LifecyclePolicyProvider,
    SimulationError,
)
from fabric_tpu.chaincode.runtime import FuncContract
from fabric_tpu.committer import Committer, TxValidator
from fabric_tpu.endorser import Endorser, assemble_transaction, signed_proposal
from fabric_tpu.ledger import KVLedger, LedgerConfig
from fabric_tpu.msp import CachedMSP
from fabric_tpu.msp.ca import DevOrg
from fabric_tpu.orderer import (
    BatchConfig,
    BroadcastHandler,
    DeliverHandler,
    Registrar,
    SeekInfo,
    block_signature_items,
)
from fabric_tpu.policy import parse_policy


def asset_contract():
    def create(stub, key, value):
        if stub.get_state(key.decode()) is not None:
            raise SimulationError("asset exists")
        stub.put_state(key.decode(), value)
        return b"created"

    def transfer(stub, key, owner):
        v = stub.get_state(key.decode())
        if v is None:
            raise SimulationError("no such asset")
        stub.put_state(key.decode(), owner)
        return b"transferred"

    return FuncContract(create=create, transfer=transfer)


def main():
    provider = init_factories(FactoryOpts(default="SW"))
    org1, org2, ord_org = DevOrg("Org1"), DevOrg("Org2"), DevOrg("OrdererOrg")
    msps = {o.mspid: CachedMSP(o.msp()) for o in (org1, org2, ord_org)}

    # ---- peer side: ledger, chaincode, endorsers, committer
    ledger = KVLedger("ch", LedgerConfig())
    registry = ChaincodeRegistry()
    registry.install(ChaincodeDefinition("assets", "1.0"), asset_contract())
    policies = LifecyclePolicyProvider(ledger.statedb)
    policies.set_policy("assets",
                        parse_policy("AND('Org1.member', 'Org2.member')"))
    endorsers = [Endorser("ch", ledger.statedb, registry, msps, provider,
                          org.new_identity(f"peer.{org.mspid}"))
                 for org in (org1, org2)]
    committer = Committer(ledger, TxValidator("ch", msps, provider, policies))

    # ---- orderer side
    registrar = Registrar()
    registrar.create_channel(
        "ch", msps, provider,
        writers_policy=parse_policy(
            "OR('Org1.member', 'Org2.member', 'OrdererOrg.member')"),
        signer=ord_org.new_identity("orderer1"),
        batch_config=BatchConfig(max_message_count=4))
    broadcast = BroadcastHandler(registrar)

    # ---- client: endorse + submit 8 transactions
    client = org1.new_identity("alice")
    for i in range(8):
        sp = signed_proposal("ch", "assets", "create",
                             [b"asset%d" % i, b"alice"], client)
        responses = [e.process_proposal(sp) for e in endorsers]
        assert all(r.status == 200 for r in responses), responses
        env = assemble_transaction(sp, responses, client)
        resp = broadcast.handle(env)
        assert resp.status == 200, resp.info
    registrar.get("ch").chain.tick(now=float("inf"))  # flush pending batch

    # ---- delivery + commit on the peer
    deliver = DeliverHandler(registrar)
    for block in deliver.deliver("ch", SeekInfo(start=0, stop="newest")):
        items = block_signature_items(block, msps)
        assert items and bool(provider.batch_verify(items).all()), \
            "orderer block signature must verify"
        res = committer.store_block(block)
        print(f"block {block.header.number}: "
              f"{res.validation.flags.valid_count()}/{len(block.data)} valid, "
              f"{res.validation.n_unique_items} unique sigs in one dispatch")

    assert ledger.get_state("assets", "asset7") == b"alice"

    # a double-create must fail at simulation time
    sp = signed_proposal("ch", "assets", "create", [b"asset0", b"bob"], client)
    r = endorsers[0].process_proposal(sp)
    assert r.status == 500 and "exists" in r.message
    print(f"height={ledger.height} | double-create rejected at simulation")
    print("TX LIFECYCLE OK")


if __name__ == "__main__":
    main()
