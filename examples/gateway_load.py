"""Closed-loop gateway load driver: N client workers, one front door.

Boots a full in-process network (raft orderer cluster + one peer per
org), then runs a closed loop: each worker keeps exactly one
transaction in flight — endorse -> submit -> commit_status through the
peer's gateway — and issues the next the moment the previous commits.
Closed-loop load is the honest way to exercise the admission queue:
offered load adapts to what the pipeline sustains, so the batcher's
coalescing (not a generator's pacing) sets the broadcast batch size.

Prints per-verb latency percentiles, end-to-end commit latency, and
the gateway's own metrics (queue depth, batch-size histogram, retry
counters) at the end.

Run CPU-only:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python examples/gateway_load.py [--workers 8] [--txs 25] \
      [--orderers 3] [--kill-orderer]

--kill-orderer stops one orderer mid-run to demonstrate the
broadcaster's failover: the run must still complete with every tx
VALID.
"""

import argparse
import json
import statistics
import tempfile
import threading
import time

from fabric_tpu.bccsp.factory import FactoryOpts, init_factories
from fabric_tpu.config import BatchConfig
from fabric_tpu.gateway import GatewayClient
from fabric_tpu.node.orderer import OrdererNode, load_signing_identity
from fabric_tpu.node.peer import PeerNode
from fabric_tpu.node.provision import provision_network
from fabric_tpu.protocol.txflags import ValidationCode


def _pct(xs, q):
    if not xs:
        return float("nan")
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def boot(base, n_orderers):
    paths = provision_network(
        base, n_orderers=n_orderers, peer_orgs=["Org1", "Org2"],
        peers_per_org=1,
        batch=BatchConfig(max_message_count=32, timeout_s=0.05))
    orderers, peers = [], []
    for p in paths["orderers"]:
        with open(p) as f:
            cfg = json.load(f)
        cfg["ops_port"] = 0         # scrapeable end-to-end: every node
        orderers.append(OrdererNode(cfg, data_dir=cfg["data_dir"]).start())
    for p in paths["peers"]:
        with open(p) as f:
            cfg = json.load(f)
        cfg["gateway"] = {"linger_s": 0.005, "max_batch": 64}
        cfg["ops_port"] = 0         # /metrics, /slo, /traces, /gateway
        peers.append(PeerNode(cfg, data_dir=cfg["data_dir"]).start())
    deadline = time.time() + 60
    while time.time() < deadline:
        if any(o.support.chain.node.role == "leader" for o in orderers):
            return paths, orderers, peers
        time.sleep(0.2)
    raise SystemExit("no raft leader elected")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--txs", type=int, default=25,
                    help="transactions per worker")
    ap.add_argument("--orderers", type=int, default=3)
    ap.add_argument("--kill-orderer", action="store_true",
                    help="stop one orderer mid-run (failover demo)")
    args = ap.parse_args()

    init_factories(FactoryOpts(default="SW"))
    with tempfile.TemporaryDirectory() as base:
        print(f"booting {args.orderers} orderers + 2 peers ...")
        paths, orderers, peers = boot(base, args.orderers)
        gw_peer = peers[0]
        with open(paths["clients"]["Org1"]) as f:
            cc = json.load(f)
        signer = load_signing_identity(
            cc["mspid"], cc["cert_pem"].encode(), cc["key_pem"].encode())

        lat_endorse, lat_commit, lat_e2e = [], [], []
        bad, trace_ids, lock = [], [], threading.Lock()
        from fabric_tpu.ops_plane import tracing

        def worker(wid):
            gw = GatewayClient(gw_peer.rpc.addr, signer, gw_peer.msps,
                               channel_id="ch")
            try:
                for i in range(args.txs):
                    key = f"w{wid}-tx{i}".encode()
                    t0 = time.monotonic()
                    # one root span per tx: all three gateway verbs ride
                    # this context, so the whole lifecycle is ONE trace
                    with tracing.tracer.start_span(
                            "client.tx",
                            attributes={"worker": wid, "i": i}) as span:
                        sp, responses = gw.endorse(
                            "assets", "create", [key, b"load"])
                        t1 = time.monotonic()
                        from fabric_tpu.endorser.proposal import (
                            assemble_transaction)
                        env = assemble_transaction(sp, responses, signer)
                        txid = env.header().channel_header.txid
                        gw.submit_envelope(env, timeout_s=60.0)
                        code, _ = gw.commit_status(txid, timeout_s=60.0)
                    t2 = time.monotonic()
                    with lock:
                        if span.recording and not trace_ids:
                            trace_ids.append(span.context.trace_id)
                        lat_endorse.append(t1 - t0)
                        lat_commit.append(t2 - t1)
                        lat_e2e.append(t2 - t0)
                        if code != int(ValidationCode.VALID):
                            bad.append((txid, code))
            except Exception as exc:
                with lock:
                    bad.append((f"w{wid}", repr(exc)))
            finally:
                gw.close()

        start = time.monotonic()
        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(args.workers)]
        for t in threads:
            t.start()
        if args.kill_orderer and len(orderers) > 1:
            time.sleep(1.0)
            victim = orderers.pop()
            print(f"killing orderer {victim.rpc.addr} mid-run ...")
            victim.stop()
        for t in threads:
            t.join()
        wall = time.monotonic() - start

        total = args.workers * args.txs
        print(f"\n{total} txs, {args.workers} closed-loop workers, "
              f"{wall:.2f}s wall -> {total / wall:.1f} tx/s")
        for name, xs in (("endorse", lat_endorse),
                         ("submit+commit", lat_commit),
                         ("end-to-end", lat_e2e)):
            if xs:
                print(f"  {name:14s} p50 {_pct(xs, .5) * 1e3:7.1f} ms   "
                      f"p95 {_pct(xs, .95) * 1e3:7.1f} ms   "
                      f"mean {statistics.mean(xs) * 1e3:7.1f} ms")
        if bad:
            print(f"  FAILURES: {bad[:5]}{' ...' if len(bad) > 5 else ''}")

        from fabric_tpu.ops_plane import registry
        print("\ngateway metrics:")
        for line in registry.expose_text().splitlines():
            if line.startswith("gateway_") and not line.startswith("#"):
                print(" ", line)

        # every node is scrapeable: render one cluster-top frame over
        # the live ops surfaces (the watch form of this is
        # `python -m fabric_tpu.node.top --targets ...`)
        from fabric_tpu.node import top as cluster_top
        targets = ",".join(f"{n.ops.addr[0]}:{n.ops.addr[1]}"
                           for n in peers + orderers if n.ops is not None)
        print(f"\ncluster top (--targets {targets}):")
        rows = [cluster_top.collect_node(t) for t in targets.split(",")]
        print(cluster_top.render(rows))

        # fetch one tx's trace over the peer's ops server: the flight
        # recorder stitches the request trace to its block trace, so the
        # Chrome JSON covers admission -> endorse -> order -> device
        # verify -> MVCC -> commit notification in one Perfetto load
        if trace_ids and gw_peer.ops is not None:
            import urllib.request
            host, port = gw_peer.ops.addr
            url = f"http://{host}:{port}/traces/{trace_ids[0]}"
            with urllib.request.urlopen(url, timeout=5) as r:
                doc = json.loads(r.read())
            names = {e["name"] for e in doc["traceEvents"]
                     if e.get("ph") == "X"}
            print(f"\ntrace {trace_ids[0]} "
                  f"({len(doc['traceEvents'])} events) via {url}")
            stages = {"admission": "gateway.queue_wait",
                      "endorsement": "endorser.simulate",
                      "ordering": "orderer.broadcast",
                      "device batch-verify": "bccsp.batch_verify",
                      "MVCC": "ledger.mvcc",
                      "commit notification": "gateway.commit_wait"}
            for stage, span_name in stages.items():
                mark = "ok" if span_name in names else "MISSING"
                print(f"  {stage:22s} {span_name:22s} {mark}")
                if span_name not in names:
                    bad.append(("trace", f"missing span {span_name}"))

        for n in peers + orderers:
            try:
                n.stop()
            except Exception:
                pass
        raise SystemExit(1 if bad else 0)


if __name__ == "__main__":
    main()
