"""End-to-end slice: endorse -> block -> verify-then-gate -> MVCC -> commit.

Drives the public framework surface the way a peer's commit path does
(SURVEY.md §3.2): builds a block of endorser transactions, validates it
with one batched signature dispatch, commits, and prints the tx filter
bitmap plus per-phase timings.

Run CPU-only:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python examples/e2e_validate.py
"""

import sys

from fabric_tpu.bccsp.factory import FactoryOpts, init_factories
from fabric_tpu.committer import Committer, PolicyRegistry, TxValidator
from fabric_tpu.ledger import KVLedger, LedgerConfig
from fabric_tpu.msp import CachedMSP
from fabric_tpu.msp.ca import DevOrg
from fabric_tpu.policy import parse_policy
from fabric_tpu.protocol import (Envelope, KVRead, KVWrite, NsRwSet, TxRwSet,
                                 ValidationCode, Version, build)


def main(n_txs: int = 20, provider_name: str = "SW") -> int:
    provider = init_factories(FactoryOpts(default=provider_name))
    org1, org2 = DevOrg("Org1"), DevOrg("Org2")
    msps = {o.mspid: CachedMSP(o.msp()) for o in (org1, org2)}
    policies = PolicyRegistry()
    policies.set_policy("mycc", parse_policy("AND('Org1.member', 'Org2.member')"))

    ledger = KVLedger("demo", LedgerConfig())
    committer = Committer(ledger, TxValidator("demo", msps, provider, policies))

    endorsers = [org1.new_identity("peer0"), org2.new_identity("peer0")]
    client = org1.new_identity("client")

    def tx(i, reads=(), writes=()):
        rwset = TxRwSet((NsRwSet("mycc", reads=tuple(reads),
                                 writes=tuple(writes)),))
        return build.endorser_tx("demo", "mycc", "1.0", rwset, client, endorsers)

    # block 0: writes
    envs = [tx(i, writes=[KVWrite(f"key{i}", f"val{i}".encode())])
            for i in range(n_txs)]
    # one corrupted creator signature
    envs[3] = Envelope(envs[3].payload, envs[3].signature[:-2] + b"\x00\x00")
    block = build.new_block(0, b"\x00" * 32, envs)
    res = committer.store_block(block)

    # block 1: a valid read-modify-write plus one stale read (MVCC conflict)
    v0 = Version(0, 0)
    b1 = build.new_block(1, block.hash(), [
        tx(0, reads=[KVRead("key0", v0)], writes=[KVWrite("key0", b"updated")]),
        tx(1, reads=[KVRead("key0", v0)], writes=[KVWrite("key0", b"loser")]),
    ])
    res1 = committer.store_block(b1)

    flags0 = res.final_flags
    flags1 = res1.final_flags
    print(f"block 0: {flags0.valid_count()}/{len(flags0)} valid | "
          f"collect={res.validation.collect_s*1e3:.1f}ms "
          f"dispatch={res.validation.dispatch_s*1e3:.1f}ms "
          f"({res.validation.n_unique_items} uniq sigs of "
          f"{res.validation.n_items} refs) "
          f"gate={res.validation.gate_s*1e3:.1f}ms")
    print(f"block 0 codes: {flags0.codes()}")
    print(f"block 1 codes: {flags1.codes()} (expect [0, MVCC={int(ValidationCode.MVCC_READ_CONFLICT)}])")
    print(f"state key0 = {ledger.get_state('mycc', 'key0')}")
    print(f"height={ledger.height} commit_hash={ledger.commit_hash.hex()[:16]}…")

    ok = (flags0.valid_count() == n_txs - 1
          and flags0.flag(3) == ValidationCode.BAD_CREATOR_SIGNATURE
          and flags1.codes() == [0, int(ValidationCode.MVCC_READ_CONFLICT)]
          and ledger.get_state("mycc", "key0") == b"updated")
    print("E2E OK" if ok else "E2E MISMATCH")
    return 0 if ok else 1


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    prov = sys.argv[2] if len(sys.argv) > 2 else "SW"
    raise SystemExit(main(n, prov))
