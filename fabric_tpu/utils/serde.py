"""Canonical deterministic serialization for framework messages.

Role-equivalent of the reference's protobuf layer + protoutil
(/root/reference/protoutil/commonutils.go, txutils.go, blockutils.go): every
on-wire / on-disk structure (identities, transactions, blocks, policies) is
encoded through here, and hashes/signatures are computed over these bytes.

Format ("FTLV"): a tiny canonical TLV scheme —
  None   -> 'N'
  bool   -> 'T'/'F'
  int    -> 'I' + 8-byte signed big-endian (or 'V' + 4-len + magnitude for big)
  bytes  -> 'B' + u32 len + raw
  str    -> 'S' + u32 len + utf-8
  list   -> 'L' + u32 count + items
  dict   -> 'D' + u32 count + sorted (str key, value) pairs
Deterministic by construction (sorted dict keys, fixed-width lengths), so
equal values always produce equal bytes — the property Fabric gets from
deterministic proto marshaling of header bytes.

Decoding is STRICT: exactly the canonical form is accepted — dict keys
must be strictly increasing (which also rejects duplicates), 'V' ints
must be minimal and >= 2^63 (below that the encoder emits 'I'), nesting
is capped at MAX_DEPTH, and trailing bytes are an error.  Strictness
makes decode/encode a bijection on the wire, which the validator's C
pass-1 walker (native/fastcollect.c) depends on: it splices signed byte
spans straight out of the original encoding, and span-splicing equals
re-encoding ONLY when every accepted encoding is canonical.  A lenient
decoder here would let an attacker craft envelopes that validate
differently on C-enabled and pure-Python peers — a state fork.
"""

from __future__ import annotations

import struct
from typing import Any

_U32 = struct.Struct(">I")
_I64 = struct.Struct(">q")

# Uniform nesting cap across every codec implementation (this module's
# Python encode/decode, native/ftlv.c, and native/fastcollect.c's
# canonical walk).  All four MUST agree: a value one implementation
# accepts and another rejects is a validation fork between peers.
MAX_DEPTH = 64


def encode(v: Any) -> bytes:
    out = bytearray()
    _enc(v, out)
    return bytes(out)


# the pure-Python implementations stay importable under these names for
# the differential tests and as the no-compiler fallback
encode_py = encode


def _enc(v: Any, out: bytearray, depth: int = 0) -> None:
    if depth > MAX_DEPTH:
        raise ValueError("nesting too deep")
    if v is None:
        out += b"N"
    elif v is True:
        out += b"T"
    elif v is False:
        out += b"F"
    elif isinstance(v, int):
        if -(2**63) <= v < 2**63:
            out += b"I"
            out += _I64.pack(v)
        else:
            if v < 0:
                raise ValueError("big negative ints unsupported")
            mag = v.to_bytes((v.bit_length() + 7) // 8, "big")
            out += b"V"
            out += _U32.pack(len(mag))
            out += mag
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out += b"B"
        out += _U32.pack(len(b))
        out += b
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out += b"S"
        out += _U32.pack(len(b))
        out += b
    elif isinstance(v, (list, tuple)):
        out += b"L"
        out += _U32.pack(len(v))
        for item in v:
            _enc(item, out, depth + 1)
    elif isinstance(v, dict):
        out += b"D"
        keys = sorted(v.keys())
        out += _U32.pack(len(keys))
        for k in keys:
            if not isinstance(k, str):
                raise TypeError("dict keys must be str")
            kb = k.encode("utf-8")
            out += _U32.pack(len(kb))
            out += kb
            _enc(v[k], out, depth + 1)
    else:
        raise TypeError(f"unsupported type {type(v)!r}")


def decode(data: bytes) -> Any:
    try:
        v, off = _dec(memoryview(data), 0)
    except struct.error as e:  # truncated length/int field
        raise ValueError(f"truncated input: {e}") from e
    if off != len(data):
        raise ValueError("trailing bytes")
    return v


def _take(mv: memoryview, off: int, n: int) -> bytes:
    if off + n > len(mv):
        raise ValueError(f"short buffer: need {n} bytes at {off}, have {len(mv) - off}")
    return mv[off:off + n].tobytes()


def _dec(mv: memoryview, off: int, depth: int = 0):
    if depth > MAX_DEPTH:
        raise ValueError("nesting too deep")
    tag = _take(mv, off, 1)
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"I":
        return _I64.unpack_from(mv, off)[0], off + 8
    if tag == b"V":
        n = _U32.unpack_from(mv, off)[0]
        off += 4
        mag = _take(mv, off, n)
        # canonical: minimal magnitude, and >= 2^63 (the encoder emits
        # 'I' below that) — a lenient 'V' would give one value two
        # encodings and break splice-equals-reencode (module docstring)
        if n < 8 or mag[0] == 0 or (n == 8 and mag[0] < 0x80):
            raise ValueError("non-canonical V int")
        return int.from_bytes(mag, "big"), off + n
    if tag == b"B":
        n = _U32.unpack_from(mv, off)[0]
        off += 4
        return _take(mv, off, n), off + n
    if tag == b"S":
        n = _U32.unpack_from(mv, off)[0]
        off += 4
        return _take(mv, off, n).decode("utf-8"), off + n
    if tag == b"L":
        n = _U32.unpack_from(mv, off)[0]
        off += 4
        items = []
        for _ in range(n):
            v, off = _dec(mv, off, depth + 1)
            items.append(v)
        return items, off
    if tag == b"D":
        n = _U32.unpack_from(mv, off)[0]
        off += 4
        d = {}
        prev = None
        for _ in range(n):
            kn = _U32.unpack_from(mv, off)[0]
            off += 4
            k = _take(mv, off, kn).decode("utf-8")
            off += kn
            # canonical: strictly increasing keys (also bans duplicates,
            # whose last-wins decode would diverge from span splicing)
            if prev is not None and not (k > prev):
                raise ValueError("non-canonical dict key order")
            prev = k
            v, off = _dec(mv, off, depth + 1)
            d[k] = v
        return d, off
    raise ValueError(f"bad tag {tag!r} at {off - 1}")


decode_py = decode


def decode_views(data) -> Any:
    """Strict decode where 'B' values are READ-ONLY memoryview slices
    into `data` instead of bytes copies.

    Same accept/reject behavior as decode() (it shares the walker); only
    the representation of bytes values differs.  Used by the zero-copy
    ingest path (comm/rpc.py stream_views): a deliver frame's
    {"block": <70 KB>} decodes without duplicating the block bytes, and
    the views keep the received frame buffer alive.  Callers must treat
    the result as immutable and not hand views to consumers that expect
    hashable bytes.
    """
    mv = memoryview(data)
    if not mv.readonly:
        mv = mv.toreadonly()
    try:
        v, off = _dec_views(mv, 0)
    except struct.error as e:  # truncated length/int field
        raise ValueError(f"truncated input: {e}") from e
    if off != len(mv):
        raise ValueError("trailing bytes")
    return v


def _dec_views(mv: memoryview, off: int, depth: int = 0):
    # identical to _dec except the 'B' arm, which returns a slice view
    if depth > MAX_DEPTH:
        raise ValueError("nesting too deep")
    tag = _take(mv, off, 1)
    if tag == b"B":
        n = _U32.unpack_from(mv, off + 1)[0]
        off += 5
        if off + n > len(mv):
            raise ValueError(
                f"short buffer: need {n} bytes at {off}, have {len(mv) - off}")
        return mv[off:off + n], off + n
    if tag == b"L":
        n = _U32.unpack_from(mv, off + 1)[0]
        off += 5
        items = []
        for _ in range(n):
            v, off = _dec_views(mv, off, depth + 1)
            items.append(v)
        return items, off
    if tag == b"D":
        n = _U32.unpack_from(mv, off + 1)[0]
        off += 5
        d = {}
        prev = None
        for _ in range(n):
            kn = _U32.unpack_from(mv, off)[0]
            off += 4
            k = _take(mv, off, kn).decode("utf-8")
            off += kn
            if prev is not None and not (k > prev):
                raise ValueError("non-canonical dict key order")
            prev = k
            v, off = _dec_views(mv, off, depth + 1)
            d[k] = v
        return d, off
    return _dec(mv, off, depth)

# hot-path C codec (fabric_tpu/native/ftlv.c) — identical wire format and
# error behavior; tests/test_serde.py exercises both differentially
try:
    from fabric_tpu import native as _native_pkg
    _ftlv = _native_pkg.load("_ftlv")
except Exception:      # pragma: no cover - import cycle / broken toolchain
    _ftlv = None
if _ftlv is not None:
    encode = _ftlv.encode
    decode = _ftlv.decode
