/* Zero-copy wire ingest — native block/envelope span parser.
 *
 * fastcollect.c took over txvalidator pass 1 *after* Python had already
 * decoded the block container and materialized a list of per-envelope
 * bytes objects.  This module moves the C plane one layer up, to the
 * wire: it takes the raw FTLV frame bytes (fabric_tpu/utils/serde.py
 * format) of a whole Block or a single Envelope and extracts the byte
 * SPANS the rest of the pipeline needs — without creating any per-tx
 * Python object.  The envelope span table is written into an
 * arena-allocated, ring-pooled buffer so steady-state block ingest does
 * not call malloc at all.
 *
 * Exported:
 *   parse_block(buf) -> (number, previous_hash, data_hash,
 *                        data_off, data_end, n, spans, meta_val_off)
 *                       | None
 *     buf must be EXACTLY the canonical encoding of
 *       {"data": [bytes, ...], "header": {"data_hash": bytes,
 *        "number": i64, "previous_hash": bytes}, "metadata": {...}}
 *     (strict canonical form throughout: sorted unique dict keys,
 *     minimal 'V' ints, valid UTF-8, nesting <= MAX_DEPTH, no trailing
 *     bytes — the same rules serde.decode enforces).  Anything else
 *     returns None and the caller falls back to Block.deserialize, so
 *     accept/reject behavior of the system never changes — only who
 *     does the work.
 *       spans        arena buffer of n (u64 off, u64 len) native-endian
 *                    pairs: block.data[i] == buf[off:off+len]
 *       data_off/end span of the whole data LIST value, so
 *                    sha256(buf[data_off:data_end]) ==
 *                    block_data_hash(block.data) bit-identically
 *       meta_val_off offset where the metadata VALUE begins; because
 *                    "metadata" is the last key of the sorted top dict,
 *                    buf[:meta_val_off] + serde.encode(metadata_dict)
 *                    re-serializes a metadata-mutated block by splice
 *   envelope_summary(buf) -> (type, channel_id, txid) | None
 *     the gateway submit path's header peek: what
 *     Envelope.deserialize(buf).header().channel_header would yield,
 *     without building the Envelope/Header object trees.  None on any
 *     deviation from the strict shape (caller falls back).
 *   stats() -> dict of arena-pool and accept/reject counters
 *
 * Arena lifecycle: parse_block writes the span table into an Arena
 * object (read-only buffer protocol).  When the Arena's refcount drops
 * to zero its backing buffer is pushed onto a small ring free-list
 * (FP_POOL entries) and the next parse_block reuses it; only pool
 * overflow frees.  All pool operations run under the GIL (parse holds
 * it throughout; tp_dealloc always has it), so no extra locking.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* FTLV cursor (format: fabric_tpu/utils/serde.py; walker idiom shared
 * with native/fastcollect.c — the two must enforce identical rules)    */

typedef struct {
    const uint8_t *p;
    const uint8_t *end;
} cur_t;

static int rd_u32(cur_t *c, uint32_t *out)
{
    if (c->end - c->p < 4) return -1;
    *out = ((uint32_t)c->p[0] << 24) | ((uint32_t)c->p[1] << 16)
         | ((uint32_t)c->p[2] << 8) | c->p[3];
    c->p += 4;
    return 0;
}

#define MAX_DEPTH 64

/* strict UTF-8 (CPython decoder semantics: no overlongs, no
 * surrogates, max U+10FFFF) */
static int utf8_ok(const uint8_t *p, uint32_t n)
{
    uint32_t i = 0;
    while (i < n) {
        uint8_t b = p[i];
        if (b < 0x80) { i++; continue; }
        if (b < 0xC2) return 0;
        if (b < 0xE0) {
            if (n - i < 2 || (p[i+1] & 0xC0) != 0x80) return 0;
            i += 2; continue;
        }
        if (b < 0xF0) {
            if (n - i < 3) return 0;
            uint8_t b1 = p[i+1], b2 = p[i+2];
            if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80) return 0;
            if (b == 0xE0 && b1 < 0xA0) return 0;
            if (b == 0xED && b1 >= 0xA0) return 0;
            i += 3; continue;
        }
        if (b < 0xF5) {
            if (n - i < 4) return 0;
            uint8_t b1 = p[i+1], b2 = p[i+2], b3 = p[i+3];
            if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80
                || (b3 & 0xC0) != 0x80) return 0;
            if (b == 0xF0 && b1 < 0x90) return 0;
            if (b == 0xF4 && b1 >= 0x90) return 0;
            i += 4; continue;
        }
        return 0;
    }
    return 1;
}

/* validate one value in strict canonical form (serde.decode rules) */
static int canon_value_d(cur_t *c, int depth)
{
    if (depth > MAX_DEPTH) return -1;
    if (c->p >= c->end) return -1;
    uint8_t tag = *c->p++;
    uint32_t n;
    switch (tag) {
    case 'N': case 'T': case 'F':
        return 0;
    case 'I':
        if (c->end - c->p < 8) return -1;
        c->p += 8;
        return 0;
    case 'V':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        if (n < 8 || c->p[0] == 0 || (n == 8 && c->p[0] < 0x80))
            return -1;
        c->p += n;
        return 0;
    case 'B':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        c->p += n;
        return 0;
    case 'S':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        if (!utf8_ok(c->p, n)) return -1;
        c->p += n;
        return 0;
    case 'L':
        if (rd_u32(c, &n) < 0) return -1;
        while (n--)
            if (canon_value_d(c, depth + 1) < 0) return -1;
        return 0;
    case 'D': {
        if (rd_u32(c, &n) < 0) return -1;
        const uint8_t *prev = NULL;
        uint32_t prev_n = 0;
        while (n--) {
            uint32_t kn;
            const uint8_t *k;
            if (rd_u32(c, &kn) < 0
                || (uint32_t)(c->end - c->p) < kn) return -1;
            k = c->p;
            c->p += kn;
            if (!utf8_ok(k, kn)) return -1;
            if (prev) {
                uint32_t m = prev_n < kn ? prev_n : kn;
                int cmp = memcmp(prev, k, m);
                if (cmp > 0 || (cmp == 0 && prev_n >= kn)) return -1;
            }
            prev = k;
            prev_n = kn;
            if (canon_value_d(c, depth + 1) < 0) return -1;
        }
        return 0;
    }
    default:
        return -1;
    }
}

/* Enter a dict ('D'): entry count out, -1 if not a dict header. */
static int dict_enter(cur_t *c, uint32_t *count)
{
    if (c->p >= c->end || *c->p != 'D') return -1;
    c->p++;
    return rd_u32(c, count);
}

/* Next dict entry's key span (must be valid UTF-8 and strictly greater
 * than *prev — the canonical-order check other walkers do inline). */
static int dict_key(cur_t *c, const uint8_t **prev, uint32_t *prev_n,
                    const uint8_t **key, uint32_t *klen)
{
    if (rd_u32(c, klen) < 0 || (uint32_t)(c->end - c->p) < *klen) return -1;
    *key = c->p;
    c->p += *klen;
    if (!utf8_ok(*key, *klen)) return -1;
    if (*prev) {
        uint32_t m = *prev_n < *klen ? *prev_n : *klen;
        int cmp = memcmp(*prev, *key, m);
        if (cmp > 0 || (cmp == 0 && *prev_n >= *klen)) return -1;
    }
    *prev = *key;
    *prev_n = *klen;
    return 0;
}

static int key_is(const uint8_t *key, uint32_t klen, const char *name)
{
    size_t n = strlen(name);
    return klen == n && memcmp(key, name, n) == 0;
}

/* read a 'B' (bytes) value's content span */
static int rd_bytes(cur_t *c, const uint8_t **p, uint32_t *n)
{
    if (c->p >= c->end || *c->p != 'B') return -1;
    c->p++;
    if (rd_u32(c, n) < 0 || (uint32_t)(c->end - c->p) < *n) return -1;
    *p = c->p;
    c->p += *n;
    return 0;
}

/* read an 'S' (str) value's content span (UTF-8 validated) */
static int rd_str(cur_t *c, const uint8_t **p, uint32_t *n)
{
    if (c->p >= c->end || *c->p != 'S') return -1;
    c->p++;
    if (rd_u32(c, n) < 0 || (uint32_t)(c->end - c->p) < *n) return -1;
    if (!utf8_ok(c->p, *n)) return -1;
    *p = c->p;
    c->p += *n;
    return 0;
}

/* read an 'I' (fixed i64) value */
static int rd_i64(cur_t *c, int64_t *out)
{
    if (c->p >= c->end || *c->p != 'I') return -1;
    c->p++;
    if (c->end - c->p < 8) return -1;
    uint64_t v = 0;
    for (int i = 0; i < 8; i++)
        v = (v << 8) | c->p[i];
    c->p += 8;
    *out = (int64_t)v;
    return 0;
}

/* ------------------------------------------------------------------ */
/* Arena: ring-pooled span buffer with read-only buffer protocol       */

#define FP_POOL 8

static struct { uint8_t *buf; size_t cap; } pool[FP_POOL];
static int pool_n = 0;

static uint64_t st_pool_hit = 0;    /* acquires served from the pool   */
static uint64_t st_pool_miss = 0;   /* acquires that hit malloc        */
static uint64_t st_pool_drop = 0;   /* releases freed (pool full)      */
static uint64_t st_blk_accept = 0;
static uint64_t st_blk_reject = 0;
static uint64_t st_env_accept = 0;
static uint64_t st_env_reject = 0;

typedef struct {
    PyObject_HEAD
    uint8_t *buf;
    size_t cap;
    Py_ssize_t len;
} FPArena;

static void arena_dealloc(PyObject *self)
{
    FPArena *a = (FPArena *)self;
    if (a->buf) {
        if (pool_n < FP_POOL) {
            pool[pool_n].buf = a->buf;
            pool[pool_n].cap = a->cap;
            pool_n++;
        } else {
            st_pool_drop++;
            PyMem_RawFree(a->buf);
        }
        a->buf = NULL;
    }
    Py_TYPE(self)->tp_free(self);
}

static int arena_getbuffer(PyObject *self, Py_buffer *view, int flags)
{
    FPArena *a = (FPArena *)self;
    return PyBuffer_FillInfo(view, self, a->buf, a->len, 1, flags);
}

static PyBufferProcs arena_as_buffer = {
    arena_getbuffer,
    NULL,
};

static Py_ssize_t arena_length(PyObject *self)
{
    return ((FPArena *)self)->len;
}

static PySequenceMethods arena_as_sequence = {
    .sq_length = arena_length,
};

static PyTypeObject FPArenaType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_fastparse.Arena",
    .tp_basicsize = sizeof(FPArena),
    .tp_dealloc = arena_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "ring-pooled read-only span buffer",
    .tp_as_buffer = &arena_as_buffer,
    .tp_as_sequence = &arena_as_sequence,
    .tp_new = NULL,                 /* not constructible from Python */
};

/* round up to the next power of two, >= 256 */
static size_t round_cap(size_t need)
{
    size_t cap = 256;
    while (cap < need)
        cap <<= 1;
    return cap;
}

static FPArena *arena_acquire(size_t need)
{
    uint8_t *buf = NULL;
    size_t cap = 0;
    for (int i = 0; i < pool_n; i++) {
        if (pool[i].cap >= need) {
            buf = pool[i].buf;
            cap = pool[i].cap;
            pool_n--;
            pool[i] = pool[pool_n];
            st_pool_hit++;
            break;
        }
    }
    if (!buf) {
        cap = round_cap(need);
        buf = PyMem_RawMalloc(cap);
        if (!buf) {
            PyErr_NoMemory();
            return NULL;
        }
        st_pool_miss++;
    }
    FPArena *a = PyObject_New(FPArena, &FPArenaType);
    if (!a) {
        /* return the buffer to the pool rather than leak/free churn */
        if (pool_n < FP_POOL) {
            pool[pool_n].buf = buf;
            pool[pool_n].cap = cap;
            pool_n++;
        } else {
            PyMem_RawFree(buf);
        }
        return NULL;
    }
    a->buf = buf;
    a->cap = cap;
    a->len = 0;
    return a;
}

/* ------------------------------------------------------------------ */
/* parse_block                                                         */

static PyObject *py_parse_block(PyObject *self, PyObject *arg)
{
    (void)self;
    Py_buffer in;
    if (PyObject_GetBuffer(arg, &in, PyBUF_CONTIG_RO) < 0)
        return NULL;
    const uint8_t *base = in.buf;
    cur_t c = {base, base + in.len};

    int64_t number = 0;
    const uint8_t *prev_p = NULL, *dhash_p = NULL;
    uint32_t prev_n = 0, dhash_n = 0;
    size_t data_off = 0, data_end = 0, meta_off = 0;
    uint32_t ndata = 0;
    FPArena *spans = NULL;

    uint32_t top_n;
    if (dict_enter(&c, &top_n) < 0 || top_n != 3)
        goto reject;

    /* --- "data": [bytes, ...] ---------------------------------------- */
    {
        const uint8_t *k; uint32_t kn;
        const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
        if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0
            || !key_is(k, kn, "data"))
            goto reject;
        if (c.p >= c.end || *c.p != 'L')
            goto reject;
        data_off = (size_t)(c.p - base);
        c.p++;
        if (rd_u32(&c, &ndata) < 0)
            goto reject;
        /* a genuine n-item list needs >= 5 bytes per 'B' item; a count
         * this buffer cannot possibly hold would otherwise make us
         * malloc a huge span table before the walk fails */
        if ((size_t)ndata > (size_t)in.len / 5)
            goto reject;
        spans = arena_acquire(ndata ? (size_t)ndata * 16 : 16);
        if (!spans)
            goto error;
        uint64_t *tab = (uint64_t *)spans->buf;
        for (uint32_t i = 0; i < ndata; i++) {
            const uint8_t *bp; uint32_t bn;
            if (rd_bytes(&c, &bp, &bn) < 0)
                goto reject;
            tab[2 * i] = (uint64_t)(bp - base);
            tab[2 * i + 1] = bn;
        }
        spans->len = (Py_ssize_t)ndata * 16;
        data_end = (size_t)(c.p - base);
    }

    /* --- "header": {data_hash, number, previous_hash} ----------------- */
    {
        const uint8_t *k; uint32_t kn;
        const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
        if (rd_u32(&c, &kn) < 0 || (uint32_t)(c.end - c.p) < kn)
            goto reject;
        k = c.p;
        c.p += kn;
        if (!key_is(k, kn, "header"))
            goto reject;
        uint32_t hn;
        if (dict_enter(&c, &hn) < 0 || hn != 3)
            goto reject;
        if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0
            || !key_is(k, kn, "data_hash")
            || rd_bytes(&c, &dhash_p, &dhash_n) < 0)
            goto reject;
        if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0
            || !key_is(k, kn, "number")
            || rd_i64(&c, &number) < 0)
            goto reject;
        if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0
            || !key_is(k, kn, "previous_hash")
            || rd_bytes(&c, &prev_p, &prev_n) < 0)
            goto reject;
    }

    /* --- "metadata": any canonical dict, last value in the buffer ----- */
    {
        const uint8_t *k; uint32_t kn;
        if (rd_u32(&c, &kn) < 0 || (uint32_t)(c.end - c.p) < kn)
            goto reject;
        k = c.p;
        c.p += kn;
        if (!key_is(k, kn, "metadata"))
            goto reject;
        meta_off = (size_t)(c.p - base);
        if (c.p >= c.end || *c.p != 'D')
            goto reject;
        if (canon_value_d(&c, 1) < 0)
            goto reject;
        if (c.p != c.end)
            goto reject;
    }

    {
        PyObject *res = Py_BuildValue(
            "(Ly#y#nnIOn)",
            (long long)number,
            (const char *)prev_p, (Py_ssize_t)prev_n,
            (const char *)dhash_p, (Py_ssize_t)dhash_n,
            (Py_ssize_t)data_off, (Py_ssize_t)data_end,
            (unsigned int)ndata,
            (PyObject *)spans,
            (Py_ssize_t)meta_off);
        Py_DECREF(spans);
        PyBuffer_Release(&in);
        if (res)
            st_blk_accept++;
        return res;
    }

reject:
    Py_XDECREF(spans);
    PyBuffer_Release(&in);
    st_blk_reject++;
    Py_RETURN_NONE;
error:
    Py_XDECREF(spans);
    PyBuffer_Release(&in);
    return NULL;
}

/* ------------------------------------------------------------------ */
/* envelope_summary                                                    */

/* Walk a strict-canonical dict; for the single entry whose key matches
 * `want`, leave a sub-cursor positioned at its value and fully
 * canon-validate every other entry.  Returns 1 found / 0 not found /
 * -1 malformed.  The full dict (including the wanted value) is
 * canonically validated either way. */
static int dict_find(cur_t *c, const char *want, cur_t *val)
{
    uint32_t n;
    int found = 0;
    if (dict_enter(c, &n) < 0) return -1;
    const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
    while (n--) {
        const uint8_t *k; uint32_t kn;
        if (dict_key(c, &kprev, &kprev_n, &k, &kn) < 0) return -1;
        const uint8_t *vstart = c->p;
        if (canon_value_d(c, 1) < 0) return -1;
        if (key_is(k, kn, want)) {
            val->p = vstart;
            val->end = c->p;
            found = 1;
        }
    }
    return found;
}

static PyObject *py_envelope_summary(PyObject *self, PyObject *arg)
{
    (void)self;
    Py_buffer in;
    if (PyObject_GetBuffer(arg, &in, PyBUF_CONTIG_RO) < 0)
        return NULL;
    const uint8_t *base = in.buf;
    cur_t c = {base, base + in.len};

    const uint8_t *type_p = NULL, *chan_p = NULL, *txid_p = NULL;
    uint32_t type_n = 0, chan_n = 0, txid_n = 0;

    /* envelope top dict: must contain payload:B and signature; whole
     * buffer strict canonical with no trailing bytes */
    cur_t payload_v = {NULL, NULL}, sig_v = {NULL, NULL};
    {
        uint32_t n;
        if (dict_enter(&c, &n) < 0) goto reject;
        const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
        while (n--) {
            const uint8_t *k; uint32_t kn;
            if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0) goto reject;
            const uint8_t *vstart = c.p;
            if (canon_value_d(&c, 1) < 0) goto reject;
            if (key_is(k, kn, "payload")) {
                payload_v.p = vstart;
                payload_v.end = c.p;
            } else if (key_is(k, kn, "signature")) {
                sig_v.p = vstart;
                sig_v.end = c.p;
            }
        }
        if (c.p != c.end || !payload_v.p || !sig_v.p) goto reject;
    }

    /* payload must be 'B'; its CONTENT is itself a canonical dict
     * (what Envelope.payload_dict() decodes) */
    {
        const uint8_t *pp; uint32_t pn;
        if (rd_bytes(&payload_v, &pp, &pn) < 0 || payload_v.p != payload_v.end)
            goto reject;
        cur_t pc = {pp, pp + pn};

        cur_t header_v = {NULL, NULL};
        int r = dict_find(&pc, "header", &header_v);
        if (r < 0 || pc.p != pc.end || r == 0) goto reject;

        /* header: needs channel_header AND signature_header (mirror:
         * Header.from_dict KeyErrors without either) */
        cur_t ch_v = {NULL, NULL}, sh_v = {NULL, NULL};
        {
            cur_t hv = header_v;
            if (dict_find(&hv, "channel_header", &ch_v) != 1) goto reject;
            hv = header_v;
            if (dict_find(&hv, "signature_header", &sh_v) != 1) goto reject;
        }
        /* signature_header: creator + nonce keys must exist */
        {
            cur_t t = sh_v, dummy = {NULL, NULL};
            if (dict_find(&t, "creator", &dummy) != 1) goto reject;
            t = sh_v;
            if (dict_find(&t, "nonce", &dummy) != 1) goto reject;
        }
        /* channel_header: type/channel_id/txid strs */
        {
            cur_t t = ch_v, v = {NULL, NULL};
            if (dict_find(&t, "type", &v) != 1
                || rd_str(&v, &type_p, &type_n) < 0 || v.p != v.end)
                goto reject;
            t = ch_v;
            if (dict_find(&t, "channel_id", &v) != 1
                || rd_str(&v, &chan_p, &chan_n) < 0 || v.p != v.end)
                goto reject;
            t = ch_v;
            if (dict_find(&t, "txid", &v) != 1
                || rd_str(&v, &txid_p, &txid_n) < 0 || v.p != v.end)
                goto reject;
        }
    }

    {
        PyObject *res = Py_BuildValue(
            "(s#s#s#)",
            (const char *)type_p, (Py_ssize_t)type_n,
            (const char *)chan_p, (Py_ssize_t)chan_n,
            (const char *)txid_p, (Py_ssize_t)txid_n);
        PyBuffer_Release(&in);
        if (res)
            st_env_accept++;
        return res;
    }

reject:
    PyBuffer_Release(&in);
    st_env_reject++;
    Py_RETURN_NONE;
}

/* ------------------------------------------------------------------ */
/* rwset_lanes — device-resident validation lane extractor.
 *
 * rwset_lanes(base_buf, spans_buf) walks every envelope span of a
 * block (spans_buf = n × (u64 off, u64 len) pairs, the same layout
 * parse_block emits) and classifies each tx against the EXACT
 * semantics of ledger/mvcc.parse_endorser_tx + protocol/types
 * from_dict laxity, emitting fixed-width uint64 lanes for the fused
 * XLA gate+MVCC program (committer/device_validate.py):
 *
 *   status 0 OK       strict endorser tx; lanes emitted
 *   status 1 SKIP     parse_endorser_tx provably returns None
 *                     (non-endorser channel-header type, or an empty
 *                     actions list)
 *   status 2 BAD      parse_endorser_tx provably RAISES (the oracle
 *                     stamps BAD_RWSET on a gate-valid tx)
 *   status 3 RANGE    well-formed endorser tx carrying a non-empty
 *                     range_queries list (interval replay is host work)
 *   status 4 UNKNOWN  host outcome is deterministic but device-
 *                     inexpressible (non-str keys, bignum/odd version
 *                     shapes, non-bool is_delete, non-bytes payload…)
 *
 * RANGE/UNKNOWN txs that could pass the signature gate force the host
 * path for the block (demotion); BAD/SKIP never do.  rw-set keys are
 * interned by a 64-bit djb2 hash over ns||0x00||key through an
 * open-addressed table with byte-exact comparison: two DISTINCT keys
 * sharing a hash set the collision flag and the whole call returns
 * flags=1 so the caller demotes — correctness never depends on hash
 * uniqueness.
 *
 * Return: (flags, n_tx, n_keys, n_reads, n_writes, arena) where the
 * arena holds native-endian u64 cells in four sections:
 *   tx      n_tx    × 3  [status, txid_off, txid_len]
 *   reads   n_reads × 5  [tx, slot, has_version, block_num, tx_num]
 *   writes  n_writes× 5  [tx, slot, is_delete, value_off, value_len]
 *   keys    n_keys  × 5  [hash, ns_off, ns_len, key_off, key_len]
 * All offsets index base_buf.  On collision: (1, 0, 0, 0, 0, None).
 * None for inputs that are not a valid span table over base_buf.
 * Scratch buffers are module-global PyMem_Raw allocations reused
 * across calls — the parse stage stays O(1) Python allocations.      */

enum {
    LN_OK = 0, LN_SKIP = 1, LN_BAD = 2, LN_RANGE = 3, LN_UNKNOWN = 4,
    LN_COLL = -1, LN_OOM = -2,
};

static uint64_t *g_tx = NULL;   static size_t g_tx_cap = 0;
static uint64_t *g_rd = NULL;   static size_t g_rd_cap = 0, g_rd_n = 0;
static uint64_t *g_wr = NULL;   static size_t g_wr_cap = 0, g_wr_n = 0;
static uint64_t *g_keys = NULL; static size_t g_keys_cap = 0, g_keys_n = 0;
static uint32_t *g_tab = NULL;  static size_t g_tab_cap = 0;

static uint64_t st_rw_accept = 0;     /* lane calls that produced lanes */
static uint64_t st_rw_reject = 0;     /* invalid span-table inputs      */
static uint64_t st_rw_collision = 0;  /* calls demoted on hash collision */
static uint64_t st_rw_keys = 0;       /* unique rw keys interned (cum.) */
static uint64_t st_rw_lanes = 0;      /* read+write lanes emitted (cum.) */

static int grow_u64(uint64_t **buf, size_t *cap, size_t need)
{
    if (*cap >= need) return 0;
    size_t ncap = *cap ? *cap : 256;
    while (ncap < need) ncap <<= 1;
    uint64_t *nb = PyMem_RawRealloc(*buf, ncap * sizeof(uint64_t));
    if (!nb) { PyErr_NoMemory(); return -1; }
    *buf = nb;
    *cap = ncap;
    return 0;
}

static int tab_grow(void)
{
    size_t ncap = g_tab_cap ? g_tab_cap * 2 : 64;
    uint32_t *nt = PyMem_RawMalloc(ncap * sizeof(uint32_t));
    if (!nt) { PyErr_NoMemory(); return -1; }
    memset(nt, 0, ncap * sizeof(uint32_t));
    for (size_t j = 0; j < g_keys_n; j++) {
        size_t i = (size_t)g_keys[5 * j] & (ncap - 1);
        while (nt[i]) i = (i + 1) & (ncap - 1);
        nt[i] = (uint32_t)(j + 1);
    }
    PyMem_RawFree(g_tab);
    g_tab = nt;
    g_tab_cap = ncap;
    return 0;
}

/* slot index, or LN_COLL (same hash, different key bytes) / LN_OOM */
static int64_t intern_key(const uint8_t *base,
                          uint64_t ns_off, uint64_t ns_len,
                          uint64_t key_off, uint64_t key_len)
{
    uint64_t h = 5381, i_;
    const uint8_t *p = base + ns_off;
    for (i_ = 0; i_ < ns_len; i_++) h = h * 33 + p[i_];
    h = h * 33;                        /* the 0x00 ns/key separator */
    p = base + key_off;
    for (i_ = 0; i_ < key_len; i_++) h = h * 33 + p[i_];

    if ((g_keys_n + 1) * 2 > g_tab_cap && tab_grow() < 0)
        return LN_OOM;
    size_t mask = g_tab_cap - 1;
    size_t i = (size_t)h & mask;
    while (g_tab[i]) {
        uint64_t *rec = &g_keys[5 * (size_t)(g_tab[i] - 1)];
        if (rec[0] == h) {
            if (rec[2] == ns_len && rec[4] == key_len
                && memcmp(base + rec[1], base + ns_off, (size_t)ns_len) == 0
                && memcmp(base + rec[3], base + key_off, (size_t)key_len) == 0)
                return (int64_t)(g_tab[i] - 1);
            return LN_COLL;
        }
        i = (i + 1) & mask;
    }
    if (grow_u64(&g_keys, &g_keys_cap, (g_keys_n + 1) * 5) < 0)
        return LN_OOM;
    uint64_t *rec = &g_keys[5 * g_keys_n];
    rec[0] = h;
    rec[1] = ns_off; rec[2] = ns_len;
    rec[3] = key_off; rec[4] = key_len;
    g_tab[i] = (uint32_t)(g_keys_n + 1);
    st_rw_keys++;
    return (int64_t)g_keys_n++;
}

/* Version.from_list mirror: None -> absent; list len<2 raises
 * (IndexError -> BAD); both ints must be fixed 'I' within i32, else
 * the host compare is device-inexpressible (UNKNOWN); extra elements
 * are ignored by from_list.  On any non-OK status the caller abandons
 * the whole envelope, so the cursor may be left mid-value. */
static int walk_version(cur_t *c, uint64_t *has, uint64_t *blk,
                        uint64_t *txn)
{
    if (c->p >= c->end) return LN_BAD;
    uint8_t tag = *c->p;
    if (tag == 'N') { c->p++; return LN_OK; }
    if (tag != 'L') return LN_UNKNOWN;
    c->p++;
    uint32_t n;
    if (rd_u32(c, &n) < 0) return LN_BAD;
    if (n < 2) return LN_BAD;          /* v[0]/v[1] IndexError */
    int64_t v0, v1;
    if (rd_i64(c, &v0) < 0 || v0 < INT32_MIN || v0 > INT32_MAX)
        return LN_UNKNOWN;
    if (rd_i64(c, &v1) < 0 || v1 < INT32_MIN || v1 > INT32_MAX)
        return LN_UNKNOWN;
    for (uint32_t i = 2; i < n; i++)
        if (canon_value_d(c, 1) < 0) return LN_BAD;
    *has = 1;
    *blk = (uint64_t)v0;
    *txn = (uint64_t)v1;
    return LN_OK;
}

static int walk_read(cur_t *c, const uint8_t *base, int emit, uint64_t tx,
                     uint64_t ns_off, uint64_t ns_len)
{
    uint32_t n;
    if (dict_enter(c, &n) < 0) return LN_BAD;  /* d["key"] raises */
    const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
    uint64_t key_off = 0, key_len = 0, has = 0, blk = 0, txn = 0;
    int have_key = 0;
    while (n--) {
        const uint8_t *k; uint32_t kn;
        if (dict_key(c, &kprev, &kprev_n, &k, &kn) < 0) return LN_BAD;
        if (key_is(k, kn, "key")) {
            const uint8_t *sp; uint32_t sn;
            if (c->p >= c->end || *c->p != 'S') return LN_UNKNOWN;
            if (rd_str(c, &sp, &sn) < 0) return LN_BAD;
            key_off = (uint64_t)(sp - base);
            key_len = sn;
            have_key = 1;
        } else if (key_is(k, kn, "version")) {
            int st = walk_version(c, &has, &blk, &txn);
            if (st != LN_OK) return st;
        } else {
            if (canon_value_d(c, 1) < 0) return LN_BAD;
        }
    }
    if (!have_key) return LN_BAD;
    if (!emit) return LN_OK;
    int64_t slot = intern_key(base, ns_off, ns_len, key_off, key_len);
    if (slot < 0) return (int)slot;
    if (grow_u64(&g_rd, &g_rd_cap, (g_rd_n + 1) * 5) < 0) return LN_OOM;
    uint64_t *r = &g_rd[5 * g_rd_n++];
    r[0] = tx; r[1] = (uint64_t)slot; r[2] = has; r[3] = blk; r[4] = txn;
    return LN_OK;
}

static int walk_write(cur_t *c, const uint8_t *base, int emit, uint64_t tx,
                      uint64_t ns_off, uint64_t ns_len)
{
    uint32_t n;
    if (dict_enter(c, &n) < 0) return LN_BAD;
    const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
    uint64_t key_off = 0, key_len = 0, del = 0, voff = 0, vlen = 0;
    int have_key = 0;
    while (n--) {
        const uint8_t *k; uint32_t kn;
        if (dict_key(c, &kprev, &kprev_n, &k, &kn) < 0) return LN_BAD;
        if (key_is(k, kn, "key")) {
            const uint8_t *sp; uint32_t sn;
            if (c->p >= c->end || *c->p != 'S') return LN_UNKNOWN;
            if (rd_str(c, &sp, &sn) < 0) return LN_BAD;
            key_off = (uint64_t)(sp - base);
            key_len = sn;
            have_key = 1;
        } else if (key_is(k, kn, "is_delete")) {
            if (c->p >= c->end) return LN_BAD;
            if (*c->p == 'T') del = 1;
            else if (*c->p == 'F') del = 0;
            else return LN_UNKNOWN;    /* truthy non-bool: mirrorable
                                        * host-side only */
            c->p++;
        } else if (key_is(k, kn, "value")) {
            const uint8_t *bp; uint32_t bn;
            if (c->p >= c->end || *c->p != 'B') return LN_UNKNOWN;
            if (rd_bytes(c, &bp, &bn) < 0) return LN_BAD;
            voff = (uint64_t)(bp - base);
            vlen = bn;
        } else {
            if (canon_value_d(c, 1) < 0) return LN_BAD;
        }
    }
    if (!have_key) return LN_BAD;
    if (!emit) return LN_OK;
    int64_t slot = intern_key(base, ns_off, ns_len, key_off, key_len);
    if (slot < 0) return (int)slot;
    if (grow_u64(&g_wr, &g_wr_cap, (g_wr_n + 1) * 5) < 0) return LN_OOM;
    uint64_t *w = &g_wr[5 * g_wr_n++];
    w[0] = tx; w[1] = (uint64_t)slot; w[2] = del; w[3] = voff; w[4] = vlen;
    return LN_OK;
}

/* One NsRwSet dict.  Canonical key order namespace < range_queries <
 * reads < writes guarantees the namespace span is known before any
 * lane is emitted; a reads/writes key reached without it means
 * d["namespace"] raises (sorted keys cannot produce it later). */
static int walk_ns(cur_t *c, const uint8_t *base, int emit, uint64_t tx)
{
    uint32_t n;
    if (dict_enter(c, &n) < 0) return LN_BAD;
    const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
    uint64_t ns_off = 0, ns_len = 0;
    int have_ns = 0, have_reads = 0, have_writes = 0, saw_range = 0;
    while (n--) {
        const uint8_t *k; uint32_t kn;
        if (dict_key(c, &kprev, &kprev_n, &k, &kn) < 0) return LN_BAD;
        if (key_is(k, kn, "namespace")) {
            const uint8_t *sp; uint32_t sn;
            if (c->p >= c->end || *c->p != 'S') return LN_UNKNOWN;
            if (rd_str(c, &sp, &sn) < 0) return LN_BAD;
            ns_off = (uint64_t)(sp - base);
            ns_len = sn;
            have_ns = 1;
        } else if (key_is(k, kn, "reads")) {
            if (!have_ns) return LN_BAD;
            if (c->p >= c->end || *c->p != 'L') return LN_UNKNOWN;
            c->p++;
            uint32_t rn;
            if (rd_u32(c, &rn) < 0) return LN_BAD;
            while (rn--) {
                int st = walk_read(c, base, emit, tx, ns_off, ns_len);
                if (st != LN_OK) return st;
            }
            have_reads = 1;
        } else if (key_is(k, kn, "writes")) {
            if (!have_ns) return LN_BAD;
            if (c->p >= c->end || *c->p != 'L') return LN_UNKNOWN;
            c->p++;
            uint32_t wn;
            if (rd_u32(c, &wn) < 0) return LN_BAD;
            while (wn--) {
                int st = walk_write(c, base, emit, tx, ns_off, ns_len);
                if (st != LN_OK) return st;
            }
            have_writes = 1;
        } else if (key_is(k, kn, "range_queries")) {
            if (c->p >= c->end || *c->p != 'L') return LN_UNKNOWN;
            cur_t peek = *c;
            peek.p++;
            uint32_t qn;
            if (rd_u32(&peek, &qn) < 0) return LN_BAD;
            if (canon_value_d(c, 1) < 0) return LN_BAD;
            if (qn > 0) saw_range = 1;
        } else {
            if (canon_value_d(c, 1) < 0) return LN_BAD;
        }
    }
    if (!have_ns || !have_reads || !have_writes) return LN_BAD;
    return saw_range ? LN_RANGE : LN_OK;
}

static int walk_rwset(cur_t *c, const uint8_t *base, int emit, uint64_t tx)
{
    uint32_t n;
    if (dict_enter(c, &n) < 0) return LN_BAD;  /* d["ns"] raises */
    const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
    int have_ns_list = 0;
    while (n--) {
        const uint8_t *k; uint32_t kn;
        if (dict_key(c, &kprev, &kprev_n, &k, &kn) < 0) return LN_BAD;
        if (key_is(k, kn, "ns")) {
            if (c->p >= c->end || *c->p != 'L') return LN_UNKNOWN;
            c->p++;
            uint32_t ln;
            if (rd_u32(c, &ln) < 0) return LN_BAD;
            while (ln--) {
                int st = walk_ns(c, base, emit, tx);
                if (st != LN_OK) return st;
            }
            have_ns_list = 1;
        } else {
            if (canon_value_d(c, 1) < 0) return LN_BAD;
        }
    }
    return have_ns_list ? LN_OK : LN_BAD;
}

static int walk_endorsement(cur_t *c)
{
    uint32_t n;
    if (dict_enter(c, &n) < 0) return LN_BAD;
    const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
    int have_e = 0, have_s = 0;
    while (n--) {
        const uint8_t *k; uint32_t kn;
        if (dict_key(c, &kprev, &kprev_n, &k, &kn) < 0) return LN_BAD;
        if (key_is(k, kn, "endorser")) have_e = 1;
        else if (key_is(k, kn, "signature")) have_s = 1;
        if (canon_value_d(c, 1) < 0) return LN_BAD;
    }
    return (have_e && have_s) ? LN_OK : LN_BAD;
}

static int walk_cc_action(cur_t *c, const uint8_t *base, int emit,
                          uint64_t tx)
{
    uint32_t n;
    if (dict_enter(c, &n) < 0) return LN_BAD;
    const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
    int have_id = 0, have_ver = 0, have_rw = 0;
    while (n--) {
        const uint8_t *k; uint32_t kn;
        if (dict_key(c, &kprev, &kprev_n, &k, &kn) < 0) return LN_BAD;
        if (key_is(k, kn, "chaincode_id")) {
            have_id = 1;
            if (canon_value_d(c, 1) < 0) return LN_BAD;
        } else if (key_is(k, kn, "chaincode_version")) {
            have_ver = 1;
            if (canon_value_d(c, 1) < 0) return LN_BAD;
        } else if (key_is(k, kn, "rwset")) {
            int st = walk_rwset(c, base, emit, tx);
            if (st != LN_OK) return st;
            have_rw = 1;
        } else {
            if (canon_value_d(c, 1) < 0) return LN_BAD;
        }
    }
    return (have_id && have_ver && have_rw) ? LN_OK : LN_BAD;
}

static int walk_action(cur_t *c, const uint8_t *base, int emit, uint64_t tx)
{
    uint32_t n;
    if (dict_enter(c, &n) < 0) return LN_BAD;
    const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
    int have_ph = 0, have_act = 0, have_end = 0;
    while (n--) {
        const uint8_t *k; uint32_t kn;
        if (dict_key(c, &kprev, &kprev_n, &k, &kn) < 0) return LN_BAD;
        if (key_is(k, kn, "action")) {
            int st = walk_cc_action(c, base, emit, tx);
            if (st != LN_OK) return st;
            have_act = 1;
        } else if (key_is(k, kn, "endorsements")) {
            if (c->p >= c->end || *c->p != 'L') return LN_UNKNOWN;
            c->p++;
            uint32_t en;
            if (rd_u32(c, &en) < 0) return LN_BAD;
            while (en--) {
                int st = walk_endorsement(c);
                if (st != LN_OK) return st;
            }
            have_end = 1;
        } else if (key_is(k, kn, "proposal_hash")) {
            have_ph = 1;
            if (canon_value_d(c, 1) < 0) return LN_BAD;
        } else {
            if (canon_value_d(c, 1) < 0) return LN_BAD;
        }
    }
    return (have_ph && have_act && have_end) ? LN_OK : LN_BAD;
}

/* Classify one envelope span; emit lanes for the first action's rwset
 * of an OK endorser tx.  Every decision mirrors a step of
 * Envelope.deserialize -> parse_endorser_tx (see module comment for
 * the status contract); evaluation ORDER matters only where it
 * changes the outcome class — notably ch["txid"] is only read after
 * Transaction.from_dict and the empty-actions check. */
static int walk_env(const uint8_t *base, const uint8_t *ep, size_t en,
                    uint64_t tx, uint64_t *txid_off, uint64_t *txid_len)
{
    cur_t c = {ep, ep + en};
    cur_t payload_v = {NULL, NULL};
    int have_sig = 0;
    uint32_t n;
    if (dict_enter(&c, &n) < 0) return LN_BAD;
    {
        const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
        while (n--) {
            const uint8_t *k; uint32_t kn;
            if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0) return LN_BAD;
            const uint8_t *vstart = c.p;
            if (canon_value_d(&c, 1) < 0) return LN_BAD;
            if (key_is(k, kn, "payload")) {
                payload_v.p = vstart;
                payload_v.end = c.p;
            } else if (key_is(k, kn, "signature")) {
                have_sig = 1;
            }
        }
    }
    if (c.p != c.end) return LN_BAD;
    if (!payload_v.p || !have_sig) return LN_BAD;   /* KeyError */
    if (*payload_v.p != 'B') return LN_UNKNOWN;     /* decode(non-bytes) */

    const uint8_t *pp; uint32_t pn;
    if (rd_bytes(&payload_v, &pp, &pn) < 0) return LN_BAD;

    cur_t header_v = {NULL, NULL};
    {
        cur_t pc = {pp, pp + pn};
        int r = dict_find(&pc, "header", &header_v);
        if (r != 1 || pc.p != pc.end) return LN_BAD;
    }
    cur_t ch_v = {NULL, NULL};
    {
        cur_t t = header_v;
        if (dict_find(&t, "channel_header", &ch_v) != 1) return LN_BAD;
    }
    {
        cur_t t = ch_v, type_v = {NULL, NULL};
        if (dict_find(&t, "type", &type_v) != 1) return LN_BAD;
        const uint8_t *sp; uint32_t sn;
        if (type_v.p >= type_v.end || *type_v.p != 'S')
            return LN_SKIP;            /* non-str != TX_ENDORSER */
        if (rd_str(&type_v, &sp, &sn) < 0) return LN_BAD;
        if (!key_is(sp, sn, "endorser_transaction")) return LN_SKIP;
    }
    cur_t data_v = {NULL, NULL};
    {
        cur_t pc = {pp, pp + pn};
        if (dict_find(&pc, "data", &data_v) != 1) return LN_BAD;
    }
    cur_t actions_v = {NULL, NULL};
    {
        cur_t t = data_v;
        if (dict_find(&t, "actions", &actions_v) != 1) return LN_BAD;
    }
    if (actions_v.p >= actions_v.end || *actions_v.p != 'L')
        return LN_UNKNOWN;
    {
        cur_t t = actions_v;
        t.p++;
        uint32_t an;
        if (rd_u32(&t, &an) < 0) return LN_BAD;
        if (an == 0) return LN_SKIP;   /* `not tx.actions` -> None,
                                        * BEFORE ch["txid"] is read */
        for (uint32_t i = 0; i < an; i++) {
            int st = walk_action(&t, base, i == 0, tx);
            if (st != LN_OK) return st;
        }
    }
    {
        cur_t t = ch_v, txid_v = {NULL, NULL};
        if (dict_find(&t, "txid", &txid_v) != 1) return LN_BAD;
        const uint8_t *sp; uint32_t sn;
        if (txid_v.p >= txid_v.end || *txid_v.p != 'S') return LN_UNKNOWN;
        if (rd_str(&txid_v, &sp, &sn) < 0) return LN_BAD;
        *txid_off = (uint64_t)(sp - base);
        *txid_len = sn;
    }
    return LN_OK;
}

static PyObject *py_rwset_lanes(PyObject *self, PyObject *args)
{
    (void)self;
    Py_buffer in, sp;
    if (!PyArg_ParseTuple(args, "y*y*", &in, &sp))
        return NULL;
    if (sp.len % 16) {
        PyBuffer_Release(&in);
        PyBuffer_Release(&sp);
        st_rw_reject++;
        Py_RETURN_NONE;
    }
    const uint8_t *base = in.buf;
    size_t blen = (size_t)in.len;
    size_t T = (size_t)sp.len / 16;

    g_rd_n = g_wr_n = g_keys_n = 0;
    if (g_tab)
        memset(g_tab, 0, g_tab_cap * sizeof(uint32_t));
    if (grow_u64(&g_tx, &g_tx_cap, T ? T * 3 : 1) < 0)
        goto error;

    int collision = 0;
    for (size_t t = 0; t < T; t++) {
        uint64_t sv[2];
        memcpy(sv, (const uint8_t *)sp.buf + 16 * t, 16);
        if (sv[0] > blen || sv[1] > blen - sv[0]) {
            st_rw_reject++;
            goto reject;
        }
        size_t rd_mark = g_rd_n, wr_mark = g_wr_n;
        uint64_t txo = 0, txl = 0;
        int st = walk_env(base, base + sv[0], (size_t)sv[1],
                          (uint64_t)t, &txo, &txl);
        if (st == LN_OOM)
            goto error;
        if (st == LN_COLL) {
            collision = 1;
            break;
        }
        if (st != LN_OK) {             /* drop this tx's partial lanes */
            g_rd_n = rd_mark;
            g_wr_n = wr_mark;
            txo = txl = 0;
        }
        g_tx[3 * t] = (uint64_t)st;
        g_tx[3 * t + 1] = txo;
        g_tx[3 * t + 2] = txl;
    }
    if (collision) {
        PyBuffer_Release(&in);
        PyBuffer_Release(&sp);
        st_rw_collision++;
        return Py_BuildValue("(iKKKKO)", 1, 0ULL, 0ULL, 0ULL, 0ULL,
                             Py_None);
    }
    {
        size_t R = g_rd_n, W = g_wr_n, K = g_keys_n;
        size_t cells = T * 3 + (R + W + K) * 5;
        FPArena *a = arena_acquire(cells ? cells * 8 : 8);
        if (!a)
            goto error;
        uint64_t *o = (uint64_t *)a->buf;
        if (T) { memcpy(o, g_tx, T * 3 * 8); o += T * 3; }
        if (R) { memcpy(o, g_rd, R * 5 * 8); o += R * 5; }
        if (W) { memcpy(o, g_wr, W * 5 * 8); o += W * 5; }
        if (K) { memcpy(o, g_keys, K * 5 * 8); }
        a->len = (Py_ssize_t)(cells * 8);
        st_rw_accept++;
        st_rw_lanes += R + W;
        PyObject *res = Py_BuildValue(
            "(iKKKKN)", 0,
            (unsigned long long)T, (unsigned long long)K,
            (unsigned long long)R, (unsigned long long)W,
            (PyObject *)a);
        PyBuffer_Release(&in);
        PyBuffer_Release(&sp);
        return res;
    }

reject:
    PyBuffer_Release(&in);
    PyBuffer_Release(&sp);
    Py_RETURN_NONE;
error:
    PyBuffer_Release(&in);
    PyBuffer_Release(&sp);
    return NULL;
}

/* ------------------------------------------------------------------ */
/* stats                                                               */

static PyObject *py_stats(PyObject *self, PyObject *noarg)
{
    (void)self;
    (void)noarg;
    return Py_BuildValue(
        "{s:K,s:K,s:K,s:i,s:K,s:K,s:K,s:K,"
        "s:K,s:K,s:K,s:K,s:K,s:K}",
        "pool_hit", (unsigned long long)st_pool_hit,
        "pool_miss", (unsigned long long)st_pool_miss,
        "pool_drop", (unsigned long long)st_pool_drop,
        "pool_free", pool_n,
        "block_accept", (unsigned long long)st_blk_accept,
        "block_reject", (unsigned long long)st_blk_reject,
        "env_accept", (unsigned long long)st_env_accept,
        "env_reject", (unsigned long long)st_env_reject,
        "rw_accept", (unsigned long long)st_rw_accept,
        "rw_reject", (unsigned long long)st_rw_reject,
        "rw_collision", (unsigned long long)st_rw_collision,
        "rw_keys", (unsigned long long)st_rw_keys,
        "rw_lanes", (unsigned long long)st_rw_lanes,
        "rw_table_slots", (unsigned long long)g_tab_cap);
}

/* ------------------------------------------------------------------ */

static PyMethodDef methods[] = {
    {"parse_block", py_parse_block, METH_O,
     "parse_block(buf) -> (number, prev_hash, data_hash, data_off, "
     "data_end, n, spans, meta_val_off) | None"},
    {"envelope_summary", py_envelope_summary, METH_O,
     "envelope_summary(buf) -> (type, channel_id, txid) | None"},
    {"rwset_lanes", py_rwset_lanes, METH_VARARGS,
     "rwset_lanes(base, spans) -> (flags, n_tx, n_keys, n_reads, "
     "n_writes, arena) | None"},
    {"stats", py_stats, METH_NOARGS,
     "stats() -> arena-pool, accept/reject and rw-lane counters"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastparse",
    "zero-copy wire-to-device block/envelope span parser", -1, methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__fastparse(void)
{
    if (PyType_Ready(&FPArenaType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&moduledef);
    if (!m)
        return NULL;
    Py_INCREF(&FPArenaType);
    if (PyModule_AddObject(m, "Arena", (PyObject *)&FPArenaType) < 0) {
        Py_DECREF(&FPArenaType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
