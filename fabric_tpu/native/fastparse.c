/* Zero-copy wire ingest — native block/envelope span parser.
 *
 * fastcollect.c took over txvalidator pass 1 *after* Python had already
 * decoded the block container and materialized a list of per-envelope
 * bytes objects.  This module moves the C plane one layer up, to the
 * wire: it takes the raw FTLV frame bytes (fabric_tpu/utils/serde.py
 * format) of a whole Block or a single Envelope and extracts the byte
 * SPANS the rest of the pipeline needs — without creating any per-tx
 * Python object.  The envelope span table is written into an
 * arena-allocated, ring-pooled buffer so steady-state block ingest does
 * not call malloc at all.
 *
 * Exported:
 *   parse_block(buf) -> (number, previous_hash, data_hash,
 *                        data_off, data_end, n, spans, meta_val_off)
 *                       | None
 *     buf must be EXACTLY the canonical encoding of
 *       {"data": [bytes, ...], "header": {"data_hash": bytes,
 *        "number": i64, "previous_hash": bytes}, "metadata": {...}}
 *     (strict canonical form throughout: sorted unique dict keys,
 *     minimal 'V' ints, valid UTF-8, nesting <= MAX_DEPTH, no trailing
 *     bytes — the same rules serde.decode enforces).  Anything else
 *     returns None and the caller falls back to Block.deserialize, so
 *     accept/reject behavior of the system never changes — only who
 *     does the work.
 *       spans        arena buffer of n (u64 off, u64 len) native-endian
 *                    pairs: block.data[i] == buf[off:off+len]
 *       data_off/end span of the whole data LIST value, so
 *                    sha256(buf[data_off:data_end]) ==
 *                    block_data_hash(block.data) bit-identically
 *       meta_val_off offset where the metadata VALUE begins; because
 *                    "metadata" is the last key of the sorted top dict,
 *                    buf[:meta_val_off] + serde.encode(metadata_dict)
 *                    re-serializes a metadata-mutated block by splice
 *   envelope_summary(buf) -> (type, channel_id, txid) | None
 *     the gateway submit path's header peek: what
 *     Envelope.deserialize(buf).header().channel_header would yield,
 *     without building the Envelope/Header object trees.  None on any
 *     deviation from the strict shape (caller falls back).
 *   stats() -> dict of arena-pool and accept/reject counters
 *
 * Arena lifecycle: parse_block writes the span table into an Arena
 * object (read-only buffer protocol).  When the Arena's refcount drops
 * to zero its backing buffer is pushed onto a small ring free-list
 * (FP_POOL entries) and the next parse_block reuses it; only pool
 * overflow frees.  All pool operations run under the GIL (parse holds
 * it throughout; tp_dealloc always has it), so no extra locking.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* FTLV cursor (format: fabric_tpu/utils/serde.py; walker idiom shared
 * with native/fastcollect.c — the two must enforce identical rules)    */

typedef struct {
    const uint8_t *p;
    const uint8_t *end;
} cur_t;

static int rd_u32(cur_t *c, uint32_t *out)
{
    if (c->end - c->p < 4) return -1;
    *out = ((uint32_t)c->p[0] << 24) | ((uint32_t)c->p[1] << 16)
         | ((uint32_t)c->p[2] << 8) | c->p[3];
    c->p += 4;
    return 0;
}

#define MAX_DEPTH 64

/* strict UTF-8 (CPython decoder semantics: no overlongs, no
 * surrogates, max U+10FFFF) */
static int utf8_ok(const uint8_t *p, uint32_t n)
{
    uint32_t i = 0;
    while (i < n) {
        uint8_t b = p[i];
        if (b < 0x80) { i++; continue; }
        if (b < 0xC2) return 0;
        if (b < 0xE0) {
            if (n - i < 2 || (p[i+1] & 0xC0) != 0x80) return 0;
            i += 2; continue;
        }
        if (b < 0xF0) {
            if (n - i < 3) return 0;
            uint8_t b1 = p[i+1], b2 = p[i+2];
            if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80) return 0;
            if (b == 0xE0 && b1 < 0xA0) return 0;
            if (b == 0xED && b1 >= 0xA0) return 0;
            i += 3; continue;
        }
        if (b < 0xF5) {
            if (n - i < 4) return 0;
            uint8_t b1 = p[i+1], b2 = p[i+2], b3 = p[i+3];
            if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80
                || (b3 & 0xC0) != 0x80) return 0;
            if (b == 0xF0 && b1 < 0x90) return 0;
            if (b == 0xF4 && b1 >= 0x90) return 0;
            i += 4; continue;
        }
        return 0;
    }
    return 1;
}

/* validate one value in strict canonical form (serde.decode rules) */
static int canon_value_d(cur_t *c, int depth)
{
    if (depth > MAX_DEPTH) return -1;
    if (c->p >= c->end) return -1;
    uint8_t tag = *c->p++;
    uint32_t n;
    switch (tag) {
    case 'N': case 'T': case 'F':
        return 0;
    case 'I':
        if (c->end - c->p < 8) return -1;
        c->p += 8;
        return 0;
    case 'V':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        if (n < 8 || c->p[0] == 0 || (n == 8 && c->p[0] < 0x80))
            return -1;
        c->p += n;
        return 0;
    case 'B':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        c->p += n;
        return 0;
    case 'S':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        if (!utf8_ok(c->p, n)) return -1;
        c->p += n;
        return 0;
    case 'L':
        if (rd_u32(c, &n) < 0) return -1;
        while (n--)
            if (canon_value_d(c, depth + 1) < 0) return -1;
        return 0;
    case 'D': {
        if (rd_u32(c, &n) < 0) return -1;
        const uint8_t *prev = NULL;
        uint32_t prev_n = 0;
        while (n--) {
            uint32_t kn;
            const uint8_t *k;
            if (rd_u32(c, &kn) < 0
                || (uint32_t)(c->end - c->p) < kn) return -1;
            k = c->p;
            c->p += kn;
            if (!utf8_ok(k, kn)) return -1;
            if (prev) {
                uint32_t m = prev_n < kn ? prev_n : kn;
                int cmp = memcmp(prev, k, m);
                if (cmp > 0 || (cmp == 0 && prev_n >= kn)) return -1;
            }
            prev = k;
            prev_n = kn;
            if (canon_value_d(c, depth + 1) < 0) return -1;
        }
        return 0;
    }
    default:
        return -1;
    }
}

/* Enter a dict ('D'): entry count out, -1 if not a dict header. */
static int dict_enter(cur_t *c, uint32_t *count)
{
    if (c->p >= c->end || *c->p != 'D') return -1;
    c->p++;
    return rd_u32(c, count);
}

/* Next dict entry's key span (must be valid UTF-8 and strictly greater
 * than *prev — the canonical-order check other walkers do inline). */
static int dict_key(cur_t *c, const uint8_t **prev, uint32_t *prev_n,
                    const uint8_t **key, uint32_t *klen)
{
    if (rd_u32(c, klen) < 0 || (uint32_t)(c->end - c->p) < *klen) return -1;
    *key = c->p;
    c->p += *klen;
    if (!utf8_ok(*key, *klen)) return -1;
    if (*prev) {
        uint32_t m = *prev_n < *klen ? *prev_n : *klen;
        int cmp = memcmp(*prev, *key, m);
        if (cmp > 0 || (cmp == 0 && *prev_n >= *klen)) return -1;
    }
    *prev = *key;
    *prev_n = *klen;
    return 0;
}

static int key_is(const uint8_t *key, uint32_t klen, const char *name)
{
    size_t n = strlen(name);
    return klen == n && memcmp(key, name, n) == 0;
}

/* read a 'B' (bytes) value's content span */
static int rd_bytes(cur_t *c, const uint8_t **p, uint32_t *n)
{
    if (c->p >= c->end || *c->p != 'B') return -1;
    c->p++;
    if (rd_u32(c, n) < 0 || (uint32_t)(c->end - c->p) < *n) return -1;
    *p = c->p;
    c->p += *n;
    return 0;
}

/* read an 'S' (str) value's content span (UTF-8 validated) */
static int rd_str(cur_t *c, const uint8_t **p, uint32_t *n)
{
    if (c->p >= c->end || *c->p != 'S') return -1;
    c->p++;
    if (rd_u32(c, n) < 0 || (uint32_t)(c->end - c->p) < *n) return -1;
    if (!utf8_ok(c->p, *n)) return -1;
    *p = c->p;
    c->p += *n;
    return 0;
}

/* read an 'I' (fixed i64) value */
static int rd_i64(cur_t *c, int64_t *out)
{
    if (c->p >= c->end || *c->p != 'I') return -1;
    c->p++;
    if (c->end - c->p < 8) return -1;
    uint64_t v = 0;
    for (int i = 0; i < 8; i++)
        v = (v << 8) | c->p[i];
    c->p += 8;
    *out = (int64_t)v;
    return 0;
}

/* ------------------------------------------------------------------ */
/* Arena: ring-pooled span buffer with read-only buffer protocol       */

#define FP_POOL 8

static struct { uint8_t *buf; size_t cap; } pool[FP_POOL];
static int pool_n = 0;

static uint64_t st_pool_hit = 0;    /* acquires served from the pool   */
static uint64_t st_pool_miss = 0;   /* acquires that hit malloc        */
static uint64_t st_pool_drop = 0;   /* releases freed (pool full)      */
static uint64_t st_blk_accept = 0;
static uint64_t st_blk_reject = 0;
static uint64_t st_env_accept = 0;
static uint64_t st_env_reject = 0;

typedef struct {
    PyObject_HEAD
    uint8_t *buf;
    size_t cap;
    Py_ssize_t len;
} FPArena;

static void arena_dealloc(PyObject *self)
{
    FPArena *a = (FPArena *)self;
    if (a->buf) {
        if (pool_n < FP_POOL) {
            pool[pool_n].buf = a->buf;
            pool[pool_n].cap = a->cap;
            pool_n++;
        } else {
            st_pool_drop++;
            PyMem_RawFree(a->buf);
        }
        a->buf = NULL;
    }
    Py_TYPE(self)->tp_free(self);
}

static int arena_getbuffer(PyObject *self, Py_buffer *view, int flags)
{
    FPArena *a = (FPArena *)self;
    return PyBuffer_FillInfo(view, self, a->buf, a->len, 1, flags);
}

static PyBufferProcs arena_as_buffer = {
    arena_getbuffer,
    NULL,
};

static Py_ssize_t arena_length(PyObject *self)
{
    return ((FPArena *)self)->len;
}

static PySequenceMethods arena_as_sequence = {
    .sq_length = arena_length,
};

static PyTypeObject FPArenaType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_fastparse.Arena",
    .tp_basicsize = sizeof(FPArena),
    .tp_dealloc = arena_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "ring-pooled read-only span buffer",
    .tp_as_buffer = &arena_as_buffer,
    .tp_as_sequence = &arena_as_sequence,
    .tp_new = NULL,                 /* not constructible from Python */
};

/* round up to the next power of two, >= 256 */
static size_t round_cap(size_t need)
{
    size_t cap = 256;
    while (cap < need)
        cap <<= 1;
    return cap;
}

static FPArena *arena_acquire(size_t need)
{
    uint8_t *buf = NULL;
    size_t cap = 0;
    for (int i = 0; i < pool_n; i++) {
        if (pool[i].cap >= need) {
            buf = pool[i].buf;
            cap = pool[i].cap;
            pool_n--;
            pool[i] = pool[pool_n];
            st_pool_hit++;
            break;
        }
    }
    if (!buf) {
        cap = round_cap(need);
        buf = PyMem_RawMalloc(cap);
        if (!buf) {
            PyErr_NoMemory();
            return NULL;
        }
        st_pool_miss++;
    }
    FPArena *a = PyObject_New(FPArena, &FPArenaType);
    if (!a) {
        /* return the buffer to the pool rather than leak/free churn */
        if (pool_n < FP_POOL) {
            pool[pool_n].buf = buf;
            pool[pool_n].cap = cap;
            pool_n++;
        } else {
            PyMem_RawFree(buf);
        }
        return NULL;
    }
    a->buf = buf;
    a->cap = cap;
    a->len = 0;
    return a;
}

/* ------------------------------------------------------------------ */
/* parse_block                                                         */

static PyObject *py_parse_block(PyObject *self, PyObject *arg)
{
    (void)self;
    Py_buffer in;
    if (PyObject_GetBuffer(arg, &in, PyBUF_CONTIG_RO) < 0)
        return NULL;
    const uint8_t *base = in.buf;
    cur_t c = {base, base + in.len};

    int64_t number = 0;
    const uint8_t *prev_p = NULL, *dhash_p = NULL;
    uint32_t prev_n = 0, dhash_n = 0;
    size_t data_off = 0, data_end = 0, meta_off = 0;
    uint32_t ndata = 0;
    FPArena *spans = NULL;

    uint32_t top_n;
    if (dict_enter(&c, &top_n) < 0 || top_n != 3)
        goto reject;

    /* --- "data": [bytes, ...] ---------------------------------------- */
    {
        const uint8_t *k; uint32_t kn;
        const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
        if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0
            || !key_is(k, kn, "data"))
            goto reject;
        if (c.p >= c.end || *c.p != 'L')
            goto reject;
        data_off = (size_t)(c.p - base);
        c.p++;
        if (rd_u32(&c, &ndata) < 0)
            goto reject;
        /* a genuine n-item list needs >= 5 bytes per 'B' item; a count
         * this buffer cannot possibly hold would otherwise make us
         * malloc a huge span table before the walk fails */
        if ((size_t)ndata > (size_t)in.len / 5)
            goto reject;
        spans = arena_acquire(ndata ? (size_t)ndata * 16 : 16);
        if (!spans)
            goto error;
        uint64_t *tab = (uint64_t *)spans->buf;
        for (uint32_t i = 0; i < ndata; i++) {
            const uint8_t *bp; uint32_t bn;
            if (rd_bytes(&c, &bp, &bn) < 0)
                goto reject;
            tab[2 * i] = (uint64_t)(bp - base);
            tab[2 * i + 1] = bn;
        }
        spans->len = (Py_ssize_t)ndata * 16;
        data_end = (size_t)(c.p - base);
    }

    /* --- "header": {data_hash, number, previous_hash} ----------------- */
    {
        const uint8_t *k; uint32_t kn;
        const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
        if (rd_u32(&c, &kn) < 0 || (uint32_t)(c.end - c.p) < kn)
            goto reject;
        k = c.p;
        c.p += kn;
        if (!key_is(k, kn, "header"))
            goto reject;
        uint32_t hn;
        if (dict_enter(&c, &hn) < 0 || hn != 3)
            goto reject;
        if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0
            || !key_is(k, kn, "data_hash")
            || rd_bytes(&c, &dhash_p, &dhash_n) < 0)
            goto reject;
        if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0
            || !key_is(k, kn, "number")
            || rd_i64(&c, &number) < 0)
            goto reject;
        if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0
            || !key_is(k, kn, "previous_hash")
            || rd_bytes(&c, &prev_p, &prev_n) < 0)
            goto reject;
    }

    /* --- "metadata": any canonical dict, last value in the buffer ----- */
    {
        const uint8_t *k; uint32_t kn;
        if (rd_u32(&c, &kn) < 0 || (uint32_t)(c.end - c.p) < kn)
            goto reject;
        k = c.p;
        c.p += kn;
        if (!key_is(k, kn, "metadata"))
            goto reject;
        meta_off = (size_t)(c.p - base);
        if (c.p >= c.end || *c.p != 'D')
            goto reject;
        if (canon_value_d(&c, 1) < 0)
            goto reject;
        if (c.p != c.end)
            goto reject;
    }

    {
        PyObject *res = Py_BuildValue(
            "(Ly#y#nnIOn)",
            (long long)number,
            (const char *)prev_p, (Py_ssize_t)prev_n,
            (const char *)dhash_p, (Py_ssize_t)dhash_n,
            (Py_ssize_t)data_off, (Py_ssize_t)data_end,
            (unsigned int)ndata,
            (PyObject *)spans,
            (Py_ssize_t)meta_off);
        Py_DECREF(spans);
        PyBuffer_Release(&in);
        if (res)
            st_blk_accept++;
        return res;
    }

reject:
    Py_XDECREF(spans);
    PyBuffer_Release(&in);
    st_blk_reject++;
    Py_RETURN_NONE;
error:
    Py_XDECREF(spans);
    PyBuffer_Release(&in);
    return NULL;
}

/* ------------------------------------------------------------------ */
/* envelope_summary                                                    */

/* Walk a strict-canonical dict; for the single entry whose key matches
 * `want`, leave a sub-cursor positioned at its value and fully
 * canon-validate every other entry.  Returns 1 found / 0 not found /
 * -1 malformed.  The full dict (including the wanted value) is
 * canonically validated either way. */
static int dict_find(cur_t *c, const char *want, cur_t *val)
{
    uint32_t n;
    int found = 0;
    if (dict_enter(c, &n) < 0) return -1;
    const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
    while (n--) {
        const uint8_t *k; uint32_t kn;
        if (dict_key(c, &kprev, &kprev_n, &k, &kn) < 0) return -1;
        const uint8_t *vstart = c->p;
        if (canon_value_d(c, 1) < 0) return -1;
        if (key_is(k, kn, want)) {
            val->p = vstart;
            val->end = c->p;
            found = 1;
        }
    }
    return found;
}

static PyObject *py_envelope_summary(PyObject *self, PyObject *arg)
{
    (void)self;
    Py_buffer in;
    if (PyObject_GetBuffer(arg, &in, PyBUF_CONTIG_RO) < 0)
        return NULL;
    const uint8_t *base = in.buf;
    cur_t c = {base, base + in.len};

    const uint8_t *type_p = NULL, *chan_p = NULL, *txid_p = NULL;
    uint32_t type_n = 0, chan_n = 0, txid_n = 0;

    /* envelope top dict: must contain payload:B and signature; whole
     * buffer strict canonical with no trailing bytes */
    cur_t payload_v = {NULL, NULL}, sig_v = {NULL, NULL};
    {
        uint32_t n;
        if (dict_enter(&c, &n) < 0) goto reject;
        const uint8_t *kprev = NULL; uint32_t kprev_n = 0;
        while (n--) {
            const uint8_t *k; uint32_t kn;
            if (dict_key(&c, &kprev, &kprev_n, &k, &kn) < 0) goto reject;
            const uint8_t *vstart = c.p;
            if (canon_value_d(&c, 1) < 0) goto reject;
            if (key_is(k, kn, "payload")) {
                payload_v.p = vstart;
                payload_v.end = c.p;
            } else if (key_is(k, kn, "signature")) {
                sig_v.p = vstart;
                sig_v.end = c.p;
            }
        }
        if (c.p != c.end || !payload_v.p || !sig_v.p) goto reject;
    }

    /* payload must be 'B'; its CONTENT is itself a canonical dict
     * (what Envelope.payload_dict() decodes) */
    {
        const uint8_t *pp; uint32_t pn;
        if (rd_bytes(&payload_v, &pp, &pn) < 0 || payload_v.p != payload_v.end)
            goto reject;
        cur_t pc = {pp, pp + pn};

        cur_t header_v = {NULL, NULL};
        int r = dict_find(&pc, "header", &header_v);
        if (r < 0 || pc.p != pc.end || r == 0) goto reject;

        /* header: needs channel_header AND signature_header (mirror:
         * Header.from_dict KeyErrors without either) */
        cur_t ch_v = {NULL, NULL}, sh_v = {NULL, NULL};
        {
            cur_t hv = header_v;
            if (dict_find(&hv, "channel_header", &ch_v) != 1) goto reject;
            hv = header_v;
            if (dict_find(&hv, "signature_header", &sh_v) != 1) goto reject;
        }
        /* signature_header: creator + nonce keys must exist */
        {
            cur_t t = sh_v, dummy = {NULL, NULL};
            if (dict_find(&t, "creator", &dummy) != 1) goto reject;
            t = sh_v;
            if (dict_find(&t, "nonce", &dummy) != 1) goto reject;
        }
        /* channel_header: type/channel_id/txid strs */
        {
            cur_t t = ch_v, v = {NULL, NULL};
            if (dict_find(&t, "type", &v) != 1
                || rd_str(&v, &type_p, &type_n) < 0 || v.p != v.end)
                goto reject;
            t = ch_v;
            if (dict_find(&t, "channel_id", &v) != 1
                || rd_str(&v, &chan_p, &chan_n) < 0 || v.p != v.end)
                goto reject;
            t = ch_v;
            if (dict_find(&t, "txid", &v) != 1
                || rd_str(&v, &txid_p, &txid_n) < 0 || v.p != v.end)
                goto reject;
        }
    }

    {
        PyObject *res = Py_BuildValue(
            "(s#s#s#)",
            (const char *)type_p, (Py_ssize_t)type_n,
            (const char *)chan_p, (Py_ssize_t)chan_n,
            (const char *)txid_p, (Py_ssize_t)txid_n);
        PyBuffer_Release(&in);
        if (res)
            st_env_accept++;
        return res;
    }

reject:
    PyBuffer_Release(&in);
    st_env_reject++;
    Py_RETURN_NONE;
}

/* ------------------------------------------------------------------ */
/* stats                                                               */

static PyObject *py_stats(PyObject *self, PyObject *noarg)
{
    (void)self;
    (void)noarg;
    return Py_BuildValue(
        "{s:K,s:K,s:K,s:i,s:K,s:K,s:K,s:K}",
        "pool_hit", (unsigned long long)st_pool_hit,
        "pool_miss", (unsigned long long)st_pool_miss,
        "pool_drop", (unsigned long long)st_pool_drop,
        "pool_free", pool_n,
        "block_accept", (unsigned long long)st_blk_accept,
        "block_reject", (unsigned long long)st_blk_reject,
        "env_accept", (unsigned long long)st_env_accept,
        "env_reject", (unsigned long long)st_env_reject);
}

/* ------------------------------------------------------------------ */

static PyMethodDef methods[] = {
    {"parse_block", py_parse_block, METH_O,
     "parse_block(buf) -> (number, prev_hash, data_hash, data_off, "
     "data_end, n, spans, meta_val_off) | None"},
    {"envelope_summary", py_envelope_summary, METH_O,
     "envelope_summary(buf) -> (type, channel_id, txid) | None"},
    {"stats", py_stats, METH_NOARGS,
     "stats() -> arena-pool and accept/reject counters"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastparse",
    "zero-copy wire-to-device block/envelope span parser", -1, methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__fastparse(void)
{
    if (PyType_Ready(&FPArenaType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&moduledef);
    if (!m)
        return NULL;
    Py_INCREF(&FPArenaType);
    if (PyModule_AddObject(m, "Arena", (PyObject *)&FPArenaType) < 0) {
        Py_DECREF(&FPArenaType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
