/* FTLV codec — C implementation of fabric_tpu.utils.serde.
 *
 * The framework's canonical TLV serialization (the slot the reference
 * fills with C-backed protobuf, /root/reference/protoutil/) sits on the
 * block-validation hot path: pass 1 of the validator decodes every
 * envelope of every block (SURVEY.md §3.2), and profiling showed the
 * pure-Python codec taking ~half of host-side collect time.  This
 * extension implements the exact same wire format and error behavior;
 * tests/test_serde.py runs differentially against the Python reference
 * implementation.
 *
 * Format (see fabric_tpu/utils/serde.py):
 *   'N' | 'T' | 'F'
 *   'I' + 8B signed big-endian
 *   'V' + u32 len + unsigned big-endian magnitude  (ints >= 2^63)
 *   'B' + u32 len + raw bytes
 *   'S' + u32 len + utf-8
 *   'L' + u32 count + items
 *   'D' + u32 count + (u32 keylen + key-utf8 + value), keys sorted
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

/* uniform nesting cap — MUST match fabric_tpu.utils.serde.MAX_DEPTH and
 * native/fastcollect.c: a value one codec accepts and another rejects
 * is a validation fork between peers */
#define FTLV_MAX_DEPTH 64

/* ------------------------------------------------------------------ */
/* growable output buffer                                              */

typedef struct {
    char *data;
    Py_ssize_t len;
    Py_ssize_t cap;
} buf_t;

static int buf_init(buf_t *b) {
    b->cap = 256;
    b->len = 0;
    b->data = PyMem_Malloc(b->cap);
    return b->data ? 0 : -1;
}

static void buf_free(buf_t *b) {
    PyMem_Free(b->data);
}

static int buf_reserve(buf_t *b, Py_ssize_t extra) {
    if (b->len + extra <= b->cap) return 0;
    Py_ssize_t cap = b->cap;
    while (cap < b->len + extra) cap *= 2;
    char *nd = PyMem_Realloc(b->data, cap);
    if (!nd) { PyErr_NoMemory(); return -1; }
    b->data = nd;
    b->cap = cap;
    return 0;
}

static int buf_put(buf_t *b, const void *src, Py_ssize_t n) {
    if (buf_reserve(b, n) < 0) return -1;
    memcpy(b->data + b->len, src, n);
    b->len += n;
    return 0;
}

static int buf_putc(buf_t *b, char c) {
    return buf_put(b, &c, 1);
}

static int buf_put_u32(buf_t *b, uint32_t v) {
    unsigned char tmp[4] = {
        (unsigned char)(v >> 24), (unsigned char)(v >> 16),
        (unsigned char)(v >> 8), (unsigned char)v };
    return buf_put(b, tmp, 4);
}

/* every length/count field is u32 on the wire; larger values must error
 * (the Python reference raises struct.error -> ValueError), never wrap */
static int buf_put_len(buf_t *b, Py_ssize_t n) {
    if (n < 0 || (uint64_t)n > 0xFFFFFFFFull) {
        PyErr_SetString(PyExc_ValueError,
                        "length does not fit a u32 field");
        return -1;
    }
    return buf_put_u32(b, (uint32_t)n);
}

/* ------------------------------------------------------------------ */
/* encode                                                              */

static int enc(PyObject *v, buf_t *b, int depth);

static int enc_int(PyObject *v, buf_t *b) {
    int overflow = 0;
    long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (!overflow) {
        if (x == -1 && PyErr_Occurred()) return -1;
        unsigned char tmp[9];
        tmp[0] = 'I';
        unsigned long long ux = (unsigned long long)x;
        for (int i = 0; i < 8; i++)
            tmp[1 + i] = (unsigned char)(ux >> (8 * (7 - i)));
        return buf_put(b, tmp, 9);
    }
    if (overflow < 0) {
        PyErr_SetString(PyExc_ValueError, "big negative ints unsupported");
        return -1;
    }
    /* big positive int: 'V' + u32 len + magnitude */
    size_t nbits = _PyLong_NumBits(v);
    if (nbits == (size_t)-1 && PyErr_Occurred()) return -1;
    Py_ssize_t n = (Py_ssize_t)((nbits + 7) / 8);
    if (buf_putc(b, 'V') < 0 || buf_put_len(b, n) < 0) return -1;
    if (buf_reserve(b, n) < 0) return -1;
    if (_PyLong_AsByteArray((PyLongObject *)v,
                            (unsigned char *)b->data + b->len, n,
                            /*little=*/0, /*signed=*/0
#if PY_VERSION_HEX >= 0x030d0000
                            , /*with_exceptions=*/1
#endif
                            ) < 0)
        return -1;
    b->len += n;
    return 0;
}

static int enc_buffer(PyObject *v, buf_t *b) {
    Py_buffer view;
    if (PyObject_GetBuffer(v, &view, PyBUF_CONTIG_RO) < 0) return -1;
    int rc = -1;
    if (buf_putc(b, 'B') == 0 && buf_put_len(b, view.len) == 0
        && buf_put(b, view.buf, view.len) == 0)
        rc = 0;
    PyBuffer_Release(&view);
    return rc;
}

static int enc_str(PyObject *v, buf_t *b) {
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(v, &n);
    if (!s) return -1;
    if (buf_putc(b, 'S') < 0 || buf_put_len(b, n) < 0) return -1;
    return buf_put(b, s, n);
}

static int enc_seq(PyObject *v, buf_t *b, int depth) {
    Py_ssize_t n = PySequence_Fast_GET_SIZE(v);
    if (buf_putc(b, 'L') < 0 || buf_put_len(b, n) < 0) return -1;
    PyObject **items = PySequence_Fast_ITEMS(v);
    for (Py_ssize_t i = 0; i < n; i++)
        if (enc(items[i], b, depth + 1) < 0) return -1;
    return 0;
}

static int enc_dict(PyObject *v, buf_t *b, int depth) {
    PyObject *keys = PyDict_Keys(v);
    if (!keys) return -1;
    if (PyList_Sort(keys) < 0) { Py_DECREF(keys); return -1; }
    Py_ssize_t n = PyList_GET_SIZE(keys);
    int rc = -1;
    if (buf_putc(b, 'D') < 0 || buf_put_len(b, n) < 0)
        goto done;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *k = PyList_GET_ITEM(keys, i);
        if (!PyUnicode_Check(k)) {
            PyErr_SetString(PyExc_TypeError, "dict keys must be str");
            goto done;
        }
        Py_ssize_t kn;
        const char *ks = PyUnicode_AsUTF8AndSize(k, &kn);
        if (!ks) goto done;
        if (buf_put_len(b, kn) < 0 || buf_put(b, ks, kn) < 0)
            goto done;
        PyObject *val = PyDict_GetItemWithError(v, k);
        if (!val) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_KeyError, "key vanished during encode");
            goto done;
        }
        if (enc(val, b, depth + 1) < 0) goto done;
    }
    rc = 0;
done:
    Py_DECREF(keys);
    return rc;
}

static int enc(PyObject *v, buf_t *b, int depth) {
    if (depth > FTLV_MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "nesting too deep");
        return -1;
    }
    if (Py_EnterRecursiveCall(" in ftlv encode")) return -1;
    int rc = -1;
    if (v == Py_None) {
        rc = buf_putc(b, 'N');
    } else if (v == Py_True) {
        rc = buf_putc(b, 'T');
    } else if (v == Py_False) {
        rc = buf_putc(b, 'F');
    } else if (PyLong_Check(v)) {
        rc = enc_int(v, b);
    } else if (PyBytes_Check(v) || PyByteArray_Check(v) || PyMemoryView_Check(v)) {
        rc = enc_buffer(v, b);
    } else if (PyUnicode_Check(v)) {
        rc = enc_str(v, b);
    } else if (PyList_Check(v) || PyTuple_Check(v)) {
        rc = enc_seq(v, b, depth);
    } else if (PyDict_Check(v)) {
        rc = enc_dict(v, b, depth);
    } else {
        PyErr_Format(PyExc_TypeError, "unsupported type %R", Py_TYPE(v));
    }
    Py_LeaveRecursiveCall();
    return rc;
}

static PyObject *py_encode(PyObject *self, PyObject *arg) {
    (void)self;
    buf_t b;
    if (buf_init(&b) < 0) return PyErr_NoMemory();
    if (enc(arg, &b, 0) < 0) {
        buf_free(&b);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(b.data, b.len);
    buf_free(&b);
    return out;
}

/* ------------------------------------------------------------------ */
/* decode                                                              */

typedef struct {
    const unsigned char *p;
    Py_ssize_t len;
    Py_ssize_t off;
} rd_t;

static int rd_need(rd_t *r, Py_ssize_t n) {
    if (r->off + n > r->len) {
        PyErr_Format(PyExc_ValueError,
                     "short buffer: need %zd bytes at %zd, have %zd",
                     n, r->off, r->len - r->off);
        return -1;
    }
    return 0;
}

static int rd_u32(rd_t *r, uint32_t *out) {
    if (rd_need(r, 4) < 0) return -1;
    const unsigned char *p = r->p + r->off;
    *out = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
         | ((uint32_t)p[2] << 8) | (uint32_t)p[3];
    r->off += 4;
    return 0;
}

static PyObject *dec(rd_t *r, int depth) {
    if (depth > FTLV_MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "nesting too deep");
        return NULL;
    }
    if (rd_need(r, 1) < 0) return NULL;
    unsigned char tag = r->p[r->off++];
    PyObject *out = NULL;
    if (Py_EnterRecursiveCall(" in ftlv decode")) return NULL;
    switch (tag) {
    case 'N': out = Py_None; Py_INCREF(out); break;
    case 'T': out = Py_True; Py_INCREF(out); break;
    case 'F': out = Py_False; Py_INCREF(out); break;
    case 'I': {
        if (rd_need(r, 8) < 0) break;
        const unsigned char *p = r->p + r->off;
        unsigned long long ux = 0;
        for (int i = 0; i < 8; i++) ux = (ux << 8) | p[i];
        r->off += 8;
        out = PyLong_FromLongLong((long long)ux);
        break;
    }
    case 'V': {
        uint32_t n;
        if (rd_u32(r, &n) < 0 || rd_need(r, n) < 0) break;
        /* canonical: minimal magnitude, >= 2^63 (encoder emits 'I'
         * below that) — matches serde.py strict decode */
        if (n < 8 || r->p[r->off] == 0
            || (n == 8 && r->p[r->off] < 0x80)) {
            PyErr_SetString(PyExc_ValueError, "non-canonical V int");
            break;
        }
        out = _PyLong_FromByteArray(r->p + r->off, n, /*little=*/0,
                                    /*signed=*/0);
        r->off += n;
        break;
    }
    case 'B': {
        uint32_t n;
        if (rd_u32(r, &n) < 0 || rd_need(r, n) < 0) break;
        out = PyBytes_FromStringAndSize((const char *)r->p + r->off, n);
        r->off += n;
        break;
    }
    case 'S': {
        uint32_t n;
        if (rd_u32(r, &n) < 0 || rd_need(r, n) < 0) break;
        out = PyUnicode_DecodeUTF8((const char *)r->p + r->off, n, NULL);
        r->off += n;
        break;
    }
    case 'L': {
        uint32_t n;
        if (rd_u32(r, &n) < 0) break;
        out = PyList_New(0);
        if (!out) break;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *item = dec(r, depth + 1);
            if (!item || PyList_Append(out, item) < 0) {
                Py_XDECREF(item);
                Py_CLEAR(out);
                break;
            }
            Py_DECREF(item);
        }
        break;
    }
    case 'D': {
        uint32_t n;
        if (rd_u32(r, &n) < 0) break;
        out = PyDict_New();
        if (!out) break;
        const unsigned char *prev_k = NULL;
        uint32_t prev_kn = 0;
        for (uint32_t i = 0; i < n; i++) {
            uint32_t kn;
            if (rd_u32(r, &kn) < 0 || rd_need(r, kn) < 0) {
                Py_CLEAR(out);
                break;
            }
            const unsigned char *kraw = r->p + r->off;
            /* canonical: strictly increasing keys, bytewise (UTF-8
             * order == code-point order) — also bans duplicates */
            if (prev_k) {
                uint32_t m = prev_kn < kn ? prev_kn : kn;
                int cmp = memcmp(prev_k, kraw, m);
                if (cmp > 0 || (cmp == 0 && prev_kn >= kn)) {
                    PyErr_SetString(PyExc_ValueError,
                                    "non-canonical dict key order");
                    Py_CLEAR(out);
                    break;
                }
            }
            prev_k = kraw;
            prev_kn = kn;
            PyObject *k = PyUnicode_DecodeUTF8(
                (const char *)r->p + r->off, kn, NULL);
            r->off += kn;
            PyObject *v = k ? dec(r, depth + 1) : NULL;
            if (!k || !v || PyDict_SetItem(out, k, v) < 0) {
                Py_XDECREF(k);
                Py_XDECREF(v);
                Py_CLEAR(out);
                break;
            }
            Py_DECREF(k);
            Py_DECREF(v);
        }
        break;
    }
    default:
        PyErr_Format(PyExc_ValueError, "bad tag %c at %zd",
                     tag, r->off - 1);
    }
    Py_LeaveRecursiveCall();
    return out;
}

static PyObject *py_decode(PyObject *self, PyObject *arg) {
    (void)self;
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_CONTIG_RO) < 0) return NULL;
    rd_t r = { (const unsigned char *)view.buf, view.len, 0 };
    PyObject *out = dec(&r, 0);
    if (out && r.off != r.len) {
        Py_DECREF(out);
        out = NULL;
        PyErr_SetString(PyExc_ValueError, "trailing bytes");
    }
    PyBuffer_Release(&view);
    return out;
}

/* ------------------------------------------------------------------ */

static PyMethodDef methods[] = {
    {"encode", py_encode, METH_O, "FTLV-encode a value to bytes."},
    {"decode", py_decode, METH_O, "Decode FTLV bytes to a value."},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_ftlv", "C FTLV codec", -1, methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__ftlv(void) {
    return PyModule_Create(&moduledef);
}
