/* Fast block collection — C implementation of txvalidator pass 1.
 *
 * The verify-then-gate validator (fabric_tpu/committer/txvalidator.py)
 * spends pass 1 walking every envelope of a block: decode, structural
 * checks, txid derivation, and collection of the signed byte spans the
 * device will verify.  The reference parallelizes the equivalent work
 * across goroutines (core/committer/txvalidator/v20/validator.go:194-209);
 * this host has ONE core, so the same win comes from doing the walk in C
 * over the canonical FTLV encoding (fabric_tpu/utils/serde.py) without
 * materializing any intermediate Python objects.
 *
 * Exported:  collect(envs: sequence[bytes], channel_id: str) -> list
 *            digest(envs, channel_id, carry, oracle) -> digested pass 1
 *            assemble(works, ...) -> per-tx gate plans + flat item table
 *            gate(plans, verdict, codes, ...) -> fold verdicts into flags
 *
 * collect() is the span-splicing walker shared by the legacy consumer
 * tail (txvalidator._collect_tx_fast, still used under SBE); digest/
 * assemble/gate are the fully-native tail: txid dedup against a C-side
 * seen-set (plus the pipelined carry window and the ledger oracle),
 * creator/endorser memo SLOT assignment, flat dispatch-ordered
 * VerifyItem interning, and a verdict-bitmap gate that never runs a
 * per-tx Python loop.  The no-compiler mirror for ALL of it is
 * committer/collect_py.py + the Python tail/gate in txvalidator.py —
 * the two paths must produce bit-identical TxFlags (state-fork
 * invariant, tested differentially in tests/test_committer.py).
 *
 * Per envelope the collect() result element is either
 *   int code — an early validation failure:
 *     1=NIL_ENVELOPE 2=BAD_PAYLOAD 3=TARGET_CHAIN_NOT_FOUND
 *     4=BAD_PROPOSAL_TXID 5=UNKNOWN_TX_TYPE 6=NIL_TXACTION
 * or the tuple
 *   (txtype, txid, creator, payload, payload_digest, signature, actions)
 *     txtype: 0 = config, 1 = endorser transaction
 *     txid:   str (hex, already checked == sha256(nonce||creator))
 *     payload_digest: sha256(payload) — the P-256 creator item payload
 *     actions: None for config txs, else a list of
 *       (chaincode_id, endorsed, endorsements, ns_writes, meta_writes)
 *         endorsed:     the exact bytes every endorsement signs
 *                       (serde {action, proposal_hash} re-spliced from
 *                        the original encoding by span copy)
 *         endorsements: [(endorser, sig, sha256(endorsed||endorser)), ...]
 *         ns_writes:    [(namespace, (written keys...)), ...]  (non-meta)
 *         meta_writes:  [(base_ns, key, value|None), ...]      ("#meta")
 *
 * SHA-256 uses the x86 SHA extensions when the CPU has them (this host
 * does) with a portable scalar fallback — hashing payload spans is the
 * bulk of the byte traffic here.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#include <cpuid.h>
#define HAVE_X86 1
#endif

/* ------------------------------------------------------------------ */
/* SHA-256                                                             */

typedef struct {
    uint32_t h[8];
    uint64_t nbytes;
    uint8_t buf[64];
    size_t buflen;
} sha256_t;

static const uint32_t K256[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2};

#define ROR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha256_block_scalar(uint32_t h[8], const uint8_t *p, size_t nblk)
{
    uint32_t w[64];
    while (nblk--) {
        for (int i = 0; i < 16; i++)
            w[i] = ((uint32_t)p[4*i] << 24) | ((uint32_t)p[4*i+1] << 16)
                 | ((uint32_t)p[4*i+2] << 8) | p[4*i+3];
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = ROR(w[i-15], 7) ^ ROR(w[i-15], 18) ^ (w[i-15] >> 3);
            uint32_t s1 = ROR(w[i-2], 17) ^ ROR(w[i-2], 19) ^ (w[i-2] >> 10);
            w[i] = w[i-16] + s0 + w[i-7] + s1;
        }
        uint32_t a=h[0],b=h[1],c=h[2],d=h[3],e=h[4],f=h[5],g=h[6],hh=h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = ROR(e,6) ^ ROR(e,11) ^ ROR(e,25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + S1 + ch + K256[i] + w[i];
            uint32_t S0 = ROR(a,2) ^ ROR(a,13) ^ ROR(a,22);
            uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = S0 + mj;
            hh=g; g=f; f=e; e=d+t1; d=c; c=b; b=a; a=t1+t2;
        }
        h[0]+=a; h[1]+=b; h[2]+=c; h[3]+=d;
        h[4]+=e; h[5]+=f; h[6]+=g; h[7]+=hh;
        p += 64;
    }
}

#ifdef HAVE_X86
__attribute__((target("sha,sse4.1")))
static void sha256_block_shani(uint32_t h[8], const uint8_t *p, size_t nblk)
{
    const __m128i MASK = _mm_set_epi64x(0x0c0d0e0f08090a0bULL,
                                        0x0405060700010203ULL);
    /* load state: h = {a,b,c,d,e,f,g,h} -> ABEF/CDGH lanes */
    __m128i tmp = _mm_loadu_si128((const __m128i *)&h[0]);   /* d c b a */
    __m128i st1 = _mm_loadu_si128((const __m128i *)&h[4]);   /* h g f e */
    tmp = _mm_shuffle_epi32(tmp, 0xB1);                      /* c d a b */
    st1 = _mm_shuffle_epi32(st1, 0x1B);                      /* e f g h */
    __m128i state0 = _mm_alignr_epi8(tmp, st1, 8);           /* abef */
    __m128i state1 = _mm_blend_epi16(st1, tmp, 0xF0);        /* cdgh */

    while (nblk--) {
        __m128i s0 = state0, s1 = state1, msg, m0, m1, m2, m3;
        m0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(p +  0)), MASK);
        m1 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(p + 16)), MASK);
        m2 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(p + 32)), MASK);
        m3 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(p + 48)), MASK);

#define RND4(mcur, mprev2, kidx)                                         \
        msg = _mm_add_epi32(mcur, _mm_loadu_si128(                       \
                  (const __m128i *)&K256[kidx]));                        \
        state1 = _mm_sha256rnds2_epu32(state1, state0, msg);             \
        msg = _mm_shuffle_epi32(msg, 0x0E);                              \
        state0 = _mm_sha256rnds2_epu32(state0, state1, msg);
#define SCHED(mnext, m_3, m_2, m_1)                                      \
        mnext = _mm_sha256msg1_epu32(mnext, m_3);                        \
        mnext = _mm_add_epi32(mnext, _mm_alignr_epi8(m_1, m_2, 4));      \
        mnext = _mm_sha256msg2_epu32(mnext, m_1);

        RND4(m0, m0, 0)
        RND4(m1, m1, 4)
        RND4(m2, m2, 8)
        RND4(m3, m3, 12)
        for (int r = 16; r < 64; r += 16) {
            SCHED(m0, m1, m2, m3) RND4(m0, m0, r)
            SCHED(m1, m2, m3, m0) RND4(m1, m1, r + 4)
            SCHED(m2, m3, m0, m1) RND4(m2, m2, r + 8)
            SCHED(m3, m0, m1, m2) RND4(m3, m3, r + 12)
        }
#undef RND4
#undef SCHED
        state0 = _mm_add_epi32(state0, s0);
        state1 = _mm_add_epi32(state1, s1);
        p += 64;
    }
    tmp = _mm_shuffle_epi32(state0, 0x1B);                   /* feba */
    st1 = _mm_shuffle_epi32(state1, 0xB1);                   /* dchg */
    state0 = _mm_blend_epi16(tmp, st1, 0xF0);                /* dcba */
    state1 = _mm_alignr_epi8(st1, tmp, 8);                   /* hgfe */
    _mm_storeu_si128((__m128i *)&h[0], state0);
    _mm_storeu_si128((__m128i *)&h[4], state1);
}
#endif

static void (*sha256_block)(uint32_t[8], const uint8_t *, size_t)
    = sha256_block_scalar;

static void sha256_init(sha256_t *s)
{
    static const uint32_t iv[8] = {
        0x6a09e667,0xbb67ae85,0x3c6ef372,0xa54ff53a,
        0x510e527f,0x9b05688c,0x1f83d9ab,0x5be0cd19};
    memcpy(s->h, iv, sizeof iv);
    s->nbytes = 0;
    s->buflen = 0;
}

static void sha256_update(sha256_t *s, const uint8_t *p, size_t n)
{
    s->nbytes += n;
    if (s->buflen) {
        size_t take = 64 - s->buflen;
        if (take > n) take = n;
        memcpy(s->buf + s->buflen, p, take);
        s->buflen += take;
        p += take;
        n -= take;
        if (s->buflen == 64) {
            sha256_block(s->h, s->buf, 1);
            s->buflen = 0;
        }
    }
    size_t nblk = n / 64;
    if (nblk) {
        sha256_block(s->h, p, nblk);
        p += nblk * 64;
        n -= nblk * 64;
    }
    if (n) {
        memcpy(s->buf, p, n);
        s->buflen = n;
    }
}

static void sha256_final(sha256_t *s, uint8_t out[32])
{
    uint64_t bits = s->nbytes * 8;
    uint8_t pad[72];
    size_t padlen = (s->buflen < 56) ? 56 - s->buflen : 120 - s->buflen;
    memset(pad, 0, sizeof pad);
    pad[0] = 0x80;
    for (int i = 0; i < 8; i++)
        pad[padlen + i] = (uint8_t)(bits >> (56 - 8 * i));
    sha256_update(s, pad, padlen + 8);
    for (int i = 0; i < 8; i++) {
        out[4*i]   = (uint8_t)(s->h[i] >> 24);
        out[4*i+1] = (uint8_t)(s->h[i] >> 16);
        out[4*i+2] = (uint8_t)(s->h[i] >> 8);
        out[4*i+3] = (uint8_t)(s->h[i]);
    }
}

static void sha256_oneshot(const uint8_t *p, size_t n, uint8_t out[32])
{
    sha256_t s;
    sha256_init(&s);
    sha256_update(&s, p, n);
    sha256_final(&s, out);
}

/* ------------------------------------------------------------------ */
/* FTLV walker (format: fabric_tpu/utils/serde.py)                     */

typedef struct {
    const uint8_t *p;
    const uint8_t *end;
} cur_t;

static int rd_u32(cur_t *c, uint32_t *out)
{
    if (c->end - c->p < 4) return -1;
    *out = ((uint32_t)c->p[0] << 24) | ((uint32_t)c->p[1] << 16)
         | ((uint32_t)c->p[2] << 8) | c->p[3];
    c->p += 4;
    return 0;
}

/* Nesting cap: legitimate framework messages are a few levels deep; an
 * attacker-crafted envelope of ~150k nested lists would otherwise blow
 * the C stack (the Python fallback raises RecursionError -> BAD_PAYLOAD;
 * the C walker must degrade identically, never segfault). */
#define MAX_DEPTH 64

/* skip one encoded value; returns 0 ok / -1 malformed-or-too-deep */
static int skip_value_d(cur_t *c, int depth)
{
    if (depth > MAX_DEPTH) return -1;
    if (c->p >= c->end) return -1;
    uint8_t tag = *c->p++;
    uint32_t n;
    switch (tag) {
    case 'N': case 'T': case 'F':
        return 0;
    case 'I':
        if (c->end - c->p < 8) return -1;
        c->p += 8;
        return 0;
    case 'V': case 'B': case 'S':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        c->p += n;
        return 0;
    case 'L':
        if (rd_u32(c, &n) < 0) return -1;
        while (n--)
            if (skip_value_d(c, depth + 1) < 0) return -1;
        return 0;
    case 'D':
        if (rd_u32(c, &n) < 0) return -1;
        while (n--) {
            uint32_t kn;
            if (rd_u32(c, &kn) < 0
                || (uint32_t)(c->end - c->p) < kn) return -1;
            c->p += kn;
            if (skip_value_d(c, depth + 1) < 0) return -1;
        }
        return 0;
    default:
        return -1;
    }
}

static int skip_value(cur_t *c)
{
    return skip_value_d(c, 0);
}

/* ------------------------------------------------------------------ */
/* Canonical-form validation.
 *
 * The walker below splices SIGNED byte spans straight out of the
 * original encoding (the endorsed bytes every endorsement signature
 * covers), while the no-compiler Python path re-encodes the decoded
 * value through serde.encode.  Splice == re-encode ONLY for canonical
 * input, so every envelope is rejected to BAD_PAYLOAD unless it is
 * exactly the canonical encoding serde.encode would produce: strictly
 * increasing (hence unique) dict keys, minimal 'V' ints >= 2^63, valid
 * UTF-8 strings, nesting <= MAX_DEPTH, no trailing bytes.  serde.py and
 * native/ftlv.c enforce the same rules on decode, keeping C-enabled
 * and pure-Python peers on identical validity bitmaps. */

/* strict UTF-8 (CPython decoder semantics: no overlongs, no
 * surrogates, max U+10FFFF) */
static int utf8_ok(const uint8_t *p, uint32_t n)
{
    uint32_t i = 0;
    while (i < n) {
        uint8_t b = p[i];
        if (b < 0x80) { i++; continue; }
        if (b < 0xC2) return 0;              /* continuation / overlong */
        if (b < 0xE0) {                      /* 2-byte */
            if (n - i < 2 || (p[i+1] & 0xC0) != 0x80) return 0;
            i += 2; continue;
        }
        if (b < 0xF0) {                      /* 3-byte */
            if (n - i < 3) return 0;
            uint8_t b1 = p[i+1], b2 = p[i+2];
            if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80) return 0;
            if (b == 0xE0 && b1 < 0xA0) return 0;        /* overlong */
            if (b == 0xED && b1 >= 0xA0) return 0;       /* surrogate */
            i += 3; continue;
        }
        if (b < 0xF5) {                      /* 4-byte */
            if (n - i < 4) return 0;
            uint8_t b1 = p[i+1], b2 = p[i+2], b3 = p[i+3];
            if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80
                || (b3 & 0xC0) != 0x80) return 0;
            if (b == 0xF0 && b1 < 0x90) return 0;        /* overlong */
            if (b == 0xF4 && b1 >= 0x90) return 0;       /* > U+10FFFF */
            i += 4; continue;
        }
        return 0;
    }
    return 1;
}

static int canon_value_d(cur_t *c, int depth)
{
    if (depth > MAX_DEPTH) return -1;
    if (c->p >= c->end) return -1;
    uint8_t tag = *c->p++;
    uint32_t n;
    switch (tag) {
    case 'N': case 'T': case 'F':
        return 0;
    case 'I':
        if (c->end - c->p < 8) return -1;
        c->p += 8;
        return 0;
    case 'V':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        /* minimal magnitude, >= 2^63 (encoder emits 'I' below that) */
        if (n < 8 || c->p[0] == 0 || (n == 8 && c->p[0] < 0x80))
            return -1;
        c->p += n;
        return 0;
    case 'B':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        c->p += n;
        return 0;
    case 'S':
        if (rd_u32(c, &n) < 0 || (uint32_t)(c->end - c->p) < n) return -1;
        if (!utf8_ok(c->p, n)) return -1;
        c->p += n;
        return 0;
    case 'L':
        if (rd_u32(c, &n) < 0) return -1;
        while (n--)
            if (canon_value_d(c, depth + 1) < 0) return -1;
        return 0;
    case 'D': {
        if (rd_u32(c, &n) < 0) return -1;
        const uint8_t *prev = NULL;
        uint32_t prev_n = 0;
        while (n--) {
            uint32_t kn;
            const uint8_t *k;
            if (rd_u32(c, &kn) < 0
                || (uint32_t)(c->end - c->p) < kn) return -1;
            k = c->p;
            c->p += kn;
            if (!utf8_ok(k, kn)) return -1;
            if (prev) {
                /* strictly increasing bytewise (UTF-8 order ==
                 * code-point order) — also bans duplicate keys */
                uint32_t m = prev_n < kn ? prev_n : kn;
                int cmp = memcmp(prev, k, m);
                if (cmp > 0 || (cmp == 0 && prev_n >= kn)) return -1;
            }
            prev = k;
            prev_n = kn;
            if (canon_value_d(c, depth + 1) < 0) return -1;
        }
        return 0;
    }
    default:
        return -1;
    }
}

/* exactly one canonical value filling the span */
static int canon_span(const uint8_t *p, size_t n)
{
    cur_t c = {p, p + n};
    if (canon_value_d(&c, 0) < 0) return -1;
    return c.p == c.end ? 0 : -1;
}

/* Enter a dict ('D'): returns entry count or -1. */
static int dict_enter(cur_t *c, uint32_t *count)
{
    if (c->p >= c->end || *c->p != 'D') return -1;
    c->p++;
    return rd_u32(c, count);
}

/* Read the next dict entry's key span; value left at cursor. */
static int dict_key(cur_t *c, const uint8_t **key, uint32_t *klen)
{
    if (rd_u32(c, klen) < 0 || (uint32_t)(c->end - c->p) < *klen) return -1;
    *key = c->p;
    c->p += *klen;
    return 0;
}

static int key_is(const uint8_t *key, uint32_t klen, const char *name)
{
    size_t n = strlen(name);
    return klen == n && memcmp(key, name, n) == 0;
}

/* read a 'B' (bytes) value span */
static int rd_bytes(cur_t *c, const uint8_t **p, uint32_t *n)
{
    if (c->p >= c->end || *c->p != 'B') return -1;
    c->p++;
    if (rd_u32(c, n) < 0 || (uint32_t)(c->end - c->p) < *n) return -1;
    *p = c->p;
    c->p += *n;
    return 0;
}

/* read an 'S' (str) value span */
static int rd_str(cur_t *c, const uint8_t **p, uint32_t *n)
{
    if (c->p >= c->end || *c->p != 'S') return -1;
    c->p++;
    if (rd_u32(c, n) < 0 || (uint32_t)(c->end - c->p) < *n) return -1;
    *p = c->p;
    c->p += *n;
    return 0;
}

/* read a bool; -1 on anything else */
static int rd_bool(cur_t *c, int *val)
{
    if (c->p >= c->end) return -1;
    if (*c->p == 'T') { *val = 1; c->p++; return 0; }
    if (*c->p == 'F') { *val = 0; c->p++; return 0; }
    return -1;
}

/* span of the next value (tag..end), cursor advanced past it */
static int value_span(cur_t *c, const uint8_t **p, size_t *n)
{
    const uint8_t *start = c->p;
    if (skip_value(c) < 0) return -1;
    *p = start;
    *n = (size_t)(c->p - start);
    return 0;
}

/* ------------------------------------------------------------------ */
/* collection                                                          */

#define E_NIL_ENVELOPE 1
#define E_BAD_PAYLOAD 2
#define E_TARGET_CHAIN 3
#define E_BAD_TXID 4
#define E_UNKNOWN_TYPE 5
#define E_NIL_TXACTION 6

static const char HEXD[] = "0123456789abcdef";

/* Parse one ns rwset dict: append written keys / meta writes to the
 * provided lists.  Returns 0 ok / -1 malformed. */
static int do_ns_rwset(cur_t *c, PyObject *ns_writes, PyObject *meta_writes)
{
    uint32_t nent;
    if (dict_enter(c, &nent) < 0) return -1;
    const uint8_t *ns_p = NULL;
    uint32_t ns_n = 0;
    const uint8_t *writes_p = NULL;
    const uint8_t *writes_end = NULL;
    while (nent--) {
        const uint8_t *key; uint32_t klen;
        if (dict_key(c, &key, &klen) < 0) return -1;
        if (key_is(key, klen, "namespace")) {
            if (rd_str(c, &ns_p, &ns_n) < 0) return -1;
        } else if (key_is(key, klen, "writes")) {
            writes_p = c->p;
            if (skip_value(c) < 0) return -1;
            writes_end = c->p;
        } else {
            if (skip_value(c) < 0) return -1;
        }
    }
    if (!ns_p) return -1;
    if (!writes_p) return 0;
    cur_t w = {writes_p, writes_end};
    if (w.p >= w.end || *w.p != 'L') return -1;
    w.p++;
    uint32_t nw;
    if (rd_u32(&w, &nw) < 0) return 0;
    if (nw == 0) return 0;

    /* ">= 5": a namespace that IS exactly "#meta" is meta with base ""
     * (Python endswith + base_namespace slicing semantics, sbe.py) */
    int is_meta = ns_n >= 5 && memcmp(ns_p + ns_n - 5, "#meta", 5) == 0;
    PyObject *ns_str = NULL, *keys_list = NULL;
    if (is_meta)
        ns_str = PyUnicode_DecodeUTF8((const char *)ns_p, ns_n - 5, NULL);
    else {
        ns_str = PyUnicode_DecodeUTF8((const char *)ns_p, ns_n, NULL);
        keys_list = PyList_New(0);
    }
    if (!ns_str || (!is_meta && !keys_list)) {
        Py_XDECREF(ns_str);
        Py_XDECREF(keys_list);
        return -1;
    }
    int rc = 0;
    while (nw-- && rc == 0) {
        uint32_t nent2;
        if (dict_enter(&w, &nent2) < 0) { rc = -1; break; }
        const uint8_t *k_p = NULL, *v_p = NULL;
        uint32_t k_n = 0, v_n = 0;
        int is_delete = 0;
        while (nent2--) {
            const uint8_t *key; uint32_t klen;
            if (dict_key(&w, &key, &klen) < 0) { rc = -1; break; }
            if (key_is(key, klen, "key")) {
                if (rd_str(&w, &k_p, &k_n) < 0) { rc = -1; break; }
            } else if (key_is(key, klen, "is_delete")) {
                if (rd_bool(&w, &is_delete) < 0) { rc = -1; break; }
            } else if (is_meta && key_is(key, klen, "value")) {
                if (rd_bytes(&w, &v_p, &v_n) < 0) { rc = -1; break; }
            } else {
                if (skip_value(&w) < 0) { rc = -1; break; }
            }
        }
        if (rc < 0 || !k_p) { rc = -1; break; }
        PyObject *kstr = PyUnicode_DecodeUTF8((const char *)k_p, k_n, NULL);
        if (!kstr) { rc = -1; break; }
        if (is_meta) {
            PyObject *val;
            if (is_delete) {
                val = Py_None;
                Py_INCREF(val);
            } else {
                val = PyBytes_FromStringAndSize((const char *)v_p, v_n);
                if (!val) { Py_DECREF(kstr); rc = -1; break; }
            }
            PyObject *tup = PyTuple_New(3);
            if (!tup) {
                Py_DECREF(kstr); Py_DECREF(val); rc = -1; break;
            }
            Py_INCREF(ns_str);
            PyTuple_SET_ITEM(tup, 0, ns_str);
            PyTuple_SET_ITEM(tup, 1, kstr);
            PyTuple_SET_ITEM(tup, 2, val);
            rc = PyList_Append(meta_writes, tup);
            Py_DECREF(tup);
        } else {
            rc = PyList_Append(keys_list, kstr);
            Py_DECREF(kstr);
        }
    }
    if (rc == 0 && !is_meta) {
        PyObject *keys_tup = PyList_AsTuple(keys_list);
        if (!keys_tup)
            rc = -1;
        else {
            PyObject *pair = PyTuple_New(2);
            if (!pair) {
                Py_DECREF(keys_tup);
                rc = -1;
            } else {
                Py_INCREF(ns_str);
                PyTuple_SET_ITEM(pair, 0, ns_str);
                PyTuple_SET_ITEM(pair, 1, keys_tup);
                rc = PyList_Append(ns_writes, pair);
                Py_DECREF(pair);
            }
        }
    }
    Py_DECREF(ns_str);
    Py_XDECREF(keys_list);
    return rc;
}

/* Parse one TransactionAction dict; returns the action result tuple or
 * NULL with no exception for malformed (caller flags BAD_PAYLOAD), or
 * NULL with exception set for allocation failures. */
static PyObject *do_action(cur_t *c, int *malformed)
{
    uint32_t nent;
    *malformed = 0;
    if (dict_enter(c, &nent) < 0) { *malformed = 1; return NULL; }
    const uint8_t *act_span = NULL, *ph_span = NULL;
    size_t act_n = 0, ph_n = 0;
    const uint8_t *ends_p = NULL, *ends_end = NULL;
    PyObject *cc_id = NULL, *ns_writes = NULL, *meta_writes = NULL;
    PyObject *result = NULL;

    while (nent--) {
        const uint8_t *key; uint32_t klen;
        if (dict_key(c, &key, &klen) < 0) goto malformed;
        if (key_is(key, klen, "action")) {
            /* remember the span AND walk inside for chaincode_id/rwset */
            cur_t inner;
            if (value_span(c, &act_span, &act_n) < 0) goto malformed;
            inner.p = act_span;
            inner.end = act_span + act_n;
            uint32_t na;
            if (dict_enter(&inner, &na) < 0) goto malformed;
            while (na--) {
                const uint8_t *k2; uint32_t k2len;
                if (dict_key(&inner, &k2, &k2len) < 0) goto malformed;
                if (key_is(k2, k2len, "chaincode_id")) {
                    const uint8_t *sp; uint32_t sn;
                    if (rd_str(&inner, &sp, &sn) < 0) goto malformed;
                    Py_XDECREF(cc_id);
                    cc_id = PyUnicode_DecodeUTF8((const char *)sp, sn, NULL);
                    if (!cc_id) goto malformed;
                } else if (key_is(k2, k2len, "rwset")) {
                    uint32_t nr;
                    if (dict_enter(&inner, &nr) < 0) goto malformed;
                    while (nr--) {
                        const uint8_t *k3; uint32_t k3len;
                        if (dict_key(&inner, &k3, &k3len) < 0) goto malformed;
                        if (key_is(k3, k3len, "ns")) {
                            if (inner.p >= inner.end || *inner.p != 'L')
                                goto malformed;
                            inner.p++;
                            uint32_t nns;
                            if (rd_u32(&inner, &nns) < 0) goto malformed;
                            if (!ns_writes) ns_writes = PyList_New(0);
                            if (!meta_writes) meta_writes = PyList_New(0);
                            if (!ns_writes || !meta_writes) goto fail;
                            while (nns--)
                                if (do_ns_rwset(&inner, ns_writes,
                                                meta_writes) < 0) {
                                    if (PyErr_Occurred()) goto fail;
                                    goto malformed;
                                }
                        } else {
                            if (skip_value(&inner) < 0) goto malformed;
                        }
                    }
                } else {
                    if (skip_value(&inner) < 0) goto malformed;
                }
            }
        } else if (key_is(key, klen, "proposal_hash")) {
            if (value_span(c, &ph_span, &ph_n) < 0) goto malformed;
        } else if (key_is(key, klen, "endorsements")) {
            ends_p = c->p;
            if (skip_value(c) < 0) goto malformed;
            ends_end = c->p;
        } else {
            if (skip_value(c) < 0) goto malformed;
        }
    }
    if (!act_span || !ph_span || !cc_id) goto malformed;
    if (!ns_writes) ns_writes = PyList_New(0);
    if (!meta_writes) meta_writes = PyList_New(0);
    if (!ns_writes || !meta_writes) goto fail;

    /* endorsed bytes: serde({"action": ..., "proposal_hash": ...})
     * respliced from the original spans (canonical: sorted keys) */
    {
        size_t total = 1 + 4 + (4 + 6) + act_n + (4 + 13) + ph_n;
        PyObject *endorsed = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)total);
        if (!endorsed) goto fail;
        uint8_t *o = (uint8_t *)PyBytes_AS_STRING(endorsed);
        *o++ = 'D';
        *o++ = 0; *o++ = 0; *o++ = 0; *o++ = 2;
        *o++ = 0; *o++ = 0; *o++ = 0; *o++ = 6;
        memcpy(o, "action", 6); o += 6;
        memcpy(o, act_span, act_n); o += act_n;
        *o++ = 0; *o++ = 0; *o++ = 0; *o++ = 13;
        memcpy(o, "proposal_hash", 13); o += 13;
        memcpy(o, ph_span, ph_n); o += ph_n;

        /* midstate over the endorsed bytes, finalized per endorser */
        sha256_t mid;
        sha256_init(&mid);
        sha256_update(&mid, (const uint8_t *)PyBytes_AS_STRING(endorsed),
                      total);

        PyObject *ends_list = PyList_New(0);
        if (!ends_list) { Py_DECREF(endorsed); goto fail; }
        if (ends_p) {
            cur_t e = {ends_p, ends_end};
            uint32_t ne;
            if (e.p >= e.end || *e.p != 'L') {
                Py_DECREF(endorsed); Py_DECREF(ends_list); goto malformed;
            }
            e.p++;
            if (rd_u32(&e, &ne) < 0) {
                Py_DECREF(endorsed); Py_DECREF(ends_list); goto malformed;
            }
            while (ne--) {
                uint32_t nent2;
                const uint8_t *edr_p = NULL, *sig_p = NULL;
                uint32_t edr_n = 0, sig_n = 0;
                if (dict_enter(&e, &nent2) < 0) {
                    Py_DECREF(endorsed); Py_DECREF(ends_list); goto malformed;
                }
                int bad = 0;
                while (nent2--) {
                    const uint8_t *k2; uint32_t k2len;
                    if (dict_key(&e, &k2, &k2len) < 0) { bad = 1; break; }
                    if (key_is(k2, k2len, "endorser")) {
                        if (rd_bytes(&e, &edr_p, &edr_n) < 0) { bad=1; break; }
                    } else if (key_is(k2, k2len, "signature")) {
                        if (rd_bytes(&e, &sig_p, &sig_n) < 0) { bad=1; break; }
                    } else {
                        if (skip_value(&e) < 0) { bad = 1; break; }
                    }
                }
                if (bad || !edr_p || !sig_p) {
                    Py_DECREF(endorsed); Py_DECREF(ends_list); goto malformed;
                }
                sha256_t fin = mid;
                uint8_t digest[32];
                sha256_update(&fin, edr_p, edr_n);
                sha256_final(&fin, digest);
                PyObject *tup = Py_BuildValue(
                    "(y#y#y#)", (const char *)edr_p, (Py_ssize_t)edr_n,
                    (const char *)sig_p, (Py_ssize_t)sig_n,
                    (const char *)digest, (Py_ssize_t)32);
                if (!tup || PyList_Append(ends_list, tup) < 0) {
                    Py_XDECREF(tup); Py_DECREF(endorsed);
                    Py_DECREF(ends_list); goto fail;
                }
                Py_DECREF(tup);
            }
        }
        result = PyTuple_New(5);
        if (!result) {
            Py_DECREF(endorsed); Py_DECREF(ends_list); goto fail;
        }
        Py_INCREF(cc_id);
        PyTuple_SET_ITEM(result, 0, cc_id);
        PyTuple_SET_ITEM(result, 1, endorsed);
        PyTuple_SET_ITEM(result, 2, ends_list);
        PyTuple_SET_ITEM(result, 3, ns_writes);
        PyTuple_SET_ITEM(result, 4, meta_writes);
        ns_writes = meta_writes = NULL;   /* ownership moved */
    }
    Py_DECREF(cc_id);
    return result;

malformed:
    *malformed = 1;
fail:
    Py_XDECREF(cc_id);
    Py_XDECREF(ns_writes);
    Py_XDECREF(meta_writes);
    return NULL;
}

/* collect one envelope -> int code or result tuple */
static PyObject *collect_env(const uint8_t *env, size_t env_n,
                             const uint8_t *chan, size_t chan_n)
{
    if (env_n == 0)
        return PyLong_FromLong(E_NIL_ENVELOPE);
    /* strict canonical gate over the whole envelope (payload is a 'B'
     * blob at this level; its interior is checked after extraction) —
     * the Python path's strict serde.decode does the same */
    if (canon_span(env, env_n) < 0)
        return PyLong_FromLong(E_BAD_PAYLOAD);
    cur_t c = {env, env + env_n};
    uint32_t nent;
    const uint8_t *payload_p = NULL, *sig_p = NULL;
    uint32_t payload_n = 0, sig_n = 0;
    if (dict_enter(&c, &nent) < 0)
        return PyLong_FromLong(E_BAD_PAYLOAD);
    while (nent--) {
        const uint8_t *key; uint32_t klen;
        if (dict_key(&c, &key, &klen) < 0)
            return PyLong_FromLong(E_BAD_PAYLOAD);
        if (key_is(key, klen, "payload")) {
            if (rd_bytes(&c, &payload_p, &payload_n) < 0)
                return PyLong_FromLong(E_BAD_PAYLOAD);
        } else if (key_is(key, klen, "signature")) {
            if (rd_bytes(&c, &sig_p, &sig_n) < 0)
                return PyLong_FromLong(E_BAD_PAYLOAD);
        } else {
            if (skip_value(&c) < 0)
                return PyLong_FromLong(E_BAD_PAYLOAD);
        }
    }
    if (!payload_p || !sig_p || c.p != c.end)
        return PyLong_FromLong(E_BAD_PAYLOAD);

    /* strict canonical gate over the payload BEFORE any use of it —
     * matches the Python path, which serde.decode()s the payload (and
     * would raise) before the channel/txid checks */
    if (canon_span(payload_p, payload_n) < 0)
        return PyLong_FromLong(E_BAD_PAYLOAD);

    /* payload: {"data": ..., "header": {...}} */
    cur_t pc = {payload_p, payload_p + payload_n};
    const uint8_t *data_p = NULL, *data_end = NULL;
    const uint8_t *type_p = NULL, *chanid_p = NULL, *txid_p = NULL;
    uint32_t type_n = 0, chanid_n = 0, txid_n = 0;
    const uint8_t *creator_p = NULL, *nonce_p = NULL;
    uint32_t creator_n = 0, nonce_n = 0;
    if (dict_enter(&pc, &nent) < 0)
        return PyLong_FromLong(E_BAD_PAYLOAD);
    while (nent--) {
        const uint8_t *key; uint32_t klen;
        if (dict_key(&pc, &key, &klen) < 0)
            return PyLong_FromLong(E_BAD_PAYLOAD);
        if (key_is(key, klen, "data")) {
            data_p = pc.p;
            if (skip_value(&pc) < 0)
                return PyLong_FromLong(E_BAD_PAYLOAD);
            data_end = pc.p;
        } else if (key_is(key, klen, "header")) {
            uint32_t nh;
            if (dict_enter(&pc, &nh) < 0)
                return PyLong_FromLong(E_BAD_PAYLOAD);
            while (nh--) {
                const uint8_t *k2; uint32_t k2len;
                if (dict_key(&pc, &k2, &k2len) < 0)
                    return PyLong_FromLong(E_BAD_PAYLOAD);
                if (key_is(k2, k2len, "channel_header")) {
                    uint32_t nc;
                    if (dict_enter(&pc, &nc) < 0)
                        return PyLong_FromLong(E_BAD_PAYLOAD);
                    while (nc--) {
                        const uint8_t *k3; uint32_t k3len;
                        if (dict_key(&pc, &k3, &k3len) < 0)
                            return PyLong_FromLong(E_BAD_PAYLOAD);
                        int rc2 = 0;
                        if (key_is(k3, k3len, "type"))
                            rc2 = rd_str(&pc, &type_p, &type_n);
                        else if (key_is(k3, k3len, "channel_id"))
                            rc2 = rd_str(&pc, &chanid_p, &chanid_n);
                        else if (key_is(k3, k3len, "txid"))
                            rc2 = rd_str(&pc, &txid_p, &txid_n);
                        else
                            rc2 = skip_value(&pc);
                        if (rc2 < 0)
                            return PyLong_FromLong(E_BAD_PAYLOAD);
                    }
                } else if (key_is(k2, k2len, "signature_header")) {
                    uint32_t ns;
                    if (dict_enter(&pc, &ns) < 0)
                        return PyLong_FromLong(E_BAD_PAYLOAD);
                    while (ns--) {
                        const uint8_t *k3; uint32_t k3len;
                        if (dict_key(&pc, &k3, &k3len) < 0)
                            return PyLong_FromLong(E_BAD_PAYLOAD);
                        int rc2 = 0;
                        if (key_is(k3, k3len, "creator"))
                            rc2 = rd_bytes(&pc, &creator_p, &creator_n);
                        else if (key_is(k3, k3len, "nonce"))
                            rc2 = rd_bytes(&pc, &nonce_p, &nonce_n);
                        else
                            rc2 = skip_value(&pc);
                        if (rc2 < 0)
                            return PyLong_FromLong(E_BAD_PAYLOAD);
                    }
                } else {
                    if (skip_value(&pc) < 0)
                        return PyLong_FromLong(E_BAD_PAYLOAD);
                }
            }
        } else {
            if (skip_value(&pc) < 0)
                return PyLong_FromLong(E_BAD_PAYLOAD);
        }
    }
    if (!type_p || !chanid_p || !txid_p || !creator_p || !nonce_p)
        return PyLong_FromLong(E_BAD_PAYLOAD);

    if (chanid_n != chan_n || memcmp(chanid_p, chan, chan_n) != 0)
        return PyLong_FromLong(E_TARGET_CHAIN);

    /* txid == hex(sha256(nonce || creator))  (protoutil.ComputeTxID) */
    {
        sha256_t s;
        uint8_t digest[32];
        char hex[64];
        sha256_init(&s);
        sha256_update(&s, nonce_p, nonce_n);
        sha256_update(&s, creator_p, creator_n);
        sha256_final(&s, digest);
        for (int i = 0; i < 32; i++) {
            hex[2*i] = HEXD[digest[i] >> 4];
            hex[2*i+1] = HEXD[digest[i] & 15];
        }
        if (txid_n != 64 || memcmp(txid_p, hex, 64) != 0)
            return PyLong_FromLong(E_BAD_TXID);
    }

    /* Failures from here on happen AFTER the txid is known-good: the
     * Python reference path registers the txid in seen_txids BEFORE
     * type/body validation, so later duplicates of such a tx must
     * still flag DUPLICATE_TXID.  These return (code, txid) pairs so
     * the Python tail can register the txid first — bare-int codes
     * are strictly pre-registration failures. */
#define LATE_ERR(code)  Py_BuildValue("(is#)", (code), \
        (const char *)txid_p, (Py_ssize_t)txid_n)

    int is_config = key_is(type_p, type_n, "config");
    if (!is_config && !key_is(type_p, type_n, "endorser_transaction"))
        return LATE_ERR(E_UNKNOWN_TYPE);

    PyObject *actions = NULL;
    if (!is_config) {
        /* data: {"actions": [TransactionAction...]} */
        if (!data_p)
            return LATE_ERR(E_BAD_PAYLOAD);
        cur_t dc = {data_p, data_end};
        uint32_t nd;
        const uint8_t *acts_p = NULL, *acts_end = NULL;
        if (dict_enter(&dc, &nd) < 0)
            return LATE_ERR(E_BAD_PAYLOAD);
        while (nd--) {
            const uint8_t *key; uint32_t klen;
            if (dict_key(&dc, &key, &klen) < 0)
                return LATE_ERR(E_BAD_PAYLOAD);
            if (key_is(key, klen, "actions")) {
                acts_p = dc.p;
                if (skip_value(&dc) < 0)
                    return LATE_ERR(E_BAD_PAYLOAD);
                acts_end = dc.p;
            } else {
                if (skip_value(&dc) < 0)
                    return LATE_ERR(E_BAD_PAYLOAD);
            }
        }
        if (!acts_p)
            return LATE_ERR(E_BAD_PAYLOAD);
        cur_t ac = {acts_p, acts_end};
        uint32_t na;
        if (ac.p >= ac.end || *ac.p != 'L')
            return LATE_ERR(E_BAD_PAYLOAD);
        ac.p++;
        if (rd_u32(&ac, &na) < 0)
            return LATE_ERR(E_BAD_PAYLOAD);
        if (na == 0)
            return LATE_ERR(E_NIL_TXACTION);
        actions = PyList_New(0);
        if (!actions)
            return NULL;
        while (na--) {
            int malformed = 0;
            PyObject *act = do_action(&ac, &malformed);
            if (!act) {
                Py_DECREF(actions);
                if (malformed && !PyErr_Occurred())
                    return LATE_ERR(E_BAD_PAYLOAD);
                return NULL;
            }
            if (PyList_Append(actions, act) < 0) {
                Py_DECREF(act);
                Py_DECREF(actions);
                return NULL;
            }
            Py_DECREF(act);
        }
    } else {
        actions = Py_None;
        Py_INCREF(actions);
    }

    uint8_t pd[32];
    sha256_oneshot(payload_p, payload_n, pd);

    PyObject *result = Py_BuildValue(
        "(is#y#y#y#y#N)",
        is_config ? 0 : 1,
        (const char *)txid_p, (Py_ssize_t)txid_n,
        (const char *)creator_p, (Py_ssize_t)creator_n,
        (const char *)payload_p, (Py_ssize_t)payload_n,
        (const char *)pd, (Py_ssize_t)32,
        (const char *)sig_p, (Py_ssize_t)sig_n,
        actions);
    if (!result)
        Py_DECREF(actions);
    return result;
}

static PyObject *py_collect(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *envs;
    const char *chan;
    Py_ssize_t chan_n;
    if (!PyArg_ParseTuple(args, "Os#", &envs, &chan, &chan_n))
        return NULL;
    PyObject *seq = PySequence_Fast(envs, "collect() needs a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (!out) {
        Py_DECREF(seq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        /* Yield the GIL periodically: this walk runs for hundreds of
         * ms on a 10k-tx block, and device transports serviced by a
         * Python-side pump thread (the axon relay) would otherwise
         * starve — measured as the TPU sitting idle through pass-1
         * instead of overlapping it. */
        if ((i & 63) == 63) {
            Py_BEGIN_ALLOW_THREADS
            Py_END_ALLOW_THREADS
        }
        PyObject *env = PySequence_Fast_GET_ITEM(seq, i);
        PyObject *r;
        if (env == Py_None) {
            r = PyLong_FromLong(E_NIL_ENVELOPE);
        } else if (PyBytes_Check(env)) {
            char *cp;
            Py_ssize_t en;
            if (PyBytes_AsStringAndSize(env, &cp, &en) < 0) {
                Py_DECREF(seq);
                Py_DECREF(out);
                return NULL;
            }
            r = collect_env((const uint8_t *)cp, (size_t)en,
                            (const uint8_t *)chan, (size_t)chan_n);
        } else {
            /* any contiguous buffer (memoryview span from the zero-copy
             * ingest path) — same walk, no intermediate bytes copy */
            Py_buffer vb;
            if (PyObject_GetBuffer(env, &vb, PyBUF_CONTIG_RO) < 0) {
                Py_DECREF(seq);
                Py_DECREF(out);
                return NULL;
            }
            r = collect_env((const uint8_t *)vb.buf, (size_t)vb.len,
                            (const uint8_t *)chan, (size_t)chan_n);
            PyBuffer_Release(&vb);
        }
        if (!r) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, i, r);
    }
    Py_DECREF(seq);
    return out;
}

/* ------------------------------------------------------------------ */
/* Digested pass-1 tail + verdict gate (the deep native path).
 *
 * digest()   walks every envelope (same collect_env walker as collect())
 *            but CONSUMES the per-tx tuples in C: txid dedup against a
 *            C-side seen dict (plus the pipelined carry window and the
 *            ledger oracle), the config-multi check, and first-seen-order
 *            SLOT assignment for unique creator/endorser identity bytes.
 *            Python only resolves each unique identity once (MSP
 *            deserialize + chain validation) instead of running a ~10k
 *            iteration bytecode loop per block.
 * assemble() turns digested works + resolved identity slots into the
 *            flat dispatch-ordered VerifyItem table (interning with a
 *            cheap plain-tuple probe — tuples hash/compare equal to the
 *            VerifyItem NamedTuple, so only FIRST occurrences pay the
 *            namedtuple construction) and per-tx gate plans.
 * gate()     folds the device verdict bitmap into final ValidationCodes
 *            with the same memoized policy-evaluation semantics as
 *            txvalidator._gate_tx/_memoized_plugin, no per-tx Python.
 *
 * The Python tail (_collect_tx_fast/_gate_tx) stays as the line-for-line
 * mirror and the SBE path; both must produce bit-identical TxFlags
 * (state-fork invariant).  ValidationCode values are mirrored from
 * protocol/txflags.py below — guarded by the differential tests.
 */

#define VC_VALID            0
#define VC_BAD_CREATOR      4
#define VC_INVALID_CONFIG   6
#define VC_DUPLICATE        9
#define VC_POLICY_FAIL     10
#define VC_INVALID_CC      25
#define VC_NOT_VALIDATED  254

/* fastcollect E_* structural code -> ValidationCode (txvalidator._FC_CODES):
 * NIL_ENVELOPE=1 BAD_PAYLOAD=2 TARGET_CHAIN_NOT_FOUND=14
 * BAD_PROPOSAL_TXID=8 UNKNOWN_TX_TYPE=13 NIL_TXACTION=16 */
static const uint8_t FC2VC[7] = {0, 1, 2, 14, 8, 13, 16};

static PyObject *s_verify_item;     /* interned "verify_item" */

/* 1 = duplicate, 0 = fresh, -1 = error.  Order matches the Python tail:
 * own block's seen dict, then the in-flight carry maps, then the ledger
 * oracle (None when the validator is unwired — skips the call). */
static int txid_is_dup(PyObject *txid, PyObject *seen, PyObject *carry,
                       Py_ssize_t ncarry, PyObject *oracle)
{
    int r = PyDict_Contains(seen, txid);
    if (r != 0)
        return r;
    for (Py_ssize_t i = 0; i < ncarry; i++) {
        r = PyDict_Contains(PyList_GET_ITEM(carry, i), txid);
        if (r != 0)
            return r;
    }
    if (oracle != Py_None) {
        PyObject *res = PyObject_CallFunctionObjArgs(oracle, txid, NULL);
        if (!res)
            return -1;
        r = PyObject_IsTrue(res);
        Py_DECREF(res);
        return r;
    }
    return 0;
}

/* first-seen-order slot assignment: map[key] -> slot, appending key to
 * list on first sight.  Returns slot, or -1 with an exception set. */
static Py_ssize_t slot_of(PyObject *map, PyObject *list, PyObject *key)
{
    PyObject *v = PyDict_GetItemWithError(map, key);
    if (v)
        return PyLong_AsSsize_t(v);
    if (PyErr_Occurred())
        return -1;
    Py_ssize_t slot = PyList_GET_SIZE(list);
    PyObject *iv = PyLong_FromSsize_t(slot);
    if (!iv)
        return -1;
    int rc = PyDict_SetItem(map, key, iv);
    Py_DECREF(iv);
    if (rc < 0 || PyList_Append(list, key) < 0)
        return -1;
    return slot;
}

/* walker actions [(cc_id, endorsed, ends, ns_writes, meta), ...] ->
 * digested [(cc_id, endorsed, [(eslot, esig, edigest)...], ns_names)].
 * Endorsements dedup by endorser bytes per action (policy.go:385-387,
 * first kept) BEFORE slot assignment — exactly the Python tail's
 * seen_idents order.  ns_names = sorted({cc_id} | write ns | meta base)
 * (the non-SBE namespace set; the deep path is only taken without SBE). */
static PyObject *digest_actions(PyObject *acts, PyObject *emap,
                                PyObject *endorsers)
{
    Py_ssize_t na = PyList_GET_SIZE(acts);
    PyObject *out = PyList_New(na);
    if (!out)
        return NULL;
    for (Py_ssize_t a = 0; a < na; a++) {
        PyObject *act = PyList_GET_ITEM(acts, a);
        PyObject *cc = PyTuple_GET_ITEM(act, 0);
        PyObject *endorsed = PyTuple_GET_ITEM(act, 1);
        PyObject *ends = PyTuple_GET_ITEM(act, 2);
        PyObject *ns_writes = PyTuple_GET_ITEM(act, 3);
        PyObject *meta = PyTuple_GET_ITEM(act, 4);
        PyObject *ns_set = NULL, *ns_names = NULL, *eseen = NULL,
                 *ends2 = NULL, *act2 = NULL;
        ns_set = PyDict_New();
        if (!ns_set)
            goto fail;
        if (PyDict_SetItem(ns_set, cc, Py_None) < 0)
            goto fail;
        for (Py_ssize_t w = 0; w < PyList_GET_SIZE(ns_writes); w++)
            if (PyDict_SetItem(ns_set,
                    PyTuple_GET_ITEM(PyList_GET_ITEM(ns_writes, w), 0),
                    Py_None) < 0)
                goto fail;
        for (Py_ssize_t m = 0; m < PyList_GET_SIZE(meta); m++)
            if (PyDict_SetItem(ns_set,
                    PyTuple_GET_ITEM(PyList_GET_ITEM(meta, m), 0),
                    Py_None) < 0)
                goto fail;
        ns_names = PyDict_Keys(ns_set);
        Py_CLEAR(ns_set);
        if (!ns_names || PyList_Sort(ns_names) < 0)
            goto fail;
        eseen = PyDict_New();
        ends2 = PyList_New(0);
        if (!eseen || !ends2)
            goto fail;
        for (Py_ssize_t e = 0; e < PyList_GET_SIZE(ends); e++) {
            PyObject *end3 = PyList_GET_ITEM(ends, e);
            PyObject *edr = PyTuple_GET_ITEM(end3, 0);
            int dup = PyDict_Contains(eseen, edr);
            if (dup < 0)
                goto fail;
            if (dup)
                continue;
            if (PyDict_SetItem(eseen, edr, Py_None) < 0)
                goto fail;
            Py_ssize_t slot = slot_of(emap, endorsers, edr);
            if (slot < 0)
                goto fail;
            PyObject *slo = PyLong_FromSsize_t(slot);
            if (!slo)
                goto fail;
            PyObject *t = PyTuple_New(3);
            if (!t) { Py_DECREF(slo); goto fail; }
            PyTuple_SET_ITEM(t, 0, slo);
            Py_INCREF(PyTuple_GET_ITEM(end3, 1));
            PyTuple_SET_ITEM(t, 1, PyTuple_GET_ITEM(end3, 1));
            Py_INCREF(PyTuple_GET_ITEM(end3, 2));
            PyTuple_SET_ITEM(t, 2, PyTuple_GET_ITEM(end3, 2));
            int rc = PyList_Append(ends2, t);
            Py_DECREF(t);
            if (rc < 0)
                goto fail;
        }
        Py_CLEAR(eseen);
        act2 = PyTuple_New(4);
        if (!act2)
            goto fail;
        Py_INCREF(cc);
        PyTuple_SET_ITEM(act2, 0, cc);
        Py_INCREF(endorsed);
        PyTuple_SET_ITEM(act2, 1, endorsed);
        PyTuple_SET_ITEM(act2, 2, ends2);
        PyTuple_SET_ITEM(act2, 3, ns_names);
        ends2 = ns_names = NULL;            /* ownership moved */
        PyList_SET_ITEM(out, a, act2);
        continue;
    fail:
        Py_XDECREF(ns_set);
        Py_XDECREF(ns_names);
        Py_XDECREF(eseen);
        Py_XDECREF(ends2);
        Py_DECREF(out);
        return NULL;
    }
    return out;
}

/* digest(envs, channel_id, carry, oracle)
 *   -> (codes: bytearray, seen: {txid: tx_num}, works, creators, endorsers)
 *
 * codes[i] is the FINAL ValidationCode for structurally-dead txs and
 * VC_NOT_VALIDATED (254) for live works.  works[j] =
 * (tx_num, txtype, creator_slot, payload, pdigest, signature, acts|None);
 * creators/endorsers are first-seen-ordered unique identity bytes whose
 * MSP resolution the Python caller performs once per slot.
 *
 * Two envelope sources share one implementation: a Python sequence of
 * bytes objects (digest(), the classic entry), or a zero-copy span
 * table over one base buffer (digest_spans(), fed straight from
 * native/fastparse.c block parses — no per-tx bytes objects exist). */
static PyObject *digest_impl(PyObject *seq,
                             const uint8_t *base, size_t base_n,
                             const uint8_t *spans, Py_ssize_t nspans,
                             const char *chan, Py_ssize_t chan_n,
                             PyObject *carry_in, PyObject *oracle)
{
    PyObject *carry = NULL, *codes = NULL, *seen = NULL,
             *works = NULL, *creators = NULL, *endorsers = NULL,
             *cmap = NULL, *emap = NULL, *ret = NULL;
    carry = PySequence_List(carry_in);
    if (!carry)
        goto done;
    Py_ssize_t ncarry = PyList_GET_SIZE(carry);
    for (Py_ssize_t i = 0; i < ncarry; i++)
        if (!PyDict_Check(PyList_GET_ITEM(carry, i))) {
            PyErr_SetString(PyExc_TypeError,
                            "digest() carry entries must be dicts");
            goto done;
        }
    Py_ssize_t n = seq ? PySequence_Fast_GET_SIZE(seq) : nspans;
    codes = PyByteArray_FromStringAndSize(NULL, n);
    seen = PyDict_New();
    works = PyList_New(0);
    creators = PyList_New(0);
    endorsers = PyList_New(0);
    cmap = PyDict_New();
    emap = PyDict_New();
    if (!codes || !seen || !works || !creators || !endorsers || !cmap
        || !emap)
        goto done;
    uint8_t *cp = (uint8_t *)PyByteArray_AS_STRING(codes);
    memset(cp, VC_NOT_VALIDATED, (size_t)n);

    for (Py_ssize_t i = 0; i < n; i++) {
        if ((i & 63) == 63) {         /* keep device pump threads fed */
            Py_BEGIN_ALLOW_THREADS
            Py_END_ALLOW_THREADS
        }
        PyObject *rec;
        if (seq) {
            PyObject *env = PySequence_Fast_GET_ITEM(seq, i);
            if (env == Py_None) {
                cp[i] = FC2VC[E_NIL_ENVELOPE];
                continue;
            }
            char *ep;
            Py_ssize_t en;
            if (PyBytes_AsStringAndSize(env, &ep, &en) < 0)
                goto done;
            rec = collect_env((const uint8_t *)ep, (size_t)en,
                              (const uint8_t *)chan, (size_t)chan_n);
        } else {
            uint64_t off, ln;
            memcpy(&off, spans + 16 * i, 8);
            memcpy(&ln, spans + 16 * i + 8, 8);
            if (off > base_n || ln > base_n - off) {
                PyErr_SetString(PyExc_ValueError,
                                "digest_spans: span out of range");
                goto done;
            }
            rec = collect_env(base + off, (size_t)ln,
                              (const uint8_t *)chan, (size_t)chan_n);
        }
        if (!rec)
            goto done;
        if (PyLong_Check(rec)) {      /* pre-registration failure */
            long code = PyLong_AsLong(rec);
            Py_DECREF(rec);
            cp[i] = FC2VC[code];
            continue;
        }
        Py_ssize_t rlen = PyTuple_GET_SIZE(rec);
        PyObject *txid = PyTuple_GET_ITEM(rec, 1);
        int dup = txid_is_dup(txid, seen, carry, ncarry, oracle);
        if (dup < 0) { Py_DECREF(rec); goto done; }
        if (dup) {
            cp[i] = VC_DUPLICATE;
            Py_DECREF(rec);
            continue;
        }
        {
            PyObject *num = PyLong_FromSsize_t(i);
            int rc = num ? PyDict_SetItem(seen, txid, num) : -1;
            Py_XDECREF(num);
            if (rc < 0) { Py_DECREF(rec); goto done; }
        }
        if (rlen == 2) {              /* post-registration failure */
            long code = PyLong_AsLong(PyTuple_GET_ITEM(rec, 0));
            Py_DECREF(rec);
            cp[i] = FC2VC[code];
            continue;
        }
        long txtype = PyLong_AsLong(PyTuple_GET_ITEM(rec, 0));
        if (txtype == 0 && n != 1) {  /* config tx in a multi-tx block */
            cp[i] = VC_INVALID_CONFIG;
            Py_DECREF(rec);
            continue;
        }
        Py_ssize_t cslot = slot_of(cmap, creators,
                                   PyTuple_GET_ITEM(rec, 2));
        if (cslot < 0) { Py_DECREF(rec); goto done; }
        PyObject *acts_in = PyTuple_GET_ITEM(rec, 6);
        PyObject *acts2;
        if (acts_in == Py_None) {
            acts2 = Py_None;
            Py_INCREF(acts2);
        } else {
            acts2 = digest_actions(acts_in, emap, endorsers);
            if (!acts2) { Py_DECREF(rec); goto done; }
        }
        PyObject *work = PyTuple_New(7);
        PyObject *txo = PyLong_FromSsize_t(i);
        PyObject *typo = PyLong_FromLong(txtype);
        PyObject *cso = PyLong_FromSsize_t(cslot);
        if (!work || !txo || !typo || !cso) {
            Py_XDECREF(work); Py_XDECREF(txo); Py_XDECREF(typo);
            Py_XDECREF(cso); Py_DECREF(acts2); Py_DECREF(rec);
            goto done;
        }
        PyTuple_SET_ITEM(work, 0, txo);
        PyTuple_SET_ITEM(work, 1, typo);
        PyTuple_SET_ITEM(work, 2, cso);
        Py_INCREF(PyTuple_GET_ITEM(rec, 3));
        PyTuple_SET_ITEM(work, 3, PyTuple_GET_ITEM(rec, 3));  /* payload */
        Py_INCREF(PyTuple_GET_ITEM(rec, 4));
        PyTuple_SET_ITEM(work, 4, PyTuple_GET_ITEM(rec, 4));  /* pdigest */
        Py_INCREF(PyTuple_GET_ITEM(rec, 5));
        PyTuple_SET_ITEM(work, 5, PyTuple_GET_ITEM(rec, 5));  /* signature */
        PyTuple_SET_ITEM(work, 6, acts2);
        Py_DECREF(rec);
        int rc = PyList_Append(works, work);
        Py_DECREF(work);
        if (rc < 0)
            goto done;
    }
    ret = PyTuple_New(5);
    if (!ret)
        goto done;
    PyTuple_SET_ITEM(ret, 0, codes);
    PyTuple_SET_ITEM(ret, 1, seen);
    PyTuple_SET_ITEM(ret, 2, works);
    PyTuple_SET_ITEM(ret, 3, creators);
    PyTuple_SET_ITEM(ret, 4, endorsers);
    codes = seen = works = creators = endorsers = NULL;
done:
    Py_XDECREF(carry);
    Py_XDECREF(codes);
    Py_XDECREF(seen);
    Py_XDECREF(works);
    Py_XDECREF(creators);
    Py_XDECREF(endorsers);
    Py_XDECREF(cmap);
    Py_XDECREF(emap);
    return ret;
}

static PyObject *py_digest(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *envs, *carry_in, *oracle;
    const char *chan;
    Py_ssize_t chan_n;
    if (!PyArg_ParseTuple(args, "Os#OO", &envs, &chan, &chan_n,
                          &carry_in, &oracle))
        return NULL;
    PyObject *seq = PySequence_Fast(envs, "digest() needs a sequence");
    if (!seq)
        return NULL;
    PyObject *ret = digest_impl(seq, NULL, 0, NULL, 0, chan, chan_n,
                                carry_in, oracle);
    Py_DECREF(seq);
    return ret;
}

/* digest_spans(base, spans, channel_id, carry, oracle) — identical
 * result to digest([base[off:off+len] for off, len in spans], ...) but
 * the envelopes are consumed in place: `spans` is a buffer of
 * native-endian (u64 off, u64 len) pairs into `base` (the layout
 * fastparse.parse_block emits). */
static PyObject *py_digest_spans(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *base_o, *spans_o, *carry_in, *oracle;
    const char *chan;
    Py_ssize_t chan_n;
    if (!PyArg_ParseTuple(args, "OOs#OO", &base_o, &spans_o, &chan,
                          &chan_n, &carry_in, &oracle))
        return NULL;
    Py_buffer base_v, spans_v;
    if (PyObject_GetBuffer(base_o, &base_v, PyBUF_CONTIG_RO) < 0)
        return NULL;
    if (PyObject_GetBuffer(spans_o, &spans_v, PyBUF_CONTIG_RO) < 0) {
        PyBuffer_Release(&base_v);
        return NULL;
    }
    PyObject *ret = NULL;
    if (spans_v.len % 16) {
        PyErr_SetString(PyExc_ValueError,
                        "digest_spans: spans length not a multiple of 16");
    } else {
        ret = digest_impl(NULL, (const uint8_t *)base_v.buf,
                          (size_t)base_v.len,
                          (const uint8_t *)spans_v.buf, spans_v.len / 16,
                          chan, chan_n, carry_in, oracle);
    }
    PyBuffer_Release(&spans_v);
    PyBuffer_Release(&base_v);
    return ret;
}

/* VerifyItem interning.  index maps item -> dispatch position; for
 * P-256 items we probe with a plain 4-tuple FIRST (a tuple hashes and
 * compares equal to the NamedTuple with the same fields) so repeats —
 * the overwhelmingly common case on real blocks — never construct the
 * NamedTuple at all.  Stored keys must be real VerifyItems because the
 * dispatch path reads .scheme/.pubkey attributes off them. */
static Py_ssize_t intern_p256(PyObject *index, PyObject *cls,
                              PyObject *scheme, PyObject *wire,
                              PyObject *sig, PyObject *dig)
{
    PyObject *probe = PyTuple_Pack(4, scheme, wire, sig, dig);
    if (!probe)
        return -1;
    PyObject *v = PyDict_GetItemWithError(index, probe);
    if (v) {
        Py_DECREF(probe);
        return PyLong_AsSsize_t(v);
    }
    if (PyErr_Occurred()) { Py_DECREF(probe); return -1; }
    PyObject *item = PyObject_CallObject(cls, probe);
    Py_DECREF(probe);
    if (!item)
        return -1;
    Py_ssize_t idx = PyDict_GET_SIZE(index);
    PyObject *iv = PyLong_FromSsize_t(idx);
    int rc = iv ? PyDict_SetItem(index, item, iv) : -1;
    Py_XDECREF(iv);
    Py_DECREF(item);
    return rc < 0 ? -1 : idx;
}

/* non-P-256 item (already a VerifyItem/own item shape): plain intern */
static Py_ssize_t intern_item(PyObject *index, PyObject *item)
{
    PyObject *v = PyDict_GetItemWithError(index, item);
    if (v)
        return PyLong_AsSsize_t(v);
    if (PyErr_Occurred())
        return -1;
    Py_ssize_t idx = PyDict_GET_SIZE(index);
    PyObject *iv = PyLong_FromSsize_t(idx);
    if (!iv)
        return -1;
    int rc = PyDict_SetItem(index, item, iv);
    Py_DECREF(iv);
    return rc < 0 ? -1 : idx;
}

/* assemble(works, c_ents, e_ents, endorsers, codes, index, plans,
 *          verify_item_cls, scheme_p256, policy_for, pol_cache) -> n_refs
 *
 * c_ents/e_ents: per-slot (identity, p256_pub_wire|None) or None for
 * identities the MSP rejected.  Appends to `plans`
 * (tx_num, creator_idx, [(policy, [(item_idx, identity)...])...]) and
 * interns items into `index` in EXACTLY the Python tail's order:
 * creator first, then each action's endorsements, then that action's
 * namespace policy lookups (a missing policy kills the tx but keeps
 * already-interned items — n_unique_items parity).  n_refs counts
 * 1 + sigset size per namespace entry over SURVIVING works only,
 * matching _finish_inner's accounting. */
static PyObject *py_assemble(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *works, *c_ents, *e_ents, *endorsers, *codes, *index,
             *plans, *cls, *scheme, *policy_for, *pol_cache;
    if (!PyArg_ParseTuple(args, "OOOOOOOOOOO", &works, &c_ents, &e_ents,
                          &endorsers, &codes, &index, &plans, &cls,
                          &scheme, &policy_for, &pol_cache))
        return NULL;
    if (!PyList_Check(works) || !PyList_Check(c_ents)
        || !PyList_Check(e_ents) || !PyList_Check(endorsers)
        || !PyByteArray_Check(codes) || !PyDict_Check(index)
        || !PyList_Check(plans) || !PyDict_Check(pol_cache)) {
        PyErr_SetString(PyExc_TypeError, "assemble(): bad argument types");
        return NULL;
    }
    uint8_t *cp = (uint8_t *)PyByteArray_AS_STRING(codes);
    Py_ssize_t ncodes = PyByteArray_GET_SIZE(codes);
    Py_ssize_t n_refs = 0;
    for (Py_ssize_t w = 0; w < PyList_GET_SIZE(works); w++) {
        if ((w & 255) == 255) {
            Py_BEGIN_ALLOW_THREADS
            Py_END_ALLOW_THREADS
        }
        PyObject *work = PyList_GET_ITEM(works, w);
        Py_ssize_t tx = PyLong_AsSsize_t(PyTuple_GET_ITEM(work, 0));
        long txtype = PyLong_AsLong(PyTuple_GET_ITEM(work, 1));
        Py_ssize_t cslot = PyLong_AsSsize_t(PyTuple_GET_ITEM(work, 2));
        if (tx < 0 || tx >= ncodes || cslot < 0
            || cslot >= PyList_GET_SIZE(c_ents)) {
            PyErr_SetString(PyExc_IndexError, "assemble(): slot range");
            return NULL;
        }
        PyObject *ent = PyList_GET_ITEM(c_ents, cslot);
        if (ent == Py_None) {         /* MSP rejected the creator */
            cp[tx] = VC_BAD_CREATOR;
            continue;
        }
        PyObject *creator = PyTuple_GET_ITEM(ent, 0);
        PyObject *wire = PyTuple_GET_ITEM(ent, 1);
        Py_ssize_t cidx;
        if (wire != Py_None) {
            cidx = intern_p256(index, cls, scheme, wire,
                               PyTuple_GET_ITEM(work, 5),   /* signature */
                               PyTuple_GET_ITEM(work, 4));  /* pdigest */
        } else {
            PyObject *item = PyObject_CallMethodObjArgs(
                creator, s_verify_item, PyTuple_GET_ITEM(work, 3),
                PyTuple_GET_ITEM(work, 5), NULL);
            if (!item)
                return NULL;
            cidx = intern_item(index, item);
            Py_DECREF(item);
        }
        if (cidx < 0)
            return NULL;
        PyObject *entries = PyList_New(0);
        if (!entries)
            return NULL;
        int dead = 0;
        PyObject *acts = PyTuple_GET_ITEM(work, 6);
        if (txtype != 0 && acts != Py_None) {
            for (Py_ssize_t a = 0;
                 !dead && a < PyList_GET_SIZE(acts); a++) {
                PyObject *act = PyList_GET_ITEM(acts, a);
                PyObject *endorsed = PyTuple_GET_ITEM(act, 1);
                PyObject *ends2 = PyTuple_GET_ITEM(act, 2);
                PyObject *ns_names = PyTuple_GET_ITEM(act, 3);
                PyObject *sigset = PyList_New(0);
                if (!sigset) { Py_DECREF(entries); return NULL; }
                for (Py_ssize_t e = 0; e < PyList_GET_SIZE(ends2); e++) {
                    PyObject *end3 = PyList_GET_ITEM(ends2, e);
                    Py_ssize_t slot =
                        PyLong_AsSsize_t(PyTuple_GET_ITEM(end3, 0));
                    if (slot < 0 || slot >= PyList_GET_SIZE(e_ents)) {
                        PyErr_SetString(PyExc_IndexError,
                                        "assemble(): endorser slot");
                        Py_DECREF(sigset); Py_DECREF(entries);
                        return NULL;
                    }
                    PyObject *eent = PyList_GET_ITEM(e_ents, slot);
                    if (eent == Py_None)   /* undeserializable: skip */
                        continue;
                    PyObject *ident = PyTuple_GET_ITEM(eent, 0);
                    PyObject *ewire = PyTuple_GET_ITEM(eent, 1);
                    Py_ssize_t eidx;
                    if (ewire != Py_None) {
                        eidx = intern_p256(index, cls, scheme, ewire,
                                           PyTuple_GET_ITEM(end3, 1),
                                           PyTuple_GET_ITEM(end3, 2));
                    } else {
                        PyObject *msg = PySequence_Concat(
                            endorsed, PyList_GET_ITEM(endorsers, slot));
                        if (!msg) {
                            Py_DECREF(sigset); Py_DECREF(entries);
                            return NULL;
                        }
                        PyObject *item = PyObject_CallMethodObjArgs(
                            ident, s_verify_item, msg,
                            PyTuple_GET_ITEM(end3, 1), NULL);
                        Py_DECREF(msg);
                        if (!item) {
                            Py_DECREF(sigset); Py_DECREF(entries);
                            return NULL;
                        }
                        eidx = intern_item(index, item);
                        Py_DECREF(item);
                    }
                    if (eidx < 0) {
                        Py_DECREF(sigset); Py_DECREF(entries);
                        return NULL;
                    }
                    PyObject *eio = PyLong_FromSsize_t(eidx);
                    PyObject *pair = eio ? PyTuple_New(2) : NULL;
                    if (!pair) {
                        Py_XDECREF(eio);
                        Py_DECREF(sigset); Py_DECREF(entries);
                        return NULL;
                    }
                    PyTuple_SET_ITEM(pair, 0, eio);
                    Py_INCREF(ident);
                    PyTuple_SET_ITEM(pair, 1, ident);
                    int rc = PyList_Append(sigset, pair);
                    Py_DECREF(pair);
                    if (rc < 0) {
                        Py_DECREF(sigset); Py_DECREF(entries);
                        return NULL;
                    }
                }
                for (Py_ssize_t s = 0; s < PyList_GET_SIZE(ns_names);
                     s++) {
                    PyObject *ns = PyList_GET_ITEM(ns_names, s);
                    PyObject *pol =
                        PyDict_GetItemWithError(pol_cache, ns);
                    if (!pol) {
                        if (PyErr_Occurred()) {
                            Py_DECREF(sigset); Py_DECREF(entries);
                            return NULL;
                        }
                        pol = PyObject_CallFunctionObjArgs(policy_for,
                                                           ns, NULL);
                        if (!pol || PyDict_SetItem(pol_cache, ns,
                                                   pol) < 0) {
                            Py_XDECREF(pol);
                            Py_DECREF(sigset); Py_DECREF(entries);
                            return NULL;
                        }
                        Py_DECREF(pol);   /* pol_cache holds it */
                    }
                    if (pol == Py_None) {  /* unknown namespace */
                        cp[tx] = VC_INVALID_CC;
                        dead = 1;
                        break;
                    }
                    PyObject *entry = PyTuple_New(2);
                    if (!entry) {
                        Py_DECREF(sigset); Py_DECREF(entries);
                        return NULL;
                    }
                    Py_INCREF(pol);
                    PyTuple_SET_ITEM(entry, 0, pol);
                    Py_INCREF(sigset);
                    PyTuple_SET_ITEM(entry, 1, sigset);
                    int rc = PyList_Append(entries, entry);
                    Py_DECREF(entry);
                    if (rc < 0) {
                        Py_DECREF(sigset); Py_DECREF(entries);
                        return NULL;
                    }
                }
                Py_DECREF(sigset);
            }
        }
        if (dead) {
            Py_DECREF(entries);
            continue;
        }
        n_refs += 1;
        for (Py_ssize_t s = 0; s < PyList_GET_SIZE(entries); s++)
            n_refs += PyList_GET_SIZE(
                PyTuple_GET_ITEM(PyList_GET_ITEM(entries, s), 1));
        PyObject *plan = PyTuple_New(3);
        PyObject *cio = PyLong_FromSsize_t(cidx);
        if (!plan || !cio) {
            Py_XDECREF(plan); Py_XDECREF(cio); Py_DECREF(entries);
            return NULL;
        }
        Py_INCREF(PyTuple_GET_ITEM(work, 0));
        PyTuple_SET_ITEM(plan, 0, PyTuple_GET_ITEM(work, 0));
        PyTuple_SET_ITEM(plan, 1, cio);
        PyTuple_SET_ITEM(plan, 2, entries);
        int rc = PyList_Append(plans, plan);
        Py_DECREF(plan);
        if (rc < 0)
            return NULL;
    }
    return PyLong_FromSsize_t(n_refs);
}

/* gate(plans, verdict: buffer[u8], codes, plugin, evaluator, eval_cache)
 *
 * Folds the device verdict bitmap into final ValidationCodes without a
 * per-tx Python loop.  Per plan: creator bit (miss -> BAD_CREATOR_SIG),
 * then per (policy, sigset) the verdict-filtered valid-identity list is
 * evaluated via `plugin` memoized in eval_cache keyed
 * (id(policy), id(ident)...) — same purity argument as
 * txvalidator._memoized_plugin (policies and identities are interned
 * per block, so ids are stable).  Any falsy evaluation ->
 * ENDORSEMENT_POLICY_FAILURE, else VALID. */
static PyObject *py_gate(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *plans, *codes, *plugin, *evaluator, *eval_cache;
    Py_buffer vb;
    if (!PyArg_ParseTuple(args, "Oy*OOOO", &plans, &vb, &codes, &plugin,
                          &evaluator, &eval_cache))
        return NULL;
    if (!PyList_Check(plans) || !PyByteArray_Check(codes)
        || !PyDict_Check(eval_cache)) {
        PyBuffer_Release(&vb);
        PyErr_SetString(PyExc_TypeError, "gate(): bad argument types");
        return NULL;
    }
    const uint8_t *v = (const uint8_t *)vb.buf;
    Py_ssize_t nv = vb.len;
    uint8_t *cp = (uint8_t *)PyByteArray_AS_STRING(codes);
    Py_ssize_t ncodes = PyByteArray_GET_SIZE(codes);
    for (Py_ssize_t p = 0; p < PyList_GET_SIZE(plans); p++) {
        if ((p & 255) == 255) {
            Py_BEGIN_ALLOW_THREADS
            Py_END_ALLOW_THREADS
        }
        PyObject *plan = PyList_GET_ITEM(plans, p);
        Py_ssize_t tx = PyLong_AsSsize_t(PyTuple_GET_ITEM(plan, 0));
        Py_ssize_t cidx = PyLong_AsSsize_t(PyTuple_GET_ITEM(plan, 1));
        if (tx < 0 || tx >= ncodes)
            goto typefail;
        if (cidx < 0 || cidx >= nv || !v[cidx]) {
            cp[tx] = VC_BAD_CREATOR;
            continue;
        }
        PyObject *entries = PyTuple_GET_ITEM(plan, 2);
        int failed = 0;
        for (Py_ssize_t s = 0;
             !failed && s < PyList_GET_SIZE(entries); s++) {
            PyObject *entry = PyList_GET_ITEM(entries, s);
            PyObject *pol = PyTuple_GET_ITEM(entry, 0);
            PyObject *sigset = PyTuple_GET_ITEM(entry, 1);
            Py_ssize_t m = PyList_GET_SIZE(sigset);
            PyObject *valid = PyList_New(0);
            if (!valid)
                goto fail;
            for (Py_ssize_t e = 0; e < m; e++) {
                PyObject *pair = PyList_GET_ITEM(sigset, e);
                Py_ssize_t idx =
                    PyLong_AsSsize_t(PyTuple_GET_ITEM(pair, 0));
                if (idx >= 0 && idx < nv && v[idx]
                    && PyList_Append(valid,
                                     PyTuple_GET_ITEM(pair, 1)) < 0) {
                    Py_DECREF(valid);
                    goto fail;
                }
            }
            Py_ssize_t nvalid = PyList_GET_SIZE(valid);
            PyObject *key = PyTuple_New(1 + nvalid);
            if (!key) { Py_DECREF(valid); goto fail; }
            PyObject *ko = PyLong_FromVoidPtr((void *)pol);
            if (!ko) { Py_DECREF(key); Py_DECREF(valid); goto fail; }
            PyTuple_SET_ITEM(key, 0, ko);
            int keyfail = 0;
            for (Py_ssize_t e = 0; e < nvalid; e++) {
                ko = PyLong_FromVoidPtr(
                    (void *)PyList_GET_ITEM(valid, e));
                if (!ko) { keyfail = 1; break; }
                PyTuple_SET_ITEM(key, 1 + e, ko);
            }
            if (keyfail) {
                Py_DECREF(key); Py_DECREF(valid);
                goto fail;
            }
            PyObject *r = PyDict_GetItemWithError(eval_cache, key);
            int truth;
            if (r) {
                truth = PyObject_IsTrue(r);
            } else {
                if (PyErr_Occurred()) {
                    Py_DECREF(key); Py_DECREF(valid);
                    goto fail;
                }
                PyObject *r2 = PyObject_CallFunctionObjArgs(
                    plugin, pol, valid, evaluator, NULL);
                if (!r2 || PyDict_SetItem(eval_cache, key, r2) < 0) {
                    Py_XDECREF(r2); Py_DECREF(key); Py_DECREF(valid);
                    goto fail;
                }
                truth = PyObject_IsTrue(r2);
                Py_DECREF(r2);
            }
            Py_DECREF(key);
            Py_DECREF(valid);
            if (truth < 0)
                goto fail;
            if (!truth) {
                cp[tx] = VC_POLICY_FAIL;
                failed = 1;
            }
        }
        if (!failed)
            cp[tx] = VC_VALID;
    }
    PyBuffer_Release(&vb);
    Py_RETURN_NONE;
typefail:
    PyErr_SetString(PyExc_IndexError, "gate(): tx out of range");
fail:
    PyBuffer_Release(&vb);
    return NULL;
}

/* ------------------------------------------------------------------ */
/* Batched strict-DER ECDSA signature parsing.
 *
 * The provider's P-256 pass parses every signature's DER SEQUENCE of
 * two INTEGERs before packing; one C call over the whole batch replaces
 * ~1.6 us/sig of per-item Python (bccsp/jaxtpu._parse_p256's
 * decode_dss_signature loop) with ~40 ns/sig.  Semantics mirror the
 * Python path exactly: strict DER (minimal lengths, minimal integer
 * encoding — what cryptography's decode_dss_signature enforces) AND the
 * range gate 0 < r,s < 2^256; any failure clears the ok flag (the
 * caller host-rejects, verdict stays False).
 *
 * parse_der_sigs(sigs: sequence[bytes]) -> (ok: bytes[N], rs: bytes[64N])
 *   rs holds r32be || s32be per signature (zero-padded on the left).
 */

/* one strict-DER unsigned INTEGER in (0, 2^256) -> 32B big-endian */
static int der_int32(const uint8_t **pp, const uint8_t *end, uint8_t out[32])
{
    const uint8_t *p = *pp;
    if (end - p < 2 || p[0] != 0x02) return -1;
    uint32_t l = p[1];
    /* values < 2^256 encode in <= 33 bytes < 128: short form only */
    if (l == 0 || l > 33 || (uint32_t)(end - p - 2) < l) return -1;
    p += 2;
    if (p[0] & 0x80) return -1;                 /* negative: out of range */
    if (l > 1 && p[0] == 0 && !(p[1] & 0x80)) return -1;   /* non-minimal */
    if (l == 33 && p[0] != 0) return -1;        /* >= 2^256 */
    const uint8_t *v = p;
    uint32_t vn = l;
    if (l == 33) { v++; vn = 32; }
    int zero = 1;
    for (uint32_t i = 0; i < vn; i++)
        if (v[i]) { zero = 0; break; }
    if (zero) return -1;                        /* r/s must be nonzero */
    memset(out, 0, 32 - vn);
    memcpy(out + (32 - vn), v, vn);
    *pp = p + l;
    return 0;
}

static PyObject *py_parse_der_sigs(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *sigs;
    if (!PyArg_ParseTuple(args, "O", &sigs))
        return NULL;
    PyObject *seq = PySequence_Fast(sigs, "parse_der_sigs needs a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *ok_b = PyBytes_FromStringAndSize(NULL, n);
    PyObject *rs_b = PyBytes_FromStringAndSize(NULL, n * 64);
    if (!ok_b || !rs_b) {
        Py_XDECREF(ok_b); Py_XDECREF(rs_b); Py_DECREF(seq);
        return NULL;
    }
    uint8_t *ok = (uint8_t *)PyBytes_AS_STRING(ok_b);
    uint8_t *rs = (uint8_t *)PyBytes_AS_STRING(rs_b);
    memset(rs, 0, (size_t)n * 64);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *sig = PySequence_Fast_GET_ITEM(seq, i);
        char *cp;
        Py_ssize_t sn;
        ok[i] = 0;
        if (PyBytes_AsStringAndSize(sig, &cp, &sn) < 0) {
            PyErr_Clear();               /* non-bytes: host reject */
            continue;
        }
        const uint8_t *p = (const uint8_t *)cp;
        const uint8_t *end = p + sn;
        /* SEQUENCE header, short-form length covering the whole rest */
        if (sn < 8 || p[0] != 0x30 || p[1] >= 0x80
            || (Py_ssize_t)p[1] != sn - 2)
            continue;
        p += 2;
        if (der_int32(&p, end, rs + i * 64) < 0) continue;
        if (der_int32(&p, end, rs + i * 64 + 32) < 0) continue;
        if (p != end) continue;          /* trailing bytes */
        ok[i] = 1;
    }
    Py_DECREF(seq);
    PyObject *out = Py_BuildValue("(NN)", ok_b, rs_b);
    if (!out) { Py_DECREF(ok_b); Py_DECREF(rs_b); }
    return out;
}

static PyObject *py_sha256(PyObject *self, PyObject *args)
{
    (void)self;
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf))
        return NULL;
    uint8_t out[32];
    sha256_oneshot(buf.buf, buf.len, out);
    PyBuffer_Release(&buf);
    return PyBytes_FromStringAndSize((const char *)out, 32);
}

static PyMethodDef methods[] = {
    {"collect", py_collect, METH_VARARGS,
     "collect(envs, channel_id) -> per-tx structural results"},
    {"digest", py_digest, METH_VARARGS,
     "digest(envs, channel_id, carry, oracle) -> "
     "(codes, seen, works, creators, endorsers)"},
    {"digest_spans", py_digest_spans, METH_VARARGS,
     "digest_spans(base, spans, channel_id, carry, oracle) -> "
     "digest() over zero-copy (u64 off, u64 len) spans into base"},
    {"assemble", py_assemble, METH_VARARGS,
     "assemble(works, c_ents, e_ents, endorsers, codes, index, plans, "
     "verify_item_cls, scheme_p256, policy_for, pol_cache) -> n_refs"},
    {"gate", py_gate, METH_VARARGS,
     "gate(plans, verdict, codes, plugin, evaluator, eval_cache)"},
    {"parse_der_sigs", py_parse_der_sigs, METH_VARARGS,
     "parse_der_sigs(sigs) -> (ok bytes, r32s32 bytes)"},
    {"sha256", py_sha256, METH_VARARGS, "sha256(data) -> 32-byte digest"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moddef = {
    PyModuleDef_HEAD_INIT, "_fastcollect",
    "C pass-1 block collection (txvalidator hot path)", -1, methods,
    NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit__fastcollect(void)
{
#ifdef HAVE_X86
    unsigned eax, ebx, ecx, edx;
    if (__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx) && (ebx & (1u << 29)))
        sha256_block = sha256_block_shani;
#endif
    s_verify_item = PyUnicode_InternFromString("verify_item");
    if (!s_verify_item)
        return NULL;
    return PyModule_Create(&moddef);
}
