"""Native (C) components of the framework, built on demand.

The reference leans on C-backed machinery for its hot paths (protobuf,
LevelDB, cgo PKCS#11 — SURVEY.md §2.1); this package holds the
TPU-native framework's equivalents.  Extensions are compiled lazily on
first import with the system compiler and cached next to their sources;
set FABRIC_TPU_NO_NATIVE=1 to force the pure-Python fallbacks.

Current extensions:
  _ftlv        — the canonical serde codec (fabric_tpu/utils/serde.py)
  _fastcollect — txvalidator pass-1 block walker + SHA-256 (SHA-NI)
  _fastparse   — zero-copy wire ingest: block/envelope span parser
"""

from __future__ import annotations

import importlib
import logging
import os
import subprocess
import sysconfig

logger = logging.getLogger("fabric_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))


def _build(name: str):
    src = os.path.join(_DIR, f"{name[1:]}.c")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so = os.path.join(_DIR, name + suffix)
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        cc = os.environ.get("CC", "cc")
        inc = sysconfig.get_path("include")
        tmp = so + f".tmp{os.getpid()}"
        # warnings are errors: a diagnostic in accelerator-adjacent C is
        # a bug report, and silent ones rot (tests/smoke.sh also runs an
        # ASan/UBSan build of the parser over the fuzz corpus)
        cmd = [cc, "-O3", "-shared", "-fPIC",
               "-Wall", "-Wextra", "-Werror",
               f"-I{inc}", src, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, so)    # atomic: concurrent builders race benignly
    return importlib.import_module(f"fabric_tpu.native.{name}")


def load(name: str):
    """Import a native extension, (re)building it if the source is newer
    than the cached .so.  Returns the module or None (unavailable /
    disabled)."""
    if os.environ.get("FABRIC_TPU_NO_NATIVE") == "1":
        return None
    try:
        # always go through _build: it checks source-vs-.so mtimes, so a
        # source edit invalidates the cache (importing first would pin a
        # stale build for every new process)
        return _build(name)
    except Exception as exc:
        logger.warning("native extension %s unavailable (%s); using "
                       "pure-Python fallback", name, exc)
        return None
