"""The handler registry + built-in implementations.

Plugin contracts (duck-typed):
  auth filter     fn(proposal, creator_identity) -> None | raise
  endorsement     fn(signing_identity, payload: bytes) -> (endorser_bytes,
                  signature)  — ESCC's Endorse
                  (default_endorsement.go:36 signs payload || endorser)
  validation      fn(policy, valid_identities, evaluator) -> bool — the
                  per-namespace commit-time decision consuming the
                  verified identity set (validation_logic.go:185)
"""

from __future__ import annotations

import threading
from typing import Callable, Dict


class HandlerRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._auth: Dict[str, Callable] = {}
        self._endorsement: Dict[str, Callable] = {}
        self._validation: Dict[str, Callable] = {}

    # -- registration (registry.go) ------------------------------------------

    def register_auth_filter(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._auth[name] = fn

    def register_endorsement(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._endorsement[name] = fn

    def register_validation(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._validation[name] = fn

    # -- lookup --------------------------------------------------------------

    def auth_filter(self, name: str) -> Callable:
        with self._lock:
            if name not in self._auth:
                raise KeyError(f"unknown auth filter {name!r}")
            return self._auth[name]

    def endorsement(self, name: str) -> Callable:
        with self._lock:
            if name not in self._endorsement:
                raise KeyError(f"unknown endorsement plugin {name!r}")
            return self._endorsement[name]

    def validation(self, name: str) -> Callable:
        with self._lock:
            if name not in self._validation:
                raise KeyError(f"unknown validation plugin {name!r}")
            return self._validation[name]


# -- built-ins ---------------------------------------------------------------

def _expiration_check(proposal, creator_identity) -> None:
    """auth/filter.expiration: reject proposals from expired certs."""
    import datetime
    exp = getattr(creator_identity, "expires_at", None)
    if exp is None:
        return
    if callable(exp):
        exp = exp()
    now = datetime.datetime.now(datetime.timezone.utc)
    if exp < now:
        raise PermissionError("creator certificate expired")


def _default_endorsement(signing_identity, payload: bytes):
    """ESCC (default_endorsement.go:36): sign payload || endorser."""
    endorser = signing_identity.serialize()
    return endorser, signing_identity.sign(payload + endorser)


def _default_validation(policy, valid_identities, evaluator) -> bool:
    """Builtin v20 policy gate over the VERIFIED endorsement set."""
    return evaluator.evaluate(policy, list(valid_identities))


default_registry = HandlerRegistry()
default_registry.register_auth_filter("ExpirationCheck", _expiration_check)
default_registry.register_endorsement("DefaultEndorsement",
                                      _default_endorsement)
default_registry.register_validation("DefaultValidation",
                                     _default_validation)


def register_auth_filter(name: str, fn: Callable) -> None:
    default_registry.register_auth_filter(name, fn)


def register_endorsement(name: str, fn: Callable) -> None:
    default_registry.register_endorsement(name, fn)


def register_validation(name: str, fn: Callable) -> None:
    default_registry.register_validation(name, fn)
