"""Pluggable handler framework: named auth / endorsement / validation
plugins.

Reference parity: core/handlers/library/registry.go — the peer config
names which handler implements each pluggable role (auth filters run
before endorsement, an endorsement plugin signs proposal responses, a
validation plugin judges txs at commit); custom Go plugins load by name
from a registry (`.so` loading stays out of scope — an in-process
registry was the explicit round-1 design decision, SURVEY.md §2.1.3).

Built-ins mirror the reference's defaults:
  auth:        "ExpirationCheck"  (reject expired creator certs)
  endorsement: "DefaultEndorsement" (ESCC: sign payload || endorser)
  validation:  "DefaultValidation"  (policy evaluation over the
               verified endorsement set — the verify-then-gate pass 2)
"""

from .registry import (
    HandlerRegistry,
    default_registry,
    register_auth_filter,
    register_endorsement,
    register_validation,
)

__all__ = [
    "HandlerRegistry", "default_registry", "register_auth_filter",
    "register_endorsement", "register_validation",
]
