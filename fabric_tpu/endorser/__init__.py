"""Endorsement plane: proposals, simulation, endorsement signing.

Reference parity (SURVEY.md §3.3): core/endorser ProcessProposal
(endorser.go:296) — unpack + validate proposal, ACL check, simulate
against chaincode, endorse via the ESCC plugin — plus the client-side
proposal/transaction assembly from protoutil/txutils.go.
"""

from .proposal import (
    Proposal,
    ProposalResponse,
    ResponseMismatchError,
    assemble_transaction,
    signed_proposal,
)
from .endorser import Endorser, EndorserError

__all__ = ["Proposal", "ProposalResponse", "ResponseMismatchError",
           "assemble_transaction", "signed_proposal", "Endorser",
           "EndorserError"]
