"""Proposal wire types + client-side assembly.

Reference parity: peer.Proposal/SignedProposal/ProposalResponse
(protoutil/{proputils,txutils}.go).  The client signs a proposal, fans it
out to endorsers, checks all returned simulation payloads are identical,
and assembles the creator-signed transaction envelope
(protoutil.CreateSignedTx checks at txutils.go: all endorsements must be
over the same ProposalResponsePayload).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

from fabric_tpu.protocol import Envelope, Transaction, TransactionAction
from fabric_tpu.protocol.build import (
    compute_txid,
    make_header,
    new_nonce,
    proposal_hash,
    signed_envelope,
)
from fabric_tpu.protocol.types import (
    ChaincodeAction,
    Endorsement,
    Header,
    TX_ENDORSER,
)
from fabric_tpu.utils import serde


@dataclass(frozen=True)
class Proposal:
    """peer.Proposal: header + invocation spec."""
    header: Header
    chaincode_id: str
    fn: str
    args: Tuple[bytes, ...]

    def to_bytes(self) -> bytes:
        return serde.encode({
            "header": self.header.to_dict(),
            "chaincode_id": self.chaincode_id,
            "fn": self.fn,
            "args": list(self.args),
        })

    @staticmethod
    def from_bytes(raw: bytes) -> "Proposal":
        d = serde.decode(raw)
        return Proposal(Header.from_dict(d["header"]), d["chaincode_id"],
                        d["fn"], tuple(d["args"]))

    def hash(self) -> bytes:
        ch = self.header.channel_header
        return proposal_hash(ch.channel_id, ch.txid, self.chaincode_id,
                             [self.fn.encode(), *self.args])


@dataclass(frozen=True)
class SignedProposal:
    proposal_bytes: bytes
    signature: bytes

    def proposal(self) -> Proposal:
        return Proposal.from_bytes(self.proposal_bytes)


@dataclass(frozen=True)
class ProposalResponse:
    """peer.ProposalResponse: status + endorsed payload + endorsement."""
    status: int
    message: str
    payload: bytes                    # TransactionAction.endorsed_bytes()
    endorsement: Endorsement = None   # None when status != 200


class ResponseMismatchError(Exception):
    """Endorsers returned divergent simulation results."""


def signed_proposal(channel_id: str, chaincode_id: str, fn: str,
                    args: Sequence[bytes], signer,
                    nonce: bytes = None) -> SignedProposal:
    """Client step 1: build + sign a proposal (CreateChaincodeProposal)."""
    nonce = new_nonce() if nonce is None else nonce
    header = make_header(TX_ENDORSER, channel_id, signer.serialize(), nonce)
    prop = Proposal(header, chaincode_id, fn, tuple(args))
    raw = prop.to_bytes()
    return SignedProposal(raw, signer.sign(raw))


def assemble_transaction(sp: SignedProposal,
                         responses: Sequence[ProposalResponse],
                         signer) -> Envelope:
    """Client step 2 (protoutil.CreateSignedTx): all endorsement payloads
    must match bit-for-bit; the envelope reuses the proposal's nonce so
    txid stays bound to the original proposal."""
    prop = sp.proposal()
    bad = [r for r in responses if r.status != 200]
    if bad:
        # any failed response aborts client-side (CreateSignedTx rejects
        # non-200): submitting under-endorsed txs burns ordering work just
        # to fail policy at commit
        raise ResponseMismatchError(
            f"{len(bad)}/{len(responses)} endorsers failed: "
            f"{bad[0].message!r}")
    ok = list(responses)
    if not ok:
        raise ResponseMismatchError("no proposal responses")
    payloads = {r.payload for r in ok}
    if len(payloads) != 1:
        raise ResponseMismatchError(
            f"{len(payloads)} distinct simulation payloads across "
            f"{len(ok)} endorsements")
    payload = ok[0].payload
    d = serde.decode(payload)
    ta = TransactionAction(d["proposal_hash"],
                           ChaincodeAction.from_dict(d["action"]),
                           tuple(r.endorsement for r in ok))
    if ta.endorsed_bytes() != payload:
        raise ResponseMismatchError("endorsed payload does not round-trip")
    sh = prop.header.signature_header
    if signer.serialize() != sh.creator:
        raise ResponseMismatchError("assembler is not the proposal creator")
    tx = Transaction((ta,))
    return signed_envelope(TX_ENDORSER, prop.header.channel_header.channel_id,
                           tx.to_dict(), signer, nonce=sh.nonce)
