"""The endorser service: ProcessProposal.

Reference parity: core/endorser/endorser.go:296 ProcessProposal →
:178 SimulateProposal → ESCC endorse (core/handlers/endorsement/builtin/
default_endorsement.go:36), with the proposal-creator signature check from
core/endorser/msgvalidation.go and the ACL check from core/aclmgmt.

Signing stays host-side (private keys never touch the TPU); the single
proposal-creator verify here is immediate, not batched — endorsement is a
low-volume interactive path, unlike commit-side block validation.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, Optional

from fabric_tpu.chaincode import ChaincodeRegistry, ChaincodeStub, SimulationError
from fabric_tpu.endorser.proposal import (
    Proposal,
    ProposalResponse,
    SignedProposal,
)
from fabric_tpu.ledger.statedb import StateDB
from fabric_tpu.msp import SigningIdentity, deserialize_from_msps
from fabric_tpu.ops_plane import tracing
from fabric_tpu.policy import PolicyEvaluator, SignaturePolicy, SignedData
from fabric_tpu.protocol.build import compute_txid
from fabric_tpu.protocol.types import (ChaincodeAction, Endorsement,
                                       TransactionAction)

logger = logging.getLogger("fabric_tpu.endorser")


class EndorserError(Exception):
    pass


class Endorser:
    """One peer's endorser service bound to a channel's state."""

    def __init__(self, channel_id: str, db: StateDB,
                 registry: ChaincodeRegistry,
                 msps: Dict[str, object], provider,
                 signer: SigningIdentity,
                 proposal_acl: Optional[SignaturePolicy] = None,
                 transient_store=None, pvt_store=None, distribute=None,
                 ledger_height=None,
                 endorsement_plugin: str = "DefaultEndorsement",
                 auth_filters=("ExpirationCheck",), acl=None):
        self.channel_id = channel_id
        self.db = db
        self.registry = registry
        self.msps = msps
        self.signer = signer
        self.proposal_acl = proposal_acl
        # aclmgmt provider: when set, the proposal gate is the
        # "peer/Propose" resource policy from the channel config
        # (core/endorser ACL check through core/aclmgmt); proposal_acl
        # stays as the static fallback
        self.acl = acl
        self.evaluator = PolicyEvaluator(msps, provider)
        # pluggable handlers (core/handlers/library/registry.go): named
        # auth filters run before simulation; the endorsement plugin
        # signs the response (ESCC slot)
        from fabric_tpu.handlers import default_registry as _handlers
        self.endorsement_plugin = _handlers.endorsement(endorsement_plugin)
        self.auth_filters = [_handlers.auth_filter(n) for n in auth_filters]
        # private-data plane (gossip/privdata distribution at endorsement):
        # cleartext write-sets are staged in the transient store and pushed
        # to collection member peers; only hashes enter the public rwset.
        self.transient_store = transient_store
        self.pvt_store = pvt_store
        self.distribute = distribute      # callable(txid, pvt_sets) -> None
        self.ledger_height = ledger_height or (lambda: 0)

    def process_proposal(self, sp: SignedProposal) -> ProposalResponse:
        """endorser.go:296.  Errors map to a non-200 response, never an
        exception — the reference returns a ProposalResponse with an error
        status to the client in all failure modes."""
        try:
            with tracing.tracer.start_span("endorser.validate",
                                           require_parent=True):
                prop, creator = self._validate(sp)
            with tracing.tracer.start_span(
                    "endorser.simulate", require_parent=True,
                    attributes={"chaincode": prop.chaincode_id}):
                payload, rwset, events = self._simulate(prop, creator)
            action = ChaincodeAction(
                prop.chaincode_id,
                self._version_of(prop.chaincode_id),
                rwset, response_payload=payload, events=events)
            ta = TransactionAction(prop.hash(), action)
            endorsed = ta.endorsed_bytes()
            # ESCC slot: the endorsement plugin signs
            # endorsed-bytes || endorser identity
            with tracing.tracer.start_span("endorser.sign",
                                           require_parent=True):
                endorser_bytes, sig = self.endorsement_plugin(self.signer,
                                                              endorsed)
            return ProposalResponse(200, "", endorsed,
                                    Endorsement(endorser_bytes, sig))
        except (EndorserError, SimulationError) as err:
            logger.info("[%s] proposal rejected: %s", self.channel_id, err)
            return ProposalResponse(500, str(err), b"", None)
        except Exception as err:
            # malformed wire input (e.g. non-bytes header fields) must not
            # crash the request path — the contract is response, not raise
            logger.warning("[%s] proposal processing error: %s",
                           self.channel_id, err)
            return ProposalResponse(500, f"internal error: {err}", b"", None)

    # -- validation (msgvalidation.go) --------------------------------------

    def _validate(self, sp: SignedProposal):
        try:
            prop = sp.proposal()
        except Exception as e:
            raise EndorserError(f"undecodable proposal: {e}") from e
        ch = prop.header.channel_header
        sh = prop.header.signature_header
        if ch.channel_id != self.channel_id:
            raise EndorserError(
                f"proposal for channel {ch.channel_id!r}, serving "
                f"{self.channel_id!r}")
        if ch.txid != compute_txid(sh.nonce, sh.creator):
            raise EndorserError("txid does not bind nonce+creator")
        creator = deserialize_from_msps(self.msps, sh.creator, validate=True)
        if creator is None:
            raise EndorserError("unknown or invalid creator identity")
        if not creator.verify(sp.proposal_bytes, sp.signature):
            raise EndorserError("bad proposal signature")
        for flt in self.auth_filters:       # core/handlers/auth chain
            try:
                flt(prop, creator)
            except Exception as e:
                raise EndorserError(f"auth filter rejected: {e}") from e
        sd = SignedData(sp.proposal_bytes, sh.creator, sp.signature)
        if self.acl is not None:
            try:
                self.acl.check_acl("peer/Propose", sd)
            except PermissionError as e:
                raise EndorserError(str(e)) from e
        elif self.proposal_acl is not None:
            if not self.evaluator.evaluate_signed_data(self.proposal_acl, [sd]):
                raise EndorserError("creator fails proposal ACL policy")
        return prop, sh.creator

    # -- simulation (endorser.go:178) ---------------------------------------

    def _simulate(self, prop: Proposal, creator: bytes):
        txid = prop.header.channel_header.txid
        stub = ChaincodeStub(self.db, prop.chaincode_id,
                             channel_id=self.channel_id,
                             txid=txid,
                             creator=creator, registry=self.registry,
                             pvt_store=self.pvt_store)
        _, payload = self.registry.execute(
            stub, prop.chaincode_id, prop.fn, list(prop.args))
        pvt_sets = stub.private_sets()
        if pvt_sets:
            if self.transient_store is not None:
                self.transient_store.persist(txid, self.ledger_height(),
                                             pvt_sets)
            if self.distribute is not None:
                self.distribute(txid, pvt_sets)
        return payload, stub.rwset(), stub.event_bytes()

    def _version_of(self, chaincode_id: str) -> str:
        d = self.registry.definition(chaincode_id)
        return d.version if d else "0"
