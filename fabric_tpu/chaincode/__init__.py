"""Chaincode plane: contract runtime, simulation stub, lifecycle.

Reference parity (SURVEY.md §2 "Endorsement side"):
  core/chaincode (gRPC FSM runtime)   -> runtime.ChaincodeRegistry (in-proc)
  shim GetState/PutState/...          -> stub.ChaincodeStub
  core/chaincode/lifecycle            -> lifecycle.LifecycleContract/_cache

TPU-native redesign note: the reference launches chaincode as separate
Docker/external-builder processes speaking a gRPC state-machine protocol
(core/chaincode/handler.go).  Here contracts execute in-process against a
read-committed simulator — the process boundary bought isolation for
untrusted Go binaries, not performance, and the simulation results (rwsets)
are byte-identical either way.  An external-runner hook stays available via
runtime.ExternalContract for out-of-process contracts.
"""

from .stub import ChaincodeStub, SimulationError
from .runtime import Contract, ChaincodeDefinition, ChaincodeRegistry, ExternalContract
from .lifecycle import LIFECYCLE_NS, LifecycleContract, LifecyclePolicyProvider

__all__ = ["ChaincodeStub", "SimulationError", "Contract",
           "ChaincodeDefinition", "ChaincodeRegistry", "ExternalContract",
           "LIFECYCLE_NS", "LifecycleContract", "LifecyclePolicyProvider"]
