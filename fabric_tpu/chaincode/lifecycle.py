"""_lifecycle system chaincode: chaincode definitions as consensus state.

Reference parity: core/chaincode/lifecycle/{lifecycle,cache}.go — org
approvals and committed definitions live in the `_lifecycle` namespace of
the channel state, so they replicate through ordinary ordering + commit;
the validator's plugin dispatcher reads each namespace's endorsement
policy from that state (plugindispatcher/dispatcher.go:102 via the
lifecycle cache).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from fabric_tpu.chaincode.runtime import ChaincodeDefinition, Contract
from fabric_tpu.chaincode.stub import ChaincodeStub, SimulationError
from fabric_tpu.ledger.statedb import StateDB
from fabric_tpu.policy import SignaturePolicy
from fabric_tpu.utils import serde

LIFECYCLE_NS = "_lifecycle"


def _def_key(name: str) -> str:
    return f"namespaces/fields/{name}/definition"


def _approval_key(name: str, sequence: int, mspid: str) -> str:
    return f"namespaces/fields/{name}/approvals/{sequence}/{mspid}"


class LifecycleContract(Contract):
    """The `_lifecycle` contract: approve_for_org / commit / query.

    approve: records the calling org's approval of (name, sequence, ...).
    commit : requires approvals recorded for the majority of the
             channel's org set (lifecycle's default LifecycleEndorsement
             majority policy), then writes the definition.

    `msp_ids` is either a static org list (single-channel/test use) or
    a callable(channel_id) -> org list, so a node-global contract
    instance evaluates each channel's commit against THAT channel's
    live org set — a fixed bootstrap-channel list would let an
    under-approved definition commit on a wider channel.
    """

    def __init__(self, msp_ids):
        self._msp_ids = msp_ids

    def _orgs(self, stub: ChaincodeStub) -> List[str]:
        if callable(self._msp_ids):
            return sorted(self._msp_ids(
                getattr(stub, "channel_id", None)))
        return sorted(self._msp_ids)

    def invoke(self, stub: ChaincodeStub, fn: str, args: List[bytes]) -> bytes:
        if fn == "approve_for_org":
            return self._approve(stub, *args)
        if fn == "commit":
            return self._commit(stub, *args)
        if fn == "query_definition":
            return self._query(stub, *args)
        raise SimulationError(f"unknown lifecycle function {fn!r}")

    def _approve(self, stub: ChaincodeStub, name: bytes, version: bytes,
                 sequence: bytes, policy: bytes = b"") -> bytes:
        # the approval is bound to the SUBMITTER's org — never an argument,
        # or any org could forge the others' approvals
        mspid_s = self._creator_mspid(stub)
        seq = int(sequence)
        stub.put_state(_approval_key(name.decode(), seq, mspid_s),
                       serde.encode({"version": version.decode(),
                                     "policy": policy}))
        return b"approved"

    def _commit(self, stub: ChaincodeStub, name: bytes, version: bytes,
                sequence: bytes, policy: bytes = b"") -> bytes:
        name_s, seq = name.decode(), int(sequence)
        want = serde.encode({"version": version.decode(), "policy": policy})
        orgs = self._orgs(stub)
        approvals = 0
        for mspid in orgs:
            got = stub.get_state(_approval_key(name_s, seq, mspid))
            if got == want:
                approvals += 1
        if not orgs or approvals <= len(orgs) // 2:
            raise SimulationError(
                f"insufficient approvals for {name_s} seq {seq}: "
                f"{approvals}/{len(orgs)}")
        prev = stub.get_state(_def_key(name_s))
        if prev is not None and serde.decode(prev)["sequence"] >= seq:
            raise SimulationError(f"sequence {seq} already committed")
        stub.put_state(_def_key(name_s), serde.encode({
            "version": version.decode(), "policy": policy, "sequence": seq}))
        return b"committed"

    def _query(self, stub: ChaincodeStub, name: bytes) -> bytes:
        got = stub.get_state(_def_key(name.decode()))
        if got is None:
            raise SimulationError(f"no definition for {name.decode()!r}")
        return got

    @staticmethod
    def _creator_mspid(stub: ChaincodeStub) -> str:
        try:
            return serde.decode(stub.creator)["mspid"]
        except Exception:
            raise SimulationError("cannot derive creator mspid")


# ---------------------------------------------------------------------------
# install / package (lifecycle.go InstallChaincode + persistence/)
# ---------------------------------------------------------------------------

def package_chaincode(label: str, code: bytes,
                      metadata: Optional[dict] = None) -> bytes:
    """Build a chaincode package (the reference's tar.gz package role:
    persistence/chaincode_package.go) — canonical serde of label +
    metadata + code bytes."""
    if not label or any(c in label for c in "/\\:"):
        raise ValueError("invalid package label")
    return serde.encode({"label": label, "code": code,
                         "metadata": metadata or {}})


def package_id(pkg: bytes) -> str:
    """`label:sha256(pkg)` — the hash-addressed package identity
    (persistence.PackageID)."""
    import hashlib
    label = serde.decode(pkg)["label"]
    return f"{label}:{hashlib.sha256(pkg).hexdigest()}"


class ChaincodeInstaller:
    """Installed-chaincode store (lifecycle.go InstallChaincode /
    QueryInstalledChaincodes): packages persisted by package id under a
    directory, content-addressed so re-install is idempotent and a
    tampered package can never impersonate an id."""

    def __init__(self, root: str):
        import os
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, pid: str) -> str:
        # filename = content hash only: labels may contain any filename
        # character, so the hash (hex) is the unambiguous disk key
        import os
        return os.path.join(self.root, pid.rsplit(":", 1)[1] + ".pkg")

    def install(self, pkg: bytes) -> str:
        import os
        pid = package_id(pkg)
        path = self._path(pid)
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(pkg)
            os.replace(tmp, path)
        return pid

    def get(self, pid: str) -> Optional[bytes]:
        import hashlib
        import os
        path = self._path(pid)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            pkg = f.read()
        try:
            ok = package_id(pkg) == pid
        except Exception:
            ok = False
        if not ok:
            raise ValueError(f"installed package {pid} corrupted on disk")
        return pkg

    def installed(self) -> List[str]:
        import os
        out = []
        for fname in sorted(os.listdir(self.root)):
            if not fname.endswith(".pkg"):
                continue
            with open(os.path.join(self.root, fname), "rb") as f:
                try:
                    out.append(package_id(f.read()))
                except Exception:
                    continue       # unreadable package: skip
        return sorted(out)


class LifecyclePolicyProvider:
    """policy_for(namespace) backed by committed _lifecycle state — the
    validator-side lifecycle cache (lifecycle/cache.go) feeding the plugin
    dispatcher.  Falls back to `default` (channel majority-endorsement)."""

    def __init__(self, db: StateDB, default: Optional[SignaturePolicy] = None,
                 system_policies: Optional[Dict[str, SignaturePolicy]] = None):
        self.db = db
        self.default = default
        self.system = dict(system_policies or {})

    def set_policy(self, namespace: str, policy: SignaturePolicy) -> None:
        """Static override for system namespaces (e.g. _lifecycle itself)."""
        self.system[namespace] = policy

    def policy_for(self, namespace: str) -> Optional[SignaturePolicy]:
        if namespace in self.system:
            return self.system[namespace]
        vv = self.db.get(LIFECYCLE_NS, _def_key(namespace))
        if vv is not None:
            raw = serde.decode(vv.value).get("policy", b"")
            if raw:
                return SignaturePolicy.deserialize(raw)
            return self.default
        return None  # undefined chaincode: validator flags INVALID_CHAINCODE

    def definition_for(self, namespace: str) -> Optional[ChaincodeDefinition]:
        vv = self.db.get(LIFECYCLE_NS, _def_key(namespace))
        if vv is None:
            return None
        d = serde.decode(vv.value)
        return ChaincodeDefinition(namespace, d["version"],
                                   d.get("policy", b""), d["sequence"])
