"""Contract registry + execution engine.

Reference parity: core/chaincode/chaincode_support.go (Launch/Execute,
:79,:154) and core/container/externalbuilder — re-designed in-process (see
package docstring).  A ChaincodeDefinition mirrors the _lifecycle committed
definition (name, version, endorsement policy, sequence); execution renders
a response `(status, payload)` plus the rwset staged in the stub.
"""

from __future__ import annotations

import subprocess
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from fabric_tpu.chaincode.stub import ChaincodeStub, SimulationError


@dataclass(frozen=True)
class ChaincodeDefinition:
    """A committed chaincode definition (lifecycle.ChaincodeDefinition)."""
    name: str
    version: str
    policy_bytes: bytes = b""   # serialized SignaturePolicy; b"" = channel default
    sequence: int = 1


class Contract:
    """Base class for in-process contracts (the shim's Chaincode iface).

    Subclasses implement `invoke(stub, fn, args) -> bytes` and may raise
    SimulationError to produce a 500 response.
    """

    def invoke(self, stub: ChaincodeStub, fn: str, args: List[bytes]) -> bytes:
        raise NotImplementedError


class ExternalContract(Contract):
    """Out-of-process contract hook (externalbuilder run-style): executes a
    command that receives the invocation on stdin and returns state ops on
    stdout, for contracts that must not run in the peer process."""

    def __init__(self, argv: List[str], timeout_s: float = 30.0):
        self.argv = argv
        self.timeout_s = timeout_s

    def invoke(self, stub: ChaincodeStub, fn: str, args: List[bytes]) -> bytes:
        from fabric_tpu.utils import serde
        req = serde.encode({"fn": fn, "args": list(args),
                            "channel": stub.channel_id, "txid": stub.txid})
        try:
            out = subprocess.run(self.argv, input=req, capture_output=True,
                                 timeout=self.timeout_s, check=True).stdout
        except subprocess.SubprocessError as e:
            raise SimulationError(f"external contract failed: {e}") from e
        resp = serde.decode(out)
        for op in resp.get("ops", []):
            if op["op"] == "put":
                stub.put_state(op["key"], op["value"])
            elif op["op"] == "del":
                stub.del_state(op["key"])
        return resp.get("payload", b"")


class ChaincodeRegistry:
    """namespace -> (definition, contract).  The Execute path of
    chaincode_support.go:154 without the process boundary."""

    def __init__(self):
        self._contracts: Dict[str, Tuple[ChaincodeDefinition, Contract]] = {}

    def install(self, definition: ChaincodeDefinition,
                contract: Contract) -> None:
        self._contracts[definition.name] = (definition, contract)

    def definition(self, name: str) -> Optional[ChaincodeDefinition]:
        entry = self._contracts.get(name)
        return entry[0] if entry else None

    def names(self) -> List[str]:
        return sorted(self._contracts)

    def execute(self, stub: ChaincodeStub, name: str, fn: str,
                args: List[bytes]) -> Tuple[int, bytes]:
        """Run one invocation; returns (status, payload). 500 on contract
        error — the rwset staged so far is DISCARDED by the caller then
        (failed simulations are not endorsed)."""
        entry = self._contracts.get(name)
        if entry is None:
            raise SimulationError(f"chaincode {name!r} not installed")
        _, contract = entry
        try:
            payload = contract.invoke(stub, fn, args)
            return 200, payload or b""
        except SimulationError:
            raise
        except Exception as e:
            raise SimulationError(f"contract {name!r} raised: {e}") from e

    def invoke_into(self, caller_stub: ChaincodeStub, name: str, fn: str,
                    args: List[bytes]) -> bytes:
        """cc2cc: run `name` against the caller's rwset, scoped to the
        callee namespace."""
        entry = self._contracts.get(name)
        if entry is None:
            raise SimulationError(f"chaincode {name!r} not installed")
        _, contract = entry
        return contract.invoke(caller_stub.scoped(name), fn, args) or b""


class FuncContract(Contract):
    """Adapter: register plain functions as contract methods."""

    def __init__(self, **handlers: Callable):
        self._handlers = handlers

    def invoke(self, stub: ChaincodeStub, fn: str, args: List[bytes]) -> bytes:
        if fn not in self._handlers:
            raise SimulationError(f"unknown function {fn!r}")
        return self._handlers[fn](stub, *args) or b""
