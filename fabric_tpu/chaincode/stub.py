"""Transaction simulation stub — the chaincode's view of the ledger.

Reference parity: the shim-side ChaincodeStubInterface (GetState/PutState/
DelState/GetStateByRange) plus the peer-side lock-based tx simulator
(core/ledger/kvledger/txmgmt/txmgr/lockbasedtxmgr) that records every read
with its committed version and stages writes, producing the TxRwSet that
endorsers sign and the MVCC validator later checks
(txmgmt/validation/validator.go:83).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from fabric_tpu.ledger.statedb import StateDB
from fabric_tpu.protocol.types import (
    KVRead,
    KVWrite,
    NsRwSet,
    RangeQueryInfo,
    TxRwSet,
)


class SimulationError(Exception):
    pass


class _NsBuilder:
    def __init__(self):
        self.reads: Dict[str, KVRead] = {}
        self.writes: Dict[str, KVWrite] = {}
        self.range_queries: List[RangeQueryInfo] = []


class ChaincodeStub:
    """One transaction's simulation context over committed state.

    Reads record the committed version (for MVCC); writes stage in the
    rwset.  get_state sees the simulation's own staged writes;
    get_state_by_range reads COMMITTED state only — same limitation as
    the reference simulator, whose range/rich queries never reflect the
    transaction's own uncommitted writes.
    """

    def __init__(self, db: StateDB, namespace: str,
                 channel_id: str = "", txid: str = "",
                 creator: bytes = b"", registry=None, pvt_store=None):
        self._db = db
        self._ns = namespace
        self.channel_id = channel_id
        self.txid = txid
        self.creator = creator
        self._registry = registry  # for cc2cc invoke
        self._pvt_store = pvt_store  # local PvtDataStore for private reads
        self._pvt_writes: Dict[tuple, Dict[str, object]] = {}
        self._builders: Dict[str, _NsBuilder] = {}
        self._event: bytes = b""
        self._done = False

    def _b(self, ns: Optional[str] = None) -> _NsBuilder:
        ns = self._ns if ns is None else ns
        return self._builders.setdefault(ns, _NsBuilder())

    # -- shim surface -------------------------------------------------------

    def get_state(self, key: str) -> Optional[bytes]:
        self._check_open()
        b = self._b()
        if key in b.writes:  # read-your-writes
            w = b.writes[key]
            return None if w.is_delete else w.value
        vv = self._db.get(self._ns, key)
        if key not in b.reads:  # first read wins (version pinning)
            b.reads[key] = KVRead(key, None if vv is None else vv.version)
        return None if vv is None else vv.value

    def put_state(self, key: str, value: bytes) -> None:
        self._check_open()
        if not key:
            raise SimulationError("empty key")
        self._b().writes[key] = KVWrite(key, value)

    def del_state(self, key: str) -> None:
        self._check_open()
        self._b().writes[key] = KVWrite(key, is_delete=True)

    def get_state_by_range(self, start_key: str, end_key: str,
                           limit: int = 0) -> List[Tuple[str, bytes]]:
        """Records a RangeQueryInfo with raw reads; validation replays the
        same scan at commit time (rangequery_validator.go, phantom reads).
        Committed state only — this simulation's staged writes are NOT
        visible to range scans (reference simulator limitation kept)."""
        self._check_open()
        results = []
        reads = []
        exhausted = True
        for key, vv in self._db.range_scan(self._ns, start_key, end_key):
            if limit and len(results) >= limit:
                exhausted = False
                break
            reads.append(KVRead(key, vv.version))
            results.append((key, vv.value))
        self._b().range_queries.append(RangeQueryInfo(
            start_key, end_key, exhausted, tuple(reads)))
        return results

    def get_query_result(self, selector: dict, limit: int = 0):
        """Rich query over committed JSON-document state (shim
        GetQueryResult; statecouchdb option).  Reads committed state only
        and stages NO read-set entries — rich-query results are not
        MVCC-protected, exactly like the reference."""
        self._check_open()
        return [(k, vv.value)
                for k, vv in self._db.execute_query(self._ns, selector,
                                                    limit)]

    def invoke_chaincode(self, chaincode_id: str, fn: str,
                         args: List[bytes]) -> bytes:
        """cc2cc invocation: the callee simulates into THIS rwset under its
        own namespace (core/chaincode handler cc2cc semantics)."""
        self._check_open()
        if self._registry is None:
            raise SimulationError("no chaincode registry for cc2cc")
        return self._registry.invoke_into(self, chaincode_id, fn, args)

    # -- key-level endorsement (SBE) ----------------------------------------
    # Reference: shim SetStateValidationParameter / GetStateValidationParameter
    # backed by statebased/validator_keylevel.go; parameters are ordinary
    # versioned writes in the companion metadata namespace, so MVCC orders
    # concurrent updates and the policy flips at the block boundary.

    def set_event(self, name: str, payload: bytes) -> None:
        """Chaincode event (shim SetEvent): at most one per invocation,
        carried in the endorsed ChaincodeAction and surfaced to event
        listeners after the tx commits VALID (peer/deliver events)."""
        from fabric_tpu.utils import serde as _serde
        self._check_open()
        self._event = _serde.encode({"name": name, "payload": payload})

    def event_bytes(self) -> bytes:
        return self._event

    def set_state_validation_parameter(self, key: str, policy) -> None:
        self._check_open()
        from fabric_tpu.committer import sbe
        raw = sbe.encode_policy(policy) if policy is not None else None
        mns = sbe.meta_namespace(self._ns)
        if raw is None:
            self._b(mns).writes[key] = KVWrite(key, is_delete=True)
        else:
            self._b(mns).writes[key] = KVWrite(key, raw)

    def get_state_validation_parameter(self, key: str):
        self._check_open()
        from fabric_tpu.committer import sbe
        mns = sbe.meta_namespace(self._ns)
        b = self._b(mns)
        if key in b.writes:
            w = b.writes[key]
            return None if w.is_delete else sbe.decode_policy(w.value)
        vv = self._db.get(mns, key)
        if key not in b.reads:
            b.reads[key] = KVRead(key, None if vv is None else vv.version)
        return None if vv is None else sbe.decode_policy(vv.value)

    # -- private data (collections) -----------------------------------------
    # Reference: the chaincode shim's GetPrivateData/PutPrivateData; the
    # public rwset carries only hash(key)->hash(value) under the hashed
    # namespace ns$collection, the cleartext goes to the transient store
    # (gossip/privdata distribution model, VERDICT.md missing #2).

    def put_private_data(self, collection: str, key: str, value: bytes) -> None:
        self._check_open()
        if not key:
            raise SimulationError("empty key")
        from fabric_tpu.privdata.collection import (hash_key, hash_value,
                                                    pvt_namespace)
        hns = pvt_namespace(self._ns, collection)
        self._b(hns).writes[hash_key(key)] = KVWrite(hash_key(key),
                                                     hash_value(value))
        self._pvt_writes.setdefault((self._ns, collection), {})[key] = value

    def del_private_data(self, collection: str, key: str) -> None:
        self._check_open()
        from fabric_tpu.privdata.collection import hash_key, pvt_namespace
        hns = pvt_namespace(self._ns, collection)
        self._b(hns).writes[hash_key(key)] = KVWrite(hash_key(key),
                                                     is_delete=True)
        self._pvt_writes.setdefault((self._ns, collection), {})[key] = None

    def get_private_data(self, collection: str, key: str) -> Optional[bytes]:
        # Cleartext from the local pvt store; the MVCC-relevant read is
        # recorded against the HASHED namespace so every peer (member or
        # not) validates it identically.
        self._check_open()
        from fabric_tpu.privdata.collection import hash_key, pvt_namespace
        staged = self._pvt_writes.get((self._ns, collection), {})
        if key in staged:
            return staged[key]
        hns = pvt_namespace(self._ns, collection)
        hk = hash_key(key)
        b = self._b(hns)
        vv = self._db.get(hns, hk)
        if hk not in b.reads:
            b.reads[hk] = KVRead(hk, None if vv is None else vv.version)
        if self._pvt_store is None:
            return None
        return self._pvt_store.get(self._ns, collection, key)

    def private_sets(self) -> Dict[tuple, Dict[str, object]]:
        # {(namespace, collection): {key: value|None}}
        return dict(self._pvt_writes)

    # -- result -------------------------------------------------------------

    def rwset(self) -> TxRwSet:
        self._done = True
        ns_sets = []
        for ns in sorted(self._builders):
            b = self._builders[ns]
            ns_sets.append(NsRwSet(
                ns,
                reads=tuple(b.reads[k] for k in sorted(b.reads)),
                writes=tuple(b.writes[k] for k in sorted(b.writes)),
                range_queries=tuple(b.range_queries)))
        return TxRwSet(tuple(ns_sets))

    def _check_open(self) -> None:
        if self._done:
            raise SimulationError("simulation already finalized")

    # -- namespace-scoped view for cc2cc -----------------------------------

    def scoped(self, namespace: str) -> "ChaincodeStub":
        view = ChaincodeStub.__new__(ChaincodeStub)
        view.__dict__.update(self.__dict__)
        view._ns = namespace
        return view
