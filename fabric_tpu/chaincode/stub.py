"""Transaction simulation stub — the chaincode's view of the ledger.

Reference parity: the shim-side ChaincodeStubInterface (GetState/PutState/
DelState/GetStateByRange) plus the peer-side lock-based tx simulator
(core/ledger/kvledger/txmgmt/txmgr/lockbasedtxmgr) that records every read
with its committed version and stages writes, producing the TxRwSet that
endorsers sign and the MVCC validator later checks
(txmgmt/validation/validator.go:83).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from fabric_tpu.ledger.statedb import StateDB
from fabric_tpu.protocol.types import (
    KVRead,
    KVWrite,
    NsRwSet,
    RangeQueryInfo,
    TxRwSet,
)


class SimulationError(Exception):
    pass


class _NsBuilder:
    def __init__(self):
        self.reads: Dict[str, KVRead] = {}
        self.writes: Dict[str, KVWrite] = {}
        self.range_queries: List[RangeQueryInfo] = []


class ChaincodeStub:
    """One transaction's simulation context over committed state.

    Reads record the committed version (for MVCC); writes stage in the
    rwset.  get_state sees the simulation's own staged writes;
    get_state_by_range reads COMMITTED state only — same limitation as
    the reference simulator, whose range/rich queries never reflect the
    transaction's own uncommitted writes.
    """

    def __init__(self, db: StateDB, namespace: str,
                 channel_id: str = "", txid: str = "",
                 creator: bytes = b"", registry=None):
        self._db = db
        self._ns = namespace
        self.channel_id = channel_id
        self.txid = txid
        self.creator = creator
        self._registry = registry  # for cc2cc invoke
        self._builders: Dict[str, _NsBuilder] = {}
        self._done = False

    def _b(self, ns: Optional[str] = None) -> _NsBuilder:
        ns = self._ns if ns is None else ns
        return self._builders.setdefault(ns, _NsBuilder())

    # -- shim surface -------------------------------------------------------

    def get_state(self, key: str) -> Optional[bytes]:
        self._check_open()
        b = self._b()
        if key in b.writes:  # read-your-writes
            w = b.writes[key]
            return None if w.is_delete else w.value
        vv = self._db.get(self._ns, key)
        if key not in b.reads:  # first read wins (version pinning)
            b.reads[key] = KVRead(key, None if vv is None else vv.version)
        return None if vv is None else vv.value

    def put_state(self, key: str, value: bytes) -> None:
        self._check_open()
        if not key:
            raise SimulationError("empty key")
        self._b().writes[key] = KVWrite(key, value)

    def del_state(self, key: str) -> None:
        self._check_open()
        self._b().writes[key] = KVWrite(key, is_delete=True)

    def get_state_by_range(self, start_key: str, end_key: str,
                           limit: int = 0) -> List[Tuple[str, bytes]]:
        """Records a RangeQueryInfo with raw reads; validation replays the
        same scan at commit time (rangequery_validator.go, phantom reads).
        Committed state only — this simulation's staged writes are NOT
        visible to range scans (reference simulator limitation kept)."""
        self._check_open()
        results = []
        reads = []
        exhausted = True
        for key, vv in self._db.range_scan(self._ns, start_key, end_key):
            if limit and len(results) >= limit:
                exhausted = False
                break
            reads.append(KVRead(key, vv.version))
            results.append((key, vv.value))
        self._b().range_queries.append(RangeQueryInfo(
            start_key, end_key, exhausted, tuple(reads)))
        return results

    def invoke_chaincode(self, chaincode_id: str, fn: str,
                         args: List[bytes]) -> bytes:
        """cc2cc invocation: the callee simulates into THIS rwset under its
        own namespace (core/chaincode handler cc2cc semantics)."""
        self._check_open()
        if self._registry is None:
            raise SimulationError("no chaincode registry for cc2cc")
        return self._registry.invoke_into(self, chaincode_id, fn, args)

    # -- result -------------------------------------------------------------

    def rwset(self) -> TxRwSet:
        self._done = True
        ns_sets = []
        for ns in sorted(self._builders):
            b = self._builders[ns]
            ns_sets.append(NsRwSet(
                ns,
                reads=tuple(b.reads[k] for k in sorted(b.reads)),
                writes=tuple(b.writes[k] for k in sorted(b.writes)),
                range_queries=tuple(b.range_queries)))
        return TxRwSet(tuple(ns_sets))

    def _check_open(self) -> None:
        if self._done:
            raise SimulationError("simulation already finalized")

    # -- namespace-scoped view for cc2cc -----------------------------------

    def scoped(self, namespace: str) -> "ChaincodeStub":
        view = ChaincodeStub.__new__(ChaincodeStub)
        view.__dict__.update(self.__dict__)
        view._ns = namespace
        return view
