"""External-builder pipeline: detect / build / run from an installed
chaincode package to a launched process.

Reference parity: core/container/externalbuilder/externalbuilder.go —
the peer walks its configured builders; the first whose `bin/detect`
accepts the package gets `bin/build`, and the built artifact's
`bin/run` becomes the chaincode's long-running process.  Round-4
verdict missing #6: extcc previously launched only an operator-supplied
command line; this module derives the launch command from the package
itself.

Layout of an operator builder directory (exactly the reference's):

    <builder>/bin/detect  <pkg_dir> <metadata_dir>     rc 0 = mine
    <builder>/bin/build   <pkg_dir> <metadata_dir> <output_dir>
    <builder>/bin/run     <output_dir> <run_metadata_dir>

A BUILTIN python builder ships in-process so a package whose metadata
declares ``{"type": "python"}`` (or whose label ends in ``.py``) runs
with zero operator configuration: build materializes the code as
``chaincode.py``; run executes it with the current interpreter.  The
chaincode source speaks the shim protocol (extcc.shim_main) via the
FABRIC_TPU_CC_* env the launcher provides.

Build outputs are cached by package id (hash-addressed, like the
installer) so re-install/re-launch never rebuilds.
"""

from __future__ import annotations

import logging
import os
import shutil
import subprocess
import sys
from dataclasses import dataclass
from typing import List, Optional

from fabric_tpu.utils import serde

from .lifecycle import package_id

logger = logging.getLogger("fabric_tpu.chaincode.externalbuilder")


@dataclass(frozen=True)
class BuildResult:
    package_id: str
    builder: str
    output_dir: str
    run_argv: List[str]


class ExternalBuilder:
    """One operator-provided builder directory (bin/detect|build|run)."""

    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path

    def _bin(self, tool: str) -> str:
        return os.path.join(self.path, "bin", tool)

    def detect(self, pkg_dir: str, meta_dir: str) -> bool:
        exe = self._bin("detect")
        if not os.access(exe, os.X_OK):
            return False
        rc = subprocess.run([exe, pkg_dir, meta_dir],
                            capture_output=True).returncode
        return rc == 0

    def build(self, pkg_dir: str, meta_dir: str, out_dir: str) -> None:
        proc = subprocess.run([self._bin("build"), pkg_dir, meta_dir,
                               out_dir], capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"builder {self.name!r} build failed: {proc.stderr[-500:]}")

    def run_argv(self, out_dir: str, run_meta_dir: str) -> List[str]:
        return [self._bin("run"), out_dir, run_meta_dir]


class _PythonBuiltin:
    """Zero-config builder for python-source chaincode packages."""

    name = "python-builtin"

    @staticmethod
    def wants(label: str, metadata: dict) -> bool:
        return (metadata.get("type") == "python"
                or str(label).endswith(".py"))

    @staticmethod
    def build(code: bytes, out_dir: str) -> List[str]:
        path = os.path.join(out_dir, "chaincode.py")
        with open(path, "wb") as f:
            f.write(code)
        return [sys.executable, path]


class BuildPipeline:
    """detect -> build -> run resolution, cached by package id."""

    def __init__(self, build_root: str,
                 builders: Optional[List[ExternalBuilder]] = None):
        self.build_root = build_root
        self.builders = list(builders or [])
        os.makedirs(build_root, exist_ok=True)

    def build(self, pkg: bytes) -> BuildResult:
        """Resolve and build one installed package; idempotent."""
        pid = package_id(pkg)
        d = serde.decode(pkg)
        label, code = d["label"], d["code"]
        metadata = d.get("metadata") or {}
        key = pid.rsplit(":", 1)[1]
        root = os.path.join(self.build_root, key)
        out_dir = os.path.join(root, "release")
        run_meta = os.path.join(root, "run")
        done = os.path.join(root, "BUILDER")
        if os.path.exists(done):
            with open(done) as f:
                builder_name, *argv = f.read().splitlines()
            return BuildResult(pid, builder_name, out_dir, argv)

        pkg_dir = os.path.join(root, "pkg")
        meta_dir = os.path.join(root, "meta")
        for p in (pkg_dir, meta_dir, out_dir, run_meta):
            os.makedirs(p, exist_ok=True)
        with open(os.path.join(pkg_dir, "code"), "wb") as f:
            f.write(code)
        with open(os.path.join(meta_dir, "metadata.json"), "w") as f:
            import json
            json.dump({"label": label,
                       **{k: v for k, v in metadata.items()
                          if isinstance(v, (str, int, bool, float))}}, f)

        builder_name = None
        argv: List[str] = []
        for b in self.builders:
            if b.detect(pkg_dir, meta_dir):
                b.build(pkg_dir, meta_dir, out_dir)
                builder_name = b.name
                argv = b.run_argv(out_dir, run_meta)
                break
        if builder_name is None and _PythonBuiltin.wants(label, metadata):
            argv = _PythonBuiltin.build(code, out_dir)
            builder_name = _PythonBuiltin.name
        if builder_name is None:
            shutil.rmtree(root, ignore_errors=True)
            raise RuntimeError(
                f"no builder detected package {pid!r} (label {label!r})")
        with open(done, "w") as f:
            f.write("\n".join([builder_name, *argv]))
        logger.info("built %s with %s -> %s", pid, builder_name, out_dir)
        return BuildResult(pid, builder_name, out_dir, argv)


def launch_installed(support, pipeline: BuildPipeline, name: str,
                     pkg: bytes) -> BuildResult:
    """Install-package -> running process: build via the pipeline, then
    hand the derived run command to ChaincodeSupport.launch — no
    operator-supplied command line anywhere."""
    res = pipeline.build(pkg)
    support.launch(name, res.run_argv)
    return res
