"""Out-of-process chaincode: launch, stream FSM, shim.

Reference parity: core/chaincode/chaincode_support.go (:129 Register,
:154 Execute, launch timeout), core/chaincode/handler.go (the
peer<->chaincode message FSM: the chaincode calls GetState/PutState/...
back over the SAME stream while an Invoke is in flight), and
core/container/externalbuilder (running the contract as its own OS
process).  The reference speaks gRPC bidi streams; here the stream is a
u32-length-framed serde message socket over a unix domain socket —
chaincode processes are co-located with their peer by definition, and
the authenticated RPC plane stays reserved for the network.

Peer side: ChaincodeSupport serves the socket, launches chaincode
processes (waiting for their Register within the launch timeout),
drives invocations, and relaunches dead chaincodes on the next Execute.
Chaincode side: `shim_main` connects, registers, and dispatches
invocations to a Contract-like callable via a proxy stub.

Message protocol (all serde dicts, u32-framed):
  cc -> peer   {"type": "register", "name": str}
  peer -> cc   {"type": "registered"}
  peer -> cc   {"type": "invoke", "txid", "fn", "args": [bytes]}
  cc -> peer   {"type": "get_state" | "del_state" | "get_private" |
                "put_state" | "put_private" | "del_private" |
                "range" | "set_event" | ...}       (callbacks, see FSM)
  peer -> cc   {"type": "resp", ...}               (callback answers)
  cc -> peer   {"type": "complete", "payload"} | {"type": "error", "message"}
  either way   {"type": "ping"} / {"type": "pong"} (keepalive)
"""

from __future__ import annotations

import hmac
import logging
import os
import secrets
import socket
import struct
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from fabric_tpu.utils import serde

from .runtime import Contract
from .stub import SimulationError

logger = logging.getLogger("fabric_tpu.chaincode.extcc")

_FRAME = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024


def _send(sock: socket.socket, msg: dict) -> None:
    raw = serde.encode(msg)
    sock.sendall(_FRAME.pack(len(raw)) + raw)


def _recv(sock: socket.socket, timeout: Optional[float] = None) -> dict:
    sock.settimeout(timeout)
    hdr = b""
    while len(hdr) < _FRAME.size:
        chunk = sock.recv(_FRAME.size - len(hdr))
        if not chunk:
            raise ConnectionError("chaincode stream closed")
        hdr += chunk
    (n,) = _FRAME.unpack(hdr)
    if n > MAX_FRAME:
        raise ConnectionError("oversized chaincode frame")
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("chaincode stream closed")
        buf += chunk
    return serde.decode(buf)


class _CCHandle:
    """One registered chaincode process: its stream + process handle."""

    def __init__(self, name: str, sock: socket.socket,
                 proc: Optional[subprocess.Popen] = None):
        self.name = name
        self.sock = sock
        self.proc = proc
        self.lock = threading.Lock()    # one invocation at a time

    def alive(self) -> bool:
        """Cheap liveness: process state only.  No ping round trip per
        invoke — a dead stream surfaces as a failed invoke, whose error
        path already tears the handle down for relaunch."""
        return self.proc is None or self.proc.poll() is None

    def ping(self) -> bool:
        """Explicit keepalive probe (used by periodic health checks, not
        the per-invoke hot path)."""
        if not self.alive():
            return False
        try:
            with self.lock:
                _send(self.sock, {"type": "ping"})
                msg = _recv(self.sock, timeout=2.0)
            return msg.get("type") == "pong"
        except (OSError, ValueError, ConnectionError):
            return False

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()


class ChaincodeSupport:
    """Peer-side chaincode process manager (chaincode_support.go)."""

    def __init__(self, sock_dir: str, launch_timeout_s: float = 10.0,
                 invoke_timeout_s: float = 30.0):
        os.makedirs(sock_dir, mode=0o700, exist_ok=True)
        os.chmod(sock_dir, 0o700)
        self.sock_path = os.path.join(sock_dir, "chaincode.sock")
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        self.launch_timeout_s = launch_timeout_s
        self.invoke_timeout_s = invoke_timeout_s
        self._handles: Dict[str, _CCHandle] = {}
        self._launch_cmds: Dict[str, List[str]] = {}
        self._pending: Dict[str, socket.socket] = {}
        # per-launch registration tokens: a registration for `name` is
        # only accepted while a launch() for that name is in flight AND
        # the register message carries the token handed to that child
        # via env — the reference authenticates chaincode streams with
        # peer-generated TLS client certs (core/chaincode handler auth);
        # here the unix-socket analogue is a random bearer token.
        self._expected_tokens: Dict[str, str] = {}
        self._cond = threading.Condition()
        self._closing = False
        old_umask = os.umask(0o077)
        try:
            self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._srv.bind(self.sock_path)
        finally:
            os.umask(old_umask)
        self._srv.listen(16)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # -- registration (chaincode_support.go:129) -----------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._register_conn, args=(conn,),
                             daemon=True).start()

    def _register_conn(self, conn: socket.socket) -> None:
        try:
            msg = _recv(conn, timeout=self.launch_timeout_s)
            if msg.get("type") != "register" or not msg.get("name"):
                conn.close()
                return
            name = msg["name"]
            token = msg.get("token", "")
            with self._cond:
                expected = self._expected_tokens.get(name)
            if expected is None or not hmac.compare_digest(
                    str(token), expected):
                logger.warning(
                    "rejecting chaincode registration for %r: no launch "
                    "in flight or bad token", name)
                conn.close()
                return
            _send(conn, {"type": "registered"})
        except (OSError, ValueError, ConnectionError):
            conn.close()
            return
        with self._cond:
            self._pending[name] = conn
            self._cond.notify_all()

    def launch(self, name: str, argv: List[str]) -> None:
        """Spawn the chaincode process and wait for its Register (launch
        timeout parity: chaincode_support.go Launch)."""
        self._launch_cmds[name] = list(argv)
        token = secrets.token_hex(16)
        env = dict(os.environ)
        env["FABRIC_TPU_CC_SOCK"] = self.sock_path
        env["FABRIC_TPU_CC_NAME"] = name
        env["FABRIC_TPU_CC_TOKEN"] = token
        with self._cond:
            # purge any stale registration from a PREVIOUS launch whose
            # child passed the token check but registered after that
            # launch timed out — pairing a new process with the old
            # child's socket would route invokes to the wrong process
            stale = self._pending.pop(name, None)
            self._expected_tokens[name] = token
        if stale is not None:
            try:
                stale.close()
            except OSError:
                pass
        try:
            proc = subprocess.Popen(argv, env=env)
            deadline = time.monotonic() + self.launch_timeout_s
            with self._cond:
                while name not in self._pending:
                    left = deadline - time.monotonic()
                    if left <= 0 or proc.poll() is not None:
                        proc.kill()
                        raise SimulationError(
                            f"chaincode {name!r} failed to register within "
                            f"{self.launch_timeout_s}s")
                    self._cond.wait(timeout=min(left, 0.5))
                conn = self._pending.pop(name)
        finally:
            with self._cond:
                self._expected_tokens.pop(name, None)
                late = self._pending.pop(name, None)
            if late is not None:
                # registered between the timeout and the token purge:
                # nothing will ever consume this socket — close it
                try:
                    late.close()
                except OSError:
                    pass
        old = self._handles.get(name)
        if old is not None:
            old.close()
        self._handles[name] = _CCHandle(name, conn, proc)
        logger.info("chaincode %s registered (pid %s)", name, proc.pid)

    # -- execution FSM (handler.go) ------------------------------------------

    def execute(self, stub, name: str, fn: str, args: List[bytes]) -> bytes:
        handle = self._handles.get(name)
        if handle is None or not handle.alive():
            argv = self._launch_cmds.get(name)
            if argv is None:
                raise SimulationError(
                    f"chaincode {name!r} not launched and no launch "
                    "command known")
            logger.warning("chaincode %s dead; relaunching", name)
            self.launch(name, argv)
            handle = self._handles[name]
        with handle.lock:
            try:
                return self._drive(handle, stub, fn, args)
            except (OSError, ConnectionError, ValueError) as e:
                handle.close()
                self._handles.pop(name, None)
                raise SimulationError(
                    f"chaincode {name!r} stream failed: {e}") from e

    def _drive(self, handle: _CCHandle, stub, fn: str,
               args: List[bytes]) -> bytes:
        _send(handle.sock, {"type": "invoke", "txid": stub.txid or "",
                            "fn": fn, "args": [bytes(a) for a in args]})
        deadline = time.monotonic() + self.invoke_timeout_s
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise ConnectionError("invoke timeout")
            msg = _recv(handle.sock, timeout=left)
            t = msg.get("type")
            if t == "complete":
                return msg.get("payload", b"")
            if t == "error":
                raise SimulationError(str(msg.get("message", "chaincode "
                                                             "error")))
            if t == "ping":
                _send(handle.sock, {"type": "pong"})
            elif t == "get_state":
                v = stub.get_state(msg["key"])
                _send(handle.sock, {"type": "resp",
                                    "value": v if v is not None else b"",
                                    "found": v is not None})
            elif t == "put_state":
                stub.put_state(msg["key"], msg["value"])
                _send(handle.sock, {"type": "resp"})
            elif t == "del_state":
                stub.del_state(msg["key"])
                _send(handle.sock, {"type": "resp"})
            elif t == "range":
                items = [[k, v] for k, v in stub.get_state_by_range(
                    msg["start"], msg["end"], limit=int(msg.get("limit", 0)))]
                _send(handle.sock, {"type": "resp", "items": items})
            elif t == "get_private":
                v = stub.get_private_data(msg["collection"], msg["key"])
                _send(handle.sock, {"type": "resp",
                                    "value": v if v is not None else b"",
                                    "found": v is not None})
            elif t == "put_private":
                stub.put_private_data(msg["collection"], msg["key"],
                                      msg["value"])
                _send(handle.sock, {"type": "resp"})
            elif t == "del_private":
                stub.del_private_data(msg["collection"], msg["key"])
                _send(handle.sock, {"type": "resp"})
            elif t == "set_event":
                stub.set_event(msg["name"], msg["payload"])
                _send(handle.sock, {"type": "resp"})
            else:
                raise ConnectionError(f"unknown chaincode message {t!r}")

    def stop(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass
        for handle in self._handles.values():
            handle.close()
        self._handles.clear()


class ExtProcessContract(Contract):
    """Registry adapter: routes invoke() through a ChaincodeSupport-managed
    external process (the in-process registry stays the dev mode)."""

    def __init__(self, support: ChaincodeSupport, name: str,
                 argv: List[str]):
        self.support = support
        self.name = name
        self.argv = argv
        self._launched = False

    def invoke(self, stub, fn: str, args: List[bytes]) -> bytes:
        if not self._launched:
            self.support.launch(self.name, self.argv)
            self._launched = True
        return self.support.execute(stub, self.name, fn, args)


# ---------------------------------------------------------------------------
# chaincode-side shim
# ---------------------------------------------------------------------------

class ShimStub:
    """The chaincode process's view of the peer stub: every call is a
    callback message over the registration stream (handler.go FSM)."""

    def __init__(self, sock: socket.socket, txid: str):
        self._sock = sock
        self.txid = txid

    def _call(self, msg: dict) -> dict:
        _send(self._sock, msg)
        return _recv(self._sock, timeout=30.0)

    def get_state(self, key: str) -> Optional[bytes]:
        r = self._call({"type": "get_state", "key": key})
        return r["value"] if r.get("found") else None

    def put_state(self, key: str, value: bytes) -> None:
        self._call({"type": "put_state", "key": key, "value": value})

    def del_state(self, key: str) -> None:
        self._call({"type": "del_state", "key": key})

    def get_state_by_range(self, start: str, end: str, limit: int = 0):
        r = self._call({"type": "range", "start": start, "end": end,
                        "limit": limit})
        return [(k, v) for k, v in r.get("items", [])]

    def get_private_data(self, collection: str, key: str) -> Optional[bytes]:
        r = self._call({"type": "get_private", "collection": collection,
                        "key": key})
        return r["value"] if r.get("found") else None

    def put_private_data(self, collection: str, key: str,
                         value: bytes) -> None:
        self._call({"type": "put_private", "collection": collection,
                    "key": key, "value": value})

    def del_private_data(self, collection: str, key: str) -> None:
        self._call({"type": "del_private", "collection": collection,
                    "key": key})

    def set_event(self, name: str, payload: bytes) -> None:
        self._call({"type": "set_event", "name": name, "payload": payload})


def shim_main(contract, name: Optional[str] = None,
              sock_path: Optional[str] = None) -> None:
    """Chaincode process entry point: connect, register, serve invokes.

    `contract` is anything with invoke(stub, fn, args) -> bytes (the
    Contract interface) or a plain callable(stub, fn, args).
    """
    name = name or os.environ["FABRIC_TPU_CC_NAME"]
    sock_path = sock_path or os.environ["FABRIC_TPU_CC_SOCK"]
    token = os.environ.get("FABRIC_TPU_CC_TOKEN", "")
    invoke = (contract.invoke if hasattr(contract, "invoke") else contract)
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(sock_path)
    _send(sock, {"type": "register", "name": name, "token": token})
    msg = _recv(sock, timeout=10.0)
    if msg.get("type") != "registered":
        raise RuntimeError("registration rejected")
    while True:
        msg = _recv(sock, timeout=None)
        t = msg.get("type")
        if t == "ping":
            _send(sock, {"type": "pong"})
            continue
        if t != "invoke":
            continue
        stub = ShimStub(sock, msg.get("txid", ""))
        try:
            payload = invoke(stub, msg["fn"], list(msg.get("args", [])))
            _send(sock, {"type": "complete",
                         "payload": payload if payload else b""})
        except Exception as e:                     # noqa: BLE001
            _send(sock, {"type": "error", "message": str(e)})
