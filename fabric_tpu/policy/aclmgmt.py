"""aclmgmt — API-resource name -> channel-policy registry.

Reference parity: /root/reference/core/aclmgmt/aclmgmt.go:15 (the
ACLProvider interface CheckACL(resource, channel, idinfo)) and
core/aclmgmt/resources.go (the named-resource catalogue with default
policies).  The reference resolves a resource to a policy name through
the channel config's ACLs section (configurable by config tx,
sampleconfig/configtx.yaml Application.ACLs) falling back to hardcoded
defaults; this module does the same against ChannelConfig.acls
(fabric_tpu/config/channelconfig.py) — so an ACL change committed in a
config transaction changes authorization behavior at every consuming
call site with no code change.
"""

from __future__ import annotations

from typing import Dict, Optional

from .evaluator import PolicyEvaluator
from .policy import SignedData

# resource name -> default channel-policy name (resources.go defaults:
# proposals need Writers, queries need Readers, admin verbs need Admins)
DEFAULT_ACLS: Dict[str, str] = {
    "peer/Propose": "Writers",
    "peer/ChaincodeToChaincode": "Writers",
    "qscc/GetChainInfo": "Readers",
    "qscc/GetBlockByNumber": "Readers",
    "qscc/GetBlockByHash": "Readers",
    "qscc/GetTransactionByID": "Readers",
    "cscc/GetChannels": "Readers",
    "cscc/GetChannelConfig": "Readers",
    "cscc/JoinChain": "Admins",
    "discovery/Discover": "Readers",
    "event/Block": "Readers",
    "privdata/Fetch": "Readers",
    "participation/Join": "Admins",
    "participation/Remove": "Admins",
    "participation/List": "Admins",
    # NOTE: lifecycle/Install and lifecycle/QueryInstalled are PEER-
    # LOCAL operations gated against the local org's admin principal
    # (PeerNode._check_local_admin), not channel-config ACL mappings.
}


class ACLError(PermissionError):
    pass


class ACLProvider:
    """Evaluates a named API resource's policy against a SignedData.

    Bound to a BundleSource so config-tx ACL updates (and policy/MSP
    rotations) take effect at the block boundary, like every other
    consumer of the live bundle."""

    def __init__(self, bundle_source, provider):
        self.bundle_source = bundle_source
        self.provider = provider

    def policy_name(self, resource: str) -> Optional[str]:
        bundle = self.bundle_source.current()
        name = bundle.config.acls.get(resource)
        if name:
            return name
        return DEFAULT_ACLS.get(resource)

    def _policy(self, resource: str):
        name = self.policy_name(resource)
        if name is None:
            raise ACLError(f"{resource}: no ACL mapping")
        bundle = self.bundle_source.current()
        policy = bundle.config.policies.get(name)
        if policy is None:
            raise ACLError(f"{resource}: policy {name!r} not defined")
        return bundle, policy, name

    def check_acl(self, resource: str, sd: Optional[SignedData]) -> None:
        """Raises ACLError unless `sd` satisfies the resource's policy.

        Unknown resources and unresolvable policy names DENY (the
        reference fails closed, aclmgmt resource checks)."""
        if sd is None:
            raise ACLError(f"{resource}: no signed data")
        bundle, policy, name = self._policy(resource)
        evaluator = PolicyEvaluator(bundle.msps, self.provider)
        if not evaluator.evaluate_signed_data(policy, [sd]):
            raise ACLError(f"{resource}: signed data does not satisfy "
                           f"policy {name!r}")

    def check(self, resource: str, subject) -> None:
        """Polymorphic gate: SignedData -> signature-verified check;
        identity object/bytes -> handshake-authenticated check."""
        if subject is None:
            raise ACLError(f"{resource}: unauthenticated caller")
        if isinstance(subject, SignedData):
            return self.check_acl(resource, subject)
        if hasattr(subject, "serialize"):
            return self.check_identity(resource, subject.serialize())
        return self.check_identity(resource, subject)

    def check_identity(self, resource: str, identity_bytes) -> None:
        """check_acl for a HANDSHAKE-AUTHENTICATED caller: the RPC plane
        already proved possession of the identity's key (comm/secure.py
        handshake binding), so the resource policy is evaluated over the
        identity's principals without a per-request signature — the slot
        the reference fills by evaluating ACLs against the mTLS/creator
        identity."""
        if identity_bytes is None:
            raise ACLError(f"{resource}: unauthenticated caller")
        bundle, policy, name = self._policy(resource)
        from fabric_tpu.msp import deserialize_from_msps
        ident = deserialize_from_msps(bundle.msps, bytes(identity_bytes),
                                      validate=True)
        if ident is None:
            raise ACLError(f"{resource}: unknown caller identity")
        evaluator = PolicyEvaluator(bundle.msps, self.provider)
        if not evaluator.evaluate(policy, [ident]):
            raise ACLError(f"{resource}: caller does not satisfy "
                           f"policy {name!r}")
