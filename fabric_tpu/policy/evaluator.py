"""Verify-then-gate policy evaluation — the north-star restructure.

Reference flow being restructured (SURVEY.md §3.2):
  policies/policy.go:365-401 SignatureSetToValidIdentities
    - deserialize each SignedData identity, DEDUP by identity
      (policy.go:385-387),
    - Verify() each signature immediately (policy.go:389-393; a bad
      signature only excludes that identity, it is not fatal),
  cauthdsl/cauthdsl.go:24-92 compiled NOutOf/SignedBy evaluation with
      greedy used-once identity consumption.

Here the same decision logic is split into:
  collect()  : produce dedup'd VerifyItems (no crypto),
  [provider.batch_verify over an entire block — ONE TPU dispatch],
  gate()     : keep identities whose verdict bit is set,
  evaluate() : the exact cauthdsl greedy semantics over valid identities.
`evaluate_signed_data` composes all three for single-policy use.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from fabric_tpu.bccsp import VerifyItem
from fabric_tpu.msp import Identity, Principal
from fabric_tpu.utils import serde
from .policy import PolicyError, SignaturePolicy, SignedData


@dataclass
class CollectResult:
    """Dedup'd verification workload for one signature set."""
    items: List[VerifyItem] = field(default_factory=list)
    identities: List[Identity] = field(default_factory=list)  # aligned w/ items

    def __len__(self):
        return len(self.items)


class PolicyEvaluator:
    """Binds an MSP routing table + crypto provider to policy logic.

    msps: mspid -> MSP-like (must expose deserialize_identity and
    satisfies_principal; CachedMSP recommended).
    """

    def __init__(self, msps: Dict[str, object], provider):
        self.msps = msps
        self.provider = provider

    # -- pass 1: collect ----------------------------------------------------

    def collect(self, signed_data: Sequence[SignedData]) -> CollectResult:
        """Deserialize + dedup identities, emit VerifyItems (no crypto)."""
        out = CollectResult()
        seen = set()
        for sd in signed_data:
            if sd.identity in seen:  # policy.go:385-387 dedup rule
                continue
            seen.add(sd.identity)
            try:
                # cheap route on the serialized envelope's mspid, then ONE
                # (cached) full deserialization in the owning MSP
                mspid = serde.decode(sd.identity).get("mspid")
                msp = self.msps.get(mspid)
                if msp is None:
                    continue
                ident = msp.deserialize_identity(sd.identity)
            except Exception:
                continue  # undeserializable identity is skipped, not fatal
            out.items.append(ident.verify_item(sd.data, sd.signature))
            out.identities.append(ident)
        return out

    # -- pass 2 happens in the provider (batched) ---------------------------

    # -- pass 3: gate + evaluate --------------------------------------------

    @staticmethod
    def gate(collected: CollectResult, verdicts: np.ndarray) -> List[Identity]:
        """Identities whose signatures verified (policy.go:390-393: invalid
        signatures only exclude, never fail the set)."""
        return [ident for ident, ok in zip(collected.identities, verdicts) if ok]

    def evaluate(self, policy: SignaturePolicy,
                 identities: Sequence[Identity]) -> bool:
        """cauthdsl.go:24-92 compiled semantics: greedy, used-once."""
        used = [False] * len(identities)
        return self._eval(policy, identities, used)

    def _eval(self, node: SignaturePolicy, idents, used) -> bool:
        if node.kind == "signed_by":
            p = node.principal
            msp = self.msps.get(p.mspid) if p.mspid else None
            for i, ident in enumerate(idents):
                if used[i]:
                    continue
                target = msp if msp is not None else self.msps.get(ident.mspid)
                if target is None:
                    continue
                if target.satisfies_principal(ident, p):
                    used[i] = True
                    return True
            return False
        if node.kind == "n_out_of":
            # cauthdsl.go:44-58: ALL rules are evaluated (no early exit) and
            # every satisfied rule commits its identity consumption — a
            # satisfied OR branch consumes identities that outer rules then
            # cannot reuse.  Bit-identical verdicts require this exactly.
            satisfied = 0
            for rule in node.rules:
                snapshot = list(used)
                if self._eval(rule, idents, used):
                    satisfied += 1
                else:
                    used[:] = snapshot  # failed branch consumes nothing
            return satisfied >= node.n
        raise PolicyError(f"unknown node kind {node.kind!r}")

    # -- one-shot composition ----------------------------------------------

    def evaluate_signed_data(self, policy: SignaturePolicy,
                             signed_data: Sequence[SignedData]) -> bool:
        collected = self.collect(signed_data)
        if not collected.items:
            return self.evaluate(policy, [])
        verdicts = self.provider.batch_verify(collected.items)
        return self.evaluate(policy, self.gate(collected, verdicts))
