"""Policy plane: signature policies, compiled evaluators, verify-then-gate.

Re-design of /root/reference/common/cauthdsl + common/policies:
- the NOutOf/SignedBy policy tree and its compiled evaluator
  (cauthdsl/cauthdsl.go:24-92),
- the Fabric policy-expression language AND()/OR()/OutOf()
  (cauthdsl/policyparser.go),
- SignedData evaluation (policies/policy.go:282 EvaluateSignedData).

The TPU-native restructure (SURVEY.md §7, north star): signature
verification is SPLIT OUT of evaluation.  `collect()` walks signature sets
and produces dedup'd VerifyItems; the batched provider verifies them all in
one dispatch; `evaluate()` then re-runs the exact reference decision logic
(dedup-by-identity, greedy used-once NOutOf semantics) consuming the
verdict bitmap instead of calling ECDSA per endorsement.
"""

from .policy import SignedData, PolicyError, SignaturePolicy, signed_by, n_out_of
from .dsl import parse_policy
from .evaluator import PolicyEvaluator, CollectResult
from .aclmgmt import ACLError, ACLProvider, DEFAULT_ACLS

__all__ = ["SignedData", "PolicyError", "SignaturePolicy", "signed_by",
           "n_out_of", "parse_policy", "PolicyEvaluator", "CollectResult"]
