"""Fabric policy-expression language parser.

Parity: /root/reference/common/cauthdsl/policyparser.go — expressions like
  AND('Org1.member', 'Org2.member')
  OR('Org1.admin', AND('Org2.peer', 'Org3.member'))
  OutOf(2, 'Org1.member', 'Org2.member', 'Org3.member')
Roles: member | admin | client | peer | orderer (client/peer/orderer are
treated as member-grade roles here; OU-based role refinement arrives with
NodeOUs).
"""

from __future__ import annotations

import ast

from fabric_tpu.msp import Principal
from .policy import PolicyError, SignaturePolicy, n_out_of, signed_by

_ROLES = {"member", "admin", "client", "peer", "orderer"}


def parse_policy(expr: str) -> SignaturePolicy:
    """Parse a policy expression string into a SignaturePolicy tree."""
    try:
        tree = ast.parse(expr.strip(), mode="eval")
    except SyntaxError as e:
        raise PolicyError(f"bad policy expression: {e}") from e
    return _conv(tree.body)


def _principal_from_str(s: str) -> Principal:
    if "." not in s:
        raise PolicyError(f"principal {s!r} must be 'MSPID.role'")
    mspid, role = s.rsplit(".", 1)
    if role not in _ROLES:
        raise PolicyError(f"unknown role {role!r} in {s!r}")
    if role == "admin":
        return Principal.admin(mspid)
    return Principal.member(mspid)


def _conv(node) -> SignaturePolicy:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return signed_by(_principal_from_str(node.value))
    if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
        raise PolicyError("expected AND()/OR()/OutOf() call or 'MSP.role' string")
    name = node.func.id.upper()
    if name == "AND":
        rules = [_conv(a) for a in node.args]
        if not rules:
            raise PolicyError("AND() needs at least one argument")
        return n_out_of(len(rules), rules)
    if name == "OR":
        rules = [_conv(a) for a in node.args]
        if not rules:
            raise PolicyError("OR() needs at least one argument")
        return n_out_of(1, rules)
    if name == "OUTOF":
        if len(node.args) < 2 or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, int):
            raise PolicyError("OutOf(n, rule, ...) needs an int then rules")
        n = node.args[0].value
        rules = [_conv(a) for a in node.args[1:]]
        return n_out_of(n, rules)
    raise PolicyError(f"unknown combinator {node.func.id!r}")
