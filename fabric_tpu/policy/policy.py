"""Signature-policy AST and SignedData (protoutil/signeddata.go:21 parity)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Union

from fabric_tpu.msp import Principal
from fabric_tpu.utils import serde


class PolicyError(Exception):
    pass


@dataclass(frozen=True)
class SignedData:
    """The (data, identity, signature) triple every policy evaluates over."""
    data: bytes
    identity: bytes   # serialized Identity
    signature: bytes


@dataclass(frozen=True)
class SignaturePolicy:
    """A node of the policy tree: either a SignedBy leaf or an NOutOf gate.

    kind: "signed_by" (principal set) | "n_out_of" (n, rules)
    """
    kind: str
    principal: Optional[Principal] = None
    n: int = 0
    rules: tuple = ()

    def to_dict(self) -> dict:
        if self.kind == "signed_by":
            p = self.principal
            return {"kind": "signed_by",
                    "principal": {"pkind": p.kind, "mspid": p.mspid,
                                  "role": p.role, "org_unit": p.org_unit,
                                  "identity_bytes": p.identity_bytes}}
        return {"kind": "n_out_of", "n": self.n,
                "rules": [r.to_dict() for r in self.rules]}

    @staticmethod
    def from_dict(d: dict) -> "SignaturePolicy":
        if d["kind"] == "signed_by":
            pd = d["principal"]
            return signed_by(Principal(pd["pkind"], mspid=pd["mspid"],
                                       role=pd["role"], org_unit=pd["org_unit"],
                                       identity_bytes=pd["identity_bytes"]))
        return n_out_of(d["n"], [SignaturePolicy.from_dict(r) for r in d["rules"]])

    def serialize(self) -> bytes:
        return serde.encode(self.to_dict())

    @staticmethod
    def deserialize(data: bytes) -> "SignaturePolicy":
        return SignaturePolicy.from_dict(serde.decode(data))


def signed_by(principal: Principal) -> SignaturePolicy:
    return SignaturePolicy("signed_by", principal=principal)


def n_out_of(n: int, rules: List[SignaturePolicy]) -> SignaturePolicy:
    if n < 0 or n > len(rules):
        raise PolicyError(f"NOutOf({n}) with {len(rules)} rules")
    return SignaturePolicy("n_out_of", n=n, rules=tuple(rules))
