"""Per-block transaction validation-code bitmap.

Reference parity: internal/pkg/txflags/validation_flags.go and the
TxValidationCode enum from fabric-protos.  The committer writes this
bitmap into block metadata (validator.go:214-260) and the ledger treats
code==VALID as the commit predicate.
"""

from __future__ import annotations

import enum
from typing import Iterable, List


class ValidationCode(enum.IntEnum):
    VALID = 0
    NIL_ENVELOPE = 1
    BAD_PAYLOAD = 2
    BAD_COMMON_HEADER = 3
    BAD_CREATOR_SIGNATURE = 4
    INVALID_ENDORSER_TRANSACTION = 5
    INVALID_CONFIG_TRANSACTION = 6
    UNSUPPORTED_TX_PAYLOAD = 7
    BAD_PROPOSAL_TXID = 8
    DUPLICATE_TXID = 9
    ENDORSEMENT_POLICY_FAILURE = 10
    MVCC_READ_CONFLICT = 11
    PHANTOM_READ_CONFLICT = 12
    UNKNOWN_TX_TYPE = 13
    TARGET_CHAIN_NOT_FOUND = 14
    MARSHAL_TX_ERROR = 15
    NIL_TXACTION = 16
    EXPIRED_CHAINCODE = 17
    CHAINCODE_VERSION_CONFLICT = 18
    BAD_HEADER_EXTENSION = 19
    BAD_CHANNEL_HEADER = 20
    BAD_RESPONSE_PAYLOAD = 21
    BAD_RWSET = 22
    ILLEGAL_WRITESET = 23
    INVALID_WRITESET = 24
    INVALID_CHAINCODE = 25
    NOT_VALIDATED = 254
    INVALID_OTHER_REASON = 255


class TxFlags:
    """Mutable per-block validation bitmap (txflags.ValidationFlags)."""

    def __init__(self, n: int, fill: ValidationCode = ValidationCode.NOT_VALIDATED):
        self._codes: List[int] = [int(fill)] * n

    @staticmethod
    def from_codes(codes: Iterable[int]) -> "TxFlags":
        f = TxFlags(0)
        f._codes = [int(c) for c in codes]
        return f

    def __len__(self) -> int:
        return len(self._codes)

    def set(self, i: int, code: ValidationCode) -> None:
        self._codes[i] = int(code)

    def flag(self, i: int) -> ValidationCode:
        return ValidationCode(self._codes[i])

    def is_valid(self, i: int) -> bool:
        return self._codes[i] == int(ValidationCode.VALID)

    def is_set_to(self, i: int, code: ValidationCode) -> bool:
        return self._codes[i] == int(code)

    def all_validated(self) -> bool:
        return all(c != int(ValidationCode.NOT_VALIDATED) for c in self._codes)

    def valid_count(self) -> int:
        return sum(1 for c in self._codes if c == int(ValidationCode.VALID))

    def codes(self) -> List[int]:
        return list(self._codes)

    def to_bytes(self) -> bytes:
        return bytes(self._codes)

    @staticmethod
    def from_bytes(data: bytes) -> "TxFlags":
        return TxFlags.from_codes(data)
