"""Zero-copy wire views: lazy Block/Envelope access over raw frame bytes.

The committer's deliver path and the gateway's submit path both used to
turn every received frame into a full Python object tree
(Block.deserialize -> per-envelope bytes -> per-field dataclasses)
before any validation ran.  native/fastparse.c extracts the byte SPANS
those paths actually touch — envelope positions, header fields, the
metadata splice point — in one C walk, and this module wraps them:

  parse_block(raw)      -> BlockView (native parse) | Block (fallback)
  BlockView             duck-types Block for every consumer on the
                        covered path; materializes .data / .metadata
                        lazily only when a consumer truly needs Python
                        objects (MVCC, config handling)
  envelope_summary(raw) -> (type, channel_id, txid) | None — the gateway
                        header peek, no Envelope/Header trees
  parse_block_py / envelope_summary_py
                        pure-Python line-for-line mirrors of the native
                        accept/reject decisions and extracted fields,
                        used by the differential fuzz suite
  n_txs(block)          len(block.data) without forcing a BlockView to
                        materialize its envelope list

Fallback semantics: the native parser accepts EXACTLY the strict
canonical block shape; anything else (including every malformed input)
returns None and parse_block falls back to Block.deserialize, so
accept/reject behavior — down to the exception raised — is unchanged
from the pure-Python path.  A BlockView is only ever produced for bytes
Block.deserialize would have accepted.

Key layout fact (fabric_tpu/utils/serde.py): block encodings are
canonical dicts with sorted keys data < header < metadata.  So the data
LIST's value span inside the raw bytes IS serde.encode(list(data)) —
sha256 over it equals block_data_hash(block.data) — and metadata is the
LAST value, so a metadata-mutated block re-serializes as
raw[:meta_val_off] + serde.encode(metadata), a pure splice.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Any, Dict, List, Optional, Tuple, Union

from fabric_tpu.utils import serde
from fabric_tpu.protocol.types import (
    Block,
    BlockHeader,
    BlockMetadata,
    Envelope,
    block_header_hash,
)

try:
    from fabric_tpu import native as _native_pkg
    _fastparse = _native_pkg.load("_fastparse")
except Exception:  # pragma: no cover - broken toolchain
    _fastparse = None

_Raw = Union[bytes, bytearray, memoryview]


class BlockView:
    """A Block over raw wire bytes; Python objects are built on demand.

    Cheap always: .header, .n_data, .raw, .data_spans, .computed_data_hash,
    .hash(), .serialize() (identity until .metadata is touched).
    Materializing: .data (full envelope bytes list, cached), .metadata
    (decoded dict, cached — after first access serialize() re-splices,
    which is bit-identical for unmutated metadata by serde bijection).
    """

    __slots__ = ("raw", "header", "n_data", "_data_off", "_data_end",
                 "_spans", "_meta_off", "_data", "_metadata", "_dhash")

    def __init__(self, raw: _Raw, number: int, previous_hash: bytes,
                 data_hash: bytes, data_off: int, data_end: int,
                 n_data: int, spans, meta_off: int):
        self.raw = raw
        self.header = BlockHeader(number, previous_hash, data_hash)
        self.n_data = n_data
        self._data_off = data_off
        self._data_end = data_end
        self._spans = spans
        self._meta_off = meta_off
        self._data: Optional[List[bytes]] = None
        self._metadata: Optional[BlockMetadata] = None
        self._dhash: Optional[bytes] = None

    # -- covered-path accessors (no per-tx objects) ---------------------

    @property
    def data_spans(self):
        """(base, spans) pair for _fastcollect.digest_spans."""
        return self.raw, self._spans

    @property
    def rwset_lanes(self):
        """Fixed-width uint64 validation lanes for the fused device
        program: (flags, n_tx, n_keys, n_reads, n_writes, arena) —
        see rwset_lanes() below.  Zero-copy like data_spans: no
        per-tx Python objects are built."""
        return rwset_lanes(self.raw, self._spans)

    @property
    def computed_data_hash(self) -> bytes:
        """sha256 over the data list's value span ==
        block_data_hash(self.data), computed without materializing."""
        if self._dhash is None:
            self._dhash = hashlib.sha256(
                self.raw[self._data_off:self._data_end]).digest()
        return self._dhash

    def hash(self) -> bytes:
        return block_header_hash(self.header)

    def serialize(self) -> _Raw:
        if self._metadata is None:
            return self.raw
        return (bytes(self.raw[:self._meta_off])
                + serde.encode(self._metadata.to_dict()))

    # -- materializing accessors ---------------------------------------

    @property
    def data(self) -> List[bytes]:
        if self._data is None:
            raw = self.raw
            tab = memoryview(self._spans).cast("Q")
            self._data = [bytes(raw[tab[2 * i]:tab[2 * i] + tab[2 * i + 1]])
                          for i in range(self.n_data)]
        return self._data

    @property
    def metadata(self) -> BlockMetadata:
        if self._metadata is None:
            md = serde.decode(bytes(self.raw[self._meta_off:]))
            self._metadata = BlockMetadata.from_dict(md)
        return self._metadata

    def envelopes(self) -> List[Envelope]:
        return [Envelope.deserialize(b) for b in self.data]

    def to_dict(self) -> dict:
        return {"header": self.header.to_dict(), "data": list(self.data),
                "metadata": self.metadata.to_dict()}

    def to_block(self) -> Block:
        return Block(self.header, list(self.data), self.metadata)


def parse_block(raw: _Raw) -> Union[BlockView, Block]:
    """Wire bytes -> BlockView (native fast path) or Block (fallback).

    Raises exactly what Block.deserialize raises for bytes neither
    accepts; never raises for bytes Block.deserialize accepts.
    """
    if _fastparse is not None:
        r = _fastparse.parse_block(raw)
        if r is not None:
            return BlockView(raw, *r)
    return Block.deserialize(raw)


def n_txs(block) -> int:
    """len(block.data) without forcing a BlockView to materialize."""
    n = getattr(block, "n_data", None)
    return len(block.data) if n is None else n


def envelope_summary(raw: _Raw) -> Optional[Tuple[str, str, str]]:
    """(type, channel_id, txid) of a serialized Envelope, or None when
    the bytes deviate from the strict shape (caller falls back to the
    Envelope.deserialize path, preserving its exact error behavior)."""
    if _fastparse is None:
        return None
    return _fastparse.envelope_summary(raw)


# ---------------------------------------------------------------------------
# pure-Python mirrors — the differential-fuzz reference implementations.
# Native accept/reject and every extracted field must match these
# byte-for-byte (tests/test_fastparse.py); like collect_py they are the
# plain-language statement of what the C walk does.


def parse_block_py(raw: _Raw):
    """Mirror of _fastparse.parse_block: (number, previous_hash,
    data_hash, data list, metadata dict, meta_val_off) or None."""
    try:
        d = serde.decode_py(bytes(raw))
    except Exception:
        return None
    if not isinstance(d, dict) or sorted(d) != ["data", "header", "metadata"]:
        return None
    h = d["header"]
    if (not isinstance(h, dict)
            or sorted(h) != ["data_hash", "number", "previous_hash"]):
        return None
    number = h["number"]
    # native reads a fixed 'I' i64; bignum ('V') numbers fall back
    if (not isinstance(number, int) or isinstance(number, bool)
            or not -(2 ** 63) <= number < 2 ** 63):
        return None
    if not isinstance(h["previous_hash"], bytes):
        return None
    if not isinstance(h["data_hash"], bytes):
        return None
    if not isinstance(d["data"], list):
        return None
    for item in d["data"]:
        if not isinstance(item, bytes):
            return None
    if not isinstance(d["metadata"], dict):
        return None
    # metadata is the top dict's last key: its value span runs to the end
    meta_off = len(bytes(raw)) - len(serde.encode_py(d["metadata"]))
    return (number, h["previous_hash"], h["data_hash"], d["data"],
            d["metadata"], meta_off)


# ---------------------------------------------------------------------------
# rw-set validation lanes (device-resident block validation)
#
# rwset_lanes(base, spans) classifies every envelope span against the
# exact semantics of ledger/mvcc.parse_endorser_tx and emits fixed-width
# uint64 lane tables for the fused XLA gate+MVCC program
# (committer/device_validate.py).  Statuses:
#
#   0 OK       strict endorser tx, lanes emitted
#   1 SKIP     parse_endorser_tx provably returns None
#   2 BAD      parse_endorser_tx provably raises (oracle stamps
#              BAD_RWSET on a gate-valid tx)
#   3 RANGE    endorser tx with a non-empty range_queries list
#   4 UNKNOWN  host outcome deterministic but device-inexpressible
#
# Result tuple (flags, n_tx, n_keys, n_reads, n_writes, arena):
#   flags  0 ok | 1 key-hash collision (arena is None; caller demotes)
#   arena  native-endian u64 cells in four sections
#          tx      n_tx    x 3  [status, txid_off, txid_len]
#          reads   n_reads x 5  [tx, slot, has_version, block, txn]
#          writes  n_writes x 5 [tx, slot, is_delete, value_off, value_len]
#          keys    n_keys  x 5  [hash, ns_off, ns_len, key_off, key_len]
# or None when spans is not a valid span table over base.

LANE_OK, LANE_SKIP, LANE_BAD, LANE_RANGE, LANE_UNKNOWN = 0, 1, 2, 3, 4


def rwset_lanes(base: _Raw, spans) -> Optional[tuple]:
    """Native lane extraction when available, else the Python mirror."""
    if _fastparse is not None:
        return _fastparse.rwset_lanes(base, spans)
    return rwset_lanes_py(base, spans)


def envelope_summary_py(raw: _Raw) -> Optional[Tuple[str, str, str]]:
    """Mirror of _fastparse.envelope_summary."""
    try:
        d = serde.decode_py(bytes(raw))
        if not isinstance(d, dict) or "payload" not in d or "signature" not in d:
            return None
        payload = d["payload"]
        if not isinstance(payload, bytes):
            return None
        p = serde.decode_py(payload)
        header = p["header"]
        ch = header["channel_header"]
        sh = header["signature_header"]
        if not isinstance(ch, dict) or not isinstance(sh, dict):
            return None
        if "creator" not in sh or "nonce" not in sh:
            return None
        t, cid, txid = ch["type"], ch["channel_id"], ch["txid"]
        if not (isinstance(t, str) and isinstance(cid, str)
                and isinstance(txid, str)):
            return None
        return (t, cid, txid)
    except Exception:
        return None


# -- rwset_lanes mirror ------------------------------------------------------
# Line-for-line mirror of the C lane extractor (native/fastparse.c
# py_rwset_lanes and its walk_* helpers).  Every status decision and
# every emitted cell must match the native output byte-for-byte
# (tests/test_device_validate.py drives them differentially); it is
# also the no-compiler fallback wired through rwset_lanes() above.

_M64 = (1 << 64) - 1


class _LaneStat(Exception):
    """Terminal per-envelope lane status (first terminal wins)."""

    def __init__(self, st: int):
        self.st = st


class _LaneColl(Exception):
    """Two distinct rw keys share a hash: the whole call demotes."""


class _LaneCur:
    """Byte cursor over the base buffer (mirror of the C cur_t)."""

    __slots__ = ("b", "p", "end")

    def __init__(self, b: bytes, p: int, end: int):
        self.b = b
        self.p = p
        self.end = end


class _LaneState:
    """Per-call lane accumulators (mirror of the C module globals)."""

    __slots__ = ("base", "reads", "writes", "keys", "by_hash")

    def __init__(self, base: bytes):
        self.base = base
        self.reads: list = []
        self.writes: list = []
        self.keys: list = []
        self.by_hash: dict = {}

    def intern(self, ns_off, ns_len, key_off, key_len) -> int:
        base = self.base
        h = 5381
        for byte in base[ns_off:ns_off + ns_len]:
            h = (h * 33 + byte) & _M64
        h = (h * 33) & _M64            # the 0x00 ns/key separator
        for byte in base[key_off:key_off + key_len]:
            h = (h * 33 + byte) & _M64
        rec = self.by_hash.get(h)
        if rec is not None:
            slot, noff, nlen, koff, klen = rec
            if (nlen == ns_len and klen == key_len
                    and base[noff:noff + nlen] == base[ns_off:ns_off + ns_len]
                    and base[koff:koff + klen]
                    == base[key_off:key_off + key_len]):
                return slot
            raise _LaneColl()
        slot = len(self.keys)
        self.keys.append((h, ns_off, ns_len, key_off, key_len))
        self.by_hash[h] = (slot, ns_off, ns_len, key_off, key_len)
        return slot


def _lane_u32(c: _LaneCur) -> int:
    if c.end - c.p < 4:
        raise _LaneStat(LANE_BAD)
    v = int.from_bytes(c.b[c.p:c.p + 4], "big")
    c.p += 4
    return v


def _lane_i64(c: _LaneCur):
    """rd_i64 mirror: None on non-'I' tag / truncation, else the int."""
    if c.p >= c.end or c.b[c.p] != 0x49 or c.end - c.p < 10:
        return None
    v = int.from_bytes(c.b[c.p + 1:c.p + 9], "big", signed=True)
    c.p += 9
    return v


def _lane_str(c: _LaneCur):
    """rd_str mirror: (off, len) span of an 'S' value, BAD otherwise."""
    if c.p >= c.end or c.b[c.p] != 0x53:
        raise _LaneStat(LANE_BAD)
    c.p += 1
    n = _lane_u32(c)
    if c.end - c.p < n:
        raise _LaneStat(LANE_BAD)
    try:
        c.b[c.p:c.p + n].decode("utf-8")
    except UnicodeDecodeError:
        raise _LaneStat(LANE_BAD) from None
    off = c.p
    c.p += n
    return off, n


def _lane_bytes(c: _LaneCur):
    """rd_bytes mirror: (off, len) span of a 'B' value, BAD otherwise."""
    if c.p >= c.end or c.b[c.p] != 0x42:
        raise _LaneStat(LANE_BAD)
    c.p += 1
    n = _lane_u32(c)
    if c.end - c.p < n:
        raise _LaneStat(LANE_BAD)
    off = c.p
    c.p += n
    return off, n


def _lane_canon(c: _LaneCur, depth: int) -> None:
    """canon_value_d mirror: skip one strict-canonical value or BAD."""
    if depth > serde.MAX_DEPTH or c.p >= c.end:
        raise _LaneStat(LANE_BAD)
    tag = c.b[c.p]
    c.p += 1
    if tag in (0x4E, 0x54, 0x46):              # N T F
        return
    if tag == 0x49:                            # I
        if c.end - c.p < 8:
            raise _LaneStat(LANE_BAD)
        c.p += 8
        return
    if tag == 0x56:                            # V
        n = _lane_u32(c)
        if (c.end - c.p < n or n < 8 or c.b[c.p] == 0
                or (n == 8 and c.b[c.p] < 0x80)):
            raise _LaneStat(LANE_BAD)
        c.p += n
        return
    if tag == 0x42:                            # B
        n = _lane_u32(c)
        if c.end - c.p < n:
            raise _LaneStat(LANE_BAD)
        c.p += n
        return
    if tag == 0x53:                            # S
        c.p -= 1
        _lane_str(c)
        return
    if tag == 0x4C:                            # L
        n = _lane_u32(c)
        for _ in range(n):
            _lane_canon(c, depth + 1)
        return
    if tag == 0x44:                            # D
        n = _lane_u32(c)
        prev = [None]
        for _ in range(n):
            _lane_dict_key(c, prev)
            _lane_canon(c, depth + 1)
        return
    raise _LaneStat(LANE_BAD)


def _lane_dict_enter(c: _LaneCur) -> int:
    if c.p >= c.end or c.b[c.p] != 0x44:
        raise _LaneStat(LANE_BAD)
    c.p += 1
    return _lane_u32(c)


def _lane_dict_key(c: _LaneCur, prev: list) -> bytes:
    kn = _lane_u32(c)
    if c.end - c.p < kn:
        raise _LaneStat(LANE_BAD)
    k = c.b[c.p:c.p + kn]
    c.p += kn
    try:
        k.decode("utf-8")
    except UnicodeDecodeError:
        raise _LaneStat(LANE_BAD) from None
    if prev[0] is not None and prev[0] >= k:
        raise _LaneStat(LANE_BAD)
    prev[0] = k
    return k


def _lane_dict_find(c: _LaneCur, want: bytes):
    """dict_find mirror: value span (off, end) or None; BAD on
    malformation.  Canon-validates the full dict either way."""
    n = _lane_dict_enter(c)
    prev = [None]
    found = None
    for _ in range(n):
        k = _lane_dict_key(c, prev)
        vstart = c.p
        _lane_canon(c, 1)
        if k == want:
            found = (vstart, c.p)
    return found


def _lane_version(c: _LaneCur):
    if c.p >= c.end:
        raise _LaneStat(LANE_BAD)
    tag = c.b[c.p]
    if tag == 0x4E:                            # N: absent version
        c.p += 1
        return 0, 0, 0
    if tag != 0x4C:
        raise _LaneStat(LANE_UNKNOWN)
    c.p += 1
    n = _lane_u32(c)
    if n < 2:
        raise _LaneStat(LANE_BAD)              # v[0]/v[1] IndexError
    v0 = _lane_i64(c)
    if v0 is None or not -(2 ** 31) <= v0 <= 2 ** 31 - 1:
        raise _LaneStat(LANE_UNKNOWN)
    v1 = _lane_i64(c)
    if v1 is None or not -(2 ** 31) <= v1 <= 2 ** 31 - 1:
        raise _LaneStat(LANE_UNKNOWN)
    for _ in range(n - 2):
        _lane_canon(c, 1)
    return 1, v0 & _M64, v1 & _M64


def _lane_read(c: _LaneCur, st: _LaneState, emit: bool, tx: int,
               ns_off: int, ns_len: int) -> None:
    n = _lane_dict_enter(c)
    prev = [None]
    key_off = key_len = 0
    has = blk = txn = 0
    have_key = False
    for _ in range(n):
        k = _lane_dict_key(c, prev)
        if k == b"key":
            if c.p >= c.end or c.b[c.p] != 0x53:
                raise _LaneStat(LANE_UNKNOWN)
            key_off, key_len = _lane_str(c)
            have_key = True
        elif k == b"version":
            has, blk, txn = _lane_version(c)
        else:
            _lane_canon(c, 1)
    if not have_key:
        raise _LaneStat(LANE_BAD)
    if emit:
        slot = st.intern(ns_off, ns_len, key_off, key_len)
        st.reads.append((tx, slot, has, blk, txn))


def _lane_write(c: _LaneCur, st: _LaneState, emit: bool, tx: int,
                ns_off: int, ns_len: int) -> None:
    n = _lane_dict_enter(c)
    prev = [None]
    key_off = key_len = 0
    delete = voff = vlen = 0
    have_key = False
    for _ in range(n):
        k = _lane_dict_key(c, prev)
        if k == b"key":
            if c.p >= c.end or c.b[c.p] != 0x53:
                raise _LaneStat(LANE_UNKNOWN)
            key_off, key_len = _lane_str(c)
            have_key = True
        elif k == b"is_delete":
            if c.p >= c.end:
                raise _LaneStat(LANE_BAD)
            if c.b[c.p] == 0x54:               # T
                delete = 1
            elif c.b[c.p] == 0x46:             # F
                delete = 0
            else:
                raise _LaneStat(LANE_UNKNOWN)  # truthy non-bool
            c.p += 1
        elif k == b"value":
            if c.p >= c.end or c.b[c.p] != 0x42:
                raise _LaneStat(LANE_UNKNOWN)
            voff, vlen = _lane_bytes(c)
        else:
            _lane_canon(c, 1)
    if not have_key:
        raise _LaneStat(LANE_BAD)
    if emit:
        slot = st.intern(ns_off, ns_len, key_off, key_len)
        st.writes.append((tx, slot, delete, voff, vlen))


def _lane_ns(c: _LaneCur, st: _LaneState, emit: bool, tx: int) -> bool:
    """One NsRwSet dict; True when a non-empty range_queries list was
    seen (caller escalates the whole envelope to RANGE)."""
    n = _lane_dict_enter(c)
    prev = [None]
    ns_off = ns_len = 0
    have_ns = have_reads = have_writes = saw_range = False
    for _ in range(n):
        k = _lane_dict_key(c, prev)
        if k == b"namespace":
            if c.p >= c.end or c.b[c.p] != 0x53:
                raise _LaneStat(LANE_UNKNOWN)
            ns_off, ns_len = _lane_str(c)
            have_ns = True
        elif k == b"reads":
            if not have_ns:
                raise _LaneStat(LANE_BAD)
            if c.p >= c.end or c.b[c.p] != 0x4C:
                raise _LaneStat(LANE_UNKNOWN)
            c.p += 1
            for _ in range(_lane_u32(c)):
                _lane_read(c, st, emit, tx, ns_off, ns_len)
            have_reads = True
        elif k == b"writes":
            if not have_ns:
                raise _LaneStat(LANE_BAD)
            if c.p >= c.end or c.b[c.p] != 0x4C:
                raise _LaneStat(LANE_UNKNOWN)
            c.p += 1
            for _ in range(_lane_u32(c)):
                _lane_write(c, st, emit, tx, ns_off, ns_len)
            have_writes = True
        elif k == b"range_queries":
            if c.p >= c.end or c.b[c.p] != 0x4C:
                raise _LaneStat(LANE_UNKNOWN)
            peek = _LaneCur(c.b, c.p + 1, c.end)
            qn = _lane_u32(peek)
            _lane_canon(c, 1)
            if qn > 0:
                saw_range = True
        else:
            _lane_canon(c, 1)
    if not (have_ns and have_reads and have_writes):
        raise _LaneStat(LANE_BAD)
    return saw_range


def _lane_rwset(c: _LaneCur, st: _LaneState, emit: bool, tx: int) -> None:
    n = _lane_dict_enter(c)
    prev = [None]
    saw_range = False
    have_ns_list = False
    for _ in range(n):
        k = _lane_dict_key(c, prev)
        if k == b"ns":
            if c.p >= c.end or c.b[c.p] != 0x4C:
                raise _LaneStat(LANE_UNKNOWN)
            c.p += 1
            for _ in range(_lane_u32(c)):
                if _lane_ns(c, st, emit, tx):
                    saw_range = True
            have_ns_list = True
        else:
            _lane_canon(c, 1)
    if not have_ns_list:
        raise _LaneStat(LANE_BAD)
    if saw_range:
        raise _LaneStat(LANE_RANGE)


def _lane_endorsement(c: _LaneCur) -> None:
    n = _lane_dict_enter(c)
    prev = [None]
    have_e = have_s = False
    for _ in range(n):
        k = _lane_dict_key(c, prev)
        if k == b"endorser":
            have_e = True
        elif k == b"signature":
            have_s = True
        _lane_canon(c, 1)
    if not (have_e and have_s):
        raise _LaneStat(LANE_BAD)


def _lane_cc_action(c: _LaneCur, st: _LaneState, emit: bool,
                    tx: int) -> None:
    n = _lane_dict_enter(c)
    prev = [None]
    have_id = have_ver = have_rw = False
    for _ in range(n):
        k = _lane_dict_key(c, prev)
        if k == b"chaincode_id":
            have_id = True
            _lane_canon(c, 1)
        elif k == b"chaincode_version":
            have_ver = True
            _lane_canon(c, 1)
        elif k == b"rwset":
            _lane_rwset(c, st, emit, tx)
            have_rw = True
        else:
            _lane_canon(c, 1)
    if not (have_id and have_ver and have_rw):
        raise _LaneStat(LANE_BAD)


def _lane_action(c: _LaneCur, st: _LaneState, emit: bool, tx: int) -> None:
    n = _lane_dict_enter(c)
    prev = [None]
    have_ph = have_act = have_end = False
    for _ in range(n):
        k = _lane_dict_key(c, prev)
        if k == b"action":
            _lane_cc_action(c, st, emit, tx)
            have_act = True
        elif k == b"endorsements":
            if c.p >= c.end or c.b[c.p] != 0x4C:
                raise _LaneStat(LANE_UNKNOWN)
            c.p += 1
            for _ in range(_lane_u32(c)):
                _lane_endorsement(c)
            have_end = True
        elif k == b"proposal_hash":
            have_ph = True
            _lane_canon(c, 1)
        else:
            _lane_canon(c, 1)
    if not (have_ph and have_act and have_end):
        raise _LaneStat(LANE_BAD)


def _lane_env(base: bytes, off: int, ln: int, tx: int, st: _LaneState):
    """walk_env mirror: (txid_off, txid_len) of an OK endorser tx, or a
    _LaneStat with the terminal status."""
    c = _LaneCur(base, off, off + ln)
    payload_span = None
    have_sig = False
    n = _lane_dict_enter(c)
    prev = [None]
    for _ in range(n):
        k = _lane_dict_key(c, prev)
        vstart = c.p
        _lane_canon(c, 1)
        if k == b"payload":
            payload_span = (vstart, c.p)
        elif k == b"signature":
            have_sig = True
    if c.p != c.end:
        raise _LaneStat(LANE_BAD)              # trailing bytes
    if payload_span is None or not have_sig:
        raise _LaneStat(LANE_BAD)              # KeyError
    if base[payload_span[0]] != 0x42:
        raise _LaneStat(LANE_UNKNOWN)          # decode(non-bytes)
    pc = _LaneCur(base, payload_span[0], payload_span[1])
    poff, pn = _lane_bytes(pc)

    pc = _LaneCur(base, poff, poff + pn)
    header_v = _lane_dict_find(pc, b"header")
    if header_v is None or pc.p != pc.end:
        raise _LaneStat(LANE_BAD)
    ch_v = _lane_dict_find(_LaneCur(base, *header_v), b"channel_header")
    if ch_v is None:
        raise _LaneStat(LANE_BAD)
    type_v = _lane_dict_find(_LaneCur(base, *ch_v), b"type")
    if type_v is None:
        raise _LaneStat(LANE_BAD)
    tv = _LaneCur(base, *type_v)
    if tv.p >= tv.end or base[tv.p] != 0x53:
        raise _LaneStat(LANE_SKIP)             # non-str != TX_ENDORSER
    soff, sn = _lane_str(tv)
    if base[soff:soff + sn] != b"endorser_transaction":
        raise _LaneStat(LANE_SKIP)

    pc = _LaneCur(base, poff, poff + pn)
    data_v = _lane_dict_find(pc, b"data")
    if data_v is None:
        raise _LaneStat(LANE_BAD)
    actions_v = _lane_dict_find(_LaneCur(base, *data_v), b"actions")
    if actions_v is None:
        raise _LaneStat(LANE_BAD)
    av = _LaneCur(base, *actions_v)
    if av.p >= av.end or base[av.p] != 0x4C:
        raise _LaneStat(LANE_UNKNOWN)
    av.p += 1
    an = _lane_u32(av)
    if an == 0:
        raise _LaneStat(LANE_SKIP)             # `not tx.actions` -> None,
                                               # BEFORE ch["txid"] is read
    for i in range(an):
        _lane_action(av, st, i == 0, tx)

    txid_v = _lane_dict_find(_LaneCur(base, *ch_v), b"txid")
    if txid_v is None:
        raise _LaneStat(LANE_BAD)
    xv = _LaneCur(base, *txid_v)
    if xv.p >= xv.end or base[xv.p] != 0x53:
        raise _LaneStat(LANE_UNKNOWN)
    return _lane_str(xv)


def rwset_lanes_py(base: _Raw, spans) -> Optional[tuple]:
    """Mirror of _fastparse.rwset_lanes (same result tuple, same arena
    bytes — see the lane-layout comment above rwset_lanes())."""
    base = bytes(base)
    sp = bytes(spans)
    if len(sp) % 16:
        return None
    blen = len(base)
    n_tx = len(sp) // 16
    st = _LaneState(base)
    txs = []
    for t in range(n_tx):
        off, ln = struct.unpack_from("QQ", sp, 16 * t)
        if off > blen or ln > blen - off:
            return None
        rd_mark, wr_mark = len(st.reads), len(st.writes)
        try:
            txid_off, txid_len = _lane_env(base, off, ln, t, st)
            stat = LANE_OK
        except _LaneStat as e:
            del st.reads[rd_mark:]             # drop partial lanes;
            del st.writes[wr_mark:]            # interned keys stay (C
            stat, txid_off, txid_len = e.st, 0, 0  # parity)
        except _LaneColl:
            return (1, 0, 0, 0, 0, None)
        txs.append((stat, txid_off, txid_len))
    cells: list = []
    for rec in txs:
        cells.extend(rec)
    for rec in st.reads:
        cells.extend(rec)
    for rec in st.writes:
        cells.extend(rec)
    for rec in st.keys:
        cells.extend(rec)
    arena = struct.pack(f"{len(cells)}Q", *cells)
    return (0, n_tx, len(st.keys), len(st.reads), len(st.writes), arena)
