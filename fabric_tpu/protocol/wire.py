"""Zero-copy wire views: lazy Block/Envelope access over raw frame bytes.

The committer's deliver path and the gateway's submit path both used to
turn every received frame into a full Python object tree
(Block.deserialize -> per-envelope bytes -> per-field dataclasses)
before any validation ran.  native/fastparse.c extracts the byte SPANS
those paths actually touch — envelope positions, header fields, the
metadata splice point — in one C walk, and this module wraps them:

  parse_block(raw)      -> BlockView (native parse) | Block (fallback)
  BlockView             duck-types Block for every consumer on the
                        covered path; materializes .data / .metadata
                        lazily only when a consumer truly needs Python
                        objects (MVCC, config handling)
  envelope_summary(raw) -> (type, channel_id, txid) | None — the gateway
                        header peek, no Envelope/Header trees
  parse_block_py / envelope_summary_py
                        pure-Python line-for-line mirrors of the native
                        accept/reject decisions and extracted fields,
                        used by the differential fuzz suite
  n_txs(block)          len(block.data) without forcing a BlockView to
                        materialize its envelope list

Fallback semantics: the native parser accepts EXACTLY the strict
canonical block shape; anything else (including every malformed input)
returns None and parse_block falls back to Block.deserialize, so
accept/reject behavior — down to the exception raised — is unchanged
from the pure-Python path.  A BlockView is only ever produced for bytes
Block.deserialize would have accepted.

Key layout fact (fabric_tpu/utils/serde.py): block encodings are
canonical dicts with sorted keys data < header < metadata.  So the data
LIST's value span inside the raw bytes IS serde.encode(list(data)) —
sha256 over it equals block_data_hash(block.data) — and metadata is the
LAST value, so a metadata-mutated block re-serializes as
raw[:meta_val_off] + serde.encode(metadata), a pure splice.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple, Union

from fabric_tpu.utils import serde
from fabric_tpu.protocol.types import (
    Block,
    BlockHeader,
    BlockMetadata,
    Envelope,
    block_header_hash,
)

try:
    from fabric_tpu import native as _native_pkg
    _fastparse = _native_pkg.load("_fastparse")
except Exception:  # pragma: no cover - broken toolchain
    _fastparse = None

_Raw = Union[bytes, bytearray, memoryview]


class BlockView:
    """A Block over raw wire bytes; Python objects are built on demand.

    Cheap always: .header, .n_data, .raw, .data_spans, .computed_data_hash,
    .hash(), .serialize() (identity until .metadata is touched).
    Materializing: .data (full envelope bytes list, cached), .metadata
    (decoded dict, cached — after first access serialize() re-splices,
    which is bit-identical for unmutated metadata by serde bijection).
    """

    __slots__ = ("raw", "header", "n_data", "_data_off", "_data_end",
                 "_spans", "_meta_off", "_data", "_metadata", "_dhash")

    def __init__(self, raw: _Raw, number: int, previous_hash: bytes,
                 data_hash: bytes, data_off: int, data_end: int,
                 n_data: int, spans, meta_off: int):
        self.raw = raw
        self.header = BlockHeader(number, previous_hash, data_hash)
        self.n_data = n_data
        self._data_off = data_off
        self._data_end = data_end
        self._spans = spans
        self._meta_off = meta_off
        self._data: Optional[List[bytes]] = None
        self._metadata: Optional[BlockMetadata] = None
        self._dhash: Optional[bytes] = None

    # -- covered-path accessors (no per-tx objects) ---------------------

    @property
    def data_spans(self):
        """(base, spans) pair for _fastcollect.digest_spans."""
        return self.raw, self._spans

    @property
    def computed_data_hash(self) -> bytes:
        """sha256 over the data list's value span ==
        block_data_hash(self.data), computed without materializing."""
        if self._dhash is None:
            self._dhash = hashlib.sha256(
                self.raw[self._data_off:self._data_end]).digest()
        return self._dhash

    def hash(self) -> bytes:
        return block_header_hash(self.header)

    def serialize(self) -> _Raw:
        if self._metadata is None:
            return self.raw
        return (bytes(self.raw[:self._meta_off])
                + serde.encode(self._metadata.to_dict()))

    # -- materializing accessors ---------------------------------------

    @property
    def data(self) -> List[bytes]:
        if self._data is None:
            raw = self.raw
            tab = memoryview(self._spans).cast("Q")
            self._data = [bytes(raw[tab[2 * i]:tab[2 * i] + tab[2 * i + 1]])
                          for i in range(self.n_data)]
        return self._data

    @property
    def metadata(self) -> BlockMetadata:
        if self._metadata is None:
            md = serde.decode(bytes(self.raw[self._meta_off:]))
            self._metadata = BlockMetadata.from_dict(md)
        return self._metadata

    def envelopes(self) -> List[Envelope]:
        return [Envelope.deserialize(b) for b in self.data]

    def to_dict(self) -> dict:
        return {"header": self.header.to_dict(), "data": list(self.data),
                "metadata": self.metadata.to_dict()}

    def to_block(self) -> Block:
        return Block(self.header, list(self.data), self.metadata)


def parse_block(raw: _Raw) -> Union[BlockView, Block]:
    """Wire bytes -> BlockView (native fast path) or Block (fallback).

    Raises exactly what Block.deserialize raises for bytes neither
    accepts; never raises for bytes Block.deserialize accepts.
    """
    if _fastparse is not None:
        r = _fastparse.parse_block(raw)
        if r is not None:
            return BlockView(raw, *r)
    return Block.deserialize(raw)


def n_txs(block) -> int:
    """len(block.data) without forcing a BlockView to materialize."""
    n = getattr(block, "n_data", None)
    return len(block.data) if n is None else n


def envelope_summary(raw: _Raw) -> Optional[Tuple[str, str, str]]:
    """(type, channel_id, txid) of a serialized Envelope, or None when
    the bytes deviate from the strict shape (caller falls back to the
    Envelope.deserialize path, preserving its exact error behavior)."""
    if _fastparse is None:
        return None
    return _fastparse.envelope_summary(raw)


# ---------------------------------------------------------------------------
# pure-Python mirrors — the differential-fuzz reference implementations.
# Native accept/reject and every extracted field must match these
# byte-for-byte (tests/test_fastparse.py); like collect_py they are the
# plain-language statement of what the C walk does.


def parse_block_py(raw: _Raw):
    """Mirror of _fastparse.parse_block: (number, previous_hash,
    data_hash, data list, metadata dict, meta_val_off) or None."""
    try:
        d = serde.decode_py(bytes(raw))
    except Exception:
        return None
    if not isinstance(d, dict) or sorted(d) != ["data", "header", "metadata"]:
        return None
    h = d["header"]
    if (not isinstance(h, dict)
            or sorted(h) != ["data_hash", "number", "previous_hash"]):
        return None
    number = h["number"]
    # native reads a fixed 'I' i64; bignum ('V') numbers fall back
    if (not isinstance(number, int) or isinstance(number, bool)
            or not -(2 ** 63) <= number < 2 ** 63):
        return None
    if not isinstance(h["previous_hash"], bytes):
        return None
    if not isinstance(h["data_hash"], bytes):
        return None
    if not isinstance(d["data"], list):
        return None
    for item in d["data"]:
        if not isinstance(item, bytes):
            return None
    if not isinstance(d["metadata"], dict):
        return None
    # metadata is the top dict's last key: its value span runs to the end
    meta_off = len(bytes(raw)) - len(serde.encode_py(d["metadata"]))
    return (number, h["previous_hash"], h["data_hash"], d["data"],
            d["metadata"], meta_off)


def envelope_summary_py(raw: _Raw) -> Optional[Tuple[str, str, str]]:
    """Mirror of _fastparse.envelope_summary."""
    try:
        d = serde.decode_py(bytes(raw))
        if not isinstance(d, dict) or "payload" not in d or "signature" not in d:
            return None
        payload = d["payload"]
        if not isinstance(payload, bytes):
            return None
        p = serde.decode_py(payload)
        header = p["header"]
        ch = header["channel_header"]
        sh = header["signature_header"]
        if not isinstance(ch, dict) or not isinstance(sh, dict):
            return None
        if "creator" not in sh or "nonce" not in sh:
            return None
        t, cid, txid = ch["type"], ch["channel_id"], ch["txid"]
        if not (isinstance(t, str) and isinstance(cid, str)
                and isinstance(txid, str)):
            return None
        return (t, cid, txid)
    except Exception:
        return None
