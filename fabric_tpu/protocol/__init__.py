from .txflags import ValidationCode, TxFlags
from .types import (
    ChannelHeader,
    SignatureHeader,
    Header,
    Envelope,
    KVRead,
    KVWrite,
    RangeQueryInfo,
    NsRwSet,
    TxRwSet,
    Endorsement,
    ChaincodeAction,
    TransactionAction,
    Transaction,
    BlockHeader,
    BlockMetadata,
    Block,
    Version,
    TX_ENDORSER,
    TX_CONFIG,
    block_data_hash,
    block_header_hash,
)
from . import build

__all__ = [
    "ValidationCode", "TxFlags", "ChannelHeader", "SignatureHeader", "Header",
    "Envelope", "KVRead", "KVWrite", "RangeQueryInfo", "NsRwSet", "TxRwSet",
    "Endorsement", "ChaincodeAction", "TransactionAction", "Transaction",
    "BlockHeader", "BlockMetadata", "Block", "Version",
    "TX_ENDORSER", "TX_CONFIG", "block_data_hash", "block_header_hash",
    "build",
]
