"""Constructors for envelopes/transactions/blocks (protoutil parity).

Reference: protoutil/txutils.go CreateSignedTx, protoutil/commonutils.go
ComputeTxID (sha256 over nonce||creator), protoutil/blockutils.go NewBlock.
Signing identities are fabric_tpu.msp.SigningIdentity; signatures cover the
canonical payload bytes, exactly what the verify-then-gate collector later
re-derives (SURVEY.md §7).
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import List, Optional, Sequence

from fabric_tpu.utils import serde

from .types import (
    Block,
    BlockHeader,
    BlockMetadata,
    ChaincodeAction,
    ChannelHeader,
    Endorsement,
    Envelope,
    Header,
    SignatureHeader,
    Transaction,
    TransactionAction,
    TxRwSet,
    TX_CONFIG,
    TX_ENDORSER,
    META_TXFLAGS,
    block_data_hash,
    block_header_hash,
)


def compute_txid(nonce: bytes, creator: bytes) -> str:
    """protoutil.ComputeTxID: sha256(nonce || creator), hex."""
    return hashlib.sha256(nonce + creator).hexdigest()


def new_nonce() -> bytes:
    return os.urandom(24)


def make_header(tx_type: str, channel_id: str, creator: bytes,
                nonce: Optional[bytes] = None,
                timestamp: Optional[int] = None) -> Header:
    nonce = new_nonce() if nonce is None else nonce
    ts = int(time.time()) if timestamp is None else timestamp
    return Header(
        ChannelHeader(tx_type, channel_id, compute_txid(nonce, creator),
                      timestamp=ts),
        SignatureHeader(creator, nonce))


def proposal_hash(channel_id: str, txid: str, chaincode_id: str,
                  args: Sequence[bytes]) -> bytes:
    """Binds endorsements to the simulated proposal
    (protoutil GetProposalHash2 role)."""
    return hashlib.sha256(serde.encode(
        {"channel_id": channel_id, "txid": txid,
         "chaincode_id": chaincode_id, "args": list(args)})).digest()


def endorse(action: TransactionAction, signer) -> Endorsement:
    """ESCC signing step (default_endorsement.go:36): signature over
    endorsed-bytes || serialized endorser identity."""
    ident = signer.serialize()
    return Endorsement(ident, signer.sign(action.endorsed_bytes() + ident))


def signed_envelope(tx_type: str, channel_id: str, data: dict, signer,
                    nonce: Optional[bytes] = None,
                    timestamp: Optional[int] = None) -> Envelope:
    """Assemble + creator-sign an envelope (protoutil CreateSignedEnvelope)."""
    header = make_header(tx_type, channel_id, signer.serialize(), nonce,
                         timestamp)
    payload = serde.encode({"header": header.to_dict(), "data": data})
    return Envelope(payload, signer.sign(payload))


def endorser_tx(channel_id: str, chaincode_id: str, chaincode_version: str,
                rwset: TxRwSet, creator, endorsers: Sequence,
                args: Sequence[bytes] = (),
                response_payload: bytes = b"",
                nonce: Optional[bytes] = None,
                timestamp: Optional[int] = None) -> Envelope:
    """One-call endorser transaction: simulate-result -> endorsed ->
    creator-signed envelope (protoutil.CreateSignedTx flow)."""
    nonce = new_nonce() if nonce is None else nonce
    creator_bytes = creator.serialize()
    txid = compute_txid(nonce, creator_bytes)
    action = ChaincodeAction(chaincode_id, chaincode_version, rwset,
                             response_payload=response_payload)
    ta = TransactionAction(
        proposal_hash(channel_id, txid, chaincode_id, args), action)
    ta = TransactionAction(ta.proposal_hash, ta.action,
                           tuple(endorse(ta, e) for e in endorsers))
    tx = Transaction((ta,))
    return signed_envelope(TX_ENDORSER, channel_id, tx.to_dict(), creator,
                           nonce=nonce, timestamp=timestamp)


def new_block(number: int, previous_hash: bytes,
              envelopes: Sequence[Envelope]) -> Block:
    """protoutil.NewBlock + data-hash computation."""
    data = [e.serialize() for e in envelopes]
    return Block(BlockHeader(number, previous_hash, block_data_hash(data)),
                 data, BlockMetadata())


def genesis_block(channel_id: str, config_data: dict, signer) -> Block:
    """Block 0: a config envelope carrying the channel config
    (configtxgen's output shape)."""
    env = signed_envelope(TX_CONFIG, channel_id, config_data, signer)
    return new_block(0, b"\x00" * 32, [env])
