"""Wire/on-disk message structures: envelopes, transactions, blocks.

Role-equivalent of fabric-protos-go common/peer messages plus protoutil
(/root/reference/protoutil/{commonutils,txutils,blockutils}.go).  Encoding
is the canonical FTLV scheme in fabric_tpu.utils.serde; all hashes and
signatures are computed over those bytes, mirroring how the reference
hashes deterministic proto marshals (protoutil/blockutils.go BlockDataHash,
BlockHeaderHash).

Structure map (reference -> here):
  common.Envelope{Payload,Signature}            -> Envelope
  common.Header{ChannelHeader,SignatureHeader}  -> Header
  peer.Transaction /{TransactionAction}         -> Transaction/TransactionAction
  rwset.TxReadWriteSet (kvrwset)                -> TxRwSet/NsRwSet/KVRead/KVWrite
  peer.Endorsement                              -> Endorsement
  common.Block{Header,Data,Metadata}            -> Block
  version.Height (core/ledger/.../version)      -> Version
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from fabric_tpu.utils import serde

# channel-header types (common.HeaderType equivalents)
TX_ENDORSER = "endorser_transaction"
TX_CONFIG = "config"

# block metadata indexes (common.BlockMetadataIndex)
META_SIGNATURES = "signatures"
META_TXFLAGS = "txflags"
META_LAST_CONFIG = "last_config"
META_COMMIT_HASH = "commit_hash"


def _d(obj) -> dict:
    """Strip None values so encodings stay minimal and stable."""
    return {k: v for k, v in obj.items() if v is not None}


# ---------------------------------------------------------------------------
# headers / envelopes


@dataclass(frozen=True)
class ChannelHeader:
    """common.ChannelHeader (protoutil/commonutils.go MakeChannelHeader)."""
    type: str
    channel_id: str
    txid: str
    epoch: int = 0
    timestamp: int = 0  # unix seconds; NOT part of txid derivation

    def to_dict(self) -> dict:
        return {"type": self.type, "channel_id": self.channel_id,
                "txid": self.txid, "epoch": self.epoch,
                "timestamp": self.timestamp}

    @staticmethod
    def from_dict(d: dict) -> "ChannelHeader":
        return ChannelHeader(d["type"], d["channel_id"], d["txid"],
                             d.get("epoch", 0), d.get("timestamp", 0))


@dataclass(frozen=True)
class SignatureHeader:
    """common.SignatureHeader{Creator, Nonce}."""
    creator: bytes  # serialized Identity
    nonce: bytes

    def to_dict(self) -> dict:
        return {"creator": self.creator, "nonce": self.nonce}

    @staticmethod
    def from_dict(d: dict) -> "SignatureHeader":
        return SignatureHeader(d["creator"], d["nonce"])


@dataclass(frozen=True)
class Header:
    channel_header: ChannelHeader
    signature_header: SignatureHeader

    def to_dict(self) -> dict:
        return {"channel_header": self.channel_header.to_dict(),
                "signature_header": self.signature_header.to_dict()}

    @staticmethod
    def from_dict(d: dict) -> "Header":
        return Header(ChannelHeader.from_dict(d["channel_header"]),
                      SignatureHeader.from_dict(d["signature_header"]))


@dataclass(frozen=True)
class Envelope:
    """common.Envelope: payload bytes + creator signature over them.

    payload decodes to {"header": Header, "data": <tx-type-specific>}.
    """
    payload: bytes
    signature: bytes

    def serialize(self) -> bytes:
        return serde.encode({"payload": self.payload, "signature": self.signature})

    @staticmethod
    def deserialize(data: bytes) -> "Envelope":
        d = serde.decode(data)
        return Envelope(d["payload"], d["signature"])

    def payload_dict(self) -> dict:
        return serde.decode(self.payload)

    def header(self) -> Header:
        return Header.from_dict(self.payload_dict()["header"])


# ---------------------------------------------------------------------------
# read/write sets


@dataclass(frozen=True)
class Version:
    """version.Height — (block_num, tx_num) of the committing write."""
    block_num: int
    tx_num: int

    def to_list(self) -> list:
        return [self.block_num, self.tx_num]

    @staticmethod
    def from_list(v) -> Optional["Version"]:
        return None if v is None else Version(v[0], v[1])

    def __lt__(self, other: "Version") -> bool:
        return (self.block_num, self.tx_num) < (other.block_num, other.tx_num)


@dataclass(frozen=True)
class KVRead:
    key: str
    version: Optional[Version]  # None = key absent at read time

    def to_dict(self) -> dict:
        return {"key": self.key,
                "version": None if self.version is None else self.version.to_list()}

    @staticmethod
    def from_dict(d: dict) -> "KVRead":
        return KVRead(d["key"], Version.from_list(d.get("version")))


@dataclass(frozen=True)
class KVWrite:
    key: str
    value: bytes = b""
    is_delete: bool = False

    def to_dict(self) -> dict:
        return {"key": self.key, "value": self.value, "is_delete": self.is_delete}

    @staticmethod
    def from_dict(d: dict) -> "KVWrite":
        return KVWrite(d["key"], d.get("value", b""), d.get("is_delete", False))


@dataclass(frozen=True)
class RangeQueryInfo:
    """kvrwset.RangeQueryInfo — raw-reads variant: the full result list is
    replayed at validation (rangequery_validator.go)."""
    start_key: str
    end_key: str
    itr_exhausted: bool
    reads: Tuple[KVRead, ...] = ()

    def to_dict(self) -> dict:
        return {"start_key": self.start_key, "end_key": self.end_key,
                "itr_exhausted": self.itr_exhausted,
                "reads": [r.to_dict() for r in self.reads]}

    @staticmethod
    def from_dict(d: dict) -> "RangeQueryInfo":
        return RangeQueryInfo(d["start_key"], d["end_key"], d["itr_exhausted"],
                              tuple(KVRead.from_dict(r) for r in d["reads"]))


@dataclass(frozen=True)
class NsRwSet:
    namespace: str
    reads: Tuple[KVRead, ...] = ()
    writes: Tuple[KVWrite, ...] = ()
    range_queries: Tuple[RangeQueryInfo, ...] = ()

    def to_dict(self) -> dict:
        return {"namespace": self.namespace,
                "reads": [r.to_dict() for r in self.reads],
                "writes": [w.to_dict() for w in self.writes],
                "range_queries": [q.to_dict() for q in self.range_queries]}

    @staticmethod
    def from_dict(d: dict) -> "NsRwSet":
        return NsRwSet(
            d["namespace"],
            tuple(KVRead.from_dict(r) for r in d["reads"]),
            tuple(KVWrite.from_dict(w) for w in d["writes"]),
            tuple(RangeQueryInfo.from_dict(q) for q in d.get("range_queries", [])))


@dataclass(frozen=True)
class TxRwSet:
    ns_rwsets: Tuple[NsRwSet, ...] = ()

    def to_dict(self) -> dict:
        return {"ns": [n.to_dict() for n in self.ns_rwsets]}

    @staticmethod
    def from_dict(d: dict) -> "TxRwSet":
        return TxRwSet(tuple(NsRwSet.from_dict(n) for n in d["ns"]))

    def serialize(self) -> bytes:
        return serde.encode(self.to_dict())

    @staticmethod
    def deserialize(data: bytes) -> "TxRwSet":
        return TxRwSet.from_dict(serde.decode(data))


# ---------------------------------------------------------------------------
# endorser transactions


@dataclass(frozen=True)
class Endorsement:
    """peer.Endorsement: endorser identity + signature over
    (response_payload || endorser)."""
    endorser: bytes  # serialized Identity
    signature: bytes

    def to_dict(self) -> dict:
        return {"endorser": self.endorser, "signature": self.signature}

    @staticmethod
    def from_dict(d: dict) -> "Endorsement":
        return Endorsement(d["endorser"], d["signature"])


@dataclass(frozen=True)
class ChaincodeAction:
    """peer.ChaincodeAction: the simulation result all endorsers signed.

    proposal_hash binds the action to the simulated proposal
    (protoutil/txutils.go GetProposalHash2 role).
    """
    chaincode_id: str
    chaincode_version: str
    rwset: TxRwSet
    response_status: int = 200
    response_payload: bytes = b""
    events: bytes = b""

    def to_dict(self) -> dict:
        return {"chaincode_id": self.chaincode_id,
                "chaincode_version": self.chaincode_version,
                "rwset": self.rwset.to_dict(),
                "response_status": self.response_status,
                "response_payload": self.response_payload,
                "events": self.events}

    @staticmethod
    def from_dict(d: dict) -> "ChaincodeAction":
        return ChaincodeAction(d["chaincode_id"], d["chaincode_version"],
                               TxRwSet.from_dict(d["rwset"]),
                               d.get("response_status", 200),
                               d.get("response_payload", b""),
                               d.get("events", b""))

    def serialize(self) -> bytes:
        return serde.encode(self.to_dict())


@dataclass(frozen=True)
class TransactionAction:
    """peer.TransactionAction: proposal hash + action payload + endorsements.

    The bytes every endorsement signature covers are
    `endorsed_bytes()` || endorser-identity (validation_logic.go:185-217
    checks sig over ProposalResponsePayload || endorser).
    """
    proposal_hash: bytes
    action: ChaincodeAction
    endorsements: Tuple[Endorsement, ...] = ()

    def endorsed_bytes(self) -> bytes:
        return serde.encode({"proposal_hash": self.proposal_hash,
                             "action": self.action.to_dict()})

    def to_dict(self) -> dict:
        return {"proposal_hash": self.proposal_hash,
                "action": self.action.to_dict(),
                "endorsements": [e.to_dict() for e in self.endorsements]}

    @staticmethod
    def from_dict(d: dict) -> "TransactionAction":
        return TransactionAction(d["proposal_hash"],
                                 ChaincodeAction.from_dict(d["action"]),
                                 tuple(Endorsement.from_dict(e)
                                       for e in d["endorsements"]))


@dataclass(frozen=True)
class Transaction:
    """peer.Transaction: ordered list of actions (in practice length 1)."""
    actions: Tuple[TransactionAction, ...]

    def to_dict(self) -> dict:
        return {"actions": [a.to_dict() for a in self.actions]}

    @staticmethod
    def from_dict(d: dict) -> "Transaction":
        return Transaction(tuple(TransactionAction.from_dict(a)
                                 for a in d["actions"]))


# ---------------------------------------------------------------------------
# blocks


@dataclass(frozen=True)
class BlockHeader:
    """common.BlockHeader — hash-chained (blockutils.go BlockHeaderHash)."""
    number: int
    previous_hash: bytes
    data_hash: bytes

    def to_dict(self) -> dict:
        return {"number": self.number, "previous_hash": self.previous_hash,
                "data_hash": self.data_hash}

    @staticmethod
    def from_dict(d: dict) -> "BlockHeader":
        return BlockHeader(d["number"], d["previous_hash"], d["data_hash"])


@dataclass
class BlockMetadata:
    """common.BlockMetadata keyed by META_* (mutable: the committer fills
    txflags/commit_hash after ordering signed the block)."""
    items: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return dict(self.items)

    @staticmethod
    def from_dict(d: dict) -> "BlockMetadata":
        return BlockMetadata(dict(d))


@dataclass
class Block:
    header: BlockHeader
    data: List[bytes]  # serialized Envelopes
    metadata: BlockMetadata = field(default_factory=BlockMetadata)

    def to_dict(self) -> dict:
        return {"header": self.header.to_dict(), "data": list(self.data),
                "metadata": self.metadata.to_dict()}

    @staticmethod
    def from_dict(d: dict) -> "Block":
        return Block(BlockHeader.from_dict(d["header"]), list(d["data"]),
                     BlockMetadata.from_dict(d["metadata"]))

    def serialize(self) -> bytes:
        return serde.encode(self.to_dict())

    @staticmethod
    def deserialize(data: bytes) -> "Block":
        return Block.from_dict(serde.decode(data))

    def envelopes(self) -> List[Envelope]:
        return [Envelope.deserialize(b) for b in self.data]

    def hash(self) -> bytes:
        return block_header_hash(self.header)


def block_data_hash(data: List[bytes]) -> bytes:
    """protoutil.BlockDataHash: hash over the concatenated tx bytes."""
    return hashlib.sha256(serde.encode(list(data))).digest()


def block_header_hash(header: BlockHeader) -> bytes:
    """protoutil.BlockHeaderHash: the chain link (prev_hash of block n+1)."""
    return hashlib.sha256(serde.encode(header.to_dict())).digest()
