"""Certstore: pull-replicated identity certificates.

Reference parity: gossip/gossip/certstore.go — peers replicate each
other's identity certificates via the pull mechanism so gossip message
signatures can be verified even for peers never heard from directly.
Items are serialized MSP identities keyed by their sha256; `add`
validates against the channel MSPs (an identity no MSP vouches for is
rejected — idStore.put's verification in the reference), so a malicious
responder cannot poison the store.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from typing import Dict, List, Optional

from .pull import PullStore

logger = logging.getLogger("fabric_tpu.gossip.certstore")


def identity_digest(identity: bytes) -> str:
    return hashlib.sha256(identity).hexdigest()


class CertStore(PullStore):
    def __init__(self, msps: Dict[str, object], self_identity: bytes = b""):
        self.msps = msps
        self._lock = threading.Lock()
        self._certs: Dict[str, bytes] = {}
        if self_identity:
            self.add(identity_digest(self_identity), self_identity)

    def digests(self) -> List[str]:
        with self._lock:
            return sorted(self._certs)

    def get(self, item_id: str) -> Optional[bytes]:
        with self._lock:
            return self._certs.get(item_id)

    def add(self, item_id: str, payload: bytes) -> bool:
        if identity_digest(payload) != item_id:
            return False                      # id must bind the content
        from fabric_tpu.msp import deserialize_from_msps
        ident = deserialize_from_msps(self.msps, payload, validate=True)
        if ident is None:
            logger.debug("certstore: rejected unvouched identity %s",
                         item_id[:16])
            return False
        with self._lock:
            self._certs[item_id] = payload
        return True

    def lookup(self, identity: bytes) -> Optional[bytes]:
        return self.get(identity_digest(identity))

    def __len__(self) -> int:
        with self._lock:
            return len(self._certs)
