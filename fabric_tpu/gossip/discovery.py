"""Membership discovery: alive messages + expiry.

Reference parity: gossip/discovery/discovery_impl.go — each peer
periodically gossips a signed alive message carrying a monotonically
increasing sequence number; peers expire members whose last alive is
older than aliveExpirationTimeout.  Failure detection for the whole
framework hangs off this (SURVEY.md §5).

Deterministic: time advances via tick(); one tick = one heartbeat
period.  Signatures: alive messages are signed by the member and
verified through the MCS before acceptance (mcs.verify_peer_msg).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from fabric_tpu.utils import serde

MSG_ALIVE = "gossip.alive"
MSG_MEMBERSHIP_REQ = "gossip.mem_req"
MSG_MEMBERSHIP_RESP = "gossip.mem_resp"


@dataclass
class Peer:
    """discovery.NetworkMember equivalent."""
    id: str
    endpoint: tuple = ()          # transport address, opaque
    identity: bytes = b""         # serialized msp identity
    seq: int = 0                  # alive sequence number
    last_seen_tick: int = 0


class Discovery:
    """One node's membership view."""

    def __init__(self, endpoint, self_identity: bytes = b"",
                 mcs=None, signer=None,
                 alive_expiration_ticks: int = 5,
                 bootstrap: Optional[List[str]] = None):
        self.endpoint = endpoint
        self.id = endpoint.id
        self.identity = self_identity
        self.mcs = mcs
        self.signer = signer
        self.expiration = alive_expiration_ticks
        self._members: Dict[str, Peer] = {}
        self._seq = 0
        self._tick = 0
        self._bootstrap = list(bootstrap or [])
        self.on_expire: Callable[[str], None] = lambda peer_id: None

    # -- outbound -----------------------------------------------------------

    def tick(self) -> None:
        """One heartbeat period: send alive to known members (and
        bootstrap anchors), probe one peer for ITS membership view
        (transitive learning — the reference's MembershipRequest
        exchange, gossip/discovery/discovery_impl.go), then expire the
        silent."""
        self._tick += 1
        self._seq += 1
        body = self._alive_body()
        targets = sorted(set(self.alive_ids()) | set(self._bootstrap))
        for to in targets:
            if to != self.id:
                self.endpoint.send(to, MSG_ALIVE, body)
        peers = [t for t in targets if t != self.id]
        if peers:
            self.endpoint.send(peers[self._tick % len(peers)],
                               MSG_MEMBERSHIP_REQ, {})
        self._expire()

    def _alive_body(self) -> dict:
        payload = {"id": self.id, "seq": self._seq,
                   "endpoint": list(self.endpoint.address)
                   if hasattr(self.endpoint, "address") else [],
                   "identity": self.identity}
        signature = b""
        if self.signer is not None:
            signature = self.signer.sign(serde.encode(payload))
        return {"payload": payload, "signature": signature}

    def _expire(self) -> None:
        for peer_id in list(self._members):
            if self._tick - self._members[peer_id].last_seen_tick \
                    > self.expiration:
                del self._members[peer_id]
                self.on_expire(peer_id)

    # -- inbound ------------------------------------------------------------

    def handle(self, msg_type: str, frm: str, body: dict) -> None:
        if msg_type == MSG_ALIVE:
            self._on_alive(body)
        elif msg_type == MSG_MEMBERSHIP_REQ:
            self.endpoint.send(frm, MSG_MEMBERSHIP_RESP,
                               {"alive": [self._peer_dict(p)
                                          for p in self._members.values()]})
        elif msg_type == MSG_MEMBERSHIP_RESP:
            for entry in body.get("alive", []):
                self._learn(entry)

    def _on_alive(self, body: dict) -> None:
        try:
            payload = body["payload"]
            peer_id = payload["id"]
            seq = int(payload["seq"])
        except (KeyError, TypeError, ValueError):
            return
        if peer_id == self.id:
            return
        if self.mcs is not None and not self.mcs.verify_peer_msg(
                payload.get("identity", b""),
                serde.encode(payload), body.get("signature", b"")):
            return  # unauthenticated alive: ignored
        member = self._members.get(peer_id)
        if member is not None and seq <= member.seq:
            return  # stale or replayed
        self._members[peer_id] = Peer(
            peer_id, tuple(payload.get("endpoint", ())),
            payload.get("identity", b""), seq, self._tick)
        # learn transport address for real-socket transports
        if hasattr(self.endpoint, "net"):
            pass
        elif hasattr(self.endpoint, "add_peer") and payload.get("endpoint"):
            self.endpoint.add_peer(peer_id, tuple(payload["endpoint"]))

    def _learn(self, entry: dict) -> None:
        """Indirect membership via exchange — unauthenticated hint; the
        peer only becomes a member once its own signed alive arrives."""
        peer_id = entry.get("id")
        if peer_id and peer_id != self.id and peer_id not in self._bootstrap \
                and peer_id not in self._members:
            self._bootstrap.append(peer_id)
            if hasattr(self.endpoint, "add_peer") and entry.get("endpoint"):
                self.endpoint.add_peer(peer_id, tuple(entry["endpoint"]))

    def _peer_dict(self, p: Peer) -> dict:
        return {"id": p.id, "endpoint": list(p.endpoint),
                "identity": p.identity}

    # -- queries ------------------------------------------------------------

    def alive_ids(self) -> List[str]:
        return sorted(self._members)

    def known_ids(self) -> List[str]:
        """Alive members PLUS configured-but-not-yet-heard bootstrap
        peers — the widest reachable-target set.  Planes that must reach
        peers before membership converges (fraud-proof gossip: a
        conviction can land within the first few ticks) send here;
        unreachable entries just drop (gossip tolerates loss)."""
        return sorted((set(self._members) | set(self._bootstrap))
                      - {self.id})

    def members(self) -> List[Peer]:
        return [self._members[k] for k in sorted(self._members)]

    def is_alive(self, peer_id: str) -> bool:
        return peer_id in self._members
