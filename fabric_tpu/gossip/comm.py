"""Gossip transport layer.

Reference parity: gossip/comm/comm_impl.go — a bidirectional message
stream between peers with an authenticated connection handshake.  Two
transports share one interface:

  InProcNetwork: N in-process endpoints with explicit `deliver_all()`
    pumping — how the reference's gossip tests run N instances in one
    process (gossip_test.go), deterministic for fault injection.
  TcpTransport: length-prefixed serde frames over TCP on localhost/LAN,
    one listener thread per node — the real-socket path (the reference
    uses gRPC bidi streams; the framing is ours, the trust model — signed
    handshake, msg signatures checked above this layer — is the same).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

from fabric_tpu.utils import serde

_FRAME = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024

# message envelope on the wire: {"type": str, "frm": str, "body": dict}
Handler = Callable[[str, str, dict], None]  # (msg_type, from_id, body)


class InProcNetwork:
    """Deterministic in-process message fabric for tests/simulation."""

    def __init__(self):
        self._handlers: Dict[str, Handler] = {}
        self._queues: Dict[str, List[Tuple[str, str, dict]]] = {}
        self.dropped: set = set()      # unreachable endpoints
        self.partitions: List[set] = []  # optional partition groups

    def register(self, peer_id: str, handler: Handler) -> "InProcEndpoint":
        self._handlers[peer_id] = handler
        self._queues[peer_id] = []
        return InProcEndpoint(self, peer_id)

    def _reachable(self, frm: str, to: str) -> bool:
        if frm in self.dropped or to in self.dropped:
            return False
        if self.partitions:
            for group in self.partitions:
                if frm in group:
                    return to in group
        return True

    def send(self, frm: str, to: str, msg_type: str, body: dict) -> None:
        if to in self._queues and self._reachable(frm, to):
            self._queues[to].append((msg_type, frm, body))

    def deliver_all(self, max_rounds: int = 100) -> None:
        for _ in range(max_rounds):
            any_msg = False
            for peer_id in list(self._queues):
                queue, self._queues[peer_id] = self._queues[peer_id], []
                for msg_type, frm, body in queue:
                    any_msg = True
                    if peer_id not in self.dropped:
                        self._handlers[peer_id](msg_type, frm, body)
            if not any_msg:
                return

    def peer_ids(self) -> List[str]:
        return sorted(self._handlers)


class InProcEndpoint:
    def __init__(self, net: InProcNetwork, peer_id: str):
        self.net = net
        self.id = peer_id

    def send(self, to: str, msg_type: str, body: dict) -> None:
        self.net.send(self.id, to, msg_type, body)


class TcpTransport:
    """Real-socket endpoint: serde frames over TCP, handler per message.

    Address book maps peer_id -> (host, port); connections are opened per
    send and cached.  Wire frame: u32 len ‖ serde{"type","frm","body"}.
    """

    def __init__(self, peer_id: str, host: str = "127.0.0.1", port: int = 0):
        self.id = peer_id
        self._handler: Optional[Handler] = None
        self._addrs: Dict[str, Tuple[str, int]] = {}
        self._conns: Dict[str, socket.socket] = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.address = self._srv.getsockname()
        self._closing = False
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)

    def start(self, handler: Handler) -> None:
        self._handler = handler
        self._accept_thread.start()

    def add_peer(self, peer_id: str, address: Tuple[str, int]) -> None:
        self._addrs[peer_id] = tuple(address)

    def send(self, to: str, msg_type: str, body: dict) -> None:
        raw = serde.encode({"type": msg_type, "frm": self.id, "body": body})
        frame = _FRAME.pack(len(raw)) + raw
        with self._lock:
            sock = self._conns.get(to)
            if sock is None:
                addr = self._addrs.get(to)
                if addr is None:
                    return  # unknown peer: drop, discovery will re-learn
                try:
                    sock = socket.create_connection(addr, timeout=5)
                except OSError:
                    return  # unreachable: gossip tolerates message loss
                self._conns[to] = sock
            try:
                sock.sendall(frame)
            except OSError:
                self._conns.pop(to, None)

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._read_loop, args=(conn,),
                             daemon=True).start()

    def _read_loop(self, conn: socket.socket) -> None:
        buf = b""
        while not self._closing:
            try:
                chunk = conn.recv(65536)
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while len(buf) >= _FRAME.size:
                (n,) = _FRAME.unpack_from(buf)
                if n > MAX_FRAME:
                    return  # protocol violation: drop connection
                if len(buf) < _FRAME.size + n:
                    break
                raw, buf = buf[_FRAME.size:_FRAME.size + n], \
                    buf[_FRAME.size + n:]
                try:
                    msg = serde.decode(raw)
                    self._handler(msg["type"], msg["frm"], msg["body"])
                except (ValueError, KeyError, TypeError):
                    pass  # malformed frame: ignore (peer msgs are untrusted)

    def close(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            for sock in self._conns.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()
