"""Gossip transport layer.

Reference parity: gossip/comm/comm_impl.go — a bidirectional message
stream between peers with an authenticated connection handshake.  Three
transports share one interface:

  InProcNetwork: N in-process endpoints with explicit `deliver_all()`
    pumping — how the reference's gossip tests run N instances in one
    process (gossip_test.go), deterministic for fault injection.
  SecureGossipTransport: THE production path — gossip casts ride the
    node's authenticated AEAD channel plane (fabric_tpu/comm: X25519 +
    signed transcript bound to MSP identities, the slot of the
    reference's mTLS + signed handshake, comm_impl.go:134-169).  Peers
    outside the channel MSPs are rejected at handshake; each inbound
    message carries the handshake-verified sender org.
  TcpTransport: length-prefixed cleartext TCP frames — DEV/TEST ONLY
    (message signatures are still checked above this layer, but there is
    no transport confidentiality or org gating).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

from fabric_tpu.utils import serde

_FRAME = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024

# message envelope on the wire: {"type": str, "frm": str, "body": dict}
Handler = Callable[[str, str, dict], None]  # (msg_type, from_id, body)


class InProcNetwork:
    """Deterministic in-process message fabric for tests/simulation."""

    def __init__(self):
        self._handlers: Dict[str, Handler] = {}
        self._queues: Dict[str, List[Tuple[str, str, dict]]] = {}
        self.dropped: set = set()      # unreachable endpoints
        self.partitions: List[set] = []  # optional partition groups

    def register(self, peer_id: str, handler: Handler) -> "InProcEndpoint":
        self._handlers[peer_id] = handler
        self._queues[peer_id] = []
        return InProcEndpoint(self, peer_id)

    def _reachable(self, frm: str, to: str) -> bool:
        if frm in self.dropped or to in self.dropped:
            return False
        if self.partitions:
            for group in self.partitions:
                if frm in group:
                    return to in group
        return True

    def send(self, frm: str, to: str, msg_type: str, body: dict) -> None:
        if to in self._queues and self._reachable(frm, to):
            self._queues[to].append((msg_type, frm, body))

    def deliver_all(self, max_rounds: int = 100) -> None:
        for _ in range(max_rounds):
            any_msg = False
            for peer_id in list(self._queues):
                queue, self._queues[peer_id] = self._queues[peer_id], []
                for msg_type, frm, body in queue:
                    any_msg = True
                    if peer_id not in self.dropped:
                        self._handlers[peer_id](msg_type, frm, body)
            if not any_msg:
                return

    def peer_ids(self) -> List[str]:
        return sorted(self._handlers)


class InProcEndpoint:
    def __init__(self, net: InProcNetwork, peer_id: str):
        self.net = net
        self.id = peer_id

    def send(self, to: str, msg_type: str, body: dict) -> None:
        self.net.send(self.id, to, msg_type, body)


class SecureGossipTransport:
    """Gossip endpoint on the authenticated RPC plane.

    Registers a `gossip.msg` cast on the node's RpcServer and sends via
    cached authenticated connections (dropped and re-dialed on failure —
    gossip tolerates loss).  peer ids are "host:port" strings of peers'
    RPC endpoints.  The AEAD channel handshake enforces channel-MSP
    membership (rogue orgs never reach the handler); the verified sender
    mspid rides to the handler in body["_from_mspid"] for org-scoped
    decisions above this layer.
    """

    DIAL_BACKOFF_S = 5.0

    def __init__(self, rpc_server, signer, msps):
        self.rpc = rpc_server
        self.signer = signer
        self.msps = msps
        self.id = f"{rpc_server.addr[0]}:{rpc_server.addr[1]}"
        self._handler: Optional[Handler] = None
        self._conns: Dict[str, object] = {}
        self._down_until: Dict[str, float] = {}
        self._lock = threading.Lock()
        rpc_server.serve_cast("gossip.msg", self._on_msg)

    def start(self, handler: Handler) -> None:
        self._handler = handler

    def _on_msg(self, body: dict, peer_identity) -> None:
        if self._handler is None:
            return
        try:
            msg_type = body["type"]
            frm = body["frm"]
            inner = dict(body["body"])
        except (KeyError, TypeError, ValueError):
            return    # malformed gossip frame: ignore (peer msgs untrusted)
        inner["_from_mspid"] = getattr(peer_identity, "mspid", None)
        try:
            self._handler(msg_type, frm, inner)
        except Exception:
            # a processing bug must be VISIBLE, not mistaken for noise
            import logging
            logging.getLogger("fabric_tpu.gossip.comm").exception(
                "gossip handler failed for %s from %s", msg_type, frm)

    def send(self, to: str, msg_type: str, body: dict) -> None:
        import time as _time
        from fabric_tpu.comm.rpc import connect
        payload = {"type": msg_type, "frm": self.id, "body": body}
        now = _time.monotonic()
        with self._lock:
            conn = self._conns.get(to)
            if conn is None and now < self._down_until.get(to, 0.0):
                return    # recent dial failure: skip (gossip tolerates loss)
        try:
            if conn is None:
                host, port = to.rsplit(":", 1)
                conn = connect((host, int(port)), self.signer, self.msps,
                               timeout=1.0)
                with self._lock:
                    existing = self._conns.get(to)
                    if existing is not None:
                        # lost a dial race: keep the first connection
                        conn.close()
                        conn = existing
                    else:
                        self._conns[to] = conn
                        self._down_until.pop(to, None)
            # fault_label exposes the inner gossip type to the fault
            # plane: rules can target e.g. "gossip.msg/gossip.block"
            # instead of the opaque multiplexed wire method
            conn.cast("gossip.msg", payload,
                      fault_label=f"gossip.msg/{msg_type}")
        except Exception:
            with self._lock:
                conn = self._conns.pop(to, None)
                self._down_until[to] = _time.monotonic() + self.DIAL_BACKOFF_S
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass
            # dropped: gossip tolerates message loss

    def close(self) -> None:
        with self._lock:
            for conn in self._conns.values():
                try:
                    conn.close()
                except Exception:
                    pass
            self._conns.clear()


class ChannelMux:
    """Channel multiplexer over ONE underlying transport.

    The reference runs one gossip instance per peer with per-CHANNEL
    state inside it (gossip/gossip_impl.go channel registry); here each
    channel keeps its own GossipNode, and this mux lets them share one
    authenticated transport: outbound messages carry a "_ch" tag,
    inbound messages route to the owning channel's handler.  Untagged
    messages route to the default (bootstrap) channel.
    """

    def __init__(self, transport, default_channel: str):
        self.transport = transport
        self.default_channel = default_channel
        self._handlers: Dict[str, Handler] = {}
        self._started = False
        self._lock = threading.Lock()

    def _route(self, msg_type: str, frm: str, body: dict) -> None:
        ch = body.pop("_ch", None) or self.default_channel
        handler = self._handlers.get(ch)
        if handler is not None:
            handler(msg_type, frm, body)

    def register_for(self, channel_id: str):
        """-> a `register(peer_id, handler)` callable for GossipNode."""
        mux = self

        class _Facade:
            id = self.transport.id

            @staticmethod
            def send(to: str, msg_type: str, body: dict) -> None:
                tagged = dict(body)
                tagged["_ch"] = channel_id
                mux.transport.send(to, msg_type, tagged)

        def register(peer_id, handler):
            with mux._lock:
                mux._handlers[channel_id] = handler
                if not mux._started:
                    mux.transport.start(mux._route)
                    mux._started = True
            return _Facade()

        return register


class TcpTransport:
    """Real-socket endpoint: serde frames over TCP, handler per message.

    Address book maps peer_id -> (host, port); connections are opened per
    send and cached.  Wire frame: u32 len ‖ serde{"type","frm","body"}.
    """

    def __init__(self, peer_id: str, host: str = "127.0.0.1", port: int = 0):
        self.id = peer_id
        self._handler: Optional[Handler] = None
        self._addrs: Dict[str, Tuple[str, int]] = {}
        self._conns: Dict[str, socket.socket] = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.address = self._srv.getsockname()
        self._closing = False
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)

    def start(self, handler: Handler) -> None:
        self._handler = handler
        self._accept_thread.start()

    def add_peer(self, peer_id: str, address: Tuple[str, int]) -> None:
        self._addrs[peer_id] = tuple(address)

    def send(self, to: str, msg_type: str, body: dict) -> None:
        raw = serde.encode({"type": msg_type, "frm": self.id, "body": body})
        frame = _FRAME.pack(len(raw)) + raw
        with self._lock:
            sock = self._conns.get(to)
            if sock is None:
                addr = self._addrs.get(to)
                if addr is None:
                    return  # unknown peer: drop, discovery will re-learn
                try:
                    sock = socket.create_connection(addr, timeout=5)
                except OSError:
                    return  # unreachable: gossip tolerates message loss
                self._conns[to] = sock
            try:
                sock.sendall(frame)
            except OSError:
                self._conns.pop(to, None)

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._read_loop, args=(conn,),
                             daemon=True).start()

    def _read_loop(self, conn: socket.socket) -> None:
        buf = b""
        while not self._closing:
            try:
                chunk = conn.recv(65536)
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while len(buf) >= _FRAME.size:
                (n,) = _FRAME.unpack_from(buf)
                if n > MAX_FRAME:
                    return  # protocol violation: drop connection
                if len(buf) < _FRAME.size + n:
                    break
                raw, buf = buf[_FRAME.size:_FRAME.size + n], \
                    buf[_FRAME.size + n:]
                try:
                    msg = serde.decode(raw)
                    self._handler(msg["type"], msg["frm"], msg["body"])
                except (ValueError, KeyError, TypeError):
                    pass  # malformed frame: ignore (peer msgs are untrusted)

    def close(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            for sock in self._conns.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()
