"""Pull-digest anti-entropy: the gossip pull algorithm.

Reference parity: gossip/gossip/algo/pull.go — the four-phase exchange
(Hello -> Digest -> Request -> Response) by which a peer learns items it
is missing from a randomly chosen neighbor.  The reference runs this for
identity certificates (certstore.go) and channel messages; here it backs
the certstore (blocks use range-based anti-entropy instead — blocks are
totally ordered, so [height, peer_height) range requests strictly beat
digest diffs for them, gossip/state.py).

Items are opaque (item_id -> payload bytes) behind the PullStore
interface; stores validate payloads in `add` (e.g. the certstore rejects
identities no channel MSP vouches for), so a malicious responder cannot
poison the store.
"""

from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional

logger = logging.getLogger("fabric_tpu.gossip.pull")

MSG_PULL_HELLO = "gossip.pull_hello"
MSG_PULL_DIGEST = "gossip.pull_digest"
MSG_PULL_REQ = "gossip.pull_req"
MSG_PULL_RESP = "gossip.pull_resp"

PULL_MSGS = {MSG_PULL_HELLO, MSG_PULL_DIGEST, MSG_PULL_REQ, MSG_PULL_RESP}


class PullStore:
    """Interface pulled items live behind."""

    def digests(self) -> List[str]:
        raise NotImplementedError

    def get(self, item_id: str) -> Optional[bytes]:
        raise NotImplementedError

    def add(self, item_id: str, payload: bytes) -> bool:
        """Validate + store; returns False (and stores nothing) for
        payloads that fail validation or mismatch their id."""
        raise NotImplementedError


class PullMediator:
    """One pull kind's engine (algo/pull.go PullEngine).

    tick() initiates a round with `fanout` random alive peers; handle()
    serves both sides of the exchange.  Nonces bind responses to the
    initiating round so unsolicited digests/responses are ignored.
    """

    def __init__(self, endpoint, discovery, kind: str, store: PullStore,
                 fanout: int = 2, rng: Optional[random.Random] = None):
        self.endpoint = endpoint
        self.discovery = discovery
        self.kind = kind
        self.store = store
        self.fanout = fanout
        self.rng = rng or random.Random()
        self._pending: Dict[int, str] = {}      # nonce -> peer id
        self.stats = {"rounds": 0, "items_pulled": 0}

    # -- initiator side ------------------------------------------------------

    def tick(self) -> None:
        peers = [p for p in self.discovery.alive_ids()
                 if p != self.endpoint.id]
        self.rng.shuffle(peers)
        for to in peers[:self.fanout]:
            nonce = self.rng.getrandbits(63)
            self._pending[nonce] = to
            self.stats["rounds"] += 1
            self.endpoint.send(to, MSG_PULL_HELLO,
                               {"kind": self.kind, "nonce": nonce})
        # drop stale rounds (bounded memory under unresponsive peers)
        if len(self._pending) > 64:
            for nonce in list(self._pending)[:-64]:
                del self._pending[nonce]

    # -- both sides ----------------------------------------------------------

    def handle(self, msg_type: str, frm: str, body: dict) -> None:
        if body.get("kind") != self.kind:
            return
        if msg_type == MSG_PULL_HELLO:
            self.endpoint.send(frm, MSG_PULL_DIGEST, {
                "kind": self.kind, "nonce": body.get("nonce", 0),
                "digests": self.store.digests()})
        elif msg_type == MSG_PULL_DIGEST:
            nonce = body.get("nonce", 0)
            if self._pending.pop(nonce, None) != frm:
                return                      # unsolicited digest: ignore
            have = set(self.store.digests())
            want = [d for d in body.get("digests", []) if d not in have]
            if want:
                self.endpoint.send(frm, MSG_PULL_REQ, {
                    "kind": self.kind, "nonce": nonce, "items": want})
        elif msg_type == MSG_PULL_REQ:
            items = []
            for item_id in body.get("items", [])[:256]:
                payload = self.store.get(item_id)
                if payload is not None:
                    items.append([item_id, payload])
            if items:
                self.endpoint.send(frm, MSG_PULL_RESP, {
                    "kind": self.kind, "nonce": body.get("nonce", 0),
                    "items": items})
        elif msg_type == MSG_PULL_RESP:
            for entry in body.get("items", []):
                try:
                    item_id, payload = entry[0], entry[1]
                except (TypeError, IndexError):
                    continue
                if self.store.add(item_id, payload):
                    self.stats["items_pulled"] += 1
