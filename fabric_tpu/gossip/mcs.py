"""Message crypto service: the gossip plane's verification gateway.

Reference parity: internal/peer/gossip/mcs.go — VerifyBlock (:124,
orderer signature over the block) and VerifyByChannel/Verify (:204, peer
message signatures).  TPU-native: `block_verify_items` exposes the block
check as batchable VerifyItems so a catch-up window of blocks is one
device dispatch; `verify_peer_msg` stays immediate (interactive path).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from fabric_tpu.msp import deserialize_from_msps
from fabric_tpu.orderer.blockwriter import block_signature_items
from fabric_tpu.protocol import Block


class MessageCryptoService:
    def __init__(self, msps: Dict[str, object], provider):
        self.msps = msps
        self.provider = provider

    # -- block verification (mcs.go:124) ------------------------------------

    def block_verify_items(self, block: Block):
        """VerifyItems for a block's orderer signature(s), or None when
        structurally invalid (no/malformed signature metadata)."""
        if block.header.data_hash != self._data_hash(block):
            return None  # data does not match the signed header
        return block_signature_items(block, self.msps)

    def verify_block(self, block: Block) -> bool:
        items = self.block_verify_items(block)
        if not items:
            return False
        return bool(np.asarray(self.provider.batch_verify(items)).all())

    def verify_window(self, blocks: List[Block]) -> List[bool]:
        """Batch-verify a window of blocks in ONE provider dispatch
        (SURVEY.md §7 step 6 / BASELINE config 5).  Structural failures
        short-circuit to False without touching the device."""
        spans: List[Optional[slice]] = []
        items = []
        for block in blocks:
            bi = self.block_verify_items(block)
            if not bi:
                spans.append(None)
                continue
            spans.append(slice(len(items), len(items) + len(bi)))
            items.extend(bi)
        verdicts = (np.asarray(self.provider.batch_verify(items))
                    if items else np.zeros(0, dtype=bool))
        return [bool(verdicts[s].all()) if s is not None else False
                for s in spans]

    @staticmethod
    def _data_hash(block: Block) -> bytes:
        # BlockView exposes the hash over its raw data span — identical
        # bytes to block_data_hash(block.data) without materializing the
        # per-envelope list (protocol/wire.py layout fact)
        pre = getattr(block, "computed_data_hash", None)
        if pre is not None:
            return pre
        from fabric_tpu.protocol.types import block_data_hash
        return block_data_hash(block.data)

    # -- peer message verification (mcs.go:204) ------------------------------

    def verify_peer_msg(self, identity: bytes, msg: bytes,
                        signature: bytes) -> bool:
        ident = deserialize_from_msps(self.msps, identity, validate=True)
        if ident is None:
            return False
        try:
            return ident.verify(msg, signature)
        except Exception:
            return False
