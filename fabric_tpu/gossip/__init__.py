"""Gossip plane: membership, election, state transfer, delivery.

Reference parity (SURVEY.md §2 "Gossip / data dissemination"):
  gossip/comm        -> comm.InProcTransport / comm.TcpTransport
  gossip/discovery   -> discovery.Discovery (alive msgs, expiry)
  gossip/election    -> election.LeaderElection
  gossip/state       -> state.GossipState (payload buffer + anti-entropy)
  internal/peer/gossip/mcs.go -> mcs.MessageCryptoService
  blocksprovider/deliveryclient -> blocksprovider.BlocksProvider

TPU-native notes: block dissemination fan-out stays host-side (network
I/O), but every signature the plane checks — orderer block signatures and
peer message signatures — is emitted as batchable VerifyItems so a node
verifies a whole catch-up window in one TPU dispatch
(blocksprovider.verify_window)."""

from .comm import InProcNetwork, TcpTransport
from .discovery import Discovery, Peer
from .election import LeaderElection
from .mcs import MessageCryptoService
from .state import GossipState
from .blocksprovider import BlocksProvider

__all__ = ["InProcNetwork", "TcpTransport", "Discovery", "Peer",
           "LeaderElection", "MessageCryptoService", "GossipState",
           "BlocksProvider"]
