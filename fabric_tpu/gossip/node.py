"""GossipNode: one peer's full gossip stack wired together.

Reference parity: gossip/service/gossip_service.go InitializeChannel —
discovery + election + state transfer + (leader-only) deliver client,
one instance per peer, shared across channels in the reference; one
node per (peer, channel) here for clarity.
"""

from __future__ import annotations

from typing import Optional

from fabric_tpu.gossip.blocksprovider import BlocksProvider
from fabric_tpu.gossip.certstore import CertStore
from fabric_tpu.gossip.discovery import (
    Discovery,
    MSG_ALIVE,
    MSG_MEMBERSHIP_REQ,
    MSG_MEMBERSHIP_RESP,
)
from fabric_tpu.gossip.election import MSG_LEADERSHIP, LeaderElection
from fabric_tpu.gossip.pull import PULL_MSGS, PullMediator
from fabric_tpu.gossip.state import (
    GossipState,
    MSG_BLOCK,
    MSG_STATE_REQ,
    MSG_STATE_RESP,
)
from fabric_tpu.byzantine.proofgossip import MSG_FRAUD_PROOF, MSG_PARDON

_DISCOVERY_MSGS = {MSG_ALIVE, MSG_MEMBERSHIP_REQ, MSG_MEMBERSHIP_RESP}
_STATE_MSGS = {MSG_BLOCK, MSG_STATE_REQ, MSG_STATE_RESP}


class GossipNode:
    def __init__(self, register, peer_id: str, committer, mcs=None,
                 signer=None, deliver_handler=None, bootstrap=None,
                 window: int = 32, msps=None):
        """`register` is a callable(peer_id, handler) -> endpoint
        (InProcNetwork.register, a TcpTransport starter, or a
        SecureGossipTransport starter)."""
        self.id = peer_id
        self.endpoint = register(peer_id, self.handle)
        identity = signer.serialize() if signer is not None else b""
        self.discovery = Discovery(self.endpoint, identity, mcs=mcs,
                                   signer=signer, bootstrap=bootstrap)
        self.state = GossipState(self.endpoint, self.discovery, committer,
                                 mcs=mcs)
        self.election = LeaderElection(self.discovery)
        # certstore: identities replicate via pull-digest anti-entropy
        # (gossip/gossip/certstore.go + algo/pull.go)
        self.certstore = (CertStore(msps, identity)
                          if msps is not None else None)
        self.cert_pull: Optional[PullMediator] = None
        if self.certstore is not None:
            self.cert_pull = PullMediator(self.endpoint, self.discovery,
                                          "certs", self.certstore)
        self.provider: Optional[BlocksProvider] = None
        if deliver_handler is not None:
            self.provider = BlocksProvider(
                committer.validator.channel_id
                if hasattr(committer, "validator") else "ch",
                deliver_handler, self.state, mcs=mcs, window=window)

    def handle(self, msg_type: str, frm: str, body: dict) -> None:
        if msg_type in _DISCOVERY_MSGS:
            self.discovery.handle(msg_type, frm, body)
        elif msg_type in _STATE_MSGS:
            self.state.handle(msg_type, frm, body)
        elif msg_type == MSG_LEADERSHIP:
            self.election.handle(msg_type, frm, body)
        elif msg_type in PULL_MSGS and self.cert_pull is not None:
            self.cert_pull.handle(msg_type, frm, body)
        elif msg_type == MSG_FRAUD_PROOF and self.state.proofs is not None:
            self.state.proofs.handle(frm, body)
        elif msg_type == MSG_PARDON and self.state.proofs is not None:
            self.state.proofs.handle_pardon(frm, body)

    def tick(self) -> None:
        """One gossip period: heartbeat, elect, (leader) pull, anti-entropy."""
        self.discovery.tick()
        self.election.tick()
        if self.election.is_leader and self.provider is not None:
            self.provider.pull_window()
        self.state.anti_entropy_tick()
        if self.cert_pull is not None:
            self.cert_pull.tick()
        if self.state.proofs is not None:
            self.state.proofs.tick()

    @property
    def height(self) -> int:
        return self.state.committer.height
