"""Peer-side deliver client: pull blocks from the ordering service.

Reference parity: internal/pkg/peer/blocksprovider/blocksprovider.go —
DeliverBlocks (:113) seeks from the current ledger height, verifies each
block's orderer signature (:226 -> mcs.go:124), and hands verified blocks
to gossip for dissemination + commit; reconnects with capped exponential
backoff on stream failure.  core/deliverservice/deliveryclient.go:82
starts/stops one provider per channel when leadership changes.

TPU-native: `pull_window` fetches up to `window` blocks and verifies all
their orderer signatures in ONE batched dispatch (mcs.verify_window)
before committing any — the streaming window of BASELINE config 5.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

from fabric_tpu.orderer.deliver import (
    BEHAVIOR_FAIL_IF_NOT_READY,
    DeliverError,
    NotReadyError,
    SeekInfo,
)
from fabric_tpu.ops_plane import tracing

logger = logging.getLogger("fabric_tpu.gossip.blocksprovider")


class BlocksProvider:
    """One channel's orderer puller (runs on the elected leader peer)."""

    def __init__(self, channel_id: str, deliver_handler, gossip_state,
                 mcs=None, window: int = 32,
                 backoff_base_s: float = 0.05, backoff_max_s: float = 2.0,
                 signed=None, standing=None):
        self.channel_id = channel_id
        self.deliver = deliver_handler   # orderer DeliverHandler (or client)
        self.state = gossip_state
        self.mcs = mcs
        self.window = window
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.signed = signed
        # optional callable(sender identity) -> bool: True means the
        # stream's source is quarantined.  A standing-aware deliver
        # client (node/peer.RemoteDeliver) only serves from such a
        # source as a last resort, so a flagged window is counted and
        # logged here — visibility that the channel is running degraded,
        # not a refusal (the byzantine monitor still judges every block)
        self.standing = standing
        self.last_resort_windows = 0
        self._failures = 0
        self._stopped = False

    # -- one-shot window pull (deterministic; loop() wraps it) ---------------

    def pull_window(self) -> int:
        """Fetch + batch-verify + hand over up to `window` blocks.
        Returns how many blocks were accepted.

        The whole pull runs under a `gossip.pull_window` root span, so
        the deliver req frame carries a traceparent (comm/rpc.py attaches
        "tp" from the ambient context) and the orderer's `orderer.deliver`
        span lands in the SAME trace — one /traces/<id> export covers
        seek, stream, window sig-verify and handover.  These traces are
        high-frequency (one per poll); cap them with the recorder's
        per-root retention policy (tracing config `retention`)."""
        height = self.state.committer.height
        with tracing.tracer.start_span(
                "gossip.pull_window",
                attributes={"channel": self.channel_id, "height": height,
                            "window": self.window}) as span:
            blocks: List = []
            sender = None
            try:
                for item in self.deliver.deliver(
                        self.channel_id,
                        SeekInfo(start=height, stop=height + self.window - 1,
                                 behavior=BEHAVIOR_FAIL_IF_NOT_READY),
                        signed=self.signed):
                    # deliver handlers yield bare blocks; standing-aware
                    # clients yield (block, attests, sender)
                    if isinstance(item, tuple):
                        block, sender = item[0], item[2]
                    else:
                        block = item
                    blocks.append(block)
            except NotReadyError:
                pass  # reached the orderer tip mid-window: fine
            except DeliverError as e:
                self._failures += 1
                logger.warning("[%s] deliver failed (%d): %s",
                               self.channel_id, self._failures, e)
                span.set_attribute("error", str(e))
                return 0
            except Exception as e:
                # transport-level death (RpcClosed/RpcTimeout/ConnectionError
                # — a severed channel or partitioned orderer), not a deliver
                # protocol error: same retry treatment, the loop()'s backoff
                # + re-pull IS the catch-up path once the partition heals
                self._failures += 1
                logger.warning("[%s] deliver transport failed (%d): %r",
                               self.channel_id, self._failures, e)
                span.set_attribute("error", repr(e))
                return 0
            if not blocks:
                if self._failures:
                    self._mark_healed(0)   # reachable again, already at tip
                return 0
            if (self.standing is not None and sender is not None
                    and self.standing(sender)):
                self.last_resort_windows += 1
                logger.warning(
                    "[%s] window served by a QUARANTINED source (last "
                    "resort; every healthy endpoint failed)",
                    self.channel_id)
                span.set_attribute("last_resort", True)
                try:
                    from fabric_tpu.ops_plane import registry
                    registry.counter(
                        "gossip_deliver_last_resort_total",
                        "deliver windows pulled from a quarantined "
                        "source").add(1, channel=self.channel_id)
                except Exception:
                    pass
            if self.mcs is not None:
                with tracing.tracer.start_span(
                        "gossip.verify_window",
                        attributes={"blocks": len(blocks)}):
                    verdicts = self.mcs.verify_window(blocks)  # ONE dispatch
            else:
                verdicts = [True] * len(blocks)
            accepted = 0
            for block, ok in zip(blocks, verdicts):
                if not ok:
                    self._failures += 1
                    logger.error("[%s] block %d failed orderer-sig verify; "
                                 "dropping rest of window", self.channel_id,
                                 block.header.number)
                    break  # later blocks chain off the bad one
                self.state.add_block(block)
                accepted += 1
            span.set_attribute("blocks", len(blocks))
            span.set_attribute("accepted", accepted)
            if accepted:
                if self._failures:
                    self._mark_healed(accepted)
                self._failures = 0
            return accepted

    def _mark_healed(self, accepted: int) -> None:
        """First successful deliver contact after a failure streak."""
        from fabric_tpu.ops_plane.logging import jlog
        jlog(logger, "deliver.healed", channel=self.channel_id,
             failures=self._failures, accepted=accepted,
             height=self.state.committer.height)
        self._failures = 0
        try:
            from fabric_tpu.ops_plane import registry
            registry.counter(
                "gossip_deliver_recoveries_total",
                "deliver reconnects after a failure streak").add(
                    1, channel=self.channel_id)
        except Exception:
            pass

    def catch_up(self, max_windows: int = 1000) -> int:
        """Drain to the orderer tip NOW: pull windows until one comes
        back empty.  The chaos harness calls this after healing a
        partition instead of waiting out the poll/backoff cadence; the
        steady-state loop() converges the same way, just slower."""
        total = 0
        for _ in range(max_windows):
            got = self.pull_window()
            total += got
            if got == 0:
                break
        return total

    def backoff_s(self) -> float:
        """Capped exponential backoff (blocksprovider.go retry loop)."""
        return min(self.backoff_max_s,
                   self.backoff_base_s * (2 ** min(self._failures, 16)))

    # -- continuous loop (real deployments; tests call pull_window) ----------

    def loop(self, poll_s: float = 0.05) -> None:
        while not self._stopped:
            got = self.pull_window()
            if got == 0:
                time.sleep(self.backoff_s() if self._failures else poll_s)

    def stop(self) -> None:
        self._stopped = True
