"""Per-channel leader election over gossip membership.

Reference parity: gossip/election/election.go — peers gossip leadership
declarations; the peer with the smallest ID among the alive candidates
is leader (the reference compares peer IDs too).  The leader runs the
channel's deliver client (one orderer puller per org, blocks then fan
out via gossip) — wired in blocksprovider.

Deterministic: piggybacks on Discovery ticks; leadership is re-derived
from the current membership view each tick, and an explicit declaration
message lets followers yield faster than expiry alone.
"""

from __future__ import annotations

from typing import Callable, Optional

MSG_LEADERSHIP = "gossip.leadership"


class LeaderElection:
    def __init__(self, discovery, on_gain: Callable[[], None] = lambda: None,
                 on_lose: Callable[[], None] = lambda: None):
        self.discovery = discovery
        self.id = discovery.id
        self.on_gain = on_gain
        self.on_lose = on_lose
        self._is_leader = False

    def tick(self) -> None:
        """Re-derive leadership: smallest id among self + alive members."""
        candidates = [self.id] + self.discovery.alive_ids()
        leader = min(candidates)
        if leader == self.id and not self._is_leader:
            self._is_leader = True
            self._declare()
            self.on_gain()
        elif leader != self.id and self._is_leader:
            self._is_leader = False
            self.on_lose()

    def _declare(self) -> None:
        for to in self.discovery.alive_ids():
            self.discovery.endpoint.send(to, MSG_LEADERSHIP,
                                         {"leader": self.id})

    def handle(self, msg_type: str, frm: str, body: dict) -> None:
        if msg_type != MSG_LEADERSHIP:
            return
        if body.get("leader", "") < self.id and self._is_leader:
            self._is_leader = False  # yield to the smaller id immediately
            self.on_lose()

    @property
    def is_leader(self) -> bool:
        return self._is_leader
