"""Gossip state transfer: ordered block delivery + anti-entropy.

Reference parity: gossip/state/state.go — deliverPayloads (:547) drains
an out-of-order payload buffer strictly in block order into the
committer (commitBlock :781 -> coordinator.StoreBlock), and antiEntropy
(:591) asks peers for the [our_height, their_height) range when gaps
persist.  Block payloads arriving via gossip are MCS-verified before
buffering.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from fabric_tpu.protocol import Block
from fabric_tpu.protocol import wire

logger = logging.getLogger("fabric_tpu.gossip.state")

MSG_BLOCK = "gossip.block"
MSG_STATE_REQ = "gossip.state_req"
MSG_STATE_RESP = "gossip.state_resp"

MAX_BUFFER = 256          # payload buffer cap (state.go buffer size role)
MAX_RANGE_PER_REQ = 32    # anti-entropy batch (state.go defAntiEntropyBatchSize)


class GossipState:
    """One channel's block intake: buffer -> verify -> commit in order."""

    def __init__(self, endpoint, discovery, committer, mcs=None,
                 fanout: int = 3):
        self.endpoint = endpoint
        self.discovery = discovery
        self.committer = committer  # needs .height and .store_block(block)
        self.mcs = mcs
        self.fanout = fanout
        # byzantine.ByzantineMonitor, wired post-construction by the
        # peer channel; None = classic blind intake
        self.monitor = None
        # byzantine.ProofGossip, wired post-construction alongside the
        # monitor; None = fraud proofs stay node-local (pre-r14 behavior)
        self.proofs = None
        self._buffer: Dict[int, Block] = {}
        # deliver loop + gossip dispatch threads both drain; the lock
        # closes the pop->store window (two threads pop adjacent heights
        # and the later store races a concurrent re-buffer of the same
        # height into an out-of-order commit)
        self._drain_lock = threading.Lock()

    # -- intake -------------------------------------------------------------

    def add_block(self, block: Block, gossip: bool = True) -> None:
        """Local intake from the deliver client (leader peer); optionally
        fan out to other peers."""
        self._buffer_block(block)
        if gossip:
            self._gossip_block(block)
        self._drain()

    def handle(self, msg_type: str, frm: str, body: dict) -> None:
        if (self.monitor is not None
                and self.monitor.blocked_source(self._byz_key(frm))):
            return                      # quarantined gossip source
        if msg_type == MSG_BLOCK:
            self._on_block_msg(frm, body)
        elif msg_type == MSG_STATE_REQ:
            self._on_state_req(frm, body)
        elif msg_type == MSG_STATE_RESP:
            for raw in body.get("blocks", []):
                self._on_block_msg(frm, {"block": raw})
        self._drain()

    @staticmethod
    def _byz_key(frm: str) -> str:
        """Quarantine key for a gossip transport source.  Distinct from
        signer bindings on purpose: gossip offenses score the RELAY
        (who injected garbage), crimes convict the SIGNER."""
        return f"gossip|{frm}"

    def _on_block_msg(self, frm: str, body: dict) -> None:
        try:
            # native span parse (BlockView) with Block.deserialize
            # fallback — reject behavior identical, per-tx decode gone
            block = wire.parse_block(body["block"])
        except (KeyError, ValueError, TypeError):
            # unparseable payload: honest peers (and the crash-stop
            # fault plane, which only drops/dups/reorders whole frames)
            # never produce one — score the source
            if self.monitor is not None and frm:
                self.monitor.offense(self._byz_key(frm), "garbage")
            return
        if self.mcs is not None and not self.mcs.verify_block(block):
            logger.warning("rejected gossiped block %s: bad orderer sig",
                           getattr(block.header, "number", "?"))
            if self.monitor is not None and frm:
                self.monitor.offense(self._byz_key(frm), "bad_sig")
            return
        if self.monitor is not None:
            from fabric_tpu.byzantine.monitor import (
                VERDICT_ADMIT, VERDICT_STALE)
            verdict = self.monitor.check_block(block, self._byz_key(frm))
            if verdict == VERDICT_STALE:
                return                  # idempotent dup, not an offense
            if verdict != VERDICT_ADMIT:
                return                  # disputed/convicted: never buffer
        self._buffer_block(block)

    def _buffer_block(self, block: Block) -> None:
        num = block.header.number
        if num < self.committer.height or num in self._buffer:
            return
        if len(self._buffer) >= MAX_BUFFER:
            # full: never drop the immediately-drainable block — evict the
            # highest buffered number instead (anti-entropy re-fetches it),
            # so far-future blocks cannot wedge the buffer.
            evict = max(self._buffer)
            if num >= evict:
                return
            del self._buffer[evict]
        self._buffer[num] = block

    def _gossip_block(self, block: Block) -> None:
        raw = block.serialize()
        for to in self.discovery.alive_ids()[:self.fanout]:
            self.endpoint.send(to, MSG_BLOCK, {"block": raw})

    # -- ordered drain into the committer (deliverPayloads) ------------------

    def _drain(self) -> None:
        with self._drain_lock:
            while True:
                height = self.committer.height
                # a block popped by one drain can be re-buffered by a
                # concurrent intake before its store lands; with stores
                # serialized under the lock those copies surface here as
                # already-committed entries — purge instead of re-storing
                for num in [n for n in self._buffer if n < height]:
                    del self._buffer[num]
                if height not in self._buffer:
                    break
                if (self.monitor is not None
                        and not self.monitor.check_commit(
                            self._buffer[height])):
                    # the height became disputed AFTER this block was
                    # buffered (or this hash lost the dispute): evict it
                    # so the confirmed winner can take the slot — intake
                    # holds contested copies until resolution, and
                    # anti-entropy / deliver re-seek re-supply the winner
                    del self._buffer[height]
                    break
                self.committer.store_block(self._buffer.pop(height))

    # -- anti-entropy (state.go:591) -----------------------------------------

    def anti_entropy_tick(self) -> None:
        """If we have buffered blocks ahead of a gap (or just suspect
        lag), ask a random-ish alive peer for the missing range."""
        height = self.committer.height
        want_upto = max(self._buffer) + 1 if self._buffer else height
        peers = self.discovery.alive_ids()
        if not peers:
            return
        # ask even when no gap is visible — peers answer with their tip
        to = peers[height % len(peers)]
        self.endpoint.send(to, MSG_STATE_REQ,
                           {"from": height,
                            "to": max(want_upto, height + MAX_RANGE_PER_REQ)})

    def _on_state_req(self, frm: str, body: dict) -> None:
        try:
            start = int(body["from"])
            stop = min(int(body["to"]), start + MAX_RANGE_PER_REQ)
        except (KeyError, TypeError, ValueError):
            return
        blocks = []
        store = self.committer.ledger.blockstore
        for num in range(start, min(stop, store.height)):
            blocks.append(store.get_by_number(num).serialize())
        if blocks:
            self.endpoint.send(frm, MSG_STATE_RESP, {"blocks": blocks})

    @property
    def buffered(self) -> List[int]:
        return sorted(self._buffer)
