"""Ops surface for the byzantine plane: `GET /byzantine`.

One route aggregates the node-scoped quarantine registry and every
channel monitor's witness/fraud-proof view — the JSON twin of the `BYZ`
column in `python -m fabric_tpu.node.top`.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional


def byzantine_view(quarantine,
                   monitors: Optional[Dict[str, object]] = None) -> dict:
    """The `/byzantine` response body (also used by tests directly)."""
    body = {
        "quarantined": quarantine.count(),
        "reasons": quarantine.reasons(),
        "identities": quarantine.snapshot(),
        "pardons": quarantine.pardon_count(),
    }
    if monitors:
        channels = {}
        proofs = []
        pardons = []
        for cid, mon in sorted(monitors.items()):
            channels[cid] = mon.snapshot()
            proofs.extend(mon.proofs)
            pardons.extend(getattr(mon, "pardons", []))
        body["channels"] = channels
        body["fraud_proofs"] = proofs
        body["pardon_records"] = pardons
    return body


def register_ops(ops, quarantine,
                 monitors_fn: Optional[Callable[[], Dict[str, object]]]
                 = None) -> None:
    """Mount `GET /byzantine` on an ops server.  `monitors_fn` is called
    per request so channels joined after startup are included."""
    if ops is None:
        return

    def _get(path, body):
        mons = monitors_fn() if monitors_fn is not None else None
        return 200, byzantine_view(quarantine, mons)

    ops.register_route("GET", "/byzantine", _get)
