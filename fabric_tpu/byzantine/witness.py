"""Per-channel witness log: (block_num -> header-hash) plus who vouched.

The detection substrate for equivocation: every block admitted past
signature verification is witnessed as (height, header-hash hex,
transport source, signer bindings).  One height, one hash is the
invariant of an honest ordering service — a second, DIFFERENT hash at a
witnessed height makes the height *disputed*, and the monitor
(monitor.py) decides which vouchers committed a provable crime.

The log is compact by construction: heights below the committed chain
are pruned on every observe (the blockstore itself is the witness for
committed heights — fork checks against it read the stored block), so
the in-memory and on-disk footprint is O(uncommitted tail + live
disputes), not O(chain length).

Persistence piggybacks the trust.py discipline (atomic tmp +
os.replace) but is throttled to every `flush_every` mutations plus
every dispute transition — witnessing is on the block intake path and
must not add an fsync per block.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional

logger = logging.getLogger("fabric_tpu.byzantine")


class WitnessLog:
    """Thread-safe witness log for one channel."""

    def __init__(self, path: Optional[str] = None, keep_tail: int = 512,
                 flush_every: int = 64):
        self.path = path
        self.keep_tail = int(keep_tail)
        self.flush_every = max(1, int(flush_every))
        self._lock = threading.Lock()
        # height -> {"hashes": {hex: {"sources": [..], "signers": [..]}},
        #            "confirmed": hex|None}
        self._entries: Dict[int, dict] = {}
        self._dirty = 0
        if path is not None and os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    data = json.load(f)
                if isinstance(data, dict):
                    self._entries = {int(k): v for k, v in data.items()
                                     if isinstance(v, dict)}
            except Exception:
                logger.exception("witness log unreadable: %s", path)

    # -- recording -----------------------------------------------------------

    def vouch(self, num: int, hhex: str, source: str,
              signers: List[str]) -> dict:
        """Record that `source` delivered (and `signers` signed) header
        `hhex` at height `num`.  Returns a copy of the height's entry
        AFTER the vouch — the monitor reads conflict state off it."""
        with self._lock:
            ent = self._entries.setdefault(
                num, {"hashes": {}, "confirmed": None})
            was_disputed = len(ent["hashes"]) > 1
            rec = ent["hashes"].setdefault(
                hhex, {"sources": [], "signers": []})
            if source and source not in rec["sources"]:
                rec["sources"].append(source)
            for s in signers:
                if s and s not in rec["signers"]:
                    rec["signers"].append(s)
            disputed = len(ent["hashes"]) > 1 and ent["confirmed"] is None
            self._dirty += 1
            flush = (disputed and not was_disputed) \
                or self._dirty >= self.flush_every
            out = self._copy_entry(ent)
            if flush:
                self._save()
        return out

    def confirm(self, num: int, hhex: str) -> None:
        """Pin the winning hash at a (formerly disputed) height."""
        with self._lock:
            ent = self._entries.setdefault(
                num, {"hashes": {}, "confirmed": None})
            ent["confirmed"] = hhex
            self._save()

    def prune_below(self, height: int) -> None:
        """Drop entries the committed chain already witnesses (keep a
        short tail so late dup frames still hit a fast in-memory path)."""
        floor = height - self.keep_tail
        if floor <= 0:
            return
        with self._lock:
            stale = [n for n in self._entries if n < floor]
            for n in stale:
                del self._entries[n]
            if stale:
                self._dirty += len(stale)
                if self._dirty >= self.flush_every:
                    self._save()

    # -- reading -------------------------------------------------------------

    def get(self, num: int) -> Optional[dict]:
        with self._lock:
            ent = self._entries.get(num)
            return self._copy_entry(ent) if ent is not None else None

    def disputed_heights(self) -> List[int]:
        with self._lock:
            return sorted(n for n, e in self._entries.items()
                          if len(e["hashes"]) > 1
                          and e.get("confirmed") is None)

    def stats(self) -> dict:
        with self._lock:
            return {"heights": len(self._entries),
                    "disputed": sum(
                        1 for e in self._entries.values()
                        if len(e["hashes"]) > 1
                        and e.get("confirmed") is None),
                    "confirmed": sum(
                        1 for e in self._entries.values()
                        if e.get("confirmed") is not None)}

    def snapshot(self) -> Dict[int, dict]:
        with self._lock:
            return {n: self._copy_entry(e)
                    for n, e in sorted(self._entries.items())}

    @staticmethod
    def _copy_entry(ent: dict) -> dict:
        return {"hashes": {h: {"sources": list(r["sources"]),
                               "signers": list(r["signers"])}
                           for h, r in ent["hashes"].items()},
                "confirmed": ent.get("confirmed")}

    def _save(self) -> None:
        # caller holds the lock
        self._dirty = 0
        if self.path is None:
            return
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({str(n): e for n, e in self._entries.items()},
                          f, sort_keys=True)
            os.replace(tmp, self.path)
        except Exception:
            logger.exception("witness log not persisted")

    def flush(self) -> None:
        with self._lock:
            self._save()
