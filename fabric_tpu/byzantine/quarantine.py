"""Persistent quarantine registry: per-identity standing, revoked for
provable crimes.

The Byzantine analogue of `verify_plane/trust.py` AttestorTrust: a
thread-safe JSON-backed registry keyed by a string identity — an
orderer/peer transport binding ("mspid|cert-sha256") or a gossip
endpoint ("gossip|host:port") — where a proven crime (equivocation,
fork) quarantines the identity immediately and permanently, while
scored offenses (garbage frames, bad signatures) accumulate until a
threshold quarantines repeat offenders.

Quarantine withdraws TRUST, not liveness: quarantined sources are
refused at gossip intake and skipped by the deliver client's endpoint
rotation, but no honest path depends on them — the stream re-sources
from a healthy endpoint and exactly-once survives on the committer's
replay guard.

State persists across restarts when a path is given (atomic tmp +
os.replace, exactly trust.py's discipline): a quarantined orderer stays
quarantined until an operator deletes the state file.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger("fabric_tpu.byzantine")

# crime reasons quarantine immediately; offense reasons score up to the
# threshold first (a single garbage frame is noise, a pattern is not)
CRIME_REASONS = ("equivocation", "fork", "tampered_attestation")
OFFENSE_REASONS = ("garbage", "bad_sig", "bad_hash", "stale")


class QuarantineRegistry:
    """Thread-safe per-identity standing registry (node-scoped)."""

    def __init__(self, path: Optional[str] = None,
                 score_threshold: int = 3):
        self.path = path
        self.score_threshold = int(score_threshold)
        self._lock = threading.Lock()
        # key -> {"quarantined": bool, "reason": str|None, "score": n,
        #         "offenses": {reason: n}, "at": epoch|None}
        self._state: Dict[str, dict] = {}
        if path is not None and os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    data = json.load(f)
                if isinstance(data, dict):
                    self._state = {str(k): dict(v)
                                   for k, v in data.items()
                                   if isinstance(v, dict)}
            except Exception:
                logger.exception("quarantine state unreadable: %s", path)

    def _entry(self, key: str) -> dict:
        return self._state.setdefault(
            key, {"quarantined": False, "reason": None, "score": 0,
                  "offenses": {}, "at": None})

    def is_quarantined(self, key: Optional[str]) -> bool:
        if key is None:
            return False
        with self._lock:
            ent = self._state.get(key)
            return ent is not None and bool(ent.get("quarantined"))

    def quarantine(self, key: str, reason: str) -> bool:
        """Permanently quarantine `key`.  Returns True the FIRST time
        (so callers emit the fraud proof / log exactly once)."""
        with self._lock:
            ent = self._entry(key)
            first = not ent["quarantined"]
            ent["quarantined"] = True
            if first:
                ent["reason"] = reason
                ent["at"] = time.time()
            self._save()
        if first:
            logger.warning("identity %s QUARANTINED: %s", key, reason)
            self._bump("byzantine_quarantines_total",
                       "identities quarantined by the byzantine plane",
                       reason)
        return first

    def offense(self, key: str, reason: str, weight: int = 1) -> bool:
        """Score an offense against `key`; quarantines (reason
        "poison") once the accumulated score crosses the threshold.
        Returns True if this offense caused the quarantine."""
        with self._lock:
            ent = self._entry(key)
            ent["offenses"][reason] = ent["offenses"].get(reason, 0) \
                + int(weight)
            ent["score"] += int(weight)
            crossed = (not ent["quarantined"]
                       and ent["score"] >= self.score_threshold)
            self._save()
        self._bump("byzantine_offenses_total",
                   "scored byzantine offenses at gossip/deliver intake",
                   reason)
        if crossed:
            return self.quarantine(key, "poison")
        return False

    def count(self) -> int:
        with self._lock:
            return sum(1 for e in self._state.values()
                       if e.get("quarantined"))

    def reasons(self) -> Dict[str, int]:
        """reason -> quarantined-identity count (the BYZ column's
        breakdown)."""
        out: Dict[str, int] = {}
        with self._lock:
            for e in self._state.values():
                if e.get("quarantined"):
                    r = e.get("reason") or "?"
                    out[r] = out.get(r, 0) + 1
        return out

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {k: {**dict(v), "offenses": dict(v.get("offenses", {}))}
                    for k, v in self._state.items()}

    @staticmethod
    def _bump(name: str, help_text: str, reason: str) -> None:
        try:
            from fabric_tpu.ops_plane import registry
            registry.counter(name, help_text).add(1, reason=reason)
        except Exception:
            pass                  # observability never breaks containment

    def _save(self) -> None:
        # caller holds the lock; atomic replace, trust.py discipline
        if self.path is None:
            return
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._state, f, sort_keys=True)
            os.replace(tmp, self.path)
        except Exception:
            logger.exception("quarantine state not persisted")
