"""Persistent quarantine registry: per-identity standing, revoked for
provable crimes.

The Byzantine analogue of `verify_plane/trust.py` AttestorTrust: a
thread-safe JSON-backed registry keyed by a string identity — an
orderer/peer transport binding ("mspid|cert-sha256") or a gossip
endpoint ("gossip|host:port") — where a proven crime (equivocation,
fork) quarantines the identity immediately and permanently, while
scored offenses (garbage frames, bad signatures) accumulate until a
threshold quarantines repeat offenders.

Quarantine withdraws TRUST, not liveness: quarantined sources are
refused at gossip intake and skipped by the deliver client's endpoint
rotation, but no honest path depends on them — the stream re-sources
from a healthy endpoint and exactly-once survives on the committer's
replay guard.

State persists across restarts when a path is given (atomic tmp +
os.replace, exactly trust.py's discipline): a quarantined orderer stays
quarantined until an operator deletes the state file.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger("fabric_tpu.byzantine")

# crime reasons quarantine immediately; offense reasons score up to the
# threshold first (a single garbage frame is noise, a pattern is not)
CRIME_REASONS = ("equivocation", "fork", "tampered_attestation")
OFFENSE_REASONS = ("garbage", "bad_sig", "bad_hash", "stale")


class QuarantineRegistry:
    """Thread-safe per-identity standing registry (node-scoped)."""

    def __init__(self, path: Optional[str] = None,
                 score_threshold: int = 3):
        self.path = path
        self.score_threshold = int(score_threshold)
        self._lock = threading.Lock()
        # key -> {"quarantined": bool, "reason": str|None, "score": n,
        #         "offenses": {reason: n}, "at": epoch|None}
        self._state: Dict[str, dict] = {}
        if path is not None and os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    data = json.load(f)
                if isinstance(data, dict):
                    self._state = {str(k): dict(v)
                                   for k, v in data.items()
                                   if isinstance(v, dict)}
            except Exception:
                logger.exception("quarantine state unreadable: %s", path)

    def _entry(self, key: str) -> dict:
        return self._state.setdefault(
            key, {"quarantined": False, "reason": None, "score": 0,
                  "offenses": {}, "at": None, "last_offense_at": None,
                  "pardons": 0})

    def is_quarantined(self, key: Optional[str]) -> bool:
        if key is None:
            return False
        with self._lock:
            ent = self._state.get(key)
            return ent is not None and bool(ent.get("quarantined"))

    def quarantine(self, key: str, reason: str) -> bool:
        """Permanently quarantine `key`.  Returns True the FIRST time
        (so callers emit the fraud proof / log exactly once)."""
        with self._lock:
            ent = self._entry(key)
            first = not ent["quarantined"]
            ent["quarantined"] = True
            if first:
                ent["reason"] = reason
                ent["at"] = time.time()
            self._save()
        if first:
            logger.warning("identity %s QUARANTINED: %s", key, reason)
            self._bump("byzantine_quarantines_total",
                       "identities quarantined by the byzantine plane",
                       reason)
        return first

    def offense(self, key: str, reason: str, weight: int = 1) -> bool:
        """Score an offense against `key`; quarantines (reason
        "poison") once the accumulated score crosses the threshold.
        Returns True if this offense caused the quarantine."""
        with self._lock:
            ent = self._entry(key)
            ent["offenses"][reason] = ent["offenses"].get(reason, 0) \
                + int(weight)
            ent["score"] += int(weight)
            ent["last_offense_at"] = time.time()
            crossed = (not ent["quarantined"]
                       and ent["score"] >= self.score_threshold)
            self._save()
        self._bump("byzantine_offenses_total",
                   "scored byzantine offenses at gossip/deliver intake",
                   reason)
        if crossed:
            return self.quarantine(key, "poison")
        return False

    # -- proof-backed pardon (fleet lifecycle r18) ---------------------------
    # Offense-based quarantines ("poison": a SCORE crossed a threshold)
    # may be pardoned after a clean-observation window — scores measure
    # behaviour, and behaviour can improve.  Crime convictions never
    # decay: equivocation/fork/tampered_attestation are proven by signed
    # evidence, and a signature does not become less valid with time.

    def pardonable_keys(self, clean_window_s: float,
                        now: Optional[float] = None) -> list:
        """Quarantined identities eligible for pardon: offense-based
        reason AND no offense observed for `clean_window_s`."""
        now = time.time() if now is None else now
        out = []
        with self._lock:
            for key, ent in self._state.items():
                if not ent.get("quarantined"):
                    continue
                if ent.get("reason") in CRIME_REASONS:
                    continue
                since = ent.get("last_offense_at") or ent.get("at") or now
                if now - since >= clean_window_s:
                    out.append(key)
        return sorted(out)

    def pardon(self, key: str) -> bool:
        """Restore `key`'s standing (offense-based quarantines only).
        Returns True when standing was restored, False when refused —
        crime convictions NEVER decay, and a non-quarantined key has
        nothing to pardon.  Live readers (standing-aware deliver, gossip
        intake) see the restoration immediately: they consult
        is_quarantined() per use, never a cached verdict."""
        with self._lock:
            ent = self._state.get(key)
            if ent is None or not ent.get("quarantined"):
                return False
            if ent.get("reason") in CRIME_REASONS:
                logger.warning("pardon REFUSED for %s: %s is a crime "
                               "conviction", key, ent.get("reason"))
                return False
            ent["quarantined"] = False
            ent["reason"] = None
            ent["score"] = 0
            ent["offenses"] = {}
            ent["at"] = None
            ent["pardons"] = int(ent.get("pardons", 0)) + 1
            self._save()
        logger.warning("identity %s PARDONED (standing restored)", key)
        self._bump("byzantine_pardons_total",
                   "offense quarantines pardoned after a clean window",
                   "poison")
        return True

    def decay_scores(self, clean_window_s: float, amount: int = 1,
                     now: Optional[float] = None) -> int:
        """Sub-threshold standing decay: a NON-quarantined identity that
        has stayed clean for a window sheds `amount` score (offense
        tallies remain as history).  Returns how many entries decayed."""
        now = time.time() if now is None else now
        decayed = 0
        with self._lock:
            for ent in self._state.values():
                if ent.get("quarantined") or ent.get("score", 0) <= 0:
                    continue
                since = max(ent.get("last_offense_at") or 0,
                            ent.get("decayed_at") or 0)
                if since and now - since >= clean_window_s:
                    ent["score"] = max(0, ent["score"] - int(amount))
                    ent["decayed_at"] = now
                    decayed += 1
            if decayed:
                self._save()
        return decayed

    def pardon_count(self) -> int:
        with self._lock:
            return sum(int(e.get("pardons", 0))
                       for e in self._state.values())

    def count(self) -> int:
        with self._lock:
            return sum(1 for e in self._state.values()
                       if e.get("quarantined"))

    def reasons(self) -> Dict[str, int]:
        """reason -> quarantined-identity count (the BYZ column's
        breakdown)."""
        out: Dict[str, int] = {}
        with self._lock:
            for e in self._state.values():
                if e.get("quarantined"):
                    r = e.get("reason") or "?"
                    out[r] = out.get(r, 0) + 1
        return out

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {k: {**dict(v), "offenses": dict(v.get("offenses", {}))}
                    for k, v in self._state.items()}

    @staticmethod
    def _bump(name: str, help_text: str, reason: str) -> None:
        try:
            from fabric_tpu.ops_plane import registry
            registry.counter(name, help_text).add(1, reason=reason)
        except Exception:
            pass                  # observability never breaks containment

    def _save(self) -> None:
        # caller holds the lock; atomic replace, trust.py discipline
        if self.path is None:
            return
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._state, f, sort_keys=True)
            os.replace(tmp, self.path)
        except Exception:
            logger.exception("quarantine state not persisted")
