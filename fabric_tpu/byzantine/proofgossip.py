"""First-class fraud-proof gossip: convictions become a NETWORK-WIDE
property, not a per-peer one.

Round 13 left a gap the two-faced adversary exploits: each peer convicts
only from its own witness log, so an orderer that equivocates per-peer
on deliver is quarantined by the one peer that saw both headers and
keeps serving everyone else.  This plane closes it:

  * every NEW local conviction broadcasts its signed portable fraud
    proof over the channel's gossip endpoint (`gossip.fraud_proof`);
  * a RECEIVED proof is judged by `ByzantineMonitor.accept_remote_proof`
    — the accuser signature AND the self-incriminating payload are
    independently re-verified, the relay is never trusted and never
    blamed — and convicts without any local witness evidence;
  * a freshly-convicting receiver re-broadcasts the proof (epidemic
    propagation past the sender's fanout); a duplicate or rejected proof
    is NOT re-broadcast, so the flood terminates at the quarantine
    registry's first-conviction gate.

Proofs travel as JSON bytes inside the gossip frame: the proof body is
a JSON document (it carries floats and is signed over its
`json.dumps(sort_keys=True)` canonical form), so re-encoding it through
the wire serde would break the accuser's signature.

The broadcast counter doubles as the crash-stop gate: a chaos run with
no Byzantine adversary must end with `broadcasts == 0` (no conviction,
no proof, no gossip) — asserted by the scenario catalog's control runs.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

logger = logging.getLogger("fabric_tpu.byzantine")

MSG_FRAUD_PROOF = "gossip.fraud_proof"
MSG_PARDON = "gossip.pardon"


class ProofGossip:
    """One channel's fraud-proof dissemination plane."""

    OUTBOX_MAX = 16

    def __init__(self, endpoint, discovery, monitor, fanout: int = 3):
        self.endpoint = endpoint
        self.discovery = discovery
        self.monitor = monitor
        self.fanout = fanout
        self.broadcasts = 0           # local-conviction broadcasts only
        self.relayed = 0              # epidemic re-broadcasts
        self.received = {"convicted": 0, "duplicate": 0, "rejected": 0}
        # anti-entropy: every proof this node ever served (bounded) is
        # periodically re-offered to one known peer, so peers that were
        # down, partitioned, or not yet discovered at broadcast time
        # still converge; duplicates die at the receiver's quarantine
        # first-conviction gate
        self._outbox = []
        self._rr = 0
        # pardon lane (r18): standing restorations ride the same plane,
        # symmetric counters + their own outbox so anti-entropy keeps
        # offering BOTH record kinds
        self.pardon_broadcasts = 0
        self.pardon_relayed = 0
        self.pardon_received = {"pardoned": 0, "duplicate": 0,
                                "rejected": 0}
        self._pardon_outbox = []

    # -- outbound ------------------------------------------------------------

    def broadcast(self, proof: dict) -> None:
        """ByzantineMonitor.on_proof hook: fan a NEW local conviction's
        proof out to alive peers."""
        self.broadcasts += 1
        self._count("byzantine_proofs_broadcast_total",
                    "fraud proofs broadcast for local convictions")
        self._fan_out(proof)

    def _targets(self) -> list:
        # known_ids reaches configured peers even before membership
        # converges — a conviction can happen within the first ticks
        if hasattr(self.discovery, "known_ids"):
            return self.discovery.known_ids()
        return self.discovery.alive_ids()

    def _fan_out(self, proof: dict) -> None:
        self._fan_out_record(proof, MSG_FRAUD_PROOF, "proof",
                             self._outbox)

    def _fan_out_record(self, record: dict, verb: str, field: str,
                        outbox: list) -> None:
        """Shared dissemination path for both record kinds: canonical
        JSON bytes (re-encoding through the wire serde would break the
        issuer's signature), bounded outbox, fanout to known peers."""
        try:
            raw = json.dumps(record, sort_keys=True).encode()
        except Exception:
            logger.exception("%s record not JSON-serializable", field)
            return
        if raw not in outbox:
            outbox.append(raw)
            del outbox[:-self.OUTBOX_MAX]
        for to in self._targets()[:self.fanout]:
            try:
                self.endpoint.send(to, verb, {field: raw})
            except Exception:
                logger.exception("%s send to %s failed", field, to)

    def broadcast_pardon(self, record: dict) -> None:
        """ByzantineMonitor.on_pardon hook: fan a NEW locally-issued
        pardon out to alive peers."""
        self.pardon_broadcasts += 1
        self._count("byzantine_pardons_broadcast_total",
                    "pardon records broadcast for local restorations")
        self._fan_out_record(record, MSG_PARDON, "pardon",
                             self._pardon_outbox)

    def tick(self) -> None:
        """Anti-entropy: re-offer every served record to ONE known peer,
        rotating through the membership — called from the gossip tick
        cadence.  No records, no traffic (the crash-stop silence gate
        stays meaningful)."""
        if not self._outbox and not self._pardon_outbox:
            return
        targets = self._targets()
        if not targets:
            return
        to = targets[self._rr % len(targets)]
        self._rr += 1
        for verb, field, outbox in (
                (MSG_FRAUD_PROOF, "proof", self._outbox),
                (MSG_PARDON, "pardon", self._pardon_outbox)):
            for raw in list(outbox):
                try:
                    self.endpoint.send(to, verb, {field: raw})
                except Exception:
                    logger.exception("%s re-offer to %s failed", field, to)

    # -- inbound -------------------------------------------------------------

    def handle(self, frm: str, body: dict) -> None:
        """Judge one received proof frame; re-broadcast only on a fresh
        conviction (the termination rule)."""
        try:
            proof = json.loads(bytes(body["proof"]).decode())
            if not isinstance(proof, dict):
                raise ValueError("proof frame is not an object")
        except Exception:
            logger.warning("unparseable fraud proof frame from %s", frm)
            self.received["rejected"] += 1
            self._count("byzantine_proofs_received_total",
                        "fraud proofs received via gossip",
                        verdict="rejected")
            return
        verdict = self.monitor.accept_remote_proof(proof, relay=frm)
        self.received[verdict] = self.received.get(verdict, 0) + 1
        self._count("byzantine_proofs_received_total",
                    "fraud proofs received via gossip", verdict=verdict)
        if verdict == "convicted":
            self.relayed += 1
            self._fan_out(proof)

    def handle_pardon(self, frm: str, body: dict) -> None:
        """Judge one received pardon frame; re-broadcast only on a
        fresh restoration (the same termination rule as proofs: a
        duplicate or rejected pardon dies here)."""
        try:
            record = json.loads(bytes(body["pardon"]).decode())
            if not isinstance(record, dict):
                raise ValueError("pardon frame is not an object")
        except Exception:
            logger.warning("unparseable pardon frame from %s", frm)
            self.pardon_received["rejected"] += 1
            self._count("byzantine_pardons_received_total",
                        "pardon records received via gossip",
                        verdict="rejected")
            return
        verdict = self.monitor.accept_remote_pardon(record, relay=frm)
        self.pardon_received[verdict] = \
            self.pardon_received.get(verdict, 0) + 1
        self._count("byzantine_pardons_received_total",
                    "pardon records received via gossip", verdict=verdict)
        if verdict == "pardoned":
            self.pardon_relayed += 1
            self._fan_out_record(record, MSG_PARDON, "pardon",
                                 self._pardon_outbox)

    # -- plumbing ------------------------------------------------------------

    @staticmethod
    def _count(name: str, help_text: str, **labels) -> None:
        try:
            from fabric_tpu.ops_plane import registry
            registry.counter(name, help_text).add(1, **labels)
        except Exception:
            pass

    def snapshot(self) -> dict:
        return {"broadcasts": self.broadcasts, "relayed": self.relayed,
                "received": dict(self.received),
                "pardon_broadcasts": self.pardon_broadcasts,
                "pardon_relayed": self.pardon_relayed,
                "pardon_received": dict(self.pardon_received)}
