"""ByzantineMonitor: the per-channel intake judge.

Every block that passed signature verification — from the deliver
stream or from gossip — is presented to `check_block(block, source)`
before it may enter the gossip buffer.  The verdicts:

  admit    normal path: witnessed, safe to buffer/commit
  stale    height already committed with the same hash (idempotent dup)
  hold     the height is DISPUTED (two validly-signed headers) and this
           hash has not yet won quorum confirmation — do not buffer;
           the deliver loop re-sources and anti-entropy re-supplies the
           winner once confirmed
  reject   this block is evidence of a crime (its signer equivocated or
           forked off the committed chain); the signer is quarantined
           and a signed fraud proof is persisted

Attribution policy (the no-false-positive core): only the identity
whose SIGNATURE covers a losing header is convicted.  Transport relays
are never convicted for the blocks they forward — an honest peer can
relay both sides of a fork before anyone knows it is a fork.  Transport
sources are only scored for intake offenses that honest code can never
emit (unparseable frames, bad signatures), and quarantined on repeat.

Dispute resolution: a disputed height is confirmed for hash A when
either (a) every competing hash has zero live (non-quarantined)
signers, or (b) A has >= `confirm_quorum` distinct live signers and
strictly more than every competitor.  With the default quorum of 2 this
is the f=1 containment bound: one lying consenter cannot outvote two
honest ones, and a single-consenter dev topology still resolves via
rule (a) once the liar is convicted of equivocation.

Exactly-once survives containment by construction: re-sourcing re-seeks
from the committed height and the committer's replay guard already
dedups overlap, so quarantining a stream's orderer loses nothing that
was accepted.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from fabric_tpu.byzantine.quarantine import (CRIME_REASONS,
                                             QuarantineRegistry)
from fabric_tpu.byzantine.witness import WitnessLog
from fabric_tpu.utils import serde

logger = logging.getLogger("fabric_tpu.byzantine")

VERDICT_ADMIT = "admit"
VERDICT_STALE = "stale"
VERDICT_HOLD = "hold"
VERDICT_REJECT = "reject"


def _hex(b) -> str:
    try:
        return bytes(b).hex()
    except Exception:
        return str(b)


def _jsonable_sigs(block) -> List[dict]:
    """Block metadata signature entries as JSON-safe evidence."""
    try:
        from fabric_tpu.protocol.types import META_SIGNATURES
        sigs = block.metadata.items.get(META_SIGNATURES) or []
    except Exception:
        return []
    out = []
    for entry in sigs:
        try:
            out.append({
                "creator": _hex(entry["sig_header"]["creator"]),
                "nonce": _hex(entry["sig_header"].get("nonce", b"")),
                "signature": _hex(entry["signature"])})
        except Exception:
            continue
    return out


def _incriminating_sigs(block) -> List[dict]:
    """The exact (signed-bytes, signature, creator) triples from the
    block's metadata — the portable core of a block-level fraud proof.
    Unlike `_jsonable_sigs` (display evidence), these carry the FULL
    message each signature covers, so any third party can re-verify the
    accused signed a conflicting header without trusting accuser or
    relay."""
    try:
        from fabric_tpu.orderer.blockwriter import block_signed_bytes
        from fabric_tpu.protocol.types import (META_LAST_CONFIG,
                                               META_SIGNATURES)
        sigs = block.metadata.items.get(META_SIGNATURES) or []
        last_config = block.metadata.items.get(META_LAST_CONFIG, 0)
    except Exception:
        return []
    out = []
    for entry in sigs:
        try:
            out.append({
                "creator": _hex(entry["sig_header"]["creator"]),
                "signed": _hex(block_signed_bytes(
                    block, entry["sig_header"], last_config)),
                "signature": _hex(entry["signature"])})
        except Exception:
            continue
    return out


def build_fraud_proof(channel_id: str, height: int, accused: str,
                      reason: str, evidence: dict,
                      signer=None) -> dict:
    """A self-contained, portable accusation: the witness-log extract
    plus the conflicting header we hold, signed by the accusing peer so
    a third party can check WHO is making the claim.  The provable core
    is inside `evidence`: two different header hashes at one height,
    each covered by a valid consenter signature."""
    body = {
        "v": 1, "channel": channel_id, "height": int(height),
        "accused": accused, "reason": reason, "evidence": evidence,
        "at": time.time(),
    }
    if signer is not None:
        try:
            body["accuser"] = _hex(signer.serialize())
            canonical = json.dumps(body, sort_keys=True).encode()
            body["signature"] = _hex(signer.sign(canonical))
        except Exception:
            logger.exception("fraud proof signing failed")
    return body


def verify_fraud_proof(proof: dict, msps) -> bool:
    """Check the accuser's signature over the canonical proof body."""
    try:
        from fabric_tpu.msp import deserialize_from_msps
        body = {k: v for k, v in proof.items() if k != "signature"}
        canonical = json.dumps(body, sort_keys=True).encode()
        ident = deserialize_from_msps(
            msps, bytes.fromhex(proof["accuser"]), validate=True)
        if ident is None:
            return False
        return bool(ident.verify(canonical,
                                 bytes.fromhex(proof["signature"])))
    except Exception:
        return False


def _verify_entry_equivocation(accused: str, ev: dict, msps):
    """Raft-entry equivocation evidence is fully self-contained: two
    valid consenter signatures over two DIFFERENT payloads for one
    (term, index) slot."""
    try:
        from fabric_tpu.msp import deserialize_from_msps
        from fabric_tpu.orderer import raft as raftmod
        from fabric_tpu.orderer.cluster import cert_fingerprint
        ident = deserialize_from_msps(
            msps, bytes.fromhex(ev["proposer"]), validate=True)
        if ident is None:
            return False, "unknown_proposer"
        if f"{ident.mspid}|{cert_fingerprint(ident.cert)}" != accused:
            return False, "proposer_not_accused"
        term, index = int(ev["term"]), int(ev["index"])
        payloads = set()
        for side in ("a", "b"):
            s = ev[side]
            data = bytes.fromhex(s["data"])
            kind = s["entry_kind"]
            if not ident.verify(
                    raftmod.entry_signed_bytes(term, index, data, kind),
                    bytes.fromhex(s["sig"])):
                return False, f"bad_sig_{side}"
            payloads.add((kind, data))
        if len(payloads) < 2:
            return False, "identical_payloads"
        return True, "entry_equivocation_pair"
    except Exception:
        return False, "malformed_entry_evidence"


def verify_fraud_proof_strict(proof: dict, msps, ledger=None):
    """Independently re-verify a RECEIVED fraud proof — trust neither
    relay nor accuser.  Beyond the accuser's signature, the evidence
    payload itself must incriminate the accused:

      * raft-entry equivocation: two valid signatures by the accused
        over two different payloads for one log slot (self-contained);
      * block equivocation: two valid signatures by the accused over
        two different headers at the proof height (self-contained);
      * fork: ONE valid signature by the accused over a header at the
        proof height that conflicts with OUR OWN committed chain — the
        local ledger is the second witness, so the claim is checked
        against evidence the receiver already holds.

    A proof accusing a node of anything a crash-stop fault could
    explain — no signature by the accused over conflicting payloads —
    is rejected, never convicted.  -> (ok, why)."""
    if not verify_fraud_proof(proof, msps):
        return False, "bad_accuser_sig"
    reason = proof.get("reason")
    if reason not in CRIME_REASONS:
        return False, "unprovable_reason"
    accused = proof.get("accused") or ""
    ev = proof.get("evidence") or {}
    if ev.get("kind") == "raft_entry_equivocation":
        return _verify_entry_equivocation(accused, ev, msps)
    height = int(proof.get("height", -1))
    if height < 0:
        return False, "no_height"
    hashes = set()
    for ent in ev.get("attested") or []:
        try:
            import hashlib
            from fabric_tpu.msp import deserialize_from_msps
            from fabric_tpu.orderer.cluster import cert_fingerprint
            ident = deserialize_from_msps(
                msps, bytes.fromhex(ent["creator"]), validate=True)
            if ident is None:
                continue
            if f"{ident.mspid}|{cert_fingerprint(ident.cert)}" != accused:
                continue
            signed = bytes.fromhex(ent["signed"])
            if not ident.verify(signed, bytes.fromhex(ent["signature"])):
                continue
            hdr = serde.decode(signed)["header"]
            if int(hdr.get("number", -1)) != height:
                continue
            hashes.add(hashlib.sha256(serde.encode(hdr)).hexdigest())
        except Exception:
            continue
    if not hashes:
        return False, "no_self_incriminating_signature"
    if len(hashes) >= 2:
        return True, "equivocation_pair"
    hhex = next(iter(hashes))
    if ledger is not None:
        try:
            from fabric_tpu.protocol import block_header_hash
            if height < ledger.height:
                stored = ledger.blockstore.get_by_number(height)
                if block_header_hash(stored.header).hex() != hhex:
                    return True, "fork_vs_local_chain"
                return False, "matches_local_chain"
        except Exception:
            pass
    return False, "unverifiable_single_header"


def build_pardon(channel_id: str, pardoned: str, reason: str,
                 clean_window_s: float, clean_since: float,
                 signer=None) -> dict:
    """A signed standing-restoration record, symmetric to a fraud
    proof: WHO is pardoned, WHAT offense-based reason is being cleared,
    and the clean-observation window the issuer attests to.  Receivers
    re-verify the issuer's signature and that the cleared reason is not
    a crime — a pardon can never launder an equivocation conviction."""
    body = {
        "v": 1, "kind": "pardon", "channel": channel_id,
        "pardoned": pardoned, "reason": reason,
        "clean_window_s": float(clean_window_s),
        "clean_since": float(clean_since), "at": time.time(),
    }
    if signer is not None:
        try:
            body["issuer"] = _hex(signer.serialize())
            canonical = json.dumps(body, sort_keys=True).encode()
            body["signature"] = _hex(signer.sign(canonical))
        except Exception:
            logger.exception("pardon signing failed")
    return body


def verify_pardon(pardon: dict, msps) -> bool:
    """Check the issuer's signature over the canonical pardon body."""
    try:
        from fabric_tpu.msp import deserialize_from_msps
        body = {k: v for k, v in pardon.items() if k != "signature"}
        canonical = json.dumps(body, sort_keys=True).encode()
        ident = deserialize_from_msps(
            msps, bytes.fromhex(pardon["issuer"]), validate=True)
        if ident is None:
            return False
        return bool(ident.verify(canonical,
                                 bytes.fromhex(pardon["signature"])))
    except Exception:
        return False


def verify_pardon_strict(pardon: dict, msps):
    """Independently re-verify a RECEIVED pardon — trust neither issuer
    claim nor relay.  The issuer must validate against the channel MSPs
    and have signed the canonical body (any tampering — a different
    pardoned key, an altered reason — breaks the signature), and the
    cleared reason must be offense-based: crime convictions are proven
    by signed evidence and NEVER decay, so a 'pardon' naming one is
    forged or malicious by construction.  -> (ok, why)."""
    if pardon.get("kind") != "pardon":
        return False, "not_a_pardon"
    if not verify_pardon(pardon, msps):
        return False, "bad_issuer_sig"
    if pardon.get("reason") in CRIME_REASONS:
        return False, "crime_never_decays"
    if not pardon.get("pardoned"):
        return False, "no_subject"
    return True, "verified"


class ByzantineMonitor:
    """One channel's detection/containment judge (thread-safe)."""

    def __init__(self, channel_id: str, witness: WitnessLog,
                 quarantine: QuarantineRegistry, ledger=None,
                 msps=None, signer=None, proof_dir: Optional[str] = None,
                 confirm_quorum: int = 2,
                 pardon_window_s: Optional[float] = None):
        self.channel_id = channel_id
        self.witness = witness
        self.quarantine = quarantine
        self.ledger = ledger           # needs .height + .blockstore
        self.msps = msps
        self.signer = signer
        self.proof_dir = proof_dir
        self.confirm_quorum = max(1, int(confirm_quorum))
        self._lock = threading.Lock()
        self.proofs: List[dict] = []
        self._proof_seq = 0
        # single-header proofs that arrived BEFORE our chain reached the
        # proof height (no local block to conflict with yet): parked and
        # re-judged as commits land, so a fast accuser never outruns a
        # slow receiver.  Bounded — an attacker spraying unverifiable
        # accusations only ever occupies this much memory.
        self._deferred: List[dict] = []
        self.DEFERRED_MAX = 32
        # on_proof(proof): fired once per NEW local conviction with the
        # signed portable proof — the proof-gossip plane broadcasts it.
        # NEVER fired for remotely-received proofs (accept_remote_proof),
        # so re-broadcast loops terminate at the quarantine dedup.
        self.on_proof = None
        # proof-backed pardon (r18): when pardon_window_s is set, an
        # offense-quarantined identity that stays clean for the window
        # is pardoned — a SIGNED pardon_NNNNN.json record persisted and
        # gossiped exactly like a fraud proof, re-verified by receivers.
        # None = disabled (quarantine stays permanent, r13 behaviour).
        self.pardon_window_s = pardon_window_s
        self.pardons: List[dict] = []
        self._pardon_seq = 0
        # on_pardon(record): fired once per NEW locally-issued pardon
        # (never for remotely-received ones — same loop-termination
        # discipline as on_proof).
        self.on_pardon = None
        if proof_dir is not None:
            try:
                os.makedirs(proof_dir, exist_ok=True)
                for name in sorted(os.listdir(proof_dir)):
                    if name.startswith("fraud_") and name.endswith(".json"):
                        with open(os.path.join(proof_dir, name)) as f:
                            self.proofs.append(json.load(f))
                    elif name.startswith("pardon_") \
                            and name.endswith(".json"):
                        with open(os.path.join(proof_dir, name)) as f:
                            self.pardons.append(json.load(f))
                self._proof_seq = len(self.proofs)
                self._pardon_seq = len(self.pardons)
            except Exception:
                logger.exception("fraud proof dir unreadable: %s",
                                 proof_dir)

    # -- identity helpers ----------------------------------------------------

    def signer_bindings(self, block) -> List[str]:
        """'mspid|cert-sha256' for every identity whose (already
        verified) signature the block's metadata carries."""
        try:
            from fabric_tpu.protocol.types import META_SIGNATURES
            sigs = block.metadata.items.get(META_SIGNATURES) or []
        except Exception:
            return []
        out: List[str] = []
        for entry in sigs:
            try:
                from fabric_tpu.msp import deserialize_from_msps
                from fabric_tpu.orderer.cluster import cert_fingerprint
                ident = deserialize_from_msps(
                    self.msps, entry["sig_header"]["creator"],
                    validate=False)
                if ident is None:
                    continue
                key = f"{ident.mspid}|{cert_fingerprint(ident.cert)}"
                if key not in out:
                    out.append(key)
            except Exception:
                continue
        return out

    def blocked_source(self, source: Optional[str]) -> bool:
        return self.quarantine.is_quarantined(source)

    def offense(self, source: str, reason: str) -> None:
        """Score a transport-level intake offense (garbage / bad sig)."""
        self.quarantine.offense(source, reason)

    # -- the intake judgment -------------------------------------------------

    def check_block(self, block, source: str) -> str:
        """Judge one signature-verified block from `source` (a transport
        identity key).  See module docstring for the verdicts."""
        from fabric_tpu.protocol import block_header_hash
        try:
            num = int(block.header.number)
            hhex = block_header_hash(block.header).hex()
        except Exception:
            return VERDICT_HOLD
        signers = self.signer_bindings(block)
        with self._lock:
            # 1. committed heights: the blockstore is the witness
            committed = self._committed_hash(num)
            if committed is not None:
                if committed == hhex:
                    return VERDICT_STALE
                # validly-signed header off the committed chain: every
                # signer provably signed outside consensus
                self._convict(
                    signers, num, "fork",
                    {"committed": committed, "conflicting": hhex,
                     "header": self._header_dict(block),
                     "signatures": _jsonable_sigs(block),
                     "attested": _incriminating_sigs(block),
                     "source": source})
                return VERDICT_REJECT

            # 2. witness the vouch, then judge the height's state
            ent = self.witness.vouch(num, hhex, source, signers)
            if len(ent["hashes"]) > 1:
                self._judge_dispute(num, ent, block, source)
                ent = self.witness.get(num) or ent
                confirmed = ent.get("confirmed")
                if confirmed is None:
                    return VERDICT_HOLD
                return (VERDICT_ADMIT if confirmed == hhex
                        else VERDICT_REJECT)
            # single known hash: admit unless it is vouched ONLY by
            # quarantined identities (a convicted signer's solo word is
            # not enough — re-sourcing fetches a healthy-signed copy)
            if signers and not any(
                    not self.quarantine.is_quarantined(s)
                    for s in signers):
                return VERDICT_HOLD
            return VERDICT_ADMIT

    def check_commit(self, block) -> bool:
        """Drain-time guard: may this buffered block be committed?
        False when its height is disputed-unresolved or its hash lost —
        blocks buffered BEFORE their height became disputed are caught
        here."""
        from fabric_tpu.protocol import block_header_hash
        try:
            num = int(block.header.number)
            hhex = block_header_hash(block.header).hex()
        except Exception:
            return False
        ent = self.witness.get(num)
        if ent is None:
            return True
        confirmed = ent.get("confirmed")
        if confirmed is not None:
            return confirmed == hhex
        return len(ent["hashes"]) <= 1

    def on_committed(self, height: int) -> None:
        self.witness.prune_below(height)
        self._retry_deferred()
        if self.pardon_window_s is not None:
            self.maybe_pardon()
            self.quarantine.decay_scores(self.pardon_window_s)

    # -- proof-backed pardon -------------------------------------------------

    def maybe_pardon(self, now: Optional[float] = None) -> List[dict]:
        """Issue pardons for every offense-quarantined identity whose
        clean-observation window has elapsed.  Each pardon is a signed,
        persisted record (pardon_NNNNN.json beside the fraud proofs) and
        fires on_pardon for the gossip plane.  Returns the new records.
        The registry's pardon() re-checks crime permanence, so even a
        racing conviction cannot be laundered."""
        if self.pardon_window_s is None:
            return []
        issued: List[dict] = []
        for key in self.quarantine.pardonable_keys(self.pardon_window_s,
                                                   now=now):
            # snapshot the entry BEFORE pardon() resets it: the record
            # must name the reason being cleared and the clean-since
            # instant the issuer attests to
            ent = self.quarantine.snapshot().get(key) or {}
            reason = ent.get("reason") or "poison"
            since = ent.get("last_offense_at") or ent.get("at") or 0.0
            if not self.quarantine.pardon(key):
                continue           # raced with a crime conviction: refused
            record = build_pardon(self.channel_id, key, reason,
                                  self.pardon_window_s, since,
                                  self.signer)
            with self._lock:
                self.pardons.append(record)
                self._persist_pardon(record)
            issued.append(record)
            logger.warning("[%s] issued pardon for %s (clean for %.1fs)",
                           self.channel_id, key, self.pardon_window_s)
            if self.on_pardon is not None:
                try:
                    self.on_pardon(record)
                except Exception:
                    logger.exception("pardon broadcast failed")
        return issued

    def accept_remote_pardon(self, pardon: dict,
                             relay: Optional[str] = None) -> str:
        """Judge a pardon received over the wire.  Restores standing
        only when the record independently re-verifies AND our own
        conviction for that identity is offense-based — a local CRIME
        conviction (signed evidence we hold) is never overridden by
        anyone's pardon.  -> 'pardoned' | 'duplicate' | 'rejected'."""
        ok, why = verify_pardon_strict(pardon, self.msps)
        if not ok:
            logger.warning("[%s] remote pardon rejected (%s) relay=%s",
                           self.channel_id, why, relay)
            return "rejected"
        key = pardon["pardoned"]
        if not self.quarantine.is_quarantined(key):
            return "duplicate"     # already restored (or never held here)
        if not self.quarantine.pardon(key):
            # pardon() refused: our local conviction is a crime
            logger.warning("[%s] remote pardon for %s REFUSED: local "
                           "crime conviction stands relay=%s",
                           self.channel_id, key, relay)
            return "rejected"
        with self._lock:
            self.pardons.append(pardon)
            self._persist_pardon(pardon)
        logger.warning("[%s] standing restored for %s via remote pardon "
                       "relay=%s", self.channel_id, key, relay)
        return "pardoned"

    def _persist_pardon(self, record: dict) -> None:
        """Caller holds the lock; same atomic discipline as proofs."""
        if self.proof_dir is None:
            return
        try:
            name = f"pardon_{self._pardon_seq:05d}.json"
            self._pardon_seq += 1
            tmp = os.path.join(self.proof_dir, name + ".tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(record, f, sort_keys=True)
            os.replace(tmp, os.path.join(self.proof_dir, name))
        except Exception:
            logger.exception("pardon record not persisted")

    def convict_external(self, identity: str, reason: str,
                         evidence: Optional[dict] = None) -> None:
        """Quarantine an identity for a crime proven OUTSIDE the witness
        log (e.g. a tampered attestation digest caught by the round-9
        trust registry)."""
        with self._lock:
            self._convict([identity], -1, reason, evidence or {})

    def accept_remote_proof(self, proof: dict,
                            relay: Optional[str] = None) -> str:
        """Judge a fraud proof received over the wire and convict WITHOUT
        local witness evidence when — and only when — it independently
        re-verifies (verify_fraud_proof_strict: accuser signature AND a
        self-incriminating payload by the accused).  The relay is never
        trusted and never blamed.  -> 'convicted' | 'duplicate' |
        'rejected'."""
        ok, why = verify_fraud_proof_strict(proof, self.msps,
                                            ledger=self.ledger)
        if not ok:
            if why == "unverifiable_single_header":
                # accuser sig and the self-incriminating signature both
                # held — we just have not committed the proof height
                # yet.  Park it; _retry_deferred re-judges on commit.
                with self._lock:
                    if (not self.quarantine.is_quarantined(
                            proof.get("accused"))
                            and len(self._deferred) < self.DEFERRED_MAX):
                        self._deferred.append(proof)
                        logger.info(
                            "[%s] remote fraud proof deferred (height "
                            "%s not committed yet) relay=%s",
                            self.channel_id, proof.get("height"), relay)
                        return "deferred"
            logger.warning("[%s] remote fraud proof rejected (%s) "
                           "relay=%s", self.channel_id, why, relay)
            return "rejected"
        accused, reason = proof["accused"], proof["reason"]
        with self._lock:
            if not self.quarantine.quarantine(accused, reason):
                return "duplicate"
            logger.warning("[%s] convicted %s via remote fraud proof "
                           "(%s, %s) relay=%s", self.channel_id, accused,
                           reason, why, relay)
            self.proofs.append(proof)
            self._persist_proof(proof)
        return "convicted"

    def _retry_deferred(self) -> None:
        """Re-judge parked single-header proofs against the chain we
        hold NOW.  A proof that verifies convicts like any local one —
        on_proof fires, so the epidemic resumes from here."""
        with self._lock:
            if not self._deferred:
                return
            still: List[dict] = []
            for proof in self._deferred:
                ok, why = verify_fraud_proof_strict(proof, self.msps,
                                                    ledger=self.ledger)
                if not ok:
                    if why == "unverifiable_single_header":
                        still.append(proof)   # height still ahead of us
                    continue                  # e.g. matches_local_chain
                accused, reason = proof["accused"], proof["reason"]
                if not self.quarantine.quarantine(accused, reason):
                    continue
                logger.warning("[%s] convicted %s via deferred fraud "
                               "proof (%s, %s)", self.channel_id,
                               accused, reason, why)
                self.proofs.append(proof)
                self._persist_proof(proof)
                if self.on_proof is not None:
                    try:
                        self.on_proof(proof)
                    except Exception:
                        logger.exception("fraud proof broadcast failed")
            self._deferred = still

    # -- internals -----------------------------------------------------------

    def _committed_hash(self, num: int) -> Optional[str]:
        from fabric_tpu.protocol import block_header_hash
        try:
            if self.ledger is None or num >= self.ledger.height:
                return None
            stored = self.ledger.blockstore.get_by_number(num)
            return block_header_hash(stored.header).hex()
        except Exception:
            return None

    @staticmethod
    def _header_dict(block) -> dict:
        try:
            return {k: (_hex(v) if isinstance(v, (bytes, bytearray,
                                                  memoryview)) else v)
                    for k, v in block.header.to_dict().items()}
        except Exception:
            return {}

    def _live_signers(self, rec: dict) -> List[str]:
        return [s for s in rec["signers"]
                if not self.quarantine.is_quarantined(s)]

    def _judge_dispute(self, num: int, ent: dict, block,
                       source: str) -> None:
        """Called under the lock with >= 2 hashes witnessed at `num`.
        Convicts same-signer equivocators, then tries to confirm a
        winner by live-signer quorum."""
        hashes = ent["hashes"]
        evidence = {"witness": {h: {"sources": list(r["sources"]),
                                    "signers": list(r["signers"])}
                                for h, r in hashes.items()},
                    "header": self._header_dict(block),
                    "signatures": _jsonable_sigs(block),
                    "attested": _incriminating_sigs(block),
                    "source": source}
        # (a) the perfect proof: one identity signed two different
        # headers at one height
        seen: Dict[str, str] = {}
        for h, rec in hashes.items():
            for s in rec["signers"]:
                if s in seen and seen[s] != h:
                    self._convict([s], num, "equivocation", evidence)
                else:
                    seen.setdefault(s, h)
        if ent.get("confirmed") is not None:
            return
        # (b) quorum confirmation over live signers
        live = {h: self._live_signers(rec) for h, rec in hashes.items()}
        alive = {h: sigs for h, sigs in live.items() if sigs}
        winner = None
        if len(alive) == 1:
            winner = next(iter(alive))
        elif alive:
            ranked = sorted(alive.items(), key=lambda kv: -len(kv[1]))
            top_h, top_live = ranked[0]
            if (len(top_live) >= self.confirm_quorum
                    and len(top_live) > len(ranked[1][1])):
                winner = top_h
        if winner is None:
            return
        self.witness.confirm(num, winner)
        losers = [s for h, rec in hashes.items() if h != winner
                  for s in rec["signers"]]
        self._convict(sorted(set(losers)), num, "fork",
                      {**evidence, "confirmed": winner})

    def _convict(self, identities: List[str], height: int, reason: str,
                 evidence: dict) -> None:
        """Quarantine + emit one signed fraud proof per NEW conviction.
        Caller holds the lock."""
        for ident in identities:
            if not ident:
                continue
            if not self.quarantine.quarantine(ident, reason):
                continue              # already quarantined: no new proof
            proof = build_fraud_proof(self.channel_id, height, ident,
                                      reason, evidence, self.signer)
            self.proofs.append(proof)
            self._persist_proof(proof)
            if self.on_proof is not None:
                try:
                    self.on_proof(proof)
                except Exception:
                    logger.exception("fraud proof broadcast failed")

    def _persist_proof(self, proof: dict) -> None:
        if self.proof_dir is None:
            return
        try:
            name = f"fraud_{self._proof_seq:05d}.json"
            self._proof_seq += 1
            tmp = os.path.join(self.proof_dir, name + ".tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(proof, f, sort_keys=True)
            os.replace(tmp, os.path.join(self.proof_dir, name))
        except Exception:
            logger.exception("fraud proof not persisted")

    # -- ops view ------------------------------------------------------------

    def snapshot(self) -> dict:
        return {"channel": self.channel_id,
                "witness": self.witness.stats(),
                "disputed_heights": self.witness.disputed_heights(),
                "fraud_proofs": len(self.proofs),
                "deferred_proofs": len(self._deferred),
                "pardons": len(self.pardons),
                "pardon_window_s": self.pardon_window_s}
