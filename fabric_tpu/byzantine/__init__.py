"""Byzantine detection-and-containment plane.

Rounds 3-12 hardened the stack against honest-but-dead components:
crash-stop kills, torn WALs, dropped/duplicated/reordered frames.  This
package is the step past crash-stop — components that LIE:

  equivocation   an orderer (or any block source) emits two different,
                 validly-signed headers at the same height.  Peers keep
                 a compact per-channel witness log (block_num ->
                 header-hash + who vouched) and treat a conflicting
                 second header as provable misbehavior: a signed fraud
                 proof is persisted, the signing identity is permanently
                 quarantined (the round-9 verify_plane/trust.py
                 persistent-revocation pattern), and the deliver stream
                 re-sources from a healthy endpoint without giving up
                 exactly-once (re-seek from height + committer replay
                 guard).
  gossip poison  a gossip peer injects garbage, stale, or badly-signed
                 payloads into state transfer.  Intake verifies payload
                 hash chains before admission, scores offenders, and
                 quarantines repeat offenders.

Attribution is by SIGNER, not by relay: an honest peer may forward both
sides of a fork before anyone knows it is a fork, so only the identity
whose signature covers a losing header is convicted.  Crash-stop faults
(drop/delay/dup/reorder, kill/restart) can never produce two different
validly-signed headers at one height, so a crash-stop-only chaos run
yields ZERO quarantines — the no-false-positive gate tests pin this.

Observability: `byzantine_quarantines_total{reason}` and
`byzantine_offenses_total{reason}` counters, `GET /byzantine` on the
peer ops server, and a `BYZ` column in `python -m fabric_tpu.node.top`.
"""

from fabric_tpu.byzantine.quarantine import QuarantineRegistry
from fabric_tpu.byzantine.witness import WitnessLog
from fabric_tpu.byzantine.monitor import (
    ByzantineMonitor,
    build_fraud_proof,
    build_pardon,
    verify_fraud_proof,
    verify_fraud_proof_strict,
    verify_pardon,
    verify_pardon_strict,
)
from fabric_tpu.byzantine.proofgossip import (MSG_FRAUD_PROOF, MSG_PARDON,
                                              ProofGossip)
from fabric_tpu.byzantine.ops import register_ops

__all__ = [
    "QuarantineRegistry",
    "WitnessLog",
    "ByzantineMonitor",
    "build_fraud_proof",
    "build_pardon",
    "verify_fraud_proof",
    "verify_fraud_proof_strict",
    "verify_pardon",
    "verify_pardon_strict",
    "MSG_FRAUD_PROOF",
    "MSG_PARDON",
    "ProofGossip",
    "register_ops",
]
