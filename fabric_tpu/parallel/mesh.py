"""Device-mesh sharding of signature batches (the framework's ICI tier).

The reference scales block validation with per-tx goroutines capped by
`peer.validatorPoolSize` (core/committer/txvalidator/v20/validator.go:194-209,
common/semaphore) and communicates exclusively over gRPC/mTLS (SURVEY.md
§2.2).  The TPU-native design replaces the goroutine pool with a sharded
data-parallel batch: signatures are laid out on a 1-D `Mesh` over the
'batch' axis, every chip verifies its shard, and the accept/reject bitmap
plus a psum'd valid-count ride XLA collectives over ICI — no host round
trips inside a dispatch.

This module is deliberately tiny: pick a mesh, annotate shardings, let XLA
insert the collectives (the scaling-book recipe).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as PSpec

try:                                      # jax >= 0.4.x moved it to top level
    _shard_map = jax.shard_map
except AttributeError:                    # 0.4.37 ships it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from fabric_tpu.ops import p256, ed25519

BATCH_AXIS = "batch"


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, batch-parallel."""
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


def pad_batch(arrays, batch: int, multiple: int):
    """Pad the trailing batch dim of each (.., B) array up to a multiple.

    Returns (padded_arrays, padded_batch).  Padding rows are zeros, which
    always verify False — harmless for verdict consumers that slice [:batch].
    """
    rem = batch % multiple
    if rem == 0:
        return arrays, batch
    pad = multiple - rem
    out = []
    for a in arrays:
        widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
        out.append(np.pad(np.asarray(a), widths))
    return out, batch + pad


def sharded_p256_verify(mesh: Mesh, require_low_s: bool = True):
    """Build a jitted sharded ECDSA-P256 batch verifier over `mesh`.

    Returns fn(qx, qy, r, s, e) -> (verdicts (B,), valid_count ()) where all
    inputs are (8, B) uint32 with B divisible by mesh size.  The count is
    all-reduced with psum across the mesh (the verdict bitmap equivalent of
    the reference's TRANSACTIONS_FILTER aggregation).
    """
    spec_in = PSpec(None, BATCH_AXIS)

    def local(qx, qy, r, s, e):
        v = p256.verify_words(qx, qy, r, s, e, require_low_s=require_low_s)
        count = jax.lax.psum(jnp.sum(v.astype(jnp.int32)), BATCH_AXIS)
        return v, count

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(spec_in,) * 5,
        out_specs=(PSpec(BATCH_AXIS), PSpec()))
    return jax.jit(fn)


def sharded_p256_rows_verify(mesh: Mesh, require_low_s: bool = True):
    """Sharded row-grouped multikey P-256 verifier (the production fast
    lane, ops/p256_fixed.verify_words_rows).

    fn(bank, row_key, r, s, e) -> (verdicts (R, C), valid_count ()): the
    stacked per-key table bank replicates to every device; rows shard
    over the batch axis (R divisible by mesh size — the provider pads).
    """
    from fabric_tpu.ops import p256_fixed

    word_spec = PSpec(None, BATCH_AXIS, None)
    row_spec = PSpec(BATCH_AXIS)
    bank_spec = PSpec(None, None, None)

    def local(bank, row_key, r, s, e):
        v = p256_fixed.verify_words_rows(
            bank, row_key, r, s, e, require_low_s=require_low_s)
        count = jax.lax.psum(jnp.sum(v.astype(jnp.int32)), BATCH_AXIS)
        return v, count

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(bank_spec, row_spec, word_spec, word_spec, word_spec),
        out_specs=(PSpec(BATCH_AXIS), PSpec()))
    return jax.jit(fn)


def sharded_ed25519_rows_verify(mesh: Mesh):
    """Sharded row-grouped multikey ed25519 verifier (the fast lane,
    ops/ed25519.verify_words_rows): the niels table bank replicates;
    rows shard over the batch axis."""
    from fabric_tpu.ops import ed25519

    word_spec = PSpec(None, BATCH_AXIS, None)
    sign_spec = PSpec(BATCH_AXIS, None)
    row_spec = PSpec(BATCH_AXIS)
    bank_spec = PSpec(None, None, None)

    def local(bank, row_key, ry, r_sign, s, k):
        v = ed25519.verify_words_rows(bank, row_key, ry, r_sign, s, k)
        count = jax.lax.psum(jnp.sum(v.astype(jnp.int32)), BATCH_AXIS)
        return v, count

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(bank_spec, row_spec, word_spec, sign_spec, word_spec,
                  word_spec),
        out_specs=(PSpec(BATCH_AXIS), PSpec()))
    return jax.jit(fn)


def sharded_ed25519_verify(mesh: Mesh):
    """Build a jitted sharded ed25519 batch verifier over `mesh`.

    fn(ay, a_sign, ry, r_sign, s, k) -> (verdicts (B,), valid_count ()).
    """
    word_spec = PSpec(None, BATCH_AXIS)
    bit_spec = PSpec(BATCH_AXIS)

    def local(ay, a_sign, ry, r_sign, s, k):
        v = ed25519.verify_words(ay, a_sign, ry, r_sign, s, k)
        count = jax.lax.psum(jnp.sum(v.astype(jnp.int32)), BATCH_AXIS)
        return v, count

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(word_spec, bit_spec, word_spec, bit_spec, word_spec, word_spec),
        out_specs=(PSpec(BATCH_AXIS), PSpec()))
    return jax.jit(fn)
