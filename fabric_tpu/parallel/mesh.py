"""Device-mesh sharding of signature batches (the framework's ICI tier).

The reference scales block validation with per-tx goroutines capped by
`peer.validatorPoolSize` (core/committer/txvalidator/v20/validator.go:194-209,
common/semaphore) and communicates exclusively over gRPC/mTLS (SURVEY.md
§2.2).  The TPU-native design replaces the goroutine pool with a sharded
data-parallel batch: signatures are laid out on a 1-D `Mesh` over the
'batch' axis, every chip verifies its shard, and the accept/reject bitmap
plus a psum'd valid-count ride XLA collectives over ICI — no host round
trips inside a dispatch.

This module is deliberately tiny: pick a mesh, annotate shardings, let XLA
insert the collectives (the scaling-book recipe).  Every verify lane's
arguments are named, and one regex rule table maps names to
PartitionSpecs (the match_partition_rules idiom) — adding a lane means
naming its arguments, not hand-writing another spec tuple.

Sub-mesh carving (`carve_submeshes` / `allocate_devices`) splits the
device list into disjoint contiguous groups so independent channels can
each own a slice of the chips (parallel/placement.py schedules them).
"""

from __future__ import annotations

import re

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as PSpec

try:                                      # jax >= 0.4.x moved it to top level
    _shard_map = jax.shard_map
except AttributeError:                    # 0.4.37 ships it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from fabric_tpu.ops import p256, ed25519

BATCH_AXIS = "batch"

# -- partition rules ---------------------------------------------------------
# First regex match wins.  Three placements cover every lane:
#   replicated      device-resident inputs identical on every chip (comb /
#                   niels table banks, Miller-loop line precomputes)
#   batch @ dim 0   1-D per-row / per-signature vectors (row_key, sign bits)
#   batch @ dim 1   word/limb arrays laid out (words, B) or (words, R, C)
PARTITION_RULES = (
    (r"(bank|lines|flags)", PSpec()),
    (r"sign_rows", PSpec(BATCH_AXIS, None)),
    (r"(row_key|sign|bits)", PSpec(BATCH_AXIS)),
    (r"(words|rows|limbs)", PSpec(None, BATCH_AXIS)),
)

# argument names per lane; specs are derived, never hand-listed
LANE_ARGS = {
    "p256": ("qx_words", "qy_words", "r_words", "s_words", "e_words"),
    "p256-rows": ("table_bank", "row_key", "r_rows", "s_rows", "e_rows"),
    "ed25519": ("ay_words", "a_sign", "ry_words", "r_sign", "s_words",
                "k_words"),
    "ed25519-rows": ("table_bank", "row_key", "ry_rows", "r_sign_rows",
                     "s_rows", "k_rows"),
    "idemix-pair": ("w_flags", "w_lines_a", "w_lines_b", "g2_lines_a",
                    "g2_lines_b", "x1_limbs", "y1_limbs", "x2_limbs",
                    "y2_limbs"),
}


def match_partition_rules(rules, names):
    """Resolve each argument name to its PartitionSpec via the first
    matching regex rule; unmatched names are a hard error (a silently
    replicated batch input would verify garbage on 7 of 8 chips)."""
    specs = []
    for name in names:
        for pat, spec in rules:
            if re.search(pat, name):
                specs.append(spec)
                break
        else:
            raise ValueError(f"no partition rule matches arg {name!r}")
    return tuple(specs)


def lane_specs(lane: str):
    """The in_specs tuple for a named verify lane."""
    return match_partition_rules(PARTITION_RULES, LANE_ARGS[lane])


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, batch-parallel."""
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


# -- sub-mesh carving (per-channel device placement) -------------------------

def allocate_devices(n_devices: int, weights) -> list:
    """Split `n_devices` into one power-of-two share per weight.

    Greedy doubling: every consumer starts at 1 device, then the most
    under-served one (highest weight per device) doubles while devices
    remain.  Power-of-two shares keep the padded-bucket series (and so
    the compiled-program set) identical across rebalances; deterministic
    tie-break by position.  Returns sizes summing to <= n_devices.
    """
    k = len(weights)
    if k == 0:
        return []
    if k > n_devices:
        raise ValueError(f"{k} consumers > {n_devices} devices")
    sizes = [1] * k
    free = n_devices - k
    while True:
        best, best_load = None, 0.0
        for i, w in enumerate(weights):
            if sizes[i] > free:
                continue
            load = max(float(w), 1e-9) / sizes[i]
            if load > best_load:
                best, best_load = i, load
        if best is None:
            return sizes
        free -= sizes[best]
        sizes[best] *= 2


def carve_submeshes(devices, weights) -> list:
    """Disjoint contiguous sub-meshes over `devices`, one per weight,
    sized by `allocate_devices`.  Contiguous spans keep each sub-mesh on
    neighbouring chips (ICI locality on a real slice)."""
    sizes = allocate_devices(len(devices), weights)
    out, lo = [], 0
    for sz in sizes:
        out.append(make_mesh(list(devices)[lo:lo + sz]))
        lo += sz
    return out


def pad_batch(arrays, batch: int, multiple: int):
    """Pad the trailing batch dim of each (.., B) array up to a multiple.

    Returns (padded_arrays, padded_batch).  Padding rows are zeros, which
    always verify False — harmless for verdict consumers that slice [:batch].
    """
    rem = batch % multiple
    if rem == 0:
        return arrays, batch
    pad = multiple - rem
    out = []
    for a in arrays:
        widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
        out.append(np.pad(np.asarray(a), widths))
    return out, batch + pad


def sharded_p256_verify(mesh: Mesh, require_low_s: bool = True):
    """Build a jitted sharded ECDSA-P256 batch verifier over `mesh`.

    Returns fn(qx, qy, r, s, e) -> (verdicts (B,), valid_count ()) where all
    inputs are (8, B) uint32 with B divisible by mesh size.  The count is
    all-reduced with psum across the mesh (the verdict bitmap equivalent of
    the reference's TRANSACTIONS_FILTER aggregation).
    """
    def local(qx, qy, r, s, e):
        v = p256.verify_words(qx, qy, r, s, e, require_low_s=require_low_s)
        count = jax.lax.psum(jnp.sum(v.astype(jnp.int32)), BATCH_AXIS)
        return v, count

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=lane_specs("p256"),
        out_specs=(PSpec(BATCH_AXIS), PSpec()))
    return jax.jit(fn)


def sharded_p256_rows_verify(mesh: Mesh, require_low_s: bool = True):
    """Sharded row-grouped multikey P-256 verifier (the production fast
    lane, ops/p256_fixed.verify_words_rows).

    fn(bank, row_key, r, s, e) -> (verdicts (R, C), valid_count ()): the
    stacked per-key table bank replicates to every device; rows shard
    over the batch axis (R divisible by mesh size — the provider pads).
    """
    from fabric_tpu.ops import p256_fixed

    def local(bank, row_key, r, s, e):
        v = p256_fixed.verify_words_rows(
            bank, row_key, r, s, e, require_low_s=require_low_s)
        count = jax.lax.psum(jnp.sum(v.astype(jnp.int32)), BATCH_AXIS)
        return v, count

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=lane_specs("p256-rows"),
        out_specs=(PSpec(BATCH_AXIS), PSpec()))
    return jax.jit(fn)


def sharded_ed25519_rows_verify(mesh: Mesh):
    """Sharded row-grouped multikey ed25519 verifier (the fast lane,
    ops/ed25519.verify_words_rows): the niels table bank replicates;
    rows shard over the batch axis."""
    from fabric_tpu.ops import ed25519

    def local(bank, row_key, ry, r_sign, s, k):
        v = ed25519.verify_words_rows(bank, row_key, ry, r_sign, s, k)
        count = jax.lax.psum(jnp.sum(v.astype(jnp.int32)), BATCH_AXIS)
        return v, count

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=lane_specs("ed25519-rows"),
        out_specs=(PSpec(BATCH_AXIS), PSpec()))
    return jax.jit(fn)


def sharded_ed25519_verify(mesh: Mesh):
    """Build a jitted sharded ed25519 batch verifier over `mesh`.

    fn(ay, a_sign, ry, r_sign, s, k) -> (verdicts (B,), valid_count ()).
    """
    def local(ay, a_sign, ry, r_sign, s, k):
        v = ed25519.verify_words(ay, a_sign, ry, r_sign, s, k)
        count = jax.lax.psum(jnp.sum(v.astype(jnp.int32)), BATCH_AXIS)
        return v, count

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=lane_specs("ed25519"),
        out_specs=(PSpec(BATCH_AXIS), PSpec()))
    return jax.jit(fn)


def sharded_idemix_pair_verify(mesh: Mesh):
    """Sharded BN254 dual-pairing batch check (the idemix lane,
    ops/bn254_batch.pairing_check_batch).

    fn(flags, A1, B1, A2, B2, x1, y1, x2, y2) -> (verdicts (B,),
    valid_count ()): the Miller-loop line precomputes (w and g2 sides)
    replicate to every device; the per-presentation G1 limb coordinates
    (L, B) shard over the batch axis, B divisible by mesh size.
    """
    from fabric_tpu.ops import bn254_batch as bb

    def local(flags, A1, B1, A2, B2, x1, y1, x2, y2):
        v = bb.pairing_check_batch(
            {"flags": flags, "A": A1, "B": B1},
            {"flags": flags, "A": A2, "B": B2}, x1, y1, x2, y2)
        count = jax.lax.psum(jnp.sum(v.astype(jnp.int32)), BATCH_AXIS)
        return v, count

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=lane_specs("idemix-pair"),
        out_specs=(PSpec(BATCH_AXIS), PSpec()))
    return jax.jit(fn)
