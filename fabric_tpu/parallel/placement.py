"""Per-channel device placement: carve the chip mesh into sub-meshes.

The reference validates channels independently — each channel gets its
own txvalidator goroutine pool sized by `peer.validatorPoolSize`
(core/committer/txvalidator/v20/validator.go), all contending for the
same host cores.  Here the contended resource is the device mesh: a
peer joined to N channels owns all 8 chips, and pinning every channel's
batches to the full mesh would serialize them through one compiled
program while 7/8 of each tile sits empty on light channels.

`PlacementScheduler` instead assigns each channel a **disjoint
contiguous device span** sized from its observed pressure (EWMA of the
per-flush batch sizes the validator reports via `demand`, plus the
process-global `provider_dispatch_queue_depth` backlog at report time —
a flush landing behind unresolved device work signals more pressure
than its batch size alone):

  - shares are powers of two (`mesh.allocate_devices`), so the padded
    bucket series — and therefore the compiled-program set — is stable
    across rebalances;
  - spans are contiguous (`mesh.carve_submeshes`), keeping each
    sub-mesh on ICI-neighbouring chips;
  - rebalances are hysteretic: the carve is only redone when a new
    channel registers or some channel's demand drifts by more than
    `rebalance_ratio` from the demand snapshot the current carve was
    built from.  Providers are cached per device span, so a rebalance
    that hands a channel a span some earlier carve used re-attaches the
    already-warm provider instead of recompiling.

The scheduler never blocks a verify: `provider_for` does cheap host
bookkeeping and returns a provider; device work stays inside it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from fabric_tpu.parallel import mesh as meshmod


class PlacementScheduler:
    def __init__(self, devices=None, provider_factory=None,
                 wrap: Optional[Callable] = None,
                 rebalance_ratio: float = 2.0,
                 ewma_alpha: float = 0.3,
                 idle_halflife_s: float = 30.0,
                 clock: Optional[Callable[[], float]] = None):
        """`provider_factory(mesh) -> Provider` builds the per-span
        provider (a single-device provider when the span is one chip);
        `wrap(provider) -> provider` optionally decorates each one once
        (the factory passes the degradation breaker here so per-channel
        providers keep the SW-fallback behaviour of the global one)."""
        if devices is None:
            import jax
            devices = jax.devices()
        if provider_factory is None:
            from fabric_tpu.bccsp.jaxtpu import JaxTpuProvider

            def provider_factory(m):
                return JaxTpuProvider(mesh=m)
        self.devices = list(devices)
        self.provider_factory = provider_factory
        self.wrap = wrap
        self.rebalance_ratio = float(rebalance_ratio)
        self.ewma_alpha = float(ewma_alpha)
        self.idle_halflife_s = float(idle_halflife_s)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._demand = {}          # channel -> EWMA of reported batch sizes
        self._last_report = {}     # channel -> clock() of last demand report
        self._carve_demand = {}    # demand snapshot the current carve used
        self._assign = {}          # channel -> (lo, size)
        self._providers = {}       # (lo, size) -> wrapped provider
        self.rebalances = 0

    # -- internals (callers hold self._lock) --------------------------------

    def _span_provider(self, lo: int, size: int):
        key = (lo, size)
        p = self._providers.get(key)
        if p is None:
            if size == 1:
                m = None            # single chip: skip shard_map overhead
                p = self.provider_factory(m)
                # pin dispatches to the span's chip, not devices()[0]
                dev = self.devices[lo]
                if hasattr(p, "device_labels"):
                    p.device_labels = (f"{dev.platform}:{dev.id}",)
            else:
                m = meshmod.make_mesh(self.devices[lo:lo + size])
                p = self.provider_factory(m)
            if self.wrap is not None:
                p = self.wrap(p)
            self._providers[key] = p
        return p

    def _recarve(self):
        channels = sorted(self._demand)
        sizes = meshmod.allocate_devices(
            len(self.devices), [self._demand[c] for c in channels])
        lo = 0
        self._assign = {}
        for ch, sz in zip(channels, sizes):
            self._assign[ch] = (lo, sz)
            lo += sz
        self._carve_demand = dict(self._demand)
        self.rebalances += 1
        try:
            from fabric_tpu.ops_plane import registry
            g = registry.gauge(
                "placement_channel_devices",
                "devices assigned to each channel by the placement scheduler")
            for ch, (_, sz) in self._assign.items():
                g.set(float(sz), channel=ch)
        except Exception:
            pass

    def _decay_idle(self, now: float) -> None:
        """Halve a quiet channel's EWMA every `idle_halflife_s` it goes
        without reporting demand.  Without this a channel that went
        silent kept the demand of its last busy flush forever, pinning
        its device span until some OTHER channel's registration forced a
        recarve; with it, sustained silence drifts the demand past
        `rebalance_ratio` and the next flush on any channel releases the
        span back to the busy ones."""
        hl = self.idle_halflife_s
        if hl <= 0:
            return
        for ch, last in self._last_report.items():
            steps = int((now - last) // hl)
            if steps <= 0:
                continue
            d = self._demand.get(ch)
            if d is not None and d > 1e-6:
                self._demand[ch] = max(d * 0.5 ** steps, 1e-6)
            # advance by whole half-lives so decay never compounds per call
            self._last_report[ch] = last + steps * hl

    @staticmethod
    def _queue_backlog() -> float:
        """Process-global `provider_dispatch_queue_depth` — device
        dispatches enqueued but not yet resolved.  A flush that lands
        while earlier dispatches are still in flight is under-reporting
        pressure if only its own batch size counts, so the backlog is
        folded into the demand sample (the gauge is process-global; the
        reporting channel is the one currently contending with it)."""
        try:
            from fabric_tpu.ops_plane import registry
            g = registry.gauge(
                "provider_dispatch_queue_depth",
                "device dispatches enqueued, not yet resolved")
            return max(0.0, sum(g.values().values()))
        except Exception:
            return 0.0

    def _drifted(self) -> bool:
        for ch, d in self._demand.items():
            base = self._carve_demand.get(ch)
            if base is None:
                return True
            hi, lo = max(d, base, 1e-9), max(min(d, base), 1e-9)
            if hi / lo >= self.rebalance_ratio:
                return True
        return False

    # -- public API ----------------------------------------------------------

    def provider_for(self, channel_id: str, demand: Optional[int] = None):
        """The provider for `channel_id`'s current device span.

        `demand` is the caller's queue depth at this flush (batch size);
        it feeds the EWMA that sizes the next carve.  Registration of a
        new channel always recarves; otherwise only ratio drift does."""
        with self._lock:
            now = self._clock()
            a = self.ewma_alpha
            prev = self._demand.get(channel_id)
            if demand is not None and demand > 0:
                sample = float(demand) + self._queue_backlog()
                self._demand[channel_id] = (
                    sample if prev is None
                    else (1 - a) * prev + a * sample)
                self._last_report[channel_id] = now
            elif prev is None:
                self._demand[channel_id] = 1.0
                self._last_report[channel_id] = now
            self._decay_idle(now)
            new_channel = channel_id not in self._assign
            if new_channel or (self._drifted() and self._would_resize()):
                self._recarve()
            lo, size = self._assign[channel_id]
            return self._span_provider(lo, size)

    def _would_resize(self) -> bool:
        """True when recarving under current demand changes any span
        size — drift that allocates identically is not worth a carve."""
        channels = sorted(self._demand)
        sizes = meshmod.allocate_devices(
            len(self.devices), [self._demand[c] for c in channels])
        for ch, sz in zip(channels, sizes):
            cur = self._assign.get(ch)
            if cur is None or cur[1] != sz:
                return True
        return False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "channels": {
                    ch: {"devices": sz, "span_start": lo,
                         "demand_ewma": round(self._demand.get(ch, 0.0), 2)}
                    for ch, (lo, sz) in self._assign.items()},
                "n_devices": len(self.devices),
                "rebalances": self.rebalances,
                "cached_spans": sorted(self._providers),
            }
