"""MVCC state validation — must stay bit-identical to the reference's.

Reference parity: core/ledger/kvledger/txmgmt/validation/validator.go —
validateAndPrepareBatch (:83), validateKVRead (:175), and
rangequery_validator.go.  Semantics preserved exactly:

- txs are considered in block order; only txs whose flag is still VALID
  after the signature/policy gate are state-validated;
- a read is valid iff its recorded version equals the key's current
  committed version, where "current" includes writes of *preceding valid
  txs in this same block* (the in-flight update batch);
- range queries are re-executed against committed-state-merged-with-batch
  and compared read-for-read; a mismatch (changed value version, added or
  removed key) is a PHANTOM_READ_CONFLICT;
- a valid tx's writes join the batch at Version(block_num, tx_num).

The verify-then-gate restructure (SURVEY.md §7) does not touch this pass:
it runs after the TPU verdict bitmap has been folded into the flags.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from fabric_tpu.protocol import (
    Envelope,
    KVRead,
    NsRwSet,
    Transaction,
    TxRwSet,
    Version,
)
from fabric_tpu.protocol.txflags import TxFlags, ValidationCode
from fabric_tpu.protocol.types import RangeQueryInfo, TX_ENDORSER

from .statedb import StateDB, UpdateBatch, VersionedValue


def _batch_merged_get(db: StateDB, batch: UpdateBatch, ns: str, key: str
                      ) -> Optional[VersionedValue]:
    found, vv = batch.get(ns, key)
    if found:
        return vv  # None here means staged delete
    return db.get(ns, key)


def _validate_read(db: StateDB, batch: UpdateBatch, ns: str,
                   read: KVRead) -> bool:
    """validateKVRead (validator.go:175): version equality, nil-safe."""
    vv = _batch_merged_get(db, batch, ns, read.key)
    committed = None if vv is None else vv.version
    if committed is None and read.version is None:
        return True
    if committed is None or read.version is None:
        return False
    return (committed.block_num == read.version.block_num
            and committed.tx_num == read.version.tx_num)


def _merged_range(db: StateDB, batch: UpdateBatch, ns: str,
                  start_key: str, end_key: str):
    """Committed range merged with the in-flight batch, key-ordered
    (the combined iterator in rangequery_validator.go)."""
    committed = {k: vv for k, vv in db.range_scan(ns, start_key, end_key)}
    for (bns, key), vv in batch.items():
        if bns != ns:
            continue
        if key < start_key or (end_key and key >= end_key):
            continue
        if vv is None:
            committed.pop(key, None)
        else:
            committed[key] = vv
    return sorted(committed.items())


def _validate_range_query(db: StateDB, batch: UpdateBatch, ns: str,
                          rq: RangeQueryInfo) -> bool:
    """Raw-reads replay: result set must match read-for-read.  If the
    recorded iterator was NOT exhausted, the replay may see extra trailing
    keys; any difference within the consumed prefix is a phantom."""
    actual = _merged_range(db, batch, ns, rq.start_key, rq.end_key)
    recorded = rq.reads
    if rq.itr_exhausted and len(actual) != len(recorded):
        return False
    if len(actual) < len(recorded):
        return False
    for rec, (key, vv) in zip(recorded, actual):
        if rec.key != key:
            return False
        if rec.version is None:
            return False  # recorded a missing key that now exists
        if (vv.version.block_num != rec.version.block_num
                or vv.version.tx_num != rec.version.tx_num):
            return False
    return True


def parse_endorser_tx(env: Envelope) -> Optional[Tuple[str, TxRwSet]]:
    """(txid, rwset) of an endorser tx envelope; None for other tx types.
    Decodes the payload exactly once — this runs per tx in the commit hot
    path, so no repeated FTLV decoding."""
    payload = env.payload_dict()
    ch = payload["header"]["channel_header"]
    if ch["type"] != TX_ENDORSER:
        return None
    tx = Transaction.from_dict(payload["data"])
    if not tx.actions:
        return None
    return ch["txid"], tx.actions[0].action.rwset


def extract_rwset(env: Envelope) -> Optional[TxRwSet]:
    """Compatibility wrapper over parse_endorser_tx."""
    parsed = parse_endorser_tx(env)
    return None if parsed is None else parsed[1]


def validate_and_prepare_batch(
        db: StateDB, block_num: int,
        envelopes: List[Envelope], flags: TxFlags,
) -> Tuple[UpdateBatch, List[Tuple[int, str, str, str, bytes, bool]]]:
    """validateAndPrepareBatch (validator.go:83).

    Mutates `flags` (MVCC_READ_CONFLICT / PHANTOM_READ_CONFLICT /
    BAD_RWSET) and returns (update_batch, history_writes) where
    history_writes = (tx_num, txid, ns, key, value, is_delete) of VALID txs.
    """
    batch = UpdateBatch()
    history: List[Tuple[int, str, str, str, bytes, bool]] = []
    for tx_num, env in enumerate(envelopes):
        if not flags.is_valid(tx_num):
            continue
        try:
            parsed = parse_endorser_tx(env)
        except Exception:
            flags.set(tx_num, ValidationCode.BAD_RWSET)
            continue
        if parsed is None:
            continue  # config txs etc. don't carry kv rwsets
        txid, rwset = parsed
        ok = True
        for ns_rw in rwset.ns_rwsets:
            for read in ns_rw.reads:
                if not _validate_read(db, batch, ns_rw.namespace, read):
                    flags.set(tx_num, ValidationCode.MVCC_READ_CONFLICT)
                    ok = False
                    break
            if not ok:
                break
            for rq in ns_rw.range_queries:
                if not _validate_range_query(db, batch, ns_rw.namespace, rq):
                    flags.set(tx_num, ValidationCode.PHANTOM_READ_CONFLICT)
                    ok = False
                    break
            if not ok:
                break
        if not ok:
            continue
        version = Version(block_num, tx_num)
        for ns_rw in rwset.ns_rwsets:
            for w in ns_rw.writes:
                if w.is_delete:
                    batch.delete(ns_rw.namespace, w.key, version)
                else:
                    batch.put(ns_rw.namespace, w.key, w.value, version)
                history.append((tx_num, txid, ns_rw.namespace, w.key,
                                w.value, w.is_delete))
    return batch, history
