"""Append-only block store with number/hash/txid index.

Reference parity: common/ledger/blkstorage/{blockfile_mgr,blockindex,
blockstore}.go — append-only block files + a LevelDB index keyed by block
number, block hash, and txid, plus chain info (height, current hash) and
block iterators.

Layout here: numbered segment files `blocks_000000.bin` holding
length-prefixed serialized blocks; the index is rebuilt by scanning on
open (the reference scans only the last partial file because its index is
durable; our scan is cheap at framework scale and doubles as the
crash-recovery pass — a torn trailing write is truncated, mirroring
blockfile_mgr's partial-write recovery).

A native C++ segment backend (fabric_tpu/native) can replace the Python
file I/O transparently; the index and API stay identical.

Snapshot bootstrap: a store created from a shipped state snapshot has no
blocks below the snapshot height.  A `BOOTSTRAP.json` marker records the
base height and the chain hashes at the boundary (bootstrapFromSnapshot
+ bootstrappingSnapshotInfo in the reference's blockfile_mgr), so the
chain check for the first delivered block and commit-hash chaining both
survive the gap; blocks below `base` read as pruned.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_tpu.protocol import Block, Envelope, block_header_hash
from fabric_tpu.protocol import wire
from fabric_tpu.protocol.types import META_TXFLAGS
from fabric_tpu.protocol.txflags import TxFlags, ValidationCode

_LEN = struct.Struct("<Q")
SEGMENT_MAX_BYTES = 64 * 1024 * 1024
BOOTSTRAP_FILE = "BOOTSTRAP.json"


class BlockStoreError(Exception):
    pass


@dataclass
class ChainInfo:
    """common.BlockchainInfo equivalent."""
    height: int
    current_hash: bytes
    previous_hash: bytes


@dataclass
class _Loc:
    segment: int
    offset: int
    length: int


class BlockStore:
    """One channel's block store (blkstorage.BlockStore)."""

    def __init__(self, root: Optional[str] = None,
                 segment_max_bytes: int = SEGMENT_MAX_BYTES):
        self.root = root  # None = pure in-memory (no files, no durability)
        self.segment_max = segment_max_bytes
        self._lock = threading.RLock()
        self._by_number: List[_Loc] = []
        self._mem_blocks: List[bytes] = []  # in-memory mode payloads
        self._by_hash: Dict[bytes, int] = {}
        self._by_txid: Dict[str, Tuple[int, int]] = {}  # txid -> (block, tx idx)
        self._cur_hash = b"\x00" * 32
        self._prev_hash = b"\x00" * 32
        self._open_segment_no = 0
        # snapshot-bootstrap boundary: blocks < base are pruned
        self.base = 0
        self.bootstrap_commit_hash: Optional[bytes] = None
        self._base_cur_hash = b"\x00" * 32
        self._base_prev_hash = b"\x00" * 32
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._load_bootstrap()
            self._recover()

    # -- recovery / files ---------------------------------------------------

    def _seg_path(self, n: int) -> str:
        return os.path.join(self.root, f"blocks_{n:06d}.bin")

    def _load_bootstrap(self) -> None:
        path = os.path.join(self.root, BOOTSTRAP_FILE)
        if not os.path.exists(path):
            return
        with open(path, "r", encoding="utf-8") as f:
            info = json.load(f)
        self.base = int(info["base"])
        self._base_cur_hash = bytes.fromhex(info["current_hash"])
        self._base_prev_hash = bytes.fromhex(info["previous_hash"])
        self.bootstrap_commit_hash = bytes.fromhex(info["commit_hash"])
        self._cur_hash = self._base_cur_hash
        self._prev_hash = self._base_prev_hash

    @staticmethod
    def write_bootstrap(root: str, base: int, current_hash: bytes,
                        previous_hash: bytes, commit_hash: bytes) -> None:
        """Durably stamp a snapshot-bootstrap boundary.  Written LAST by
        the snapshot installer — its presence is the commit point that
        makes an installed snapshot visible."""
        os.makedirs(root, exist_ok=True)
        path = os.path.join(root, BOOTSTRAP_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"base": int(base),
                       "current_hash": current_hash.hex(),
                       "previous_hash": previous_hash.hex(),
                       "commit_hash": commit_hash.hex()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _segments(self) -> List[int]:
        out = []
        for name in os.listdir(self.root):
            if name.startswith("blocks_") and name.endswith(".bin"):
                out.append(int(name[7:13]))
        return sorted(out)

    def _recover(self) -> None:
        """Scan all segments; truncate a torn trailing record
        (blockfile_mgr partial-write recovery)."""
        for seg in self._segments():
            path = self._seg_path(seg)
            good_end = 0
            with open(path, "rb") as f:
                data = f.read()
            off = 0
            while off + _LEN.size <= len(data):
                (n,) = _LEN.unpack_from(data, off)
                if off + _LEN.size + n > len(data):
                    break  # torn write
                try:
                    block = Block.deserialize(data[off + _LEN.size:off + _LEN.size + n])
                except ValueError:
                    break
                self._index_block(block, _Loc(seg, off, _LEN.size + n))
                off += _LEN.size + n
                good_end = off
            if good_end != len(data):
                with open(path, "r+b") as f:
                    f.truncate(good_end)
        if self._by_number:
            segs = self._segments()
            self._open_segment_no = segs[-1] if segs else 0

    def _index_block(self, block: Block, loc: _Loc) -> None:
        num = block.header.number
        if num != self.base + len(self._by_number):
            raise BlockStoreError(
                f"block {num} out of order "
                f"(height {self.base + len(self._by_number)})")
        self._by_number.append(loc)
        h = block_header_hash(block.header)
        self._by_hash[h] = num
        self._prev_hash = block.header.previous_hash
        self._cur_hash = h
        for i, env_bytes in enumerate(block.data):
            # native header peek; full decode only when it rejects
            summary = wire.envelope_summary(env_bytes)
            if summary is not None:
                txid = summary[2]
            else:
                try:
                    txid = Envelope.deserialize(
                        env_bytes).header().channel_header.txid
                except Exception:
                    continue
            # first writer wins: duplicate txids keep the earliest location
            self._by_txid.setdefault(txid, (num, i))

    # -- writes -------------------------------------------------------------

    def add_block(self, block: Block) -> None:
        with self._lock:
            if block.header.number != self.height:
                raise BlockStoreError(
                    f"expected block {self.height}, got {block.header.number}")
            if self.height > 0 and block.header.previous_hash != self._cur_hash:
                raise BlockStoreError("previous-hash mismatch")
            payload = block.serialize()
            if self.root is None:
                self._mem_blocks.append(payload)
                self._index_block(block, _Loc(-1, len(self._mem_blocks) - 1, 0))
                return
            path = self._seg_path(self._open_segment_no)
            if (os.path.exists(path)
                    and os.path.getsize(path) + len(payload) > self.segment_max):
                self._open_segment_no += 1
                path = self._seg_path(self._open_segment_no)
            offset = os.path.getsize(path) if os.path.exists(path) else 0
            with open(path, "ab") as f:
                f.write(_LEN.pack(len(payload)))
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            self._index_block(
                block, _Loc(self._open_segment_no, offset,
                            _LEN.size + len(payload)))

    def truncate(self, new_height: int) -> None:
        """Drop every block numbered >= new_height (the storage half of
        ledger rollback, blkstorage ResetBlockStore/rollback).  Rewrites
        the retained prefix — an administrative operation, not a hot
        path.  Cannot descend below a snapshot-bootstrap base (those
        blocks were never stored)."""
        with self._lock:
            if new_height < self.base or new_height >= self.height:
                return
            blocks = [self.get_by_number(i)
                      for i in range(self.base, new_height)]
            self._by_number = []
            self._mem_blocks = []
            self._by_hash = {}
            self._by_txid = {}
            self._cur_hash = self._base_cur_hash
            self._prev_hash = self._base_prev_hash
            self._open_segment_no = 0
            if self.root is not None:
                for seg in self._segments():
                    os.unlink(self._seg_path(seg))
            for block in blocks:
                self.add_block(block)

    # -- reads --------------------------------------------------------------

    @property
    def height(self) -> int:
        return self.base + len(self._by_number)

    def chain_info(self) -> ChainInfo:
        with self._lock:
            return ChainInfo(self.height, self._cur_hash, self._prev_hash)

    def _read(self, loc: _Loc) -> Block:
        if loc.segment < 0:
            return Block.deserialize(self._mem_blocks[loc.offset])
        with open(self._seg_path(loc.segment), "rb") as f:
            f.seek(loc.offset)
            raw = f.read(loc.length)
        return Block.deserialize(raw[_LEN.size:])

    def get_by_number(self, number: int) -> Block:
        with self._lock:
            if 0 <= number < self.base:
                raise BlockStoreError(
                    f"block {number} pruned below snapshot base {self.base}")
            if not self.base <= number < self.height:
                raise BlockStoreError(f"no block {number} (height {self.height})")
            return self._read(self._by_number[number - self.base])

    def get_by_hash(self, block_hash: bytes) -> Block:
        with self._lock:
            if block_hash not in self._by_hash:
                raise BlockStoreError("unknown block hash")
            return self.get_by_number(self._by_hash[block_hash])

    def get_by_txid(self, txid: str) -> Block:
        with self._lock:
            if txid not in self._by_txid:
                raise BlockStoreError(f"unknown txid {txid!r}")
            return self.get_by_number(self._by_txid[txid][0])

    def get_tx_validation_code(self, txid: str) -> ValidationCode:
        """blkstorage RetrieveTxValidationCodeByTxID."""
        with self._lock:
            if txid not in self._by_txid:
                raise BlockStoreError(f"unknown txid {txid!r}")
            num, idx = self._by_txid[txid]
            block = self.get_by_number(num)
        flags = TxFlags.from_bytes(block.metadata.items.get(META_TXFLAGS, b""))
        if idx >= len(flags):
            return ValidationCode.NOT_VALIDATED
        return flags.flag(idx)

    def has_txid(self, txid: str) -> bool:
        with self._lock:
            return txid in self._by_txid

    def iter_blocks(self, start: int = 0,
                    end: Optional[int] = None) -> Iterator[Block]:
        """Blocks [start, end) — ledger.ResultsIterator over blocks.
        Starts at the snapshot base when asked for pruned history."""
        n = max(start, self.base)
        while end is None or n < end:
            with self._lock:
                if n >= self.height:
                    return
                loc = self._by_number[n - self.base]
            yield self._read(loc)
            n += 1
