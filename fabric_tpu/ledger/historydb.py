"""Key-history index: which (block, tx) wrote each (ns, key).

Reference parity: core/ledger/kvledger/history/ — a write-only index
committed per block, queried by GetHistoryForKey (qscc / chaincode shim).
Only VALID transactions' writes are indexed, newest first on query.

Durable via the same WAL pattern as the state DB; rebuildable from the
block store (rebuild_dbs.go parity is handled by kvledger).
"""

from __future__ import annotations

import os
import struct
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from fabric_tpu.utils import serde

_LEN = struct.Struct("<Q")


@dataclass(frozen=True)
class KeyMod:
    """One historical modification (history.KeyModification)."""
    block_num: int
    tx_num: int
    txid: str
    value: bytes
    is_delete: bool


class HistoryDB:
    def __init__(self, root: Optional[str] = None):
        self.root = root
        self._lock = threading.RLock()
        self._index: Dict[Tuple[str, str], List[KeyMod]] = {}
        self._savepoint: Optional[int] = None
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._recover()

    @property
    def savepoint(self) -> Optional[int]:
        with self._lock:
            return self._savepoint

    def commit(self, block_num: int,
               writes: List[Tuple[int, str, str, str, bytes, bool]]) -> None:
        """writes: (tx_num, txid, ns, key, value, is_delete) of VALID txs."""
        with self._lock:
            if self._savepoint is not None and block_num <= self._savepoint:
                return  # already committed (recovery replay)
            if self.root is not None:
                payload = serde.encode(
                    {"block": block_num,
                     "writes": [[t, x, n, k, v, d]
                                for t, x, n, k, v, d in writes]})
                with open(self._wal_path(), "ab") as f:
                    f.write(_LEN.pack(len(payload)))
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
            self._apply(block_num, writes)

    def _apply(self, block_num, writes) -> None:
        # group the block's writes per key first, then extend each
        # key's list ONCE — one dict probe per touched key instead of
        # one per write (walk order within a key is preserved, so query
        # order is unchanged)
        grouped: Dict[Tuple[str, str], List[KeyMod]] = {}
        for tx_num, txid, ns, key, value, is_delete in writes:
            grouped.setdefault((ns, key), []).append(
                KeyMod(block_num, tx_num, txid, value, is_delete))
        index = self._index
        for k, mods in grouped.items():
            prev = index.get(k)
            if prev is None:
                index[k] = mods
            else:
                prev.extend(mods)
        self._savepoint = block_num

    def get_history(self, ns: str, key: str) -> List[KeyMod]:
        """Newest-first modification list (GetHistoryForKey)."""
        with self._lock:
            return list(reversed(self._index.get((ns, key), [])))

    def _wal_path(self) -> str:
        return os.path.join(self.root, "history.wal")

    def _recover(self) -> None:
        if not os.path.exists(self._wal_path()):
            return
        with open(self._wal_path(), "rb") as f:
            data = f.read()
        off, good_end = 0, 0
        while off + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, off)
            if off + _LEN.size + n > len(data):
                break
            try:
                rec = serde.decode(data[off + _LEN.size:off + _LEN.size + n])
            except ValueError:
                break
            off += _LEN.size + n
            good_end = off
            self._apply(rec["block"],
                        [tuple(w) for w in rec["writes"]])
        if good_end != len(data):
            with open(self._wal_path(), "r+b") as f:
                f.truncate(good_end)
