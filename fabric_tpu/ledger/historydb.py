"""Key-history index: which (block, tx) wrote each (ns, key).

Reference parity: core/ledger/kvledger/history/ — a write-only index
committed per block, queried by GetHistoryForKey (qscc / chaincode shim).
Only VALID transactions' writes are indexed, newest first on query.

Sharded by the same key-hash as the state DB (ledger/statedb.shard_of)
and durable via the same WAL + crash-consistent checkpoint pattern
(ledger/checkpoint.py): per-shard content-hashed flush files behind an
atomically-renamed manifest.  Checkpoints bound recovery to savepoint +
WAL tail replay — previously this store replayed its ENTIRE WAL on
every open.  Rebuildable from the block store (rebuild_dbs.go parity is
handled by kvledger).
"""

from __future__ import annotations

import os
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from fabric_tpu.ledger import checkpoint as ckpt
from fabric_tpu.ledger.statedb import shard_of
from fabric_tpu.utils import serde

_LEN = struct.Struct("<Q")
CHECKPOINT_EVERY = 256  # blocks between checkpoint compactions


@dataclass(frozen=True)
class KeyMod:
    """One historical modification (history.KeyModification)."""
    block_num: int
    tx_num: int
    txid: str
    value: bytes
    is_delete: bool


class HistoryDB:
    def __init__(self, root: Optional[str] = None,
                 n_shards: int = 1,
                 checkpoint_every: int = CHECKPOINT_EVERY,
                 channel: str = ""):
        self.root = root
        self.n_shards = max(1, int(n_shards))
        self.checkpoint_every = checkpoint_every
        self.channel = channel
        self._lock = threading.RLock()
        # one index stripe per shard; queries are rare enough that a
        # single store lock covers them (the sharding buys independently
        # flushable checkpoint files + placement agreement with statedb)
        self._shards: List[Dict[Tuple[str, str], List[KeyMod]]] = [
            {} for _ in range(self.n_shards)]
        self._savepoint: Optional[int] = None
        self._blocks_since_ckpt = 0
        self._ckpt_gen = 0
        # gen -> lease expiry: see statedb.pin_generation
        self._gen_pins: dict = {}
        self._pool: Optional[ThreadPoolExecutor] = None
        self.last_recovery = {"source": "fresh", "wal_blocks": 0,
                              "savepoint": None}
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._recover()

    @property
    def savepoint(self) -> Optional[int]:
        with self._lock:
            return self._savepoint

    def commit(self, block_num: int,
               writes: List[Tuple[int, str, str, str, bytes, bool]]) -> None:
        """writes: (tx_num, txid, ns, key, value, is_delete) of VALID txs."""
        with self._lock:
            if self._savepoint is not None and block_num <= self._savepoint:
                return  # already committed (recovery replay)
            if self.root is not None:
                payload = serde.encode(
                    {"block": block_num,
                     "writes": [[t, x, n, k, v, d]
                                for t, x, n, k, v, d in writes]})
                with open(self._wal_path(), "ab") as f:
                    f.write(_LEN.pack(len(payload)))
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
            self._apply(block_num, writes)
            if self.root is not None:
                self._blocks_since_ckpt += 1
                if self._blocks_since_ckpt >= self.checkpoint_every:
                    self._checkpoint_locked()

    def _apply(self, block_num, writes) -> None:
        # group the block's writes per key first, then extend each
        # key's list ONCE — one dict probe per touched key instead of
        # one per write (walk order within a key is preserved, so query
        # order is unchanged)
        grouped: Dict[Tuple[str, str], List[KeyMod]] = {}
        for tx_num, txid, ns, key, value, is_delete in writes:
            grouped.setdefault((ns, key), []).append(
                KeyMod(block_num, tx_num, txid, value, is_delete))
        for k, mods in grouped.items():
            index = self._shards[shard_of(k[0], k[1], self.n_shards)]
            prev = index.get(k)
            if prev is None:
                index[k] = mods
            else:
                prev.extend(mods)
        self._savepoint = block_num

    def get_history(self, ns: str, key: str) -> List[KeyMod]:
        """Newest-first modification list (GetHistoryForKey)."""
        with self._lock:
            index = self._shards[shard_of(ns, key, self.n_shards)]
            return list(reversed(index.get((ns, key), [])))

    @property
    def _index(self) -> Dict[Tuple[str, str], List[KeyMod]]:
        """Merged read-only view of every shard (flat-store compat for
        tests/tooling; the shards are the real storage)."""
        merged: Dict[Tuple[str, str], List[KeyMod]] = {}
        with self._lock:
            for index in self._shards:
                merged.update(index)
        return merged

    def status(self) -> dict:
        with self._lock:
            return {
                "n_shards": self.n_shards,
                "savepoint": self._savepoint,
                "keys": sum(len(s) for s in self._shards),
                "checkpoint_gen": self._ckpt_gen,
                "last_recovery": dict(self.last_recovery),
            }

    # -- persistence --------------------------------------------------------

    def _wal_path(self) -> str:
        return os.path.join(self.root, "history.wal")

    def checkpoint(self) -> Optional[dict]:
        """Flush every shard + flip the manifest (see statedb.checkpoint)."""
        with self._lock:
            if self.root is None or self._savepoint is None:
                return None
            if self._blocks_since_ckpt == 0:
                m = ckpt.read_manifest(self.root)
                if m is not None and m.get("savepoint") == self._savepoint:
                    return m
            return self._checkpoint_locked()

    def pin_generation(self, gen: int, ttl_s: float = 60.0) -> None:
        """Lease-pin a checkpoint generation against GC (see
        statedb.pin_generation — same contract, history store)."""
        with self._lock:
            self._gen_pins[int(gen)] = time.monotonic() + float(ttl_s)

    def _live_pins(self) -> set:
        now = time.monotonic()
        self._gen_pins = {g: t for g, t in self._gen_pins.items()
                          if t > now}
        return set(self._gen_pins)

    # shard-parallel checkpoint serialization: mirrors statedb's
    # core-count gate so single-core hosts never pay pool overhead
    _PARALLEL_CKPT_MIN = 512
    _HOST_CORES = os.cpu_count() or 1

    def _get_pool(self) -> ThreadPoolExecutor:
        if self._pool is None:
            workers = min(self.n_shards, max(2, os.cpu_count() or 2))
            self._pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="historydb-ckpt")
        return self._pool

    def _checkpoint_locked(self) -> dict:
        t0 = time.monotonic()
        gen = self._ckpt_gen + 1

        def _encode_shard(i: int) -> bytes:
            index = self._shards[i]
            recs = []
            for (ns, key) in sorted(index.keys()):
                recs.append(
                    [ns, key,
                     [[m.block_num, m.tx_num, m.txid, m.value, m.is_delete]
                      for m in index[(ns, key)]]])
            return serde.encode(
                {"savepoint": self._savepoint, "shard": i,
                 "n_shards": self.n_shards, "data": recs})

        # shards are read-only for the duration of the lock; pool.map
        # preserves order so the payload list is bit-identical to the
        # serial build
        total = sum(len(s) for s in self._shards)
        if (self._HOST_CORES > 1 and len(self._shards) > 1
                and total >= self._PARALLEL_CKPT_MIN):
            payloads = list(self._get_pool().map(
                _encode_shard, range(len(self._shards))))
        else:
            payloads = [_encode_shard(i) for i in range(len(self._shards))]
        manifest = ckpt.write_checkpoint(
            self.root, gen, payloads,
            meta={"savepoint": self._savepoint, "kind": "history"})
        with open(self._wal_path(), "wb") as f:
            f.truncate(0)
        ckpt.gc_generations(self.root, {gen, gen - 1} | self._live_pins())
        self._ckpt_gen = gen
        self._blocks_since_ckpt = 0
        try:
            from fabric_tpu.ops_plane import tracing
            tracing.event("history.checkpoint", channel=self.channel,
                          gen=gen, savepoint=self._savepoint,
                          seconds=round(time.monotonic() - t0, 6))
        except Exception:
            pass
        return manifest

    def _recover(self) -> None:
        source = "empty"
        manifest, payloads, src = ckpt.recover(self.root)
        if manifest is not None and manifest.get("kind") == "history":
            for d in (serde.decode(p) for p in payloads):
                for ns, key, mods in d["data"]:
                    index = self._shards[shard_of(ns, key, self.n_shards)]
                    index[(ns, key)] = [
                        KeyMod(b, t, x, v, bool(dl))
                        for b, t, x, v, dl in mods]
            self._savepoint = manifest.get("savepoint")
            self._ckpt_gen = int(manifest["gen"])
            source = src
        wal_blocks = 0
        if os.path.exists(self._wal_path()):
            with open(self._wal_path(), "rb") as f:
                data = f.read()
            off, good_end = 0, 0
            while off + _LEN.size <= len(data):
                (n,) = _LEN.unpack_from(data, off)
                if off + _LEN.size + n > len(data):
                    break
                try:
                    rec = serde.decode(
                        data[off + _LEN.size:off + _LEN.size + n])
                except ValueError:
                    break
                off += _LEN.size + n
                good_end = off
                if (self._savepoint is not None
                        and rec["block"] <= self._savepoint):
                    continue  # already in checkpoint
                self._apply(rec["block"],
                            [tuple(w) for w in rec["writes"]])
                wal_blocks += 1
            if good_end != len(data):
                with open(self._wal_path(), "r+b") as f:
                    f.truncate(good_end)
        self.last_recovery = {"source": source, "wal_blocks": wal_blocks,
                              "savepoint": self._savepoint}
