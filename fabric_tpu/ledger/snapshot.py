"""Snapshot state-transfer: bootstrap a new/wiped peer from a serving
peer's checkpoint instead of replaying the chain from genesis.

Reference parity: core/ledger/kvledger snapshot generation +
`peer node join-by-snapshot` — a snapshot is the derived DBs at one
block height plus enough chain metadata (block hash, commit hash) to
verify and continue from there.

Protocol (two unary verbs over the existing authenticated comm/rpc
plane — the transport handshake already restricts callers to channel
MSPs):

  state.snapshot_meta  {channel} ->
      {height, base, current_hash, previous_hash, commit_hash,
       state_manifest, history_manifest, files:[{db,gen,file,sha256,bytes}]}
  state.snapshot_chunk {channel, db, gen, file, offset} ->
      {data, eof, size}          (CHUNK_BYTES per call)

The serving peer reuses the newest COMPLETE on-disk checkpoint pair
when one exists (forcing one via kvledger.snapshot_export only when
none does) and streams the exact on-disk files; served generations are
lease-pinned against checkpoint GC so a new generation can be written
mid-fetch without deleting the one being streamed.  Integrity is
end-to-end:
the manifest carries each shard file's sha256 and the installer refuses
any assembled file whose hash mismatches — a corrupted/truncated
transfer is re-fetched, never installed.

Install ordering is the commit protocol: state files → state MANIFEST →
history files → history MANIFEST → blocks/BOOTSTRAP.json LAST.  The
bootstrap marker is the commit point — a kill mid-install leaves no
marker, `needs_bootstrap` stays true, and the next attempt wipes the
partial install and re-fetches.  After install the peer opens its
ledger at the snapshot height and the deliver/gossip plane tail-replays
to tip (blocks below the base read as pruned).
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Dict, List, Optional, Tuple

from fabric_tpu.ledger import checkpoint as ckpt
from fabric_tpu.ledger.blkstorage import BOOTSTRAP_FILE, BlockStore
from fabric_tpu.protocol import block_header_hash
from fabric_tpu.protocol.types import META_COMMIT_HASH

logger = logging.getLogger("fabric_tpu.ledger.snapshot")

CHUNK_BYTES = 256 * 1024
META_VERB = "state.snapshot_meta"
CHUNK_VERB = "state.snapshot_chunk"


class SnapshotError(Exception):
    pass


# -- serving side -----------------------------------------------------------

def _manifest_on_disk(root: Optional[str],
                      manifest: Optional[dict]) -> bool:
    """True when every shard file the manifest lists is present with
    the advertised size (content hashes are verified end-to-end by the
    fetching client, so an existence+size probe is enough here)."""
    if root is None or manifest is None:
        return False
    d = ckpt.gen_dir(root, int(manifest["gen"]))
    for ent in manifest["shards"]:
        try:
            if os.path.getsize(
                    os.path.join(d, os.path.basename(str(ent["file"])))) \
                    != int(ent["bytes"]):
                return False
        except (OSError, KeyError, TypeError, ValueError):
            return False
    return True


def _reusable_manifests(ledger) -> Tuple[Optional[dict], Optional[dict]]:
    """The newest COMPLETE on-disk checkpoint pair, or (None, None).

    Serving an existing generation instead of force-checkpointing per
    meta request is what lets N peers bootstrap concurrently under
    load: each forced checkpoint mints a new generation and GC keeps
    only {gen, gen-1}, so concurrent exports used to delete the very
    files another bootstrapper was mid-fetch — a refetch livelock.  A
    STALE savepoint is harmless: the joiner simply joins at the
    manifest's height and tail-replays more blocks to tip."""
    sm = ckpt.read_manifest(ledger.statedb.root) \
        if ledger.statedb.root is not None else None
    if not _manifest_on_disk(ledger.statedb.root, sm):
        return None, None
    if ledger.historydb is None or ledger.historydb.root is None:
        return sm, None
    hm = ckpt.read_manifest(ledger.historydb.root)
    # both DBs must describe the SAME savepoint for a coherent install
    if (not _manifest_on_disk(ledger.historydb.root, hm)
            or hm.get("savepoint") != sm.get("savepoint")):
        return None, None
    return sm, hm


def export_meta(ledger) -> dict:
    """Describe a servable snapshot (the state.snapshot_meta handler):
    reuse the newest complete on-disk checkpoint generation when one
    exists, force-checkpoint both derived DBs only when none does."""
    t0 = time.monotonic()
    state_manifest, history_manifest = _reusable_manifests(ledger)
    if state_manifest is not None:
        try:
            blk = ledger.blockstore.get_by_number(
                int(state_manifest["savepoint"]))
        except Exception:
            # savepoint block pruned/unavailable (e.g. this peer itself
            # snapshot-bootstrapped above it) — fall back to forcing
            state_manifest = history_manifest = None
    if state_manifest is None:
        state_manifest, history_manifest = ledger.snapshot_export()
        if state_manifest is None:
            raise SnapshotError(
                "nothing to snapshot (empty or in-memory ledger)")
        blk = ledger.blockstore.get_by_number(
            int(state_manifest["savepoint"]))
    savepoint = int(state_manifest["savepoint"])
    # lease the served generations against concurrent checkpoint GC;
    # serve_chunk refreshes the lease per chunk for the fetch duration
    ledger.statedb.pin_generation(int(state_manifest["gen"]))
    if history_manifest is not None:
        ledger.historydb.pin_generation(int(history_manifest["gen"]))
    files = [{"db": "state", "gen": state_manifest["gen"],
              "file": ent["file"], "sha256": ent["sha256"],
              "bytes": ent["bytes"]}
             for ent in state_manifest["shards"]]
    if history_manifest is not None:
        files += [{"db": "history", "gen": history_manifest["gen"],
                   "file": ent["file"], "sha256": ent["sha256"],
                   "bytes": ent["bytes"]}
                  for ent in history_manifest["shards"]]
    meta = {
        "channel": ledger.channel_id,
        "height": savepoint + 1,          # ledger height at the snapshot
        "current_hash": block_header_hash(blk.header),
        "previous_hash": blk.header.previous_hash,
        "commit_hash": blk.metadata.items.get(META_COMMIT_HASH,
                                              b"\x00" * 32),
        "state_manifest": state_manifest,
        "history_manifest": history_manifest,
        "files": files,
    }
    try:
        from fabric_tpu.ops_plane import tracing
        tracing.event("state.snapshot_export", channel=ledger.channel_id,
                      height=savepoint + 1, files=len(files),
                      seconds=round(time.monotonic() - t0, 6))
    except Exception:
        pass
    return meta


def serve_chunk(ledger, db: str, gen: int, file: str, offset: int) -> dict:
    """One CHUNK_BYTES read of a checkpoint shard file (the
    state.snapshot_chunk handler)."""
    if db == "state":
        droot = ledger.statedb.root
        store = ledger.statedb
    elif db == "history":
        droot = None if ledger.historydb is None else ledger.historydb.root
        store = ledger.historydb
    else:
        raise SnapshotError(f"unknown snapshot db {db!r}")
    if droot is None:
        raise SnapshotError(f"{db} store is not durable on this peer")
    # refresh the GC lease while the fetch is in flight (export_meta
    # took the initial lease; a slow bootstrap keeps renewing it)
    store.pin_generation(int(gen))
    # only shard payload files live in a generation dir; reject anything
    # that could traverse out of it
    if (os.path.basename(file) != file or not file.startswith("shard_")
            or not file.endswith(".bin")):
        raise SnapshotError(f"invalid snapshot file name {file!r}")
    path = os.path.join(ckpt.gen_dir(droot, int(gen)), file)
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(int(offset))
            data = f.read(CHUNK_BYTES)
    except OSError as exc:
        # the generation may have been GC'd by later checkpoints; the
        # client re-fetches meta and restarts
        raise SnapshotError(f"snapshot file gone: {exc}") from None
    try:
        from fabric_tpu.ops_plane import registry
        registry.counter("state_snapshot_chunks_total",
                         "Snapshot chunks served").add(
                             1, channel=ledger.channel_id, db=db)
        registry.counter("state_snapshot_bytes_total",
                         "Snapshot bytes served").add(
                             float(len(data)), channel=ledger.channel_id,
                             db=db)
    except Exception:
        pass
    return {"data": bytes(data), "eof": int(offset) + len(data) >= size,
            "size": size}


# -- receiving side ---------------------------------------------------------

def needs_bootstrap(ledger_root: str, channel_id: str) -> bool:
    """True when this channel has no blocks AND no installed snapshot —
    the states in which joining by snapshot is safe (never clobbers an
    existing chain)."""
    bdir = os.path.join(ledger_root, channel_id, "blocks")
    if not os.path.isdir(bdir):
        return True
    names = os.listdir(bdir)
    has_segments = any(n.startswith("blocks_") and n.endswith(".bin")
                       for n in names)
    return not has_segments and BOOTSTRAP_FILE not in names


def install(ledger_root: str, channel_id: str, meta: dict,
            payloads: Dict[str, List[bytes]]) -> None:
    """Install fetched snapshot payloads; BOOTSTRAP.json written last is
    the commit point.  Any pre-existing partial install is wiped first."""
    t0 = time.monotonic()
    base = os.path.join(ledger_root, channel_id)
    for sub in ("state", "history", "blocks"):
        shutil.rmtree(os.path.join(base, sub), ignore_errors=True)
    ckpt.install(os.path.join(base, "state"), meta["state_manifest"],
                 payloads["state"])
    if meta.get("history_manifest") is not None and "history" in payloads:
        ckpt.install(os.path.join(base, "history"),
                     meta["history_manifest"], payloads["history"])
    BlockStore.write_bootstrap(
        os.path.join(base, "blocks"), int(meta["height"]),
        meta["current_hash"], meta["previous_hash"], meta["commit_hash"])
    try:
        from fabric_tpu.ops_plane import tracing
        tracing.event("state.snapshot_install", channel=channel_id,
                      height=int(meta["height"]),
                      seconds=round(time.monotonic() - t0, 6))
    except Exception:
        pass


class _Fetcher:
    """One peer's fetch session: short per-chunk timeouts + redial-on-
    close so seeded transfer faults (drop/delay/dup) cost a retry, not
    the drill."""

    def __init__(self, addr, signer, msps, chunk_timeout_s: float,
                 attempts: int):
        self.addr = addr
        self.signer = signer
        self.msps = msps
        self.chunk_timeout_s = chunk_timeout_s
        self.attempts = attempts
        self._conn = None

    def _connection(self):
        if self._conn is None:
            from fabric_tpu.comm.rpc import connect
            self._conn = connect(tuple(self.addr), self.signer, self.msps,
                                 timeout=self.chunk_timeout_s)
        return self._conn

    def peer_identity(self):
        """Handshake-verified identity of the serving peer (dials if
        needed) — the standing check keys on WHO signed the handshake,
        not the address we dialed."""
        return getattr(self._connection().channel, "peer_identity", None)

    def call(self, method: str, body: dict) -> dict:
        from fabric_tpu.comm.rpc import RpcError
        last: Optional[Exception] = None
        for attempt in range(self.attempts):
            try:
                return self._connection().call(
                    method, body, timeout=self.chunk_timeout_s)
            except RpcError as exc:      # includes RpcTimeout/RpcClosed
                last = exc
                self.close()
                time.sleep(min(0.05 * (attempt + 1), 0.5))
        raise SnapshotError(
            f"{method} failed after {self.attempts} attempts "
            f"against {self.addr}: {last}")

    def fetch_file(self, channel_id: str, ent: dict) -> bytes:
        buf = bytearray()
        while True:
            resp = self.call(CHUNK_VERB, {
                "channel": channel_id, "db": ent["db"],
                "gen": ent["gen"], "file": ent["file"],
                "offset": len(buf)})
            buf += resp["data"]
            if resp["eof"]:
                return bytes(buf)

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass
            self._conn = None


def bootstrap_from_peers(ledger_root: str, channel_id: str, peers,
                         signer, msps, chunk_timeout_s: float = 2.0,
                         attempts: int = 12,
                         source_blocked=None) -> dict:
    """Fetch + verify + install a snapshot from the first peer that can
    serve one.  -> {"height", "from", "files", "bytes", "seconds"}.

    `source_blocked`: optional callable(handshake identity) -> bool.
    Sources it flags (quarantined signers) are DEFERRED, not refused:
    they are retried only after every honest source has failed, so a
    convicted peer degrades the rejoin before it can strand it — and a
    wiped peer (whose quarantine registry outlives its ledger) never
    bootstraps from its convicted adversary while an honest source is
    alive."""
    t0 = time.monotonic()
    last: Optional[Exception] = None
    quarantined = []
    for source_pass, addrs in (("honest", list(peers)), ("last-resort",
                                                         quarantined)):
        for addr in addrs:
            fetcher = _Fetcher(addr, signer, msps, chunk_timeout_s,
                               attempts)
            try:
                if (source_blocked is not None and source_pass == "honest"
                        and source_blocked(fetcher.peer_identity())):
                    quarantined.append(addr)
                    last = SnapshotError(
                        f"snapshot source {addr} is quarantined")
                    logger.warning(
                        "[%s] snapshot source %s is quarantined; "
                        "deferring to last resort", channel_id, addr)
                    continue
                if source_pass == "last-resort":
                    logger.warning(
                        "[%s] no honest snapshot source left; last-"
                        "resort fetch from quarantined %s", channel_id,
                        addr)
                return _fetch_and_install(fetcher, ledger_root,
                                          channel_id, addr, t0)
            except Exception as exc:
                last = exc
                logger.warning("[%s] snapshot fetch from %s failed: %s",
                               channel_id, addr, exc)
            finally:
                fetcher.close()
    raise SnapshotError(
        f"no peer could serve a snapshot for {channel_id!r}: {last}")


def _fetch_and_install(fetcher: "_Fetcher", ledger_root: str,
                       channel_id: str, addr, t0: float) -> dict:
    meta = fetcher.call(META_VERB, {"channel": channel_id})
    payloads: Dict[str, List[bytes]] = {"state": [], "history": []}
    total = 0
    for ent in meta["files"]:
        data = fetcher.fetch_file(channel_id, ent)
        import hashlib
        if hashlib.sha256(data).hexdigest() != ent["sha256"]:
            raise SnapshotError(
                f"hash mismatch for {ent['db']}/{ent['file']} "
                f"from {addr}")
        payloads[ent["db"]].append(data)
        total += len(data)
    install(ledger_root, channel_id, meta, payloads)
    seconds = time.monotonic() - t0
    logger.info(
        "[%s] snapshot installed from %s: height=%d files=%d "
        "bytes=%d in %.2fs", channel_id, addr, int(meta["height"]),
        len(meta["files"]), total, seconds)
    return {"height": int(meta["height"]), "from": list(addr),
            "files": len(meta["files"]), "bytes": total,
            "seconds": seconds}
