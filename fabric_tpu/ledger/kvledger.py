"""The channel ledger: block store + state DB + history DB, committed in
lock-step with crash recovery.

Reference parity: core/ledger/kvledger/kv_ledger.go —
  CommitLegacy (:425-508): MVCC validate-and-prepare (:452), commit-hash
  chaining (:459-465), block+pvtdata store (:470), state DB (:477),
  history DB (:487), with per-phase timing metrics (:491-499);
  recovery.go: replay blocks above each DB's savepoint on open;
  rebuild_dbs.go / reset.go / rollback.go admin operations.

The block store is the source of truth; state/history are derived and
self-heal on open (recoverDBs).
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from fabric_tpu.protocol import Block
from fabric_tpu.protocol.txflags import TxFlags, ValidationCode
from fabric_tpu.protocol.types import META_COMMIT_HASH, META_TXFLAGS

from .blkstorage import BlockStore
from .historydb import HistoryDB
from .mvcc import validate_and_prepare_batch
from .statedb import StateDB

logger = logging.getLogger("fabric_tpu.ledger")


def _safe_envelopes(block: Block):
    """Deserialize leniently: undecodable entries become None — they carry
    a non-VALID flag already, so MVCC never touches them."""
    from fabric_tpu.protocol import Envelope
    out = []
    for raw in block.data:
        try:
            out.append(Envelope.deserialize(raw))
        except Exception:
            out.append(None)
    return out


def _history_writes_from_flags(envelopes, flags: TxFlags):
    """History records of a block's VALID txs, trusting the stored flags
    (used on replay when MVCC must not re-run)."""
    from fabric_tpu.ledger.mvcc import parse_endorser_tx
    history = []
    for tx_num, env in enumerate(envelopes):
        if env is None or not flags.is_valid(tx_num):
            continue
        try:
            parsed = parse_endorser_tx(env)
        except Exception:
            continue
        if parsed is None:
            continue
        txid, rwset = parsed
        for ns_rw in rwset.ns_rwsets:
            for w in ns_rw.writes:
                history.append((tx_num, txid, ns_rw.namespace, w.key,
                                w.value, w.is_delete))
    return history


@dataclass
class LedgerConfig:
    root: Optional[str] = None          # None = fully in-memory
    enable_history: bool = True
    snapshot_every: int = 256
    # key-hash stripe width for the state plane (statedb + historydb):
    # independently locked + independently flushable shards; 1 = the
    # flat store (differential oracle)
    state_shards: int = 8
    # parallel MVCC commit plane (committer/parallel_commit/): wavefront
    # scheduler replaces the serial validate_and_prepare_batch walk —
    # bit-identical output, enforced differentially.  Must be configured
    # uniformly across the peers of a channel only as an operational
    # convention (the OUTPUT is identical; only timing differs).
    parallel_commit: bool = False
    commit_workers: int = 4             # static cap on the worker pool
    # adaptive sizing: the pool tracks the rolling max conflict-graph
    # wave width, clamped to commit_workers (scheduler.target_workers)
    commit_adaptive: bool = True
    # serial fallback: run the oracle walk directly (and count it) when
    # the wave machinery cannot pay off — 1-core host, or the adaptive
    # pool would provision a single worker anyway.  Differential tests
    # that must exercise the wave path set this False.
    commit_serial_fallback: bool = True
    # cross-block wavefront pipelining (committer/parallel_commit
    # CommitWindow): W > 0 enables the commit_begin/commit_finish entry
    # points with at most W blocks admitted-but-unretired.  The serial
    # commit() stays available (and is the differential oracle) but
    # refuses to run while window blocks are in flight.  Output is
    # bit-identical to serial commits of the same stream; only timing
    # differs.  0 = disabled.
    commit_window: int = 0
    # fused device validation (committer/device_validate.py): commit()
    # consumes the validator's prepared UpdateBatch via the registered
    # prepared-source instead of re-running host MVCC — the flags in
    # block metadata and the statedb savepoint must still match what
    # the device validated against, else host MVCC runs (always safe).
    # Default OFF until parity is proven per deployment.
    device_validate: bool = False


@dataclass
class CommitStats:
    """Per-phase timings (kv_ledger.go:491-499 metric parity)."""
    block_num: int = 0
    state_validation_s: float = 0.0
    block_commit_s: float = 0.0
    state_commit_s: float = 0.0
    history_commit_s: float = 0.0
    valid_txs: int = 0
    total_txs: int = 0


class KVLedger:
    def __init__(self, channel_id: str, config: Optional[LedgerConfig] = None):
        self.channel_id = channel_id
        self.config = config or LedgerConfig()
        root = self.config.root
        bdir = sdir = hdir = None
        if root is not None:
            base = os.path.join(root, channel_id)
            bdir = os.path.join(base, "blocks")
            sdir = os.path.join(base, "state")
            hdir = os.path.join(base, "history")
        self.blockstore = BlockStore(bdir)
        self.statedb = self._new_statedb(sdir)
        self.historydb = (self._new_historydb(hdir)
                          if self.config.enable_history else None)
        self._commit_hash = b"\x00" * 32
        self.last_stats = CommitStats()
        # set by _recover: how much work reopening this ledger cost
        self.last_recovery: Dict[str, int] = {
            "replayed_blocks": 0, "start": 0, "height": 0}
        # DeviceValidator.take_prepared when device_validate is wired:
        # (number, flags_bytes, savepoint) -> (final_flags, batch,
        # history) | None
        self._prepared_source = None
        self._commit_scheduler = None
        if self.config.parallel_commit:
            # function-level import: ledger <- committer.parallel_commit
            # <- ledger.mvcc would otherwise cycle at module load
            from fabric_tpu.committer.parallel_commit import (
                ParallelCommitScheduler)
            self._commit_scheduler = ParallelCommitScheduler(
                max_workers=self.config.commit_workers,
                channel_id=channel_id,
                adaptive=self.config.commit_adaptive,
                serial_fallback=self.config.commit_serial_fallback)
        self._commit_window = None
        if self.config.commit_window > 0:
            from fabric_tpu.committer.parallel_commit import CommitWindow
            self._commit_window = CommitWindow(
                channel_id=channel_id,
                max_window=self.config.commit_window)
        # serializes commit_finish calls (one finishing thread is the
        # intended shape; the lock makes a second one safe, not fast)
        self._finish_lock = threading.Lock()
        self._recover()

    # -- recovery (recovery.go) --------------------------------------------

    def _new_statedb(self, sdir: Optional[str]) -> StateDB:
        return StateDB(sdir, snapshot_every=self.config.snapshot_every,
                       n_shards=self.config.state_shards,
                       channel=self.channel_id)

    def _new_historydb(self, hdir: Optional[str]) -> HistoryDB:
        return HistoryDB(hdir, n_shards=self.config.state_shards,
                         checkpoint_every=self.config.snapshot_every,
                         channel=self.channel_id)

    def _recover(self) -> None:
        """Replay blocks above each derived DB's savepoint (bounded to
        the post-checkpoint tail now that the derived DBs checkpoint)."""
        height = self.blockstore.height
        base = self.blockstore.base
        self.last_recovery = {"replayed_blocks": 0, "start": height,
                              "height": height}
        if height == 0:
            return
        # restore the commit-hash chain: from the last block's metadata
        # when stored, else from the snapshot-bootstrap marker (a freshly
        # installed snapshot has base == height, no blocks yet)
        if height - 1 >= base:
            last = self.blockstore.get_by_number(height - 1)
            self._commit_hash = last.metadata.items.get(
                META_COMMIT_HASH, b"\x00" * 32)
        elif self.blockstore.bootstrap_commit_hash is not None:
            self._commit_hash = self.blockstore.bootstrap_commit_hash
        # replay from the LOWEST derived-DB savepoint: a crash between the
        # state commit and the history commit leaves history one block
        # behind, and both commits are idempotent via their savepoint guards
        savepoints = [self.statedb.savepoint]
        if self.historydb is not None:
            savepoints.append(self.historydb.savepoint)
        lowest = min((-1 if sp is None else sp) for sp in savepoints)
        start = lowest + 1
        if start < base:
            # blocks below the snapshot base are pruned; the installed
            # state checkpoint is the only source for them.  If a derived
            # DB lost its checkpoint this replay CANNOT reconstruct the
            # pre-snapshot writes — re-bootstrap from a serving peer.
            logger.warning(
                "%s: derived-DB savepoint %d below snapshot base %d — "
                "pre-snapshot history is pruned; replaying from base",
                self.channel_id, lowest, base)
            start = base
        replayed = 0
        for num in range(start, height):
            block = self.blockstore.get_by_number(num)
            self._apply_derived(block)
            replayed += 1
            logger.info("%s: recovered block %d into state/history",
                        self.channel_id, num)
        self.last_recovery = {"replayed_blocks": replayed, "start": start,
                              "height": height}

    def _apply_derived(self, block: Block) -> None:
        """Recovery replay of one stored block (final txflags in metadata)
        into the derived DBs.  If the block is already in the state DB
        (<= its savepoint), MVCC must NOT re-run — the state already
        contains this block's writes and every read would falsely
        conflict; the stored flags are authoritative, so history writes
        are extracted directly from the VALID txs."""
        num = block.header.number
        flags = TxFlags.from_bytes(block.metadata.items[META_TXFLAGS])
        envelopes = _safe_envelopes(block)
        state_has_it = (self.statedb.savepoint is not None
                        and num <= self.statedb.savepoint)
        if state_has_it:
            history = _history_writes_from_flags(envelopes, flags)
        else:
            batch, history = self._validate_and_prepare(
                num, envelopes, flags)
            self.statedb.apply_updates(batch, num)
        if self.historydb is not None:
            self.historydb.commit(num, history)  # savepoint-guarded, idempotent

    def set_prepared_source(self, fn) -> None:
        """Register the device validator's prepared-batch source
        (DeviceValidator.take_prepared).  None unregisters."""
        self._prepared_source = fn

    def _take_prepared(self, block: Block):
        """(final_flags_bytes, batch, history) from the device
        validator's stash, or None when absent/stale (host MVCC runs)."""
        if self._prepared_source is None or not self.config.device_validate:
            return None
        try:
            return self._prepared_source(
                block.header.number,
                block.metadata.items[META_TXFLAGS],
                self.statedb.savepoint)
        except Exception:
            logger.exception("prepared-batch source failed; "
                             "falling back to host MVCC")
            return None

    def _validate_and_prepare(self, num: int, envelopes, flags: TxFlags):
        """MVCC pass: the wavefront scheduler when parallel_commit is
        on, the serial oracle otherwise — identical output either way."""
        if self._commit_scheduler is not None:
            return self._commit_scheduler.validate_and_prepare_batch(
                self.statedb, num, envelopes, flags)
        return validate_and_prepare_batch(self.statedb, num,
                                          envelopes, flags)

    _APPLY_BUCKETS = (1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0,
                      16384.0, float("inf"))

    def _observe_apply(self, n_state: int, n_history: int) -> None:
        try:
            from fabric_tpu.ops_plane import registry
            h = registry.histogram(
                "commit_graph_apply_batch_size",
                "coalesced per-block apply sizes (keys / history rows)",
                buckets=self._APPLY_BUCKETS)
            h.observe(float(n_state), db="state", channel=self.channel_id)
            h.observe(float(n_history), db="history",
                      channel=self.channel_id)
        except Exception:
            pass

    # -- commit (kv_ledger.go:425-508) -------------------------------------

    def commit(self, block: Block) -> CommitStats:
        """Commit a block whose metadata txflags were finalized by the
        txvalidator.  MVCC runs here (ValidateAndPrepare), then the
        commit-hash chains, then block store, state, history."""
        if self.paused:
            raise RuntimeError(
                f"channel {self.channel_id!r} is paused (resume() first)")
        if self._commit_window is not None and self._commit_window.depth():
            raise RuntimeError(
                "serial commit while the pipelined window has blocks in "
                "flight (commit_finish them or abort_window() first)")
        if META_TXFLAGS not in block.metadata.items:
            raise ValueError("block metadata missing txflags "
                             "(txvalidator must run first)")
        # reject wrong-numbered / wrong-parent blocks BEFORE any state
        # (incl. the commit-hash chain) advances — duplicate or out-of-order
        # delivery is normal under gossip and must leave the ledger untouched
        info = self.blockstore.chain_info()
        if block.header.number != info.height:
            raise ValueError(
                f"out-of-order commit: got block {block.header.number}, "
                f"expected {info.height}")
        expected_prev = info.current_hash if info.height else b"\x00" * 32
        if block.header.previous_hash != expected_prev:
            raise ValueError(
                f"block {block.header.number} previous_hash mismatch")
        stats = CommitStats(block_num=block.header.number,
                            total_txs=len(block.data))

        t0 = time.perf_counter()
        prepared = self._take_prepared(block)
        if prepared is not None:
            # fused device validation already ran MVCC in the
            # validator's single dispatch: consume the prepared batch —
            # no envelope materialization, no host MVCC walk
            final_bytes, batch, history = prepared
            flags = TxFlags.from_bytes(final_bytes)
        else:
            flags = TxFlags.from_bytes(block.metadata.items[META_TXFLAGS])
            envelopes = _safe_envelopes(block)
            batch, history = self._validate_and_prepare(
                block.header.number, envelopes, flags)
        # split the batch by shard before the apply takes shard locks
        # (the parallel-commit / device-validate planes do the same)
        batch.preshard(getattr(self.statedb, "n_shards", 1))
        stats.state_validation_s = time.perf_counter() - t0
        stats.valid_txs = flags.valid_count()
        # MVCC may have flipped more flags — write the final bitmap back
        block.metadata.items[META_TXFLAGS] = flags.to_bytes()

        # commit-hash chaining (kv_ledger.go:459-465): binds flags+data to
        # the previous commit hash so divergent peers are detectable
        self._commit_hash = hashlib.sha256(
            self._commit_hash + block.header.data_hash + flags.to_bytes()
        ).digest()
        block.metadata.items[META_COMMIT_HASH] = self._commit_hash

        t0 = time.perf_counter()
        self.blockstore.add_block(block)
        stats.block_commit_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        self.statedb.apply_updates(batch, block.header.number)
        stats.state_commit_s = time.perf_counter() - t0

        if self.historydb is not None:
            t0 = time.perf_counter()
            self.historydb.commit(block.header.number, history)
            stats.history_commit_s = time.perf_counter() - t0

        self._observe_apply(len(batch), len(history))
        self.last_stats = stats
        logger.info(
            "[%s] committed block %d: %d/%d valid | validation=%.1fms "
            "block=%.1fms state=%.1fms history=%.1fms",
            self.channel_id, stats.block_num, stats.valid_txs,
            stats.total_txs, stats.state_validation_s * 1e3,
            stats.block_commit_s * 1e3, stats.state_commit_s * 1e3,
            stats.history_commit_s * 1e3)
        return stats

    # -- pipelined commit (the cross-block wavefront window) ----------------

    def pending_overlay(self):
        """Frozen write-set snapshot of the window's in-flight blocks
        (PendingOverlay; empty when the window is idle, None when the
        pipelined window is disabled).  The early-abort analyzer's
        overlay_source, and the dooming bound for admit-time waves."""
        if self._commit_window is None:
            return None
        return self._commit_window.pending_overlay()

    def commit_begin(self, block: Block):
        """Admit `block` to the pipelined commit window and validate its
        EARLY waves — the txs whose footprints provably avoid every
        in-flight predecessor's pending write set — typically while the
        predecessor's apply is still running on the finishing thread.
        Returns the window ticket for commit_finish.  Single admitting
        thread; blocks must arrive in chain order."""
        if self._commit_window is None:
            raise RuntimeError(
                "pipelined commit disabled (LedgerConfig.commit_window)")
        if self.paused:
            raise RuntimeError(
                f"channel {self.channel_id!r} is paused (resume() first)")
        if META_TXFLAGS not in block.metadata.items:
            raise ValueError("block metadata missing txflags "
                             "(txvalidator must run first)")
        from fabric_tpu.protocol import block_header_hash
        tail = self._commit_window.tail()
        if tail is not None:
            expected_num = tail.num + 1
            expected_prev = tail.header_hash
        else:
            # window empty: no concurrent finish can be in flight, so
            # the chain tip is stable here
            info = self.blockstore.chain_info()
            expected_num = info.height
            expected_prev = (info.current_hash if info.height
                             else b"\x00" * 32)
        if block.header.number != expected_num:
            raise ValueError(
                f"out-of-order commit_begin: got block "
                f"{block.header.number}, expected {expected_num}")
        if block.header.previous_hash != expected_prev:
            raise ValueError(
                f"block {block.header.number} previous_hash mismatch")
        flags = TxFlags.from_bytes(block.metadata.items[META_TXFLAGS])
        envelopes = _safe_envelopes(block)
        entry = self._commit_window.admit(
            self.statedb, block.header.number,
            block_header_hash(block.header), envelopes, flags)
        # the block rides the ticket un-mutated: metadata (final flags,
        # commit hash) is only stamped at finish, so an aborted window
        # leaves it pristine for the exactly-once replay
        return (entry, block)

    def commit_finish(self, ticket) -> CommitStats:
        """Promote the ticket's deferred waves, then retire it: rebuild
        the final batch in strict tx order, chain the commit hash, store
        the block, and apply state + history.  Strictly in admit order
        (head of window only) — that ordering is what keeps the windowed
        stream bit-identical to serial commits."""
        entry, block = ticket
        with self._finish_lock:
            t0 = time.perf_counter()
            batch, history = self._commit_window.finish(
                self.statedb, entry)
            batch.preshard(getattr(self.statedb, "n_shards", 1))
            flags = entry.flags
            stats = CommitStats(block_num=entry.num,
                                total_txs=len(block.data))
            stats.state_validation_s = (entry.validate_s
                                        + time.perf_counter() - t0)
            stats.valid_txs = flags.valid_count()
            block.metadata.items[META_TXFLAGS] = flags.to_bytes()
            self._commit_hash = hashlib.sha256(
                self._commit_hash + block.header.data_hash
                + flags.to_bytes()).digest()
            block.metadata.items[META_COMMIT_HASH] = self._commit_hash

            # the retirement tail is the window's overlap counterpart:
            # admits of successor blocks time their validation against
            # this span
            self._commit_window.apply_started()
            try:
                t1 = time.perf_counter()
                self.blockstore.add_block(block)
                stats.block_commit_s = time.perf_counter() - t1

                t1 = time.perf_counter()
                self.statedb.apply_updates(batch, entry.num)
                stats.state_commit_s = time.perf_counter() - t1

                if self.historydb is not None:
                    t1 = time.perf_counter()
                    self.historydb.commit(entry.num, history)
                    stats.history_commit_s = time.perf_counter() - t1
            finally:
                self._commit_window.apply_ended()
            self._commit_window.retire(entry)

            self._observe_apply(len(batch), len(history))
            self.last_stats = stats
            logger.info(
                "[%s] committed block %d (windowed, %d early / %d "
                "deferred): %d/%d valid",
                self.channel_id, stats.block_num, entry.early_n,
                entry.deferred_n, stats.valid_txs, stats.total_txs)
            return stats

    def abort_window(self) -> int:
        """Drop every admitted-but-unfinished window block (pipeline
        teardown or error recovery).  None of them reached the block
        store, so they replay later exactly once; returns the count."""
        if self._commit_window is None:
            return 0
        return self._commit_window.reset()

    # -- queries ------------------------------------------------------------

    @property
    def height(self) -> int:
        return self.blockstore.height

    @property
    def commit_hash(self) -> bytes:
        return self._commit_hash

    def get_state(self, ns: str, key: str) -> Optional[bytes]:
        vv = self.statedb.get(ns, key)
        return None if vv is None else vv.value

    def range_query(self, ns: str, start_key: str, end_key: str, limit: int = 0):
        return self.statedb.range_scan(ns, start_key, end_key, limit)

    def get_history(self, ns: str, key: str):
        if self.historydb is None:
            raise RuntimeError("history DB disabled")
        return self.historydb.get_history(ns, key)

    def state_status(self) -> dict:
        """Shard/checkpoint/recovery introspection (the /state ops route)."""
        out = {
            "channel": self.channel_id,
            "height": self.height,
            "commit_hash": self._commit_hash.hex(),
            "block_base": self.blockstore.base,
            "last_recovery": dict(self.last_recovery),
            "state": self.statedb.status(),
        }
        if self.historydb is not None:
            out["history"] = self.historydb.status()
        if self._commit_scheduler is not None:
            out["commit_serial_fallbacks"] = (
                self._commit_scheduler.serial_fallbacks)
        if self._commit_window is not None:
            out["commit_window"] = self._commit_window.stats()
        return out

    def snapshot_export(self):
        """Force a checkpoint of both derived DBs so a consistent
        (manifest + shard files) set exists on disk for state transfer.
        -> (state_manifest, history_manifest|None); None when in-memory
        or before the first block."""
        sm = self.statedb.checkpoint()
        hm = self.historydb.checkpoint() if self.historydb is not None else None
        return sm, hm

    # -- admin (reset.go / rollback.go / pause_resume.go / rebuild_dbs.go) --

    @property
    def paused(self) -> bool:
        """pause_resume.go: a paused channel refuses commits until
        resumed; the flag survives restarts via a marker file."""
        if self.config.root is None:
            return getattr(self, "_paused_mem", False)
        return os.path.exists(os.path.join(self.config.root, "PAUSED"))

    def pause(self) -> None:
        if self.config.root is None:
            self._paused_mem = True
            return
        with open(os.path.join(self.config.root, "PAUSED"), "w") as f:
            f.write("paused")

    def resume(self) -> None:
        if self.config.root is None:
            self._paused_mem = False
            return
        try:
            os.unlink(os.path.join(self.config.root, "PAUSED"))
        except FileNotFoundError:
            pass

    def rollback(self, target_height: int) -> None:
        """Roll the channel back to `target_height` blocks and rebuild
        the derived DBs from the retained chain (kvledger/rollback.go —
        there the peer re-fetches dropped blocks from ordering; here the
        deliver client does the same on restart)."""
        if target_height >= self.height:
            return
        self.blockstore.truncate(target_height)
        self.rebuild_dbs()

    def reset(self) -> None:
        """Reset to the genesis block only (kvledger/reset.go): all state
        re-derivable, blocks re-fetched from ordering by the deliver
        client."""
        self.rollback(1 if self.height else 0)

    def rebuild_dbs(self) -> None:
        """Drop state+history and rebuild from the block store."""
        sdir, hdir = self.statedb.root, None
        if self.historydb is not None:
            hdir = self.historydb.root
        for d in (sdir, hdir):
            if d and os.path.isdir(d):
                shutil.rmtree(d)
        self.statedb = self._new_statedb(sdir)
        if self.config.enable_history:
            self.historydb = self._new_historydb(hdir)
        self._commit_hash = b"\x00" * 32
        self._recover()
