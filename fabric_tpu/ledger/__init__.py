from .blkstorage import BlockStore, BlockStoreError
from .statedb import VersionedValue, StateDB, UpdateBatch
from .historydb import HistoryDB
from .kvledger import KVLedger, LedgerConfig

__all__ = ["BlockStore", "BlockStoreError", "VersionedValue", "StateDB",
           "UpdateBatch", "HistoryDB", "KVLedger", "LedgerConfig"]
