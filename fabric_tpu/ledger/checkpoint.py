"""Crash-consistent sharded checkpoints: content-hashed shard files plus
an atomically-renamed manifest.

The durability contract shared by the state and history DBs (and by the
snapshot state-transfer path, which ships these exact files):

  1. every shard payload is written to ``ckpt/<gen>/shard_NNNN.bin`` via
     tmp-file + fsync + rename, then the generation directory is fsynced
     — the files are durable BEFORE anything points at them;
  2. the manifest (generation number, savepoint, per-shard sha256) is
     written to ``MANIFEST.tmp`` + fsync, the old ``MANIFEST`` is renamed
     to ``MANIFEST.prev``, and the tmp renamed over ``MANIFEST``.

A kill at ANY instant therefore leaves one of three recoverable states:
the new manifest (complete), no manifest but a ``.prev`` (killed between
the two renames), or the old manifest (killed any earlier).  `recover`
walks current → previous, verifying every shard file against its
recorded hash, and returns the newest checkpoint whose bytes all check
out — a torn shard file, a bitflipped payload, or a manifest pointing at
a missing generation all fall through to the previous good state (and
ultimately to "no checkpoint": full replay from the block store, which
is always correct, just slow).

Reference parity: the role of core/ledger/kvledger/snapshot.go's
signed file hashes + metadata, with leveldb's MANIFEST/CURRENT rename
discipline standing in for the atomic pointer flip.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Dict, List, Optional, Tuple

from fabric_tpu.utils import serde

MANIFEST = "MANIFEST"
PREV_SUFFIX = ".prev"
CKPT_DIR = "ckpt"


def shard_file(i: int) -> str:
    return f"shard_{i:04d}.bin"


def gen_dir(root: str, gen: int) -> str:
    return os.path.join(root, CKPT_DIR, f"{int(gen):08d}")


def _fsync_dir(path: str) -> None:
    """Durably record renames/creates inside a directory (no-op on
    platforms that refuse O_RDONLY dir fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_durable(path: str, payload: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_checkpoint(root: str, gen: int, payloads: List[bytes],
                     meta: Optional[dict] = None) -> dict:
    """Write one checkpoint generation + flip the manifest to it.
    `meta` keys (savepoint etc.) are merged into the manifest.  Returns
    the manifest dict as written."""
    d = gen_dir(root, gen)
    os.makedirs(d, exist_ok=True)
    shards = []
    for i, payload in enumerate(payloads):
        name = shard_file(i)
        _write_durable(os.path.join(d, name), payload)
        shards.append({"file": name,
                       "sha256": hashlib.sha256(payload).hexdigest(),
                       "bytes": len(payload)})
    _fsync_dir(d)
    manifest = dict(meta or {})
    manifest.update({"gen": int(gen), "n_shards": len(payloads),
                     "shards": shards})
    mpath = os.path.join(root, MANIFEST)
    _write_durable(mpath + ".new", serde.encode(manifest))
    if os.path.exists(mpath):
        os.replace(mpath, mpath + PREV_SUFFIX)
    os.replace(mpath + ".new", mpath)
    _fsync_dir(root)
    return manifest


def read_manifest(root: str, previous: bool = False) -> Optional[dict]:
    """Decode MANIFEST (or MANIFEST.prev); None when absent, torn, or
    not a structurally valid manifest."""
    path = os.path.join(root, MANIFEST) + (PREV_SUFFIX if previous else "")
    try:
        with open(path, "rb") as f:
            m = serde.decode(f.read())
    except (OSError, ValueError):
        return None
    if not isinstance(m, dict) or not isinstance(m.get("shards"), list):
        return None
    try:
        int(m["gen"])
    except (KeyError, TypeError, ValueError):
        return None
    for ent in m["shards"]:
        if (not isinstance(ent, dict) or "file" not in ent
                or "sha256" not in ent):
            return None
    return m


def load_payloads(root: str, manifest: dict) -> Optional[List[bytes]]:
    """Read + hash-verify every shard file of `manifest`; None if any is
    missing, torn, or corrupted (all-or-nothing: a checkpoint is only
    usable whole)."""
    d = gen_dir(root, manifest["gen"])
    out = []
    for ent in manifest["shards"]:
        name = os.path.basename(str(ent["file"]))
        try:
            with open(os.path.join(d, name), "rb") as f:
                data = f.read()
        except OSError:
            return None
        if hashlib.sha256(data).hexdigest() != ent["sha256"]:
            return None
        out.append(data)
    return out


def recover(root: str) -> Tuple[Optional[dict], Optional[List[bytes]], str]:
    """-> (manifest, payloads, source): the newest fully-verifiable
    checkpoint, source in {"manifest", "manifest_prev", "none"}."""
    for previous, source in ((False, "manifest"), (True, "manifest_prev")):
        m = read_manifest(root, previous=previous)
        if m is None:
            continue
        payloads = load_payloads(root, m)
        if payloads is not None:
            return m, payloads, source
    return None, None, "none"


def gc_generations(root: str, keep) -> None:
    """Remove checkpoint generations not in `keep` (current + previous
    stay referenced by MANIFEST / MANIFEST.prev)."""
    base = os.path.join(root, CKPT_DIR)
    if not os.path.isdir(base):
        return
    keep = {int(g) for g in keep}
    for name in os.listdir(base):
        try:
            gen = int(name)
        except ValueError:
            continue
        if gen not in keep:
            shutil.rmtree(os.path.join(base, name), ignore_errors=True)


def install(root: str, manifest: dict, payloads: List[bytes]) -> dict:
    """Install a TRANSFERRED checkpoint (snapshot-ship receive side):
    verify every payload against the manifest's recorded hashes, then
    write it with the same durable ordering as a local checkpoint."""
    if len(payloads) != len(manifest.get("shards", [])):
        raise ValueError("snapshot install: shard count mismatch")
    for ent, payload in zip(manifest["shards"], payloads):
        if hashlib.sha256(payload).hexdigest() != ent["sha256"]:
            raise ValueError(
                f"snapshot install: hash mismatch for {ent['file']!r}")
    os.makedirs(root, exist_ok=True)
    meta = {k: v for k, v in manifest.items()
            if k not in ("gen", "n_shards", "shards")}
    return write_checkpoint(root, int(manifest["gen"]), payloads, meta=meta)
