"""Config history: historical channel-config lookups by block height.

Reference parity: core/ledger/confighistory/mgr.go — a height-indexed
store of committed configuration so components can answer "what was the
config (collection/chaincode/channel) as of block N" deterministically
during historical validation and snapshotting.  Here the tracked unit is
the serialized ChannelConfig applied at each config block (the
framework's collection configs ride inside node/chaincode config; the
channel config is the consensus-replicated piece).
"""

from __future__ import annotations

import bisect
import os
import struct
import threading
from typing import List, Optional, Tuple

_LEN = struct.Struct("<QI")


class ConfigHistory:
    """Append-only (block_num, config_bytes) log with height lookups."""

    def __init__(self, root: Optional[str] = None):
        self.root = root
        self._lock = threading.Lock()
        self._entries: List[Tuple[int, bytes]] = []
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._path = os.path.join(root, "confighistory.bin")
            self._recover()

    def _recover(self) -> None:
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as f:
            data = f.read()
        off = 0
        while off + _LEN.size <= len(data):
            num, n = _LEN.unpack_from(data, off)
            if off + _LEN.size + n > len(data):
                break               # torn tail: drop
            self._entries.append(
                (num, data[off + _LEN.size:off + _LEN.size + n]))
            off += _LEN.size + n

    def record(self, block_num: int, config_bytes: bytes) -> None:
        with self._lock:
            if self._entries and block_num <= self._entries[-1][0]:
                return              # replay during catch-up: idempotent
            self._entries.append((block_num, bytes(config_bytes)))
            if self.root is not None:
                with open(self._path, "ab") as f:
                    f.write(_LEN.pack(block_num, len(config_bytes)))
                    f.write(config_bytes)
                    f.flush()
                    os.fsync(f.fileno())

    def config_at(self, block_num: int) -> Optional[bytes]:
        """The config in force AS OF block_num (most recent entry with
        block <= block_num), or None before the first record."""
        with self._lock:
            nums = [n for n, _ in self._entries]
            i = bisect.bisect_right(nums, block_num)
            return self._entries[i - 1][1] if i else None

    def latest_height(self) -> Optional[int]:
        """Block number of the newest recorded config, or None."""
        with self._lock:
            return self._entries[-1][0] if self._entries else None

    def entries(self) -> List[Tuple[int, bytes]]:
        with self._lock:
            return list(self._entries)
