"""Versioned key-value state database with savepoint + crash recovery.

Reference parity: core/ledger/kvledger/txmgmt/statedb/statedb.go interface
and the stateleveldb implementation — versioned values (value, Height),
update batches applied atomically with a savepoint, ordered range scans.

Durability model: an append-only WAL of update batches (one record per
block) plus periodic full snapshots for compaction.  On open: load the
newest snapshot, replay WAL records past it, truncate any torn tail.
Savepoint = block number of the last applied batch; the kvledger recovery
path replays blocks above the savepoint from the block store
(core/ledger/kvledger/recovery.go semantics).

Keys are (namespace, key) pairs, ordered lexicographically for range
scans (leveldb iterator parity).
"""

from __future__ import annotations

import bisect
import os
import struct
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_tpu.protocol import Version
from fabric_tpu.utils import serde

_LEN = struct.Struct("<Q")
SNAPSHOT_EVERY = 256  # batches between snapshot compactions


@dataclass(frozen=True)
class VersionedValue:
    value: bytes
    version: Version


class UpdateBatch:
    """statedb.UpdateBatch: puts/deletes staged by MVCC validation."""

    def __init__(self):
        self._updates: Dict[Tuple[str, str], Optional[VersionedValue]] = {}

    def put(self, ns: str, key: str, value: bytes, version: Version) -> None:
        self._updates[(ns, key)] = VersionedValue(value, version)

    def delete(self, ns: str, key: str, version: Version) -> None:
        # deletes still carry the deleting tx's version (stateleveldb tombstone)
        self._updates[(ns, key)] = None

    def get(self, ns: str, key: str):
        """(found, vv) — distinguishes absent from staged-delete."""
        k = (ns, key)
        return (k in self._updates), self._updates.get(k)

    def items(self):
        return self._updates.items()

    def __len__(self):
        return len(self._updates)


def _doc_of(value) -> Optional[dict]:
    """Parse a state value as a JSON document; None when not one."""
    import json as _json
    try:
        doc = _json.loads(value.decode("utf-8"))
    except (ValueError, UnicodeDecodeError, AttributeError):
        return None
    return doc if isinstance(doc, dict) else None


def _match_selector(doc: dict, selector: dict) -> bool:
    """Mango-selector subset evaluation (implicit AND across fields)."""
    for field_name, cond in selector.items():
        if field_name == "$or":
            if not any(_match_selector(doc, alt) for alt in cond):
                return False
            continue
        if field_name == "$and":
            if not all(_match_selector(doc, alt) for alt in cond):
                return False
            continue
        have = doc.get(field_name)
        if isinstance(cond, dict):
            for op, want in cond.items():
                try:
                    if op == "$gt" and not have > want:
                        return False
                    elif op == "$gte" and not have >= want:
                        return False
                    elif op == "$lt" and not have < want:
                        return False
                    elif op == "$lte" and not have <= want:
                        return False
                    elif op == "$ne" and not have != want:
                        return False
                    elif op == "$eq" and not have == want:
                        return False
                    elif op == "$in" and have not in want:
                        return False
                except TypeError:
                    return False      # cross-type comparison: no match
        else:
            if have != cond:
                return False
    return True


def _index_sort_key(v):
    """Type-tagged sort key for an indexable scalar, or None when the
    value is not indexable.  Numbers (incl. bool — Python equality
    semantics, which _match_selector uses) share one collation class;
    strings another."""
    if isinstance(v, (int, float)) and not isinstance(v, complex):
        try:
            return (0, float(v))
        except (OverflowError, ValueError):
            return None
    if isinstance(v, str):
        return (1, v)
    return None


class _FieldIndex:
    """Sorted (sort_key, key) entries for one (namespace, field).

    Lossy float collation is fine: index lookups return a SUPERSET of
    candidates (inclusive bounds) and execute_query re-checks each doc
    with the exact selector — mirroring how the reference's CouchDB
    indexes only narrow the scan (statecouchdb query with index hint).
    """

    def __init__(self):
        self.by_key: Dict[str, tuple] = {}      # key -> sort_key
        self.sorted: List[Tuple[tuple, str]] = []

    def remove(self, key: str) -> None:
        sk = self.by_key.pop(key, None)
        if sk is not None:
            i = bisect.bisect_left(self.sorted, (sk, key))
            if i < len(self.sorted) and self.sorted[i] == (sk, key):
                self.sorted.pop(i)

    def put(self, key: str, value) -> None:
        self.remove(key)
        sk = _index_sort_key(value)
        if sk is not None:
            self.by_key[key] = sk
            bisect.insort(self.sorted, (sk, key))

    def candidates(self, lo, hi) -> List[str]:
        """Keys whose sort key is within [lo, hi] (inclusive; None =
        unbounded on that side)."""
        i = 0 if lo is None else bisect.bisect_left(self.sorted, (lo,))
        if hi is None:
            j = len(self.sorted)
        else:
            j = bisect.bisect_right(self.sorted, (hi,))
            while j < len(self.sorted) and self.sorted[j][0] == hi:
                j += 1
        return [k for _, k in self.sorted[i:j]]


class StateDB:
    """Versioned state store (VersionedDB iface, statedb.go)."""

    def __init__(self, root: Optional[str] = None,
                 snapshot_every: int = SNAPSHOT_EVERY):
        self.root = root
        self.snapshot_every = snapshot_every
        self._lock = threading.RLock()
        self._data: Dict[Tuple[str, str], VersionedValue] = {}
        self._sorted_keys: List[Tuple[str, str]] = []
        self._savepoint: Optional[int] = None
        self._batches_since_snapshot = 0
        # field indexes: (ns, field) -> _FieldIndex, maintained at every
        # apply_updates (the statecouchdb index slot — reference indexes
        # ship in chaincode META-INF/statedb/couchdb/indexes and are
        # created at deploy; here create_index is called at chaincode
        # install, node/peer.py)
        self._indexes: Dict[Tuple[str, str], _FieldIndex] = {}
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._recover()

    # -- reads --------------------------------------------------------------

    def get(self, ns: str, key: str) -> Optional[VersionedValue]:
        with self._lock:
            return self._data.get((ns, key))

    def get_version(self, ns: str, key: str) -> Optional[Version]:
        vv = self.get(ns, key)
        return None if vv is None else vv.version

    def range_scan(self, ns: str, start_key: str, end_key: str,
                   limit: int = 0) -> Iterator[Tuple[str, VersionedValue]]:
        """Ordered scan over [start_key, end_key) within a namespace;
        empty end_key = scan to namespace end (stateleveldb iterator)."""
        with self._lock:
            lo = bisect.bisect_left(self._sorted_keys, (ns, start_key))
            out = []
            for i in range(lo, len(self._sorted_keys)):
                kns, key = self._sorted_keys[i]
                if kns != ns or (end_key and key >= end_key):
                    break
                out.append((key, self._data[(kns, key)]))
                if limit and len(out) >= limit:
                    break
        return iter(out)

    # -- field indexes + rich queries ---------------------------------------

    def create_index(self, ns: str, field: str) -> None:
        """Register (and build from current state) a field index for a
        namespace.  Idempotent — peers re-register at startup and the
        index rebuilds from the recovered state."""
        with self._lock:
            idx_key = (ns, field)
            idx = _FieldIndex()
            self._indexes[idx_key] = idx
            lo = bisect.bisect_left(self._sorted_keys, (ns, ""))
            for i in range(lo, len(self._sorted_keys)):
                kns, key = self._sorted_keys[i]
                if kns != ns:
                    break
                doc = _doc_of(self._data[(kns, key)].value)
                if doc is not None:
                    idx.put(key, doc.get(field))

    def indexes_for(self, ns: str) -> List[str]:
        with self._lock:
            return [f for (n, f) in self._indexes if n == ns]

    def _index_candidates(self, ns: str, selector: dict):
        """Planner: if some top-level selector field is indexed with an
        index-coverable condition, return the candidate key list (a
        SUPERSET of matches, re-checked by the caller); else None.

        Coverable: scalar $eq / bare equality, $gt/$gte/$lt/$lte, and
        $in over scalars — conditions a field-missing or non-scalar
        document can never satisfy.  ($ne and friends match missing
        fields, so they cannot be served from the index alone.)
        """
        for field_name, cond in selector.items():
            if field_name.startswith("$"):
                continue
            idx = self._indexes.get((ns, field_name))
            if idx is None:
                continue
            if not isinstance(cond, dict):
                sk = _index_sort_key(cond)
                if sk is None:
                    continue
                return idx.candidates(sk, sk)
            lo = hi = None
            usable = False
            bad = False
            for op, want in cond.items():
                sk = None
                if op in ("$eq", "$gt", "$gte", "$lt", "$lte"):
                    sk = _index_sort_key(want)
                    if sk is None:
                        bad = True
                        break
                if op == "$eq":
                    lo = sk if lo is None or sk > lo else lo
                    hi = sk if hi is None or sk < hi else hi
                    usable = True
                elif op in ("$gt", "$gte"):
                    lo = sk if lo is None or sk > lo else lo
                    usable = True
                elif op in ("$lt", "$lte"):
                    hi = sk if hi is None or sk < hi else hi
                    usable = True
                elif op == "$in":
                    if (isinstance(want, (list, tuple))
                            and all(_index_sort_key(w) is not None
                                    for w in want)):
                        out = []
                        for w in want:
                            sw = _index_sort_key(w)
                            out.extend(idx.candidates(sw, sw))
                        return sorted(set(out))
            if bad or not usable:
                continue
            # inclusive float bounds: candidate superset, exact
            # re-check downstream (strictness enforced by the matcher)
            return idx.candidates(lo, hi)
        return None

    def execute_query(self, ns: str, selector: dict, limit: int = 0,
                      bookmark: str = ""):
        """Rich query over JSON-document values (the statecouchdb option,
        core/ledger/.../statedb/statecouchdb/statecouchdb.go — Mango
        selector subset: field equality, $gt/$gte/$lt/$lte/$ne/$in, with
        implicit AND across fields and $or for alternatives).

        Field indexes (create_index) make constrained queries sublinear:
        the planner takes candidates from one indexed field and re-checks
        the full selector — full-namespace scans only happen for
        unindexed selectors, like a CouchDB query with no matching index.

        Pagination: results come in key order; `bookmark` resumes AFTER
        the given key and `limit` caps the page (statecouchdb paginated
        queries, QueryResultsIteratorWithBookmark).  Use query_page() to
        also receive the next bookmark.

        Values that do not parse as JSON objects simply never match —
        byte-valued keys coexist with document-valued keys, exactly like
        a CouchDB-backed channel with mixed chaincodes.

        NOTE: like the reference's rich queries, results are NOT
        re-checked by MVCC phantom protection — rich queries are for
        reads/audit, not for range-protected simulation.
        """
        return iter(self._query(ns, selector, limit, bookmark))

    def query_page(self, ns: str, selector: dict, limit: int,
                   bookmark: str = ""):
        """-> (results, next_bookmark); next_bookmark '' when the result
        set is exhausted."""
        out = self._query(ns, selector, limit, bookmark)
        nb = out[-1][0] if (limit and len(out) == limit) else ""
        return out, nb

    def _query(self, ns: str, selector: dict, limit: int,
               bookmark: str) -> list:
        with self._lock:
            cand = self._index_candidates(ns, selector)
            if cand is None:
                lo = bisect.bisect_left(self._sorted_keys, (ns, ""))
                keys = []
                for i in range(lo, len(self._sorted_keys)):
                    kns, key = self._sorted_keys[i]
                    if kns != ns:
                        break
                    keys.append(key)
            else:
                keys = sorted(cand)
            pairs = [(k, self._data.get((ns, k))) for k in keys
                     if k > bookmark]
        out = []
        for key, vv in pairs:
            if vv is None:
                continue
            doc = _doc_of(vv.value)
            if doc is None or not _match_selector(doc, selector):
                continue
            out.append((key, vv))
            if limit and len(out) >= limit:
                break
        return out

    @property
    def savepoint(self) -> Optional[int]:
        with self._lock:
            return self._savepoint

    def __len__(self):
        return len(self._data)

    # -- writes -------------------------------------------------------------

    def apply_updates(self, batch: UpdateBatch, block_num: int) -> None:
        """Atomically apply one block's updates + advance the savepoint
        (statedb ApplyUpdates with sp)."""
        with self._lock:
            if self._savepoint is not None and block_num <= self._savepoint:
                raise ValueError(
                    f"batch for block {block_num} <= savepoint {self._savepoint}")
            if self.root is not None:
                self._wal_append(batch, block_num)
            self._apply_in_memory(batch, block_num)
            if self.root is not None:
                self._batches_since_snapshot += 1
                if self._batches_since_snapshot >= self.snapshot_every:
                    self._write_snapshot()

    # below this many updates the per-key bisect path wins; above it the
    # coalesced one-pass merge of _sorted_keys is O(N + B log B) instead
    # of O(B * N) list insert/pop churn
    _BATCH_APPLY_MIN = 64

    def _apply_in_memory(self, batch: UpdateBatch, block_num: int) -> None:
        if len(batch) >= self._BATCH_APPLY_MIN:
            self._apply_batched(batch)
        else:
            self._apply_per_key(batch)
        self._savepoint = block_num

    def _apply_per_key(self, batch: UpdateBatch) -> None:
        ns_indexed = {n for (n, _f) in self._indexes}
        for (ns, key), vv in batch.items():
            k = (ns, key)
            if vv is None:
                if k in self._data:
                    del self._data[k]
                    i = bisect.bisect_left(self._sorted_keys, k)
                    if i < len(self._sorted_keys) and self._sorted_keys[i] == k:
                        self._sorted_keys.pop(i)
                if ns in ns_indexed:
                    for (n, f), idx in self._indexes.items():
                        if n == ns:
                            idx.remove(key)
            else:
                if k not in self._data:
                    bisect.insort(self._sorted_keys, k)
                self._data[k] = vv
                if ns in ns_indexed:
                    doc = _doc_of(vv.value)
                    for (n, f), idx in self._indexes.items():
                        if n != ns:
                            continue
                        if doc is None:
                            idx.remove(key)
                        else:
                            idx.put(key, doc.get(f))

    def _apply_batched(self, batch: UpdateBatch) -> None:
        """One coalesced pass: mutate _data/_FieldIndexes per key, then
        rebuild _sorted_keys with a single merge of the surviving keys
        and the sorted set of newly-added ones."""
        ns_indexed = {n for (n, _f) in self._indexes}
        removed = set()
        added = set()
        data = self._data
        for k, vv in batch.items():
            ns, key = k
            if vv is None:
                if k in data:
                    del data[k]
                    removed.add(k)
                if ns in ns_indexed:
                    for (n, f), idx in self._indexes.items():
                        if n == ns:
                            idx.remove(key)
            else:
                if k not in data:
                    added.add(k)
                data[k] = vv
                if ns in ns_indexed:
                    doc = _doc_of(vv.value)
                    for (n, f), idx in self._indexes.items():
                        if n != ns:
                            continue
                        if doc is None:
                            idx.remove(key)
                        else:
                            idx.put(key, doc.get(f))
        if not removed and not added:
            return
        new_keys = sorted(added)
        merged: List[Tuple[str, str]] = []
        append = merged.append
        i = 0
        n_new = len(new_keys)
        for k in self._sorted_keys:
            if k in removed:
                continue
            while i < n_new and new_keys[i] < k:
                append(new_keys[i])
                i += 1
            append(k)
        merged.extend(new_keys[i:])
        self._sorted_keys = merged

    # -- persistence --------------------------------------------------------

    def _wal_path(self) -> str:
        return os.path.join(self.root, "state.wal")

    def _snap_path(self) -> str:
        return os.path.join(self.root, "state.snapshot")

    @staticmethod
    def _encode_batch(batch: UpdateBatch, block_num: int) -> bytes:
        recs = []
        for (ns, key), vv in sorted(batch.items()):
            recs.append({"ns": ns, "key": key,
                         "value": None if vv is None else vv.value,
                         "version": None if vv is None else vv.version.to_list()})
        return serde.encode({"block": block_num, "updates": recs})

    def _wal_append(self, batch: UpdateBatch, block_num: int) -> None:
        payload = self._encode_batch(batch, block_num)
        with open(self._wal_path(), "ab") as f:
            f.write(_LEN.pack(len(payload)))
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())

    def _write_snapshot(self) -> None:
        recs = []
        for (ns, key) in self._sorted_keys:
            vv = self._data[(ns, key)]
            recs.append({"ns": ns, "key": key, "value": vv.value,
                         "version": vv.version.to_list()})
        payload = serde.encode({"savepoint": self._savepoint, "data": recs})
        tmp = self._snap_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path())
        with open(self._wal_path(), "wb") as f:
            f.truncate(0)
        self._batches_since_snapshot = 0

    def _recover(self) -> None:
        if os.path.exists(self._snap_path()):
            with open(self._snap_path(), "rb") as f:
                snap = serde.decode(f.read())
            self._savepoint = snap["savepoint"]
            for rec in snap["data"]:
                k = (rec["ns"], rec["key"])
                self._data[k] = VersionedValue(
                    rec["value"], Version.from_list(rec["version"]))
            self._sorted_keys = sorted(self._data.keys())
        if not os.path.exists(self._wal_path()):
            return
        with open(self._wal_path(), "rb") as f:
            data = f.read()
        off, good_end = 0, 0
        while off + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, off)
            if off + _LEN.size + n > len(data):
                break
            try:
                rec = serde.decode(data[off + _LEN.size:off + _LEN.size + n])
            except ValueError:
                break
            off += _LEN.size + n
            good_end = off
            if self._savepoint is not None and rec["block"] <= self._savepoint:
                continue  # already in snapshot
            batch = UpdateBatch()
            for u in rec["updates"]:
                if u["value"] is None:
                    batch.delete(u["ns"], u["key"], Version(rec["block"], 0))
                else:
                    batch.put(u["ns"], u["key"], u["value"],
                              Version.from_list(u["version"]))
            self._apply_in_memory(batch, rec["block"])
        if good_end != len(data):
            with open(self._wal_path(), "r+b") as f:
                f.truncate(good_end)
