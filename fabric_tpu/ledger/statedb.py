"""Versioned key-value state database with savepoint + crash recovery.

Reference parity: core/ledger/kvledger/txmgmt/statedb/statedb.go interface
and the stateleveldb implementation — versioned values (value, Height),
update batches applied atomically with a savepoint, ordered range scans.

Durability model: an append-only WAL of update batches (one record per
block) plus periodic full snapshots for compaction.  On open: load the
newest snapshot, replay WAL records past it, truncate any torn tail.
Savepoint = block number of the last applied batch; the kvledger recovery
path replays blocks above the savepoint from the block store
(core/ledger/kvledger/recovery.go semantics).

Keys are (namespace, key) pairs, ordered lexicographically for range
scans (leveldb iterator parity).
"""

from __future__ import annotations

import bisect
import os
import struct
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_tpu.protocol import Version
from fabric_tpu.utils import serde

_LEN = struct.Struct("<Q")
SNAPSHOT_EVERY = 256  # batches between snapshot compactions


@dataclass(frozen=True)
class VersionedValue:
    value: bytes
    version: Version


class UpdateBatch:
    """statedb.UpdateBatch: puts/deletes staged by MVCC validation."""

    def __init__(self):
        self._updates: Dict[Tuple[str, str], Optional[VersionedValue]] = {}

    def put(self, ns: str, key: str, value: bytes, version: Version) -> None:
        self._updates[(ns, key)] = VersionedValue(value, version)

    def delete(self, ns: str, key: str, version: Version) -> None:
        # deletes still carry the deleting tx's version (stateleveldb tombstone)
        self._updates[(ns, key)] = None

    def get(self, ns: str, key: str):
        """(found, vv) — distinguishes absent from staged-delete."""
        k = (ns, key)
        return (k in self._updates), self._updates.get(k)

    def items(self):
        return self._updates.items()

    def __len__(self):
        return len(self._updates)


def _match_selector(doc: dict, selector: dict) -> bool:
    """Mango-selector subset evaluation (implicit AND across fields)."""
    for field_name, cond in selector.items():
        if field_name == "$or":
            if not any(_match_selector(doc, alt) for alt in cond):
                return False
            continue
        if field_name == "$and":
            if not all(_match_selector(doc, alt) for alt in cond):
                return False
            continue
        have = doc.get(field_name)
        if isinstance(cond, dict):
            for op, want in cond.items():
                try:
                    if op == "$gt" and not have > want:
                        return False
                    elif op == "$gte" and not have >= want:
                        return False
                    elif op == "$lt" and not have < want:
                        return False
                    elif op == "$lte" and not have <= want:
                        return False
                    elif op == "$ne" and not have != want:
                        return False
                    elif op == "$eq" and not have == want:
                        return False
                    elif op == "$in" and have not in want:
                        return False
                except TypeError:
                    return False      # cross-type comparison: no match
        else:
            if have != cond:
                return False
    return True


class StateDB:
    """Versioned state store (VersionedDB iface, statedb.go)."""

    def __init__(self, root: Optional[str] = None,
                 snapshot_every: int = SNAPSHOT_EVERY):
        self.root = root
        self.snapshot_every = snapshot_every
        self._lock = threading.RLock()
        self._data: Dict[Tuple[str, str], VersionedValue] = {}
        self._sorted_keys: List[Tuple[str, str]] = []
        self._savepoint: Optional[int] = None
        self._batches_since_snapshot = 0
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._recover()

    # -- reads --------------------------------------------------------------

    def get(self, ns: str, key: str) -> Optional[VersionedValue]:
        with self._lock:
            return self._data.get((ns, key))

    def get_version(self, ns: str, key: str) -> Optional[Version]:
        vv = self.get(ns, key)
        return None if vv is None else vv.version

    def range_scan(self, ns: str, start_key: str, end_key: str,
                   limit: int = 0) -> Iterator[Tuple[str, VersionedValue]]:
        """Ordered scan over [start_key, end_key) within a namespace;
        empty end_key = scan to namespace end (stateleveldb iterator)."""
        with self._lock:
            lo = bisect.bisect_left(self._sorted_keys, (ns, start_key))
            out = []
            for i in range(lo, len(self._sorted_keys)):
                kns, key = self._sorted_keys[i]
                if kns != ns or (end_key and key >= end_key):
                    break
                out.append((key, self._data[(kns, key)]))
                if limit and len(out) >= limit:
                    break
        return iter(out)

    def execute_query(self, ns: str, selector: dict, limit: int = 0):
        """Rich query over JSON-document values (the statecouchdb option,
        core/ledger/.../statedb/statecouchdb/statecouchdb.go — Mango
        selector subset: field equality, $gt/$gte/$lt/$lte/$ne/$in, with
        implicit AND across fields and $or for alternatives).

        Values that do not parse as JSON objects simply never match —
        byte-valued keys coexist with document-valued keys, exactly like
        a CouchDB-backed channel with mixed chaincodes.

        NOTE: like the reference's rich queries, results are NOT
        re-checked by MVCC phantom protection — rich queries are for
        reads/audit, not for range-protected simulation.
        """
        import json as _json
        out = []
        with self._lock:
            items = sorted((k[1], vv) for k, vv in self._data.items()
                           if k[0] == ns)
        for key, vv in items:
            try:
                doc = _json.loads(vv.value.decode("utf-8"))
            except (ValueError, UnicodeDecodeError, AttributeError):
                continue
            if not isinstance(doc, dict):
                continue
            if _match_selector(doc, selector):
                out.append((key, vv))
                if limit and len(out) >= limit:
                    break
        return iter(out)

    @property
    def savepoint(self) -> Optional[int]:
        with self._lock:
            return self._savepoint

    def __len__(self):
        return len(self._data)

    # -- writes -------------------------------------------------------------

    def apply_updates(self, batch: UpdateBatch, block_num: int) -> None:
        """Atomically apply one block's updates + advance the savepoint
        (statedb ApplyUpdates with sp)."""
        with self._lock:
            if self._savepoint is not None and block_num <= self._savepoint:
                raise ValueError(
                    f"batch for block {block_num} <= savepoint {self._savepoint}")
            if self.root is not None:
                self._wal_append(batch, block_num)
            self._apply_in_memory(batch, block_num)
            if self.root is not None:
                self._batches_since_snapshot += 1
                if self._batches_since_snapshot >= self.snapshot_every:
                    self._write_snapshot()

    def _apply_in_memory(self, batch: UpdateBatch, block_num: int) -> None:
        for (ns, key), vv in batch.items():
            k = (ns, key)
            if vv is None:
                if k in self._data:
                    del self._data[k]
                    i = bisect.bisect_left(self._sorted_keys, k)
                    if i < len(self._sorted_keys) and self._sorted_keys[i] == k:
                        self._sorted_keys.pop(i)
            else:
                if k not in self._data:
                    bisect.insort(self._sorted_keys, k)
                self._data[k] = vv
        self._savepoint = block_num

    # -- persistence --------------------------------------------------------

    def _wal_path(self) -> str:
        return os.path.join(self.root, "state.wal")

    def _snap_path(self) -> str:
        return os.path.join(self.root, "state.snapshot")

    @staticmethod
    def _encode_batch(batch: UpdateBatch, block_num: int) -> bytes:
        recs = []
        for (ns, key), vv in sorted(batch.items()):
            recs.append({"ns": ns, "key": key,
                         "value": None if vv is None else vv.value,
                         "version": None if vv is None else vv.version.to_list()})
        return serde.encode({"block": block_num, "updates": recs})

    def _wal_append(self, batch: UpdateBatch, block_num: int) -> None:
        payload = self._encode_batch(batch, block_num)
        with open(self._wal_path(), "ab") as f:
            f.write(_LEN.pack(len(payload)))
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())

    def _write_snapshot(self) -> None:
        recs = []
        for (ns, key) in self._sorted_keys:
            vv = self._data[(ns, key)]
            recs.append({"ns": ns, "key": key, "value": vv.value,
                         "version": vv.version.to_list()})
        payload = serde.encode({"savepoint": self._savepoint, "data": recs})
        tmp = self._snap_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path())
        with open(self._wal_path(), "wb") as f:
            f.truncate(0)
        self._batches_since_snapshot = 0

    def _recover(self) -> None:
        if os.path.exists(self._snap_path()):
            with open(self._snap_path(), "rb") as f:
                snap = serde.decode(f.read())
            self._savepoint = snap["savepoint"]
            for rec in snap["data"]:
                k = (rec["ns"], rec["key"])
                self._data[k] = VersionedValue(
                    rec["value"], Version.from_list(rec["version"]))
            self._sorted_keys = sorted(self._data.keys())
        if not os.path.exists(self._wal_path()):
            return
        with open(self._wal_path(), "rb") as f:
            data = f.read()
        off, good_end = 0, 0
        while off + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, off)
            if off + _LEN.size + n > len(data):
                break
            try:
                rec = serde.decode(data[off + _LEN.size:off + _LEN.size + n])
            except ValueError:
                break
            off += _LEN.size + n
            good_end = off
            if self._savepoint is not None and rec["block"] <= self._savepoint:
                continue  # already in snapshot
            batch = UpdateBatch()
            for u in rec["updates"]:
                if u["value"] is None:
                    batch.delete(u["ns"], u["key"], Version(rec["block"], 0))
                else:
                    batch.put(u["ns"], u["key"], u["value"],
                              Version.from_list(u["version"]))
            self._apply_in_memory(batch, rec["block"])
        if good_end != len(data):
            with open(self._wal_path(), "r+b") as f:
                f.truncate(good_end)
