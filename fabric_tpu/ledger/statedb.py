"""Versioned key-value state database: key-hash sharded, savepoint +
crash-consistent checkpoint recovery.

Reference parity: core/ledger/kvledger/txmgmt/statedb/statedb.go interface
and the stateleveldb implementation — versioned values (value, Height),
update batches applied atomically with a savepoint, ordered range scans.

Layout: keys stripe across ``n_shards`` independently-locked shards by a
deterministic hash of (namespace, key) — `shard_of`.  Batched applies
land shard-parallel (the parallel-commit and device-validate planes
pre-split their prepared batches with `UpdateBatch.preshard`, so the
split cost is off the commit lock path), while point reads take only the
owning shard's lock.

Durability model: ONE append-only WAL of update batches (a single fsync
per block keeps the savepoint atomic ACROSS shards — per-shard WALs
could tear a block between shards on crash), plus periodic sharded
checkpoints for compaction: every shard flushed to its own
content-hashed file and an atomically-renamed manifest recording
(generation, savepoint, per-shard sha256) — see ledger/checkpoint.py for
the kill-at-any-instant story.  On open: load the newest verifiable
manifest (falling back MANIFEST → MANIFEST.prev → legacy state.snapshot
→ empty), replay WAL records past its savepoint, truncate any torn
tail.  Savepoint = block number of the last applied batch; the kvledger
recovery path replays blocks above the savepoint from the block store
(core/ledger/kvledger/recovery.go semantics), so losing a checkpoint
never loses data — only recovery time.

Keys are (namespace, key) pairs, ordered lexicographically for range
scans (leveldb iterator parity); cross-shard scans are heap-merged back
into one ordered stream, bit-identical to the flat store's iteration
order.

Consistency note: `get` synchronizes only on the owning shard, so a
reader racing a multi-shard apply may observe a block partially applied
across shards (never within one).  Commit-path correctness does not
ride on this — MVCC re-validates reads at commit, same as the
reference's leveldb store, and the global lock covers scans/queries.
"""

from __future__ import annotations

import bisect
import heapq
import os
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_tpu.ledger import checkpoint as ckpt
from fabric_tpu.protocol import Version
from fabric_tpu.utils import serde

_LEN = struct.Struct("<Q")
SNAPSHOT_EVERY = 256  # batches between checkpoint compactions
N_SHARDS = 8          # default key-hash stripe width

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def shard_of(ns: str, key: str, n_shards: int) -> int:
    """Deterministic shard for a (namespace, key): FNV-1a 64 over the
    NUL-joined pair.  Stable across processes/restarts — checkpoints,
    prepared batches, and snapshot transfers all agree on placement."""
    if n_shards <= 1:
        return 0
    h = _FNV_OFFSET
    for b in (ns + "\x00" + key).encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h % n_shards


@dataclass(frozen=True)
class VersionedValue:
    value: bytes
    version: Version


class UpdateBatch:
    """statedb.UpdateBatch: puts/deletes staged by MVCC validation.

    `preshard` / `items_by_shard` cache the per-shard split so the
    parallel-commit scheduler and the device-validate rebuild can pay
    the hash cost outside the store's apply lock."""

    def __init__(self):
        self._updates: Dict[Tuple[str, str], Optional[VersionedValue]] = {}
        self._by_shard = None  # (n_shards, per-shard item lists)

    def put(self, ns: str, key: str, value: bytes, version: Version) -> None:
        self._updates[(ns, key)] = VersionedValue(value, version)
        self._by_shard = None

    def delete(self, ns: str, key: str, version: Version) -> None:
        # deletes still carry the deleting tx's version (stateleveldb tombstone)
        self._updates[(ns, key)] = None
        self._by_shard = None

    def get(self, ns: str, key: str):
        """(found, vv) — distinguishes absent from staged-delete."""
        k = (ns, key)
        return (k in self._updates), self._updates.get(k)

    def items(self):
        return self._updates.items()

    def __len__(self):
        return len(self._updates)

    def items_by_shard(self, n_shards: int) -> List[list]:
        cached = self._by_shard
        if cached is not None and cached[0] == n_shards:
            return cached[1]
        lists: List[list] = [[] for _ in range(n_shards)]
        if n_shards <= 1:
            lists[0] = list(self._updates.items())
        else:
            for item in self._updates.items():
                ns, key = item[0]
                lists[shard_of(ns, key, n_shards)].append(item)
        self._by_shard = (n_shards, lists)
        return lists

    def preshard(self, n_shards: int) -> "UpdateBatch":
        """Warm the per-shard split (idempotent; invalidated by put/delete)."""
        if n_shards > 1:
            self.items_by_shard(n_shards)
        return self


def _doc_of(value) -> Optional[dict]:
    """Parse a state value as a JSON document; None when not one."""
    import json as _json
    try:
        doc = _json.loads(value.decode("utf-8"))
    except (ValueError, UnicodeDecodeError, AttributeError):
        return None
    return doc if isinstance(doc, dict) else None


def _match_selector(doc: dict, selector: dict) -> bool:
    """Mango-selector subset evaluation (implicit AND across fields)."""
    for field_name, cond in selector.items():
        if field_name == "$or":
            if not any(_match_selector(doc, alt) for alt in cond):
                return False
            continue
        if field_name == "$and":
            if not all(_match_selector(doc, alt) for alt in cond):
                return False
            continue
        have = doc.get(field_name)
        if isinstance(cond, dict):
            for op, want in cond.items():
                try:
                    if op == "$gt" and not have > want:
                        return False
                    elif op == "$gte" and not have >= want:
                        return False
                    elif op == "$lt" and not have < want:
                        return False
                    elif op == "$lte" and not have <= want:
                        return False
                    elif op == "$ne" and not have != want:
                        return False
                    elif op == "$eq" and not have == want:
                        return False
                    elif op == "$in" and have not in want:
                        return False
                except TypeError:
                    return False      # cross-type comparison: no match
        else:
            if have != cond:
                return False
    return True


def _index_sort_key(v):
    """Type-tagged sort key for an indexable scalar, or None when the
    value is not indexable.  Numbers (incl. bool — Python equality
    semantics, which _match_selector uses) share one collation class;
    strings another."""
    if isinstance(v, (int, float)) and not isinstance(v, complex):
        try:
            return (0, float(v))
        except (OverflowError, ValueError):
            return None
    if isinstance(v, str):
        return (1, v)
    return None


class _FieldIndex:
    """Sorted (sort_key, key) entries for one (namespace, field).

    Lossy float collation is fine: index lookups return a SUPERSET of
    candidates (inclusive bounds) and execute_query re-checks each doc
    with the exact selector — mirroring how the reference's CouchDB
    indexes only narrow the scan (statecouchdb query with index hint).
    """

    def __init__(self):
        self.by_key: Dict[str, tuple] = {}      # key -> sort_key
        self.sorted: List[Tuple[tuple, str]] = []

    def remove(self, key: str) -> None:
        sk = self.by_key.pop(key, None)
        if sk is not None:
            i = bisect.bisect_left(self.sorted, (sk, key))
            if i < len(self.sorted) and self.sorted[i] == (sk, key):
                self.sorted.pop(i)

    def put(self, key: str, value) -> None:
        self.remove(key)
        sk = _index_sort_key(value)
        if sk is not None:
            self.by_key[key] = sk
            bisect.insort(self.sorted, (sk, key))

    def candidates(self, lo, hi) -> List[str]:
        """Keys whose sort key is within [lo, hi] (inclusive; None =
        unbounded on that side)."""
        i = 0 if lo is None else bisect.bisect_left(self.sorted, (lo,))
        if hi is None:
            j = len(self.sorted)
        else:
            j = bisect.bisect_right(self.sorted, (hi,))
            while j < len(self.sorted) and self.sorted[j][0] == hi:
                j += 1
        return [k for _, k in self.sorted[i:j]]


class _StateShard:
    """One stripe: its own lock, key map, ordered key list, and slice of
    every registered field index."""

    __slots__ = ("lock", "data", "sorted_keys", "indexes")

    def __init__(self):
        self.lock = threading.RLock()
        self.data: Dict[Tuple[str, str], VersionedValue] = {}
        self.sorted_keys: List[Tuple[str, str]] = []
        self.indexes: Dict[Tuple[str, str], _FieldIndex] = {}


class StateDB:
    """Versioned state store (VersionedDB iface, statedb.go)."""

    def __init__(self, root: Optional[str] = None,
                 snapshot_every: int = SNAPSHOT_EVERY,
                 n_shards: int = N_SHARDS,
                 channel: str = ""):
        self.root = root
        self.snapshot_every = snapshot_every
        self.n_shards = max(1, int(n_shards))
        self.channel = channel  # metric label only; "" = unlabeled/quiet
        self._lock = threading.RLock()
        self._shards = [_StateShard() for _ in range(self.n_shards)]
        self._savepoint: Optional[int] = None
        self._batches_since_ckpt = 0
        self._ckpt_gen = 0
        # gen -> lease-expiry monotonic time: generations a snapshot
        # fetch is streaming from; checkpoint GC keeps them alive until
        # the lease lapses (ledger/snapshot.py refreshes per chunk)
        self._gen_pins: dict = {}
        # registered (ns, field) pairs; each shard holds its own
        # _FieldIndex slice (the statecouchdb index slot — reference
        # indexes ship in chaincode META-INF/statedb/couchdb/indexes and
        # are created at deploy; here create_index is called at
        # chaincode install, node/peer.py)
        self._index_fields: set = set()
        self._pool: Optional[ThreadPoolExecutor] = None
        self.last_recovery = {"source": "fresh", "wal_blocks": 0,
                              "savepoint": None}
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._recover()

    # -- reads --------------------------------------------------------------

    def get(self, ns: str, key: str) -> Optional[VersionedValue]:
        sh = self._shards[shard_of(ns, key, self.n_shards)]
        with sh.lock:
            return sh.data.get((ns, key))

    def get_version(self, ns: str, key: str) -> Optional[Version]:
        vv = self.get(ns, key)
        return None if vv is None else vv.version

    def range_scan(self, ns: str, start_key: str, end_key: str,
                   limit: int = 0) -> Iterator[Tuple[str, VersionedValue]]:
        """Ordered scan over [start_key, end_key) within a namespace;
        empty end_key = scan to namespace end (stateleveldb iterator).
        Per-shard ordered slices are heap-merged — identical order to
        the flat store (keys are globally unique, so the merge never
        compares VersionedValues)."""
        with self._lock:
            slices = []
            for sh in self._shards:
                part = []
                lo = bisect.bisect_left(sh.sorted_keys, (ns, start_key))
                for i in range(lo, len(sh.sorted_keys)):
                    kns, key = sh.sorted_keys[i]
                    if kns != ns or (end_key and key >= end_key):
                        break
                    part.append((key, sh.data[(kns, key)]))
                    if limit and len(part) >= limit:
                        break
                if part:
                    slices.append(part)
            out = list(heapq.merge(*slices))
            if limit:
                out = out[:limit]
        return iter(out)

    # -- field indexes + rich queries ---------------------------------------

    def create_index(self, ns: str, field: str) -> None:
        """Register (and build from current state) a field index for a
        namespace.  Idempotent — peers re-register at startup and the
        index rebuilds from the recovered state."""
        with self._lock:
            self._index_fields.add((ns, field))
            for sh in self._shards:
                idx = _FieldIndex()
                sh.indexes[(ns, field)] = idx
                lo = bisect.bisect_left(sh.sorted_keys, (ns, ""))
                for i in range(lo, len(sh.sorted_keys)):
                    kns, key = sh.sorted_keys[i]
                    if kns != ns:
                        break
                    doc = _doc_of(sh.data[(kns, key)].value)
                    if doc is not None:
                        idx.put(key, doc.get(field))

    def indexes_for(self, ns: str) -> List[str]:
        with self._lock:
            return [f for (n, f) in self._index_fields if n == ns]

    def _gather_candidates(self, ns: str, field: str, lo, hi) -> List[str]:
        out: List[str] = []
        for sh in self._shards:
            idx = sh.indexes.get((ns, field))
            if idx is not None:
                out.extend(idx.candidates(lo, hi))
        return out

    def _index_candidates(self, ns: str, selector: dict):
        """Planner: if some top-level selector field is indexed with an
        index-coverable condition, return the candidate key list (a
        SUPERSET of matches, re-checked by the caller); else None.

        Coverable: scalar $eq / bare equality, $gt/$gte/$lt/$lte, and
        $in over scalars — conditions a field-missing or non-scalar
        document can never satisfy.  ($ne and friends match missing
        fields, so they cannot be served from the index alone.)
        """
        for field_name, cond in selector.items():
            if field_name.startswith("$"):
                continue
            if (ns, field_name) not in self._index_fields:
                continue
            if not isinstance(cond, dict):
                sk = _index_sort_key(cond)
                if sk is None:
                    continue
                return self._gather_candidates(ns, field_name, sk, sk)
            lo = hi = None
            usable = False
            bad = False
            for op, want in cond.items():
                sk = None
                if op in ("$eq", "$gt", "$gte", "$lt", "$lte"):
                    sk = _index_sort_key(want)
                    if sk is None:
                        bad = True
                        break
                if op == "$eq":
                    lo = sk if lo is None or sk > lo else lo
                    hi = sk if hi is None or sk < hi else hi
                    usable = True
                elif op in ("$gt", "$gte"):
                    lo = sk if lo is None or sk > lo else lo
                    usable = True
                elif op in ("$lt", "$lte"):
                    hi = sk if hi is None or sk < hi else hi
                    usable = True
                elif op == "$in":
                    if (isinstance(want, (list, tuple))
                            and all(_index_sort_key(w) is not None
                                    for w in want)):
                        out = []
                        for w in want:
                            sw = _index_sort_key(w)
                            out.extend(
                                self._gather_candidates(ns, field_name,
                                                        sw, sw))
                        return sorted(set(out))
            if bad or not usable:
                continue
            # inclusive float bounds: candidate superset, exact
            # re-check downstream (strictness enforced by the matcher)
            return self._gather_candidates(ns, field_name, lo, hi)
        return None

    def execute_query(self, ns: str, selector: dict, limit: int = 0,
                      bookmark: str = ""):
        """Rich query over JSON-document values (the statecouchdb option,
        core/ledger/.../statedb/statecouchdb/statecouchdb.go — Mango
        selector subset: field equality, $gt/$gte/$lt/$lte/$ne/$in, with
        implicit AND across fields and $or for alternatives).

        Field indexes (create_index) make constrained queries sublinear:
        the planner takes candidates from one indexed field and re-checks
        the full selector — full-namespace scans only happen for
        unindexed selectors, like a CouchDB query with no matching index.

        Pagination: results come in key order; `bookmark` resumes AFTER
        the given key and `limit` caps the page (statecouchdb paginated
        queries, QueryResultsIteratorWithBookmark).  Use query_page() to
        also receive the next bookmark.

        Values that do not parse as JSON objects simply never match —
        byte-valued keys coexist with document-valued keys, exactly like
        a CouchDB-backed channel with mixed chaincodes.

        NOTE: like the reference's rich queries, results are NOT
        re-checked by MVCC phantom protection — rich queries are for
        reads/audit, not for range-protected simulation.
        """
        return iter(self._query(ns, selector, limit, bookmark))

    def query_page(self, ns: str, selector: dict, limit: int,
                   bookmark: str = ""):
        """-> (results, next_bookmark); next_bookmark '' when the result
        set is exhausted."""
        out = self._query(ns, selector, limit, bookmark)
        nb = out[-1][0] if (limit and len(out) == limit) else ""
        return out, nb

    def _query(self, ns: str, selector: dict, limit: int,
               bookmark: str) -> list:
        with self._lock:
            cand = self._index_candidates(ns, selector)
            if cand is None:
                per_shard = []
                for sh in self._shards:
                    part = []
                    lo = bisect.bisect_left(sh.sorted_keys, (ns, ""))
                    for i in range(lo, len(sh.sorted_keys)):
                        kns, key = sh.sorted_keys[i]
                        if kns != ns:
                            break
                        part.append(key)
                    if part:
                        per_shard.append(part)
                keys = list(heapq.merge(*per_shard))
            else:
                keys = sorted(cand)
            pairs = []
            for k in keys:
                if k <= bookmark:
                    continue
                sh = self._shards[shard_of(ns, k, self.n_shards)]
                pairs.append((k, sh.data.get((ns, k))))
        out = []
        for key, vv in pairs:
            if vv is None:
                continue
            doc = _doc_of(vv.value)
            if doc is None or not _match_selector(doc, selector):
                continue
            out.append((key, vv))
            if limit and len(out) >= limit:
                break
        return out

    @property
    def savepoint(self) -> Optional[int]:
        with self._lock:
            return self._savepoint

    def __len__(self):
        return sum(len(sh.data) for sh in self._shards)

    @property
    def _data(self) -> Dict[Tuple[str, str], VersionedValue]:
        """Merged read-only view of every shard (flat-store compat for
        tests/tooling; the shards are the real storage)."""
        merged: Dict[Tuple[str, str], VersionedValue] = {}
        for sh in self._shards:
            merged.update(sh.data)
        return merged

    def shard_sizes(self) -> List[int]:
        return [len(sh.data) for sh in self._shards]

    def status(self) -> dict:
        with self._lock:
            return {
                "n_shards": self.n_shards,
                "savepoint": self._savepoint,
                "keys": sum(len(sh.data) for sh in self._shards),
                "shard_keys": [len(sh.data) for sh in self._shards],
                "checkpoint_gen": self._ckpt_gen,
                "batches_since_checkpoint": self._batches_since_ckpt,
                "last_recovery": dict(self.last_recovery),
            }

    # -- writes -------------------------------------------------------------

    def apply_updates(self, batch: UpdateBatch, block_num: int) -> None:
        """Atomically apply one block's updates + advance the savepoint
        (statedb ApplyUpdates with sp).  One WAL record + fsync covers
        every shard; the in-memory apply fans out shard-parallel for
        large batches."""
        with self._lock:
            if self._savepoint is not None and block_num <= self._savepoint:
                raise ValueError(
                    f"batch for block {block_num} <= savepoint {self._savepoint}")
            if self.root is not None:
                self._wal_append(batch, block_num)
            self._apply_in_memory(batch, block_num)
            if self.root is not None:
                self._batches_since_ckpt += 1
                if self._batches_since_ckpt >= self.snapshot_every:
                    self._checkpoint_locked()
        self._observe_shards()

    # below this many updates the per-key bisect path wins; above it the
    # coalesced one-pass merge of sorted_keys is O(N + B log B) instead
    # of O(B * N) list insert/pop churn
    _BATCH_APPLY_MIN = 64
    # below this many TOTAL updates (or with only one busy shard) the
    # thread fan-out costs more than it buys
    _PARALLEL_APPLY_MIN = 512
    # on a single-core host the fan-out is pure GIL thrash — the serial
    # per-shard loop (still sharded: smaller sorted-key merges) wins
    _HOST_CORES = os.cpu_count() or 1

    def _apply_in_memory(self, batch: UpdateBatch, block_num: int) -> None:
        per_shard = batch.items_by_shard(self.n_shards)
        busy = [i for i, items in enumerate(per_shard) if items]
        if (self._HOST_CORES > 1 and len(busy) > 1
                and len(batch) >= self._PARALLEL_APPLY_MIN):
            pool = self._get_pool()
            futs = [pool.submit(self._apply_shard, self._shards[i],
                                per_shard[i])
                    for i in busy]
            for f in futs:
                f.result()
        else:
            for i in busy:
                self._apply_shard(self._shards[i], per_shard[i])
        self._savepoint = block_num

    @classmethod
    def _apply_shard(cls, shard: _StateShard, items: list) -> None:
        with shard.lock:
            if len(items) >= cls._BATCH_APPLY_MIN:
                cls._apply_shard_batched(shard, items)
            else:
                cls._apply_shard_per_key(shard, items)

    @staticmethod
    def _apply_shard_per_key(shard: _StateShard, items: list) -> None:
        ns_indexed = {n for (n, _f) in shard.indexes}
        data = shard.data
        sorted_keys = shard.sorted_keys
        for k, vv in items:
            ns, key = k
            if vv is None:
                if k in data:
                    del data[k]
                    i = bisect.bisect_left(sorted_keys, k)
                    if i < len(sorted_keys) and sorted_keys[i] == k:
                        sorted_keys.pop(i)
                if ns in ns_indexed:
                    for (n, f), idx in shard.indexes.items():
                        if n == ns:
                            idx.remove(key)
            else:
                if k not in data:
                    bisect.insort(sorted_keys, k)
                data[k] = vv
                if ns in ns_indexed:
                    doc = _doc_of(vv.value)
                    for (n, f), idx in shard.indexes.items():
                        if n != ns:
                            continue
                        if doc is None:
                            idx.remove(key)
                        else:
                            idx.put(key, doc.get(f))

    @staticmethod
    def _apply_shard_batched(shard: _StateShard, items: list) -> None:
        """One coalesced pass: mutate data/_FieldIndexes per key, then
        rebuild sorted_keys with a single merge of the surviving keys
        and the sorted set of newly-added ones."""
        ns_indexed = {n for (n, _f) in shard.indexes}
        removed = set()
        added = set()
        data = shard.data
        for k, vv in items:
            ns, key = k
            if vv is None:
                if k in data:
                    del data[k]
                    removed.add(k)
                if ns in ns_indexed:
                    for (n, f), idx in shard.indexes.items():
                        if n == ns:
                            idx.remove(key)
            else:
                if k not in data:
                    added.add(k)
                data[k] = vv
                if ns in ns_indexed:
                    doc = _doc_of(vv.value)
                    for (n, f), idx in shard.indexes.items():
                        if n != ns:
                            continue
                        if doc is None:
                            idx.remove(key)
                        else:
                            idx.put(key, doc.get(f))
        if not removed and not added:
            return
        new_keys = sorted(added)
        merged: List[Tuple[str, str]] = []
        append = merged.append
        i = 0
        n_new = len(new_keys)
        for k in shard.sorted_keys:
            if k in removed:
                continue
            while i < n_new and new_keys[i] < k:
                append(new_keys[i])
                i += 1
            append(k)
        merged.extend(new_keys[i:])
        shard.sorted_keys = merged

    def _get_pool(self) -> ThreadPoolExecutor:
        if self._pool is None:
            workers = min(self.n_shards, max(2, os.cpu_count() or 2))
            self._pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="statedb-apply")
        return self._pool

    # -- persistence --------------------------------------------------------

    def _wal_path(self) -> str:
        return os.path.join(self.root, "state.wal")

    def _snap_path(self) -> str:
        # legacy (pre-sharding) single-file snapshot; read-only fallback
        return os.path.join(self.root, "state.snapshot")

    @staticmethod
    def _encode_batch(batch: UpdateBatch, block_num: int) -> bytes:
        recs = []
        for (ns, key), vv in sorted(batch.items()):
            recs.append({"ns": ns, "key": key,
                         "value": None if vv is None else vv.value,
                         "version": None if vv is None else vv.version.to_list()})
        return serde.encode({"block": block_num, "updates": recs})

    def _wal_append(self, batch: UpdateBatch, block_num: int) -> None:
        payload = self._encode_batch(batch, block_num)
        with open(self._wal_path(), "ab") as f:
            f.write(_LEN.pack(len(payload)))
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())

    def checkpoint(self) -> Optional[dict]:
        """Flush every shard + flip the manifest; returns the manifest
        (reusing the current one when nothing changed since the last
        checkpoint).  None for in-memory stores or before any block."""
        with self._lock:
            if self.root is None or self._savepoint is None:
                return None
            if self._batches_since_ckpt == 0:
                m = ckpt.read_manifest(self.root)
                if m is not None and m.get("savepoint") == self._savepoint:
                    return m
            return self._checkpoint_locked()

    def pin_generation(self, gen: int, ttl_s: float = 60.0) -> None:
        """Lease-pin a checkpoint generation against GC: while the lease
        is live, later checkpoints keep the generation's directory on
        disk.  The snapshot chunk server refreshes the lease on every
        chunk it serves, so an in-flight bootstrap fetch survives any
        number of concurrent checkpoints; an abandoned fetch merely
        delays GC by the TTL."""
        with self._lock:
            self._gen_pins[int(gen)] = time.monotonic() + float(ttl_s)

    def _live_pins(self) -> set:
        """Drop lapsed leases, return pinned gens (caller holds _lock)."""
        now = time.monotonic()
        self._gen_pins = {g: t for g, t in self._gen_pins.items()
                          if t > now}
        return set(self._gen_pins)

    def _checkpoint_locked(self) -> dict:
        t0 = time.monotonic()
        gen = self._ckpt_gen + 1

        def _encode_shard(i: int) -> bytes:
            sh = self._shards[i]
            recs = []
            for k in sh.sorted_keys:
                vv = sh.data[k]
                recs.append({"ns": k[0], "key": k[1], "value": vv.value,
                             "version": vv.version.to_list()})
            return serde.encode(
                {"savepoint": self._savepoint, "shard": i,
                 "n_shards": self.n_shards, "data": recs})

        # per-shard payloads are independent pure functions of shard
        # content, so the rec-build + serde.encode fans out across the
        # apply pool on multi-core hosts; pool.map preserves shard
        # order, so the payload list — and the manifest digests — are
        # bit-identical to the serial path
        total = sum(len(sh.sorted_keys) for sh in self._shards)
        if (self._HOST_CORES > 1 and len(self._shards) > 1
                and total >= self._PARALLEL_APPLY_MIN):
            payloads = list(self._get_pool().map(
                _encode_shard, range(len(self._shards))))
        else:
            payloads = [_encode_shard(i) for i in range(len(self._shards))]
        manifest = ckpt.write_checkpoint(
            self.root, gen, payloads,
            meta={"savepoint": self._savepoint, "kind": "state"})
        # WAL content is now ≤ the manifest savepoint: safe to drop.  A
        # crash before this truncate only re-skips records on recovery.
        with open(self._wal_path(), "wb") as f:
            f.truncate(0)
        try:
            os.remove(self._snap_path())   # retire any legacy snapshot
        except OSError:
            pass
        ckpt.gc_generations(self.root, {gen, gen - 1} | self._live_pins())
        self._ckpt_gen = gen
        self._batches_since_ckpt = 0
        self._observe_checkpoint(time.monotonic() - t0, gen)
        return manifest

    def _recover(self) -> None:
        source = "empty"
        manifest, payloads, src = ckpt.recover(self.root)
        if manifest is not None and manifest.get("kind", "state") == "state":
            self._load_checkpoint_payloads(payloads)
            self._savepoint = manifest.get("savepoint")
            self._ckpt_gen = int(manifest["gen"])
            source = src
        elif os.path.exists(self._snap_path()):
            with open(self._snap_path(), "rb") as f:
                snap = serde.decode(f.read())
            self._savepoint = snap["savepoint"]
            for rec in snap["data"]:
                sh = self._shards[shard_of(rec["ns"], rec["key"],
                                           self.n_shards)]
                sh.data[(rec["ns"], rec["key"])] = VersionedValue(
                    rec["value"], Version.from_list(rec["version"]))
            for sh in self._shards:
                sh.sorted_keys = sorted(sh.data.keys())
            source = "legacy_snapshot"
        wal_blocks = 0
        if os.path.exists(self._wal_path()):
            with open(self._wal_path(), "rb") as f:
                data = f.read()
            off, good_end = 0, 0
            while off + _LEN.size <= len(data):
                (n,) = _LEN.unpack_from(data, off)
                if off + _LEN.size + n > len(data):
                    break
                try:
                    rec = serde.decode(
                        data[off + _LEN.size:off + _LEN.size + n])
                except ValueError:
                    break
                off += _LEN.size + n
                good_end = off
                if (self._savepoint is not None
                        and rec["block"] <= self._savepoint):
                    continue  # already in checkpoint
                batch = UpdateBatch()
                for u in rec["updates"]:
                    if u["value"] is None:
                        batch.delete(u["ns"], u["key"],
                                     Version(rec["block"], 0))
                    else:
                        batch.put(u["ns"], u["key"], u["value"],
                                  Version.from_list(u["version"]))
                self._apply_in_memory(batch, rec["block"])
                wal_blocks += 1
            if good_end != len(data):
                with open(self._wal_path(), "r+b") as f:
                    f.truncate(good_end)
        self.last_recovery = {"source": source, "wal_blocks": wal_blocks,
                              "savepoint": self._savepoint}

    def _load_checkpoint_payloads(self, payloads: List[bytes]) -> None:
        decoded = [serde.decode(p) for p in payloads]
        direct = (len(decoded) == self.n_shards
                  and all(d.get("n_shards") == self.n_shards
                          and d.get("shard") == i
                          for i, d in enumerate(decoded)))
        if direct:
            for sh, d in zip(self._shards, decoded):
                for rec in d["data"]:
                    sh.data[(rec["ns"], rec["key"])] = VersionedValue(
                        rec["value"], Version.from_list(rec["version"]))
        else:
            # shard count changed since the checkpoint: re-stripe
            for d in decoded:
                for rec in d["data"]:
                    sh = self._shards[shard_of(rec["ns"], rec["key"],
                                               self.n_shards)]
                    sh.data[(rec["ns"], rec["key"])] = VersionedValue(
                        rec["value"], Version.from_list(rec["version"]))
        for sh in self._shards:
            sh.sorted_keys = sorted(sh.data.keys())

    # -- observability ------------------------------------------------------

    def _observe_shards(self) -> None:
        if not self.channel:
            return
        try:
            from fabric_tpu.ops_plane.metrics import registry
            g = registry.gauge("state_shard_keys",
                               "Keys resident per state shard")
            for i, sh in enumerate(self._shards):
                g.set(float(len(sh.data)), channel=self.channel,
                      shard=str(i))
        except Exception:
            pass

    def _observe_checkpoint(self, seconds: float, gen: int) -> None:
        try:
            from fabric_tpu.ops_plane import tracing
            tracing.event("state.checkpoint", channel=self.channel,
                          gen=gen, savepoint=self._savepoint,
                          seconds=round(seconds, 6))
        except Exception:
            pass
        if not self.channel:
            return
        try:
            from fabric_tpu.ops_plane.metrics import registry
            registry.counter("state_checkpoint_total",
                             "State checkpoints written").add(
                                 1, channel=self.channel)
            registry.gauge("state_checkpoint_height",
                           "Savepoint of the newest state checkpoint").set(
                               float(self._savepoint or 0),
                               channel=self.channel)
            registry.histogram("state_checkpoint_seconds",
                               "Wall time per state checkpoint").observe(
                                   seconds, channel=self.channel)
        except Exception:
            pass
