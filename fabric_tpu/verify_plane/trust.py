"""Per-identity attestation trust: standing, not just membership.

`trust_attestations` says whether verdict attestations may be honoured
AT ALL; the `attestors` allowlist says who is eligible.  This registry
adds the third axis — each attestor identity's own persistent standing.
Keyed by the same (mspid, cert sha256) binding the allowlist pins,
every identity accumulates accepted/mismatched counts, and the first
DIGEST MISMATCH permanently revokes its vouching right.

Why mismatch is the revocation signal: the attestation digest is
re-derived by the receiver from its own envelope bytes and own MSP set,
so an honest attestor can never produce a mismatch — the digest is a
pure function of bytes both sides hold.  A mismatch therefore means the
sender vouched for bytes it did not deliver (bug or compromise), and a
gateway that did it once must not keep seeding verdict caches.
Revocation only withdraws the fast path: envelopes arriving from a
revoked attestor are simply device-verified like everyone else's, so
liveness is untouched.

Standing persists across restarts when a state path is given (the
orderer keeps it under its data dir) — a revoked gateway stays revoked
until an operator deletes the state file, mirroring how the allowlist
itself is an operator decision.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, Optional, Tuple

logger = logging.getLogger("fabric_tpu.verify_plane")

Binding = Tuple[str, str]           # (mspid, cert sha256 hex)


class AttestorTrust:
    """Thread-safe per-attestor standing registry."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        # key "mspid|fp" -> {"accepted": n, "mismatched": n, "revoked": b}
        self._state: Dict[str, dict] = {}
        if path is not None and os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    data = json.load(f)
                if isinstance(data, dict):
                    self._state = {str(k): dict(v)
                                   for k, v in data.items()
                                   if isinstance(v, dict)}
            except Exception:
                logger.exception("attestor trust state unreadable: %s", path)

    @staticmethod
    def _key(binding: Binding) -> str:
        mspid, fp = binding
        return f"{mspid}|{str(fp).lower()}"

    def _entry(self, binding: Binding) -> dict:
        return self._state.setdefault(
            self._key(binding),
            {"accepted": 0, "mismatched": 0, "revoked": False})

    def allowed(self, binding: Binding) -> bool:
        """May this (allowlisted) identity still vouch?"""
        with self._lock:
            ent = self._state.get(self._key(binding))
            return ent is None or not ent.get("revoked", False)

    def note_accepted(self, binding: Binding, n: int = 1) -> None:
        with self._lock:
            self._entry(binding)["accepted"] += int(n)
            self._save()

    def note_mismatch(self, binding: Binding) -> None:
        """A vouch for bytes the sender did not deliver: revoke."""
        with self._lock:
            ent = self._entry(binding)
            ent["mismatched"] += 1
            first = not ent["revoked"]
            ent["revoked"] = True
            self._save()
        if first:
            logger.warning(
                "attestor %s|%s REVOKED: attestation digest mismatch "
                "(vouched for bytes it did not deliver)", *binding)
            try:
                from fabric_tpu.ops_plane import registry
                registry.counter(
                    "attestors_revoked_total",
                    "attestor identities revoked on digest mismatch").add(1)
            except Exception:
                pass

    def revoked_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._state.values()
                       if e.get("revoked", False))

    def snapshot(self) -> Dict[str, dict]:
        """Ops view: per-identity standing (JSON-safe copy)."""
        with self._lock:
            return {k: dict(v) for k, v in self._state.items()}

    def _save(self) -> None:
        # caller holds the lock; atomic replace so a crash mid-write
        # never leaves a torn state file
        if self.path is None:
            return
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._state, f, sort_keys=True)
            os.replace(tmp, self.path)
        except Exception:
            logger.exception("attestor trust state not persisted")
