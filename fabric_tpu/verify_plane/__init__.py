"""Verify-once plane: signed verdict cache + speculative verification.

Converts the pipeline's 3x signature work (gateway ingress, orderer
SigFilter, commit-time validator) into at most ONE device verification
per unique (identity, signature) pair per node — ROADMAP direction #2.
See cache.py for the safety model and speculative.py for the
ordering-overlap half.
"""

from .attest import accept_block_attestations, attest_block
from .cache import (CachingProvider, CoverageWindow, VerdictCache,
                    item_digest, note_device_verifications)
from .speculative import SpeculativeVerifier, derive_items
from .trust import AttestorTrust

__all__ = ["CachingProvider", "CoverageWindow", "VerdictCache",
           "item_digest", "note_device_verifications",
           "SpeculativeVerifier", "derive_items", "register_ops",
           "attest_block", "accept_block_attestations", "AttestorTrust"]


def register_ops(ops, cache: VerdictCache, spec=None, extra=None) -> None:
    """Mount GET /verify_plane on a node's ops server: the cache's live
    economics plus the speculative worker's state.  `extra()` lets the
    node add role-specific fields (e.g. the orderer's attestation-trust
    setting)."""

    def _route(path, body):
        out = cache.snapshot()
        if spec is not None:
            out["speculative_dispatched"] = spec.dispatched
        if extra is not None:
            try:
                out.update(extra())
            except Exception:
                pass
        return 200, out

    ops.register_route("GET", "/verify_plane", _route)
