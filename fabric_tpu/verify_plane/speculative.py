"""Speculative verification: fill the verdict cache DURING ordering.

The gateway's batcher hands each outgoing batch here right before it
broadcasts to the orderer.  The creator signatures are stamped
synchronously (one batched dispatch — they also back the verdict
attestations that ride beside the envelopes), and the endorsement
signatures are verified on a background worker *while the orderer is
cutting the block* (arxiv 2104.06968's validate-off-the-wire overlap).
By the time the block comes back through deliver, the commit-time
validator's dispatch degrades to cache lookups + MVCC.

Item derivation MUST be bit-identical to the committer's pass-1 walk
or the cache keys would never match at commit: envelopes go through
the same `collect_py.collect_env` record the classic tail consumes,
and items are assembled with the same P256 fast path / `verify_item`
fallback as `TxValidator._collect_tx_fast`.  MSP chain validation is
deliberately NOT consulted here — only the pure signature bit is
cached; identity validity is always judged live at the gate.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from fabric_tpu.bccsp import SCHEME_P256, VerifyItem
from fabric_tpu.committer import collect_py
from fabric_tpu.ops_plane import tracing

from .cache import VerdictCache, item_digest

# native pass-1 walker: the gateway's submit path derives items through
# the SAME extractor the committer runs, so the zero-copy ingest bytes
# never detour through a Python object tree here either.  collect_py
# stays as the no-compiler fallback and the differential oracle.
try:
    from fabric_tpu.native import load as _load_native
    _fastcollect = _load_native("_fastcollect")
except Exception:               # pragma: no cover - broken toolchain
    _fastcollect = None

logger = logging.getLogger("fabric_tpu.verify_plane")


def _raw_bytes(env):
    """Serialized envelope bytes: raw submissions pass through untouched
    (the gateway keeps wire bytes all the way here), Envelope objects
    serialize once."""
    if isinstance(env, (bytes, bytearray, memoryview)):
        return env
    return env.serialize()


def _ident_item(msps, memo: dict, ident_bytes: bytes, msg: bytes,
                sig: bytes, digest: Optional[bytes]):
    """One identity's VerifyItem, memoized per call batch.  `digest` is
    the precomputed sha256 for the P256 fast path (None falls back to
    verify_item, which hashes itself)."""
    ent = memo.get(ident_bytes, memo)
    if ent is memo:
        from fabric_tpu.msp import deserialize_from_msps
        ident = deserialize_from_msps(msps, ident_bytes)
        ent = None if ident is None else (
            ident, ident._pub_wire
            if getattr(ident, "scheme", None) == SCHEME_P256 else None)
        memo[ident_bytes] = ent
    if ent is None:
        return None
    ident, pub_wire = ent
    if pub_wire is not None and digest is not None:
        return VerifyItem(SCHEME_P256, pub_wire, sig, digest)
    return ident.verify_item(msg, sig)


def derive_items(raw_env: bytes, channel_id: str, msps,
                 memo: Optional[dict] = None) -> Tuple[List, List]:
    """(creator_items, endorsement_items) for one serialized envelope —
    the exact VerifyItems the committer will intern for it, or empty
    lists when the envelope is structurally invalid (the committer
    flags those without any crypto; nothing to speculate on)."""
    if memo is None:
        memo = {}
    if _fastcollect is not None:
        rec = _fastcollect.collect([raw_env], channel_id)[0]
    else:
        rec = collect_py.collect_env(raw_env, channel_id)
    if isinstance(rec, int) or len(rec) == 2:
        return [], []
    txtype, txid, creator, payload, pdigest, signature, actions = rec
    it = _ident_item(msps, memo, creator, payload, signature, pdigest)
    creators = [it] if it is not None else []
    endorse: List = []
    if txtype != 0:
        for cc_id, endorsed, endorsements, ns_writes, meta in actions:
            for endorser, esig, edigest in endorsements:
                it = _ident_item(msps, memo, endorser,
                                 endorsed + endorser, esig, edigest)
                if it is not None:
                    endorse.append(it)
    return creators, endorse


class SpeculativeVerifier:
    """Background verdict-cache filler for a gateway-hosting node.

    `provider_source()` returns the node's verify provider (resolved
    per dispatch so degradation/placement swaps keep working);
    `msps_source(channel_id)` returns the channel's live MSP set;
    `epoch_source(channel_id)`, when given, returns the channel's
    config sequence so entries are minted under the same per-channel
    epoch the commit gate will judge them against.
    """

    def __init__(self, cache: VerdictCache, provider_source,
                 msps_source, max_queue: int = 4096, epoch_source=None):
        self.cache = cache
        self.provider_source = provider_source
        self.msps_source = msps_source
        self.epoch_source = epoch_source
        self._queue: deque = deque(maxlen=int(max_queue))   # (cid, items)
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="verify-plane-spec", daemon=True)
        self.dispatched = 0          # items device-verified speculatively
        cache.speculative_attached = True

    def start(self) -> "SpeculativeVerifier":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)

    # -- the synchronous ingress half ------------------------------------

    def stamp(self, envs, channel_ids, spans=None) -> List[str]:
        """Verify each envelope's creator signature NOW (one batched
        dispatch for the whole gateway batch) and queue its endorsement
        set for background verification.  Returns the per-envelope
        verdict attestation digests ("" where no verdict is available)
        that ride beside the envelopes to the orderer.

        `envs` entries may be Envelope objects or raw serialized bytes;
        the gateway submit path hands wire bytes straight through so the
        native extractor works on the original frame buffer.

        `spans`, when given, are the per-envelope ordering spans; the
        ingress verify trace is linked into each so a client's request
        trace reaches the device work done on its behalf (the batcher
        thread has no ambient context, so without the link the
        speculative trace would be a disconnected root)."""
        per_env_items: List[List] = []
        memos: Dict[str, dict] = {}
        for cid in set(channel_ids):
            self._pin_epoch(cid)
        for env, cid in zip(envs, channel_ids):
            try:
                creators, endorse = derive_items(
                    _raw_bytes(env), cid, self.msps_source(cid),
                    memos.setdefault(cid, {}))
            except Exception:
                logger.debug("speculative derive failed", exc_info=True)
                creators, endorse = [], []
            per_env_items.append(creators)
            if endorse:
                with self._cv:
                    self._queue.append((cid, endorse))
                    self._cv.notify()
        # one dispatch per channel: every verdict is minted under ITS
        # channel's epoch (the scope the commit gate judges it by)
        by_cid: Dict[str, List] = {}
        for items, cid in zip(per_env_items, channel_ids):
            by_cid.setdefault(cid, []).extend(items)
        for cid, flat in by_cid.items():
            if not flat:
                continue
            tid = self._verify_batch(flat, stage="ingress", scope=cid)
            if tid and spans:
                for sp, sp_cid in zip(spans, channel_ids):
                    if sp_cid != cid:
                        continue
                    try:
                        sp.add_link(tid)
                    except Exception:
                        pass
        attests = []
        for items in per_env_items:
            if len(items) == 1 and self.cache.peek(items[0]) is True:
                attests.append(item_digest(items[0]).hex())
            else:
                attests.append("")
        return attests

    # -- the background half ----------------------------------------------

    def _pin_epoch(self, cid: str) -> None:
        """Align the cache's per-channel epoch with the channel's live
        config sequence before minting under that scope."""
        if self.epoch_source is None:
            return
        try:
            self.cache.set_epoch(self.epoch_source(cid), scope=cid)
        except Exception:
            pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    self._cv.wait(0.2)
                batches: Dict[str, List] = {}
                while self._queue:
                    cid, items = self._queue.popleft()
                    batches.setdefault(cid, []).extend(items)
            for cid, batch in batches.items():
                try:
                    self._verify_batch(batch, stage="overlap", scope=cid)
                except Exception:
                    logger.exception("speculative verify batch failed")

    def _verify_batch(self, items, stage: str, scope: str = "") -> str:
        """Dispatch the not-yet-cached subset and stamp the verdicts,
        under a span whose trace id rides into the cache entries so the
        commit-time block trace can link back to the speculative work.
        Returns that trace id ("" when nothing was dispatched)."""
        miss, _hits = self.cache.filter(items)
        if not miss:
            return ""
        sub = [items[i] for i in miss]
        span = tracing.tracer.start_span(
            "verify_plane.speculative",
            attributes={"stage": stage, "items": len(sub)})
        trace_id = span.context.trace_id if span.recording else ""
        # enter the span so the provider's bccsp.batch_verify child
        # (require_parent) attaches — this worker thread has no other
        # ambient context
        with span:
            # async-dispatch API: same result as batch_verify, but it
            # is the instrumented path (bccsp.batch_verify child span
            # with device wall time)
            out = self.provider_source().batch_verify_async(sub)()
            self.cache.store(sub, out, site="speculative",
                             trace_id=trace_id, scope=scope)
            self.dispatched += len(sub)
        return trace_id
