"""Verdict attestations riding the deliver stream (orderer -> peer).

The orderer already verified every admitted envelope's creator
signature at its SigFilter (and cached the verdict).  When
`verify_once.attest_deliver` is on, each delivered block carries a
per-envelope list of cache-key digests for the creator items whose
verdicts this orderer holds as True — the committing peer can then seed
its own verdict cache and skip re-dispatching those signatures at the
commit gate.

Trust model (same as the gateway->orderer direction, msgprocessor.py):
the digest itself is a public hash anyone can compute, so an
attestation carries NO authority of its own.  The peer only honours the
list when

  - `verify_once.trust_attestations` is on AND the transport-
    authenticated sender of the deliver stream — the orderer's
    handshake-verified identity — is pinned in the peer's configured
    `attestors` allowlist by (mspid, cert sha256); and
  - the digest re-derived from the peer's OWN envelope bytes and OWN
    MSP set is bit-identical to the attested one, so a forged or stale
    digest can never vouch for different bytes than the ones being
    committed.

Items are derived with the same `derive_items` the speculative plane
and the committer use, so an accepted attestation lands under exactly
the cache key the commit-time validator will probe.
"""

from __future__ import annotations

from typing import List, Optional

from .cache import VerdictCache, item_digest
from .speculative import derive_items


def attest_block(cache: VerdictCache, block, channel_id: str,
                 msps) -> Optional[List[Optional[str]]]:
    """Per-envelope attestation list for one block: the creator item's
    digest hex where this node's cache holds verdict True, else None.
    Returns None (send nothing) when no envelope is attestable."""
    out: List[Optional[str]] = []
    any_hit = False
    memo: dict = {}
    for raw in block.data:
        att = None
        try:
            creators, _ = derive_items(raw, channel_id, msps, memo=memo)
            if creators and cache.peek(creators[0]) is True:
                att = item_digest(creators[0]).hex()
                any_hit = True
        except Exception:
            att = None
        out.append(att)
    return out if any_hit else None


def accept_block_attestations(cache: VerdictCache, block, attests,
                              channel_id: str, msps, trust=None,
                              attestor_binding=None) -> int:
    """Seed `cache` from an AUTHORIZED sender's attestation list (the
    caller already checked the allowlist).  Every digest is re-derived
    from our own envelope bytes before acceptance.  Returns how many
    verdicts were seeded.

    `trust`/`attestor_binding` (optional) feed the sender's per-identity
    standing (trust.py): a digest that fails re-derivation is a vouch
    for bytes the sender did not deliver and revokes it; envelopes whose
    creator cannot even be derived are skipped without blame (that is a
    local MSP question, not the attestor's)."""
    if not attests:
        return 0
    n = 0
    memo: dict = {}
    for raw, att in zip(block.data, attests):
        if not att:
            continue
        try:
            creators, _ = derive_items(raw, channel_id, msps, memo=memo)
            if not creators:
                continue
            item = creators[0]
            if item_digest(item).hex() != att:
                if trust is not None and attestor_binding is not None:
                    trust.note_mismatch(attestor_binding)
                continue
            cache.put(item, True, scope=channel_id)
            n += 1
        except Exception:
            continue
    if n and trust is not None and attestor_binding is not None:
        trust.note_accepted(attestor_binding, n)
    if n:
        try:
            from .cache import _m
            _m()["attested"].add(n)
        except Exception:
            pass
    return n
