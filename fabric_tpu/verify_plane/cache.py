"""Signed verdict cache: the verify-once plane's memory.

The pipeline verifies each signature up to three times — gateway
ingress, orderer SigFilter, commit-time txvalidator — even though
`Verify` is a pure function of the VerifyItem 4-tuple (scheme, pubkey,
signature, payload): the same item always yields the same bit, no
matter which site asks.  `VerdictCache` stores that bit once per node
so every later site degrades to a host-side lookup.

Safety model (the part the differential fuzz gate enforces):

  - The cache key is a SHA-256 digest over all four VerifyItem fields
    (length-prefixed).  A signature swapped after a verdict was cached
    produces a DIFFERENT key — the stale verdict is simply never found.
  - Every entry carries an HMAC-SHA256 tag keyed by a per-node secret
    (os.urandom, never persisted) over (key ‖ verdict ‖ scope ‖ epoch).
    A poisoned entry — verdict bit flipped, tag forged, entry copied
    from another node — fails the MAC check and is dropped +
    re-verified; a MAC failure can NEVER turn into a skipped
    verification.
  - Epochs are PER SCOPE (the channel id): each entry records the
    scope it was minted under and that scope's config sequence at mint
    time, both under the MAC.  A config update (new CRL, rotated CA,
    policy change) bumps only its own channel's epoch; entries minted
    under an older sequence of that channel read as stale and force
    re-verification, while the node's other channels' entries stay
    live — one shared per-node cache never flaps between channels,
    and two channels that happen to sit at the same sequence number
    can never alias.  This is belt-and-suspenders: identity *validity*
    (MSP chain + CRL) and policy evaluation are never cached — they
    always run live at the gate — only the pure signature bit is.
  - The cache is bounded (LRU).  Eviction is silent and safe: a miss
    just means one more device verification.

Everything the plane does is observable: hits/misses/rejects{reason}/
evictions counters, per-site device-verification counters (the ≤1
device verify per unique (identity, sig) pair telemetry), and a
duplicate-verification counter that stays at zero when the plane is
doing its job.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

REASON_MAC = "mac"
REASON_STALE = "stale"


def item_digest(item) -> bytes:
    """Cache key: SHA-256 over all four VerifyItem fields.  Length
    prefixes keep (pubkey, signature, payload) splices unambiguous —
    two different items can never share a preimage."""
    scheme, pubkey, signature, payload = item
    h = hashlib.sha256()
    h.update(scheme.encode())
    h.update(b"\x00")
    for b in (pubkey, signature, payload):
        h.update(len(b).to_bytes(4, "big"))
        h.update(bytes(b))
    return h.digest()


_metrics_lock = threading.Lock()
_metrics = None


def _m():
    """Lazy singleton of the plane's ops_plane series (import cycles:
    ops_plane pulls nothing from here, but node startup order varies)."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from fabric_tpu.ops_plane import registry
            _metrics = {
                "hits": registry.counter(
                    "verify_cache_hits_total",
                    "verdict-cache lookups answered from a MAC-verified "
                    "entry"),
                "misses": registry.counter(
                    "verify_cache_misses_total",
                    "verdict-cache lookups that fell through to a device "
                    "verification"),
                "rejects": registry.counter(
                    "verify_cache_rejects_total",
                    "cached entries refused (MAC failure / stale epoch) "
                    "and re-verified"),
                "evictions": registry.counter(
                    "verify_cache_evictions_total",
                    "entries dropped by the LRU bound"),
                "device": registry.counter(
                    "verify_plane_device_verifications_total",
                    "signatures actually dispatched to the provider, by "
                    "verify site"),
                "dupes": registry.counter(
                    "verify_plane_duplicate_device_verifications_total",
                    "device verifications of an item this node had "
                    "already verified (0 = verify-once holds)"),
                "attested": registry.counter(
                    "verify_plane_attested_skips_total",
                    "orderer admissions that trusted a gateway verdict "
                    "attestation instead of re-verifying"),
            }
        return _metrics


def note_device_verifications(n: int, site: str) -> None:
    if n:
        try:
            _m()["device"].add(n, site=site)
        except Exception:
            pass


class CoverageWindow:
    """speculative_coverage_frac over a rolling block window: the
    fraction of a committed block's unique verify items whose verdicts
    were already cached when validation began (same WINDOW discipline
    as txvalidator._PipelineEconomics)."""

    WINDOW = 64

    def __init__(self):
        self._lock = threading.Lock()
        self._blocks = deque(maxlen=self.WINDOW)   # (hits, total)

    def note(self, hits: int, total: int) -> None:
        if total > 0:
            with self._lock:
                self._blocks.append((hits, total))

    def frac(self) -> float:
        with self._lock:
            hits = sum(h for h, _ in self._blocks)
            total = sum(t for _, t in self._blocks)
        return (hits / total) if total else 0.0


class VerdictCache:
    """Bounded, MAC'd, epoch-aware signature-verdict cache (one per
    node; all of the node's verify sites share it)."""

    def __init__(self, capacity: int = 65536,
                 secret: Optional[bytes] = None, owner: str = "node"):
        self.capacity = int(capacity)
        self.owner = owner
        self._secret = secret or os.urandom(32)
        self._lock = threading.Lock()
        # digest -> (mac16, verdict, scope, epoch, trace_id)
        self._data: "OrderedDict[bytes, tuple]" = OrderedDict()
        # scope (channel id) -> pinned config sequence; unregistered
        # scopes mint/judge at 0
        self._epochs: Dict[str, int] = {}
        # a speculative verifier feeds this cache (gates whether the
        # node reports speculative_coverage_frac at all)
        self.speculative_attached = False
        self.coverage = CoverageWindow()

    # -- MAC ---------------------------------------------------------------

    def _tag(self, digest: bytes, verdict: bool, scope: str,
             epoch: int) -> bytes:
        # scope last: every preceding field is fixed-width, so the
        # variable-length channel id can never splice into them
        msg = digest + (b"\x01" if verdict else b"\x00") \
            + int(epoch).to_bytes(8, "big") + scope.encode()
        return hmac.new(self._secret, msg, hashlib.sha256).digest()[:16]

    # -- epochs (per-channel config sequence) ------------------------------

    def _epoch_of(self, scope: str) -> int:
        return self._epochs.get(scope, 0)

    def set_epoch(self, epoch: int, scope: str = "") -> None:
        """Pin ONE scope (channel) to a config sequence; that scope's
        entries minted under any other sequence become stale
        (identity/policy revision bump).  Other scopes' entries are
        untouched — the cache is shared per node, the epochs are not."""
        with self._lock:
            self._epochs[scope] = int(epoch)

    def bump_epoch(self, scope: str = "") -> None:
        with self._lock:
            self._epochs[scope] = self._epochs.get(scope, 0) + 1

    # -- lookups -----------------------------------------------------------

    def get(self, item) -> Optional[bool]:
        """MAC-verified verdict for `item`, or None (miss / reject —
        either way the caller must do a full verification)."""
        v, _ = self.lookup(item)
        return v

    def lookup(self, item) -> Tuple[Optional[bool], str]:
        """(verdict-or-None, speculative trace_id) — trace_id is "" when
        the entry carries no span to link."""
        d = item_digest(item)
        reason = None
        hit = None
        with self._lock:
            ent = self._data.get(d)
            if ent is not None:
                mac, verdict, scope, epoch, trace = ent
                if not hmac.compare_digest(
                        mac, self._tag(d, verdict, scope, epoch)):
                    # poisoned entry: hard-drop, count, FULL re-verify
                    del self._data[d]
                    reason = REASON_MAC
                elif epoch != self._epoch_of(scope):
                    del self._data[d]
                    reason = REASON_STALE
                else:
                    self._data.move_to_end(d)
                    hit = (bool(verdict), trace)
        try:
            if hit is not None:
                _m()["hits"].add(1)
            else:
                if reason is not None:
                    _m()["rejects"].add(1, reason=reason)
                _m()["misses"].add(1)
        except Exception:
            pass
        return hit if hit is not None else (None, "")

    def peek(self, item) -> Optional[bool]:
        """Lookup WITHOUT touching hit/miss counters or LRU order (the
        attestation builder probes with this so economics counters keep
        describing the verify path only)."""
        d = item_digest(item)
        with self._lock:
            ent = self._data.get(d)
            if ent is None:
                return None
            mac, verdict, scope, epoch, trace = ent
            if epoch != self._epoch_of(scope) or not hmac.compare_digest(
                    mac, self._tag(d, verdict, scope, epoch)):
                return None
            return bool(verdict)

    # -- fills -------------------------------------------------------------

    def put(self, item, verdict: bool, trace_id: str = "",
            scope: str = "") -> bool:
        """Record a verdict this node just computed (or, on the orderer,
        accepted from an authorized attestation), minted under `scope`'s
        current epoch.  Returns True when the digest was already present
        with a valid entry — i.e. this was a duplicate device
        verification."""
        d = item_digest(item)
        verdict = bool(verdict)
        with self._lock:
            epoch = self._epoch_of(scope)
            prev = self._data.pop(d, None)
            dup = prev is not None and hmac.compare_digest(
                prev[0], self._tag(d, prev[1], prev[2], prev[3])) \
                and prev[3] == self._epoch_of(prev[2])
            self._data[d] = (self._tag(d, verdict, scope, epoch), verdict,
                             scope, epoch, str(trace_id))
            evicted = 0
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                evicted += 1
        if evicted:
            try:
                _m()["evictions"].add(evicted)
            except Exception:
                pass
        return dup

    def filter(self, items: Sequence) -> Tuple[List[int], List[Tuple]]:
        """Partition a dispatch batch against the cache.

        Returns (miss_positions, hits) where `hits` is a list of
        (position, verdict, trace_id).  Positions index into `items`.
        """
        miss: List[int] = []
        hits: List[Tuple[int, bool, str]] = []
        for i, it in enumerate(items):
            v, trace = self.lookup(it)
            if v is None:
                miss.append(i)
            else:
                hits.append((i, v, trace))
        return miss, hits

    def store(self, items: Sequence, verdicts, site: str,
              trace_id: str = "", scope: str = "") -> None:
        """Record a device dispatch's results and its economics: `items`
        aligned with `verdicts`, all freshly verified at `site` on
        behalf of channel `scope`."""
        dupes = 0
        for it, v in zip(items, verdicts):
            if self.put(it, bool(v), trace_id=trace_id, scope=scope):
                dupes += 1
        note_device_verifications(len(items), site)
        if dupes:
            try:
                _m()["dupes"].add(dupes, site=site)
            except Exception:
                pass

    # -- introspection ------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def snapshot(self) -> dict:
        m = None
        try:
            m = _m()
        except Exception:
            pass

        def total(name):
            try:
                return m[name].total() if m else 0
            except Exception:
                return 0

        with self._lock:
            size = len(self._data)
            epochs = dict(self._epochs)
        return {"owner": self.owner, "size": size,
                "capacity": self.capacity, "epochs": epochs,
                "speculative": self.speculative_attached,
                "coverage_frac": round(self.coverage.frac(), 4),
                "hits_total": total("hits"),
                "misses_total": total("misses"),
                "rejects_total": total("rejects"),
                "evictions_total": total("evictions")}


class CachingProvider:
    """Provider wrapper that consults/extends a VerdictCache around
    `batch_verify` — drops in wherever a Provider goes (the orderer's
    PolicyEvaluator path: SigFilter, block-signature checks), so every
    evaluate_signed_data transparently becomes verify-once."""

    def __init__(self, inner, cache: VerdictCache, site: str,
                 scope: str = ""):
        self._inner = inner
        self._cache = cache
        self._site = site
        self._scope = scope

    @property
    def name(self) -> str:
        return f"verify-once({self._inner.name})"

    def verify(self, item) -> bool:
        return bool(self.batch_verify([item])[0])

    def batch_verify(self, items):
        import numpy as np
        items = list(items)
        out = np.zeros(len(items), dtype=bool)
        miss, hits = self._cache.filter(items)
        for pos, v, _ in hits:
            out[pos] = v
        if miss:
            sub = [items[i] for i in miss]
            res = self._inner.batch_verify(sub)
            self._cache.store(sub, res, self._site, scope=self._scope)
            for i, v in zip(miss, res):
                out[i] = bool(v)
        return out

    def batch_verify_async(self, items):
        import numpy as np
        items = list(items)
        miss, hits = self._cache.filter(items)
        if not miss:
            out = np.zeros(len(items), dtype=bool)
            for pos, v, _ in hits:
                out[pos] = v
            return lambda: out
        sub = [items[i] for i in miss]
        resolve = self._inner.batch_verify_async(sub)
        cache, site, scope = self._cache, self._site, self._scope

        def resolved():
            res = resolve()
            cache.store(sub, res, site, scope=scope)
            out = np.zeros(len(items), dtype=bool)
            for pos, v, _ in hits:
                out[pos] = v
            for i, v in zip(miss, res):
                out[i] = bool(v)
            return out

        return resolved

    def __getattr__(self, name):
        return getattr(self._inner, name)
