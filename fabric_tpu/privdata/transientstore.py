"""Transient store: endorsement-time staging of private write-sets.

Reference parity: /root/reference/core/transientstore/store.go — private
simulation results are keyed by (txid, endorser-height) so the commit
coordinator can look them up when the tx lands in a block, and purged
both by txid at commit and by height retention.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class TransientStore:
    """In-memory store of txid -> list of (received_height, pvt_sets).

    pvt_sets: {(namespace, collection): {key: value|None}} — None marks a
    private delete.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_txid: Dict[str, List[Tuple[int, dict]]] = {}

    def persist(self, txid: str, height: int, pvt_sets: dict) -> None:
        with self._lock:
            self._by_txid.setdefault(txid, []).append((height, pvt_sets))

    def get(self, txid: str) -> List[dict]:
        with self._lock:
            return [sets for _, sets in self._by_txid.get(txid, [])]

    def purge_by_txids(self, txids) -> None:
        """Called post-commit for the block's transactions (store.go
        PurgeByTxids)."""
        with self._lock:
            for t in txids:
                self._by_txid.pop(t, None)

    def purge_below_height(self, height: int) -> None:
        """Retention purge (store.go PurgeBelowHeight)."""
        with self._lock:
            for txid in list(self._by_txid):
                kept = [(h, s) for h, s in self._by_txid[txid] if h >= height]
                if kept:
                    self._by_txid[txid] = kept
                else:
                    del self._by_txid[txid]

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_txid)
