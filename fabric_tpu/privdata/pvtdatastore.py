"""Committed private data store with block-to-live purging.

Reference parity: /root/reference/core/ledger/pvtdatastorage/store.go +
txmgmt/pvtstatepurgemgmt — cleartext collection state keyed by
(namespace, collection, key), an expiry index by purge-block, and purge
processing at each commit.  Durable variant: snapshot into the ledger
directory (the ledger remains the source of truth for the hashes; this
store only caches the cleartext, so losing it is recoverable by
reconciliation, not a safety issue).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class PvtDataStore:
    def __init__(self):
        self._lock = threading.Lock()
        # (ns, coll, key) -> (value, committed_block)
        self._state: Dict[Tuple[str, str, str], Tuple[bytes, int]] = {}
        # expiry_block -> list of keys to purge
        self._expiry: Dict[int, List[Tuple[str, str, str]]] = {}
        # (ns, coll, txid) -> {key: value} — the pull-service index
        self._by_txid: Dict[Tuple[str, str, str], dict] = {}
        # expiry_block -> tx-index entries to drop alongside the state keys
        self._tx_expiry: Dict[int, List[Tuple[str, str, str]]] = {}

    def commit(self, block_num: int, writes: dict, btl_by_coll: dict) -> None:
        """writes: {(ns, coll): {key: value|None}}; btl_by_coll maps
        (ns, coll) -> block_to_live (0 = forever)."""
        with self._lock:
            for (ns, coll), kvs in writes.items():
                btl = btl_by_coll.get((ns, coll), 0)
                for key, value in kvs.items():
                    sk = (ns, coll, key)
                    if value is None:
                        self._state.pop(sk, None)
                        continue
                    self._state[sk] = (value, block_num)
                    if btl:
                        self._expiry.setdefault(block_num + btl + 1, []) \
                            .append(sk)

    def process_purges(self, block_num: int) -> int:
        """Purge collections whose BTL elapsed as of block_num
        (pvtstatepurgemgmt.DeleteExpiredAndUpdateBookkeeping)."""
        purged = 0
        with self._lock:
            for expiry in [b for b in self._expiry if b <= block_num]:
                for sk in self._expiry.pop(expiry):
                    ent = self._state.get(sk)
                    # only purge if not rewritten since (a newer write has
                    # its own expiry entry)
                    if ent is not None and ent[1] + 1 <= expiry:
                        del self._state[sk]
                        purged += 1
            for expiry in [b for b in self._tx_expiry if b <= block_num]:
                for tk in self._tx_expiry.pop(expiry):
                    self._by_txid.pop(tk, None)
        return purged

    def record_tx(self, txid: str, namespace: str, collection: str,
                  kv: dict, block_num: int = 0, btl: int = 0) -> None:
        """Index a committed tx's collection cleartext by txid — the
        lookup surface the privdata pull service answers from
        (pvtdataprovider.go serves by txid+collection).  BTL applies to
        this index exactly like the keyed state: expired private data
        must stop being servable."""
        with self._lock:
            tk = (namespace, collection, txid)
            self._by_txid.setdefault(tk, {}).update(kv)
            if btl:
                self._tx_expiry.setdefault(block_num + btl + 1, []).append(tk)

    def get_tx_set(self, namespace: str, collection: str,
                   txid: str) -> Optional[dict]:
        with self._lock:
            got = self._by_txid.get((namespace, collection, txid))
            return dict(got) if got is not None else None

    def get(self, namespace: str, collection: str, key: str) -> Optional[bytes]:
        with self._lock:
            ent = self._state.get((namespace, collection, key))
            return ent[0] if ent else None

    def has_collection(self, namespace: str, collection: str) -> bool:
        with self._lock:
            return any(ns == namespace and c == collection
                       for (ns, c, _) in self._state)
