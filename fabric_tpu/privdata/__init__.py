"""Private data pillar: collections, transient + pvtdata stores, coordinator.

Re-design of the reference's private-data capability (VERDICT.md missing
#2): /root/reference/core/transientstore/store.go,
core/ledger/pvtdatastorage/store.go, gossip/privdata/coordinator.go,
gossip/privdata/pvtdataprovider.go, reconcile.go.

Model (same on-chain/off-chain split as the reference):
  - a chaincode writes to a named COLLECTION: the public rwset carries
    only hash(key) -> hash(value) writes under namespace "ns$collection";
    the cleartext keys/values travel off-chain,
  - at endorsement the cleartext is staged in the endorser's
    TransientStore and distributed to collection member peers over the
    authenticated comm plane,
  - at commit the Coordinator matches each valid tx's private write-set
    hashes against transient/received data (pulling from peers when
    missing), commits cleartext to the PvtDataStore, and purges expired
    collections by block-to-live (BTL),
  - non-member peers commit the block with hashes only; a later
    reconciliation pull can backfill if the peer joins the collection.
"""

from .collection import CollectionConfig, CollectionRegistry, pvt_namespace
from .transientstore import TransientStore
from .pvtdatastore import PvtDataStore
from .coordinator import Coordinator, MissingPvtData

__all__ = [
    "CollectionConfig", "CollectionRegistry", "pvt_namespace",
    "TransientStore", "PvtDataStore", "Coordinator", "MissingPvtData",
]
