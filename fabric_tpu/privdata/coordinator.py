"""Commit-time coordinator: match, fetch, commit, purge private data.

Reference parity: /root/reference/gossip/privdata/coordinator.go
StoreBlock — before/with the block commit, assemble each valid tx's
private write-sets: transient store first, then pull from collection
member peers (pvtdataprovider.go / fetcher), verify cleartext against
the on-chain hashes, commit to the pvt store, process BTL purges, and
purge the transient store.  Missing collections are recorded for
reconciliation (reconcile.go), which retries the pull later.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from fabric_tpu.protocol import Envelope
from fabric_tpu.protocol.txflags import TxFlags, ValidationCode
from fabric_tpu.protocol.types import META_TXFLAGS, TxRwSet

from .collection import PVT_SEP, CollectionRegistry, hash_key, hash_value
from .pvtdatastore import PvtDataStore
from .transientstore import TransientStore

logger = logging.getLogger("fabric_tpu.privdata")


@dataclass
class MissingPvtData:
    block_num: int
    txid: str
    namespace: str
    collection: str
    # on-chain hashed writes (hashed key -> hashed value, None = delete):
    # reconciliation MUST re-verify fetched cleartext against these — a
    # malicious peer answering the pull must not be able to poison state
    # (reference: gossip/privdata/reconcile.go verifies vs the block).
    expected: Dict[str, object] = field(default_factory=dict)


class Coordinator:
    """Wraps a Committer with private-data assembly.

    fetch: optional callable (txid, namespace, collection) -> dict|None —
    the network pull from member peers (reconciliation transport).
    mspid: this peer's org (collection membership decisions).
    """

    def __init__(self, committer, registry: CollectionRegistry,
                 transient: TransientStore, pvt_store: PvtDataStore,
                 mspid: str, fetch: Optional[Callable] = None):
        self.committer = committer
        self.registry = registry
        self.transient = transient
        self.pvt_store = pvt_store
        self.mspid = mspid
        self.fetch = fetch
        self.missing: List[MissingPvtData] = []

    @property
    def height(self) -> int:
        return self.committer.height

    @property
    def validator(self):
        return self.committer.validator

    @property
    def ledger(self):
        return self.committer.ledger

    # -- the StoreBlock composition -----------------------------------------

    def store_block(self, block):
        result = self.committer.store_block(block)
        flags = TxFlags.from_bytes(block.metadata.items[META_TXFLAGS])
        writes: Dict[Tuple[str, str], Dict[str, object]] = {}
        btl: Dict[Tuple[str, str], int] = {}
        txids = []
        for tx_num, env_bytes in enumerate(block.data):
            if not flags.is_valid(tx_num):
                continue
            try:
                env = Envelope.deserialize(env_bytes)
                txid = env.header().channel_header.txid
                rwset = _tx_rwset(env)
            except Exception:
                continue
            txids.append(txid)
            if rwset is None:
                continue
            for ns_set in rwset.ns_rwsets:
                if PVT_SEP not in ns_set.namespace or not ns_set.writes:
                    continue
                ns, coll = ns_set.namespace.split(PVT_SEP, 1)
                cfg = self.registry.get(ns, coll)
                if cfg is None or not cfg.is_member(self.mspid):
                    continue   # not our collection: hashes only
                expected = {w.key: (None if w.is_delete else w.value)
                            for w in ns_set.writes}
                clear = self._resolve(txid, ns, coll, expected)
                if clear is None:
                    self.missing.append(MissingPvtData(
                        block.header.number, txid, ns, coll, dict(expected)))
                    continue
                writes.setdefault((ns, coll), {}).update(clear)
                self.pvt_store.record_tx(txid, ns, coll, clear,
                                         block_num=block.header.number,
                                         btl=cfg.block_to_live)
                btl[(ns, coll)] = cfg.block_to_live
        if writes:
            self.pvt_store.commit(block.header.number, writes, btl)
        self.pvt_store.process_purges(block.header.number)
        self.transient.purge_by_txids(txids)
        return result

    def _resolve(self, txid: str, ns: str, coll: str,
                 expected: Dict[str, object]) -> Optional[dict]:
        """Find cleartext matching the on-chain hashes: transient store,
        then the network fetcher."""
        candidates = []
        for sets in self.transient.get(txid):
            if (ns, coll) in sets:
                candidates.append(sets[(ns, coll)])
        if self.fetch is not None:
            fetched = self.fetch(txid, ns, coll)
            if fetched:
                candidates.append(fetched)
        for cand in candidates:
            out = _match_hashes(cand, expected)
            if out is not None:
                return out
        return None

    # -- reconciliation ------------------------------------------------------

    def reconcile(self) -> int:
        """Retry missing collections via the fetcher (reconcile.go).
        Returns how many were recovered."""
        if self.fetch is None:
            return 0
        recovered = 0
        still = []
        for m in self.missing:
            fetched = self.fetch(m.txid, m.namespace, m.collection)
            verified = _match_hashes(fetched, m.expected) if fetched else None
            if verified is not None:
                cfg = self.registry.get(m.namespace, m.collection)
                self.pvt_store.commit(
                    m.block_num, {(m.namespace, m.collection): verified},
                    {(m.namespace, m.collection):
                     cfg.block_to_live if cfg else 0})
                self.pvt_store.record_tx(
                    m.txid, m.namespace, m.collection, verified,
                    block_num=m.block_num,
                    btl=cfg.block_to_live if cfg else 0)
                recovered += 1
            else:
                if fetched:
                    logger.warning(
                        "reconcile: fetched pvtdata for %s %s/%s failed "
                        "hash verification; discarding", m.txid,
                        m.namespace, m.collection)
                still.append(m)
        self.missing = still
        return recovered


def _tx_rwset(env: Envelope) -> Optional[TxRwSet]:
    try:
        from fabric_tpu.protocol.types import Transaction
        tx = Transaction.from_dict(env.payload_dict()["data"])
        return tx.actions[0].action.rwset if tx.actions else None
    except Exception:
        return None


def _match_hashes(cleartext: dict, expected: Dict[str, object]) -> Optional[dict]:
    """Check a candidate cleartext set against the on-chain hashed writes.
    Accepts the candidate only if EVERY hashed write is explained."""
    out = {}
    for hk, hv in expected.items():
        found = None
        for key, value in cleartext.items():
            if hash_key(key) == hk:
                found = (key, value)
                break
        if found is None:
            return None
        key, value = found
        if hv is None:           # delete
            if value is not None:
                return None
        else:
            if value is None or hash_value(value) != hv:
                return None
        out[key] = value
    return out
