"""Collection configuration + the hashed-namespace convention.

Reference parity: the collection config package (core/common/privdata,
collection criteria in gossip/privdata) reduced to the fields this
framework's planes consume: membership policy (org list), BTL, and the
required/max peer counts that drive distribution.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

PVT_SEP = "$"


def pvt_namespace(namespace: str, collection: str) -> str:
    """Public-ledger namespace carrying a collection's write HASHES."""
    return f"{namespace}{PVT_SEP}{collection}"


def hash_key(key: str) -> str:
    return hashlib.sha256(key.encode()).hexdigest()


def hash_value(value: bytes) -> bytes:
    return hashlib.sha256(value).digest()


@dataclass(frozen=True)
class CollectionConfig:
    """StaticCollectionConfig equivalent."""
    name: str
    member_orgs: Tuple[str, ...]
    block_to_live: int = 0          # 0 = never purge
    required_peer_count: int = 0    # distribution ack threshold
    maximum_peer_count: int = 2

    def is_member(self, mspid: str) -> bool:
        return mspid in self.member_orgs

    def to_dict(self) -> dict:
        return {"name": self.name, "member_orgs": list(self.member_orgs),
                "block_to_live": self.block_to_live,
                "required_peer_count": self.required_peer_count,
                "maximum_peer_count": self.maximum_peer_count}

    @staticmethod
    def from_dict(d: dict) -> "CollectionConfig":
        return CollectionConfig(d["name"], tuple(d["member_orgs"]),
                                d.get("block_to_live", 0),
                                d.get("required_peer_count", 0),
                                d.get("maximum_peer_count", 2))


class CollectionRegistry:
    """(namespace, collection) -> CollectionConfig; committed with the
    chaincode definition in the reference (_lifecycle), registered on the
    lifecycle object here."""

    def __init__(self):
        self._configs: Dict[Tuple[str, str], CollectionConfig] = {}

    def define(self, namespace: str, cfg: CollectionConfig) -> None:
        self._configs[(namespace, cfg.name)] = cfg

    def get(self, namespace: str, collection: str) -> Optional[CollectionConfig]:
        return self._configs.get((namespace, collection))

    def for_namespace(self, namespace: str) -> List[CollectionConfig]:
        return [c for (ns, _), c in self._configs.items() if ns == namespace]
