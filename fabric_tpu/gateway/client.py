"""GatewayClient: the thin client SDK over the gateway verbs.

What the Fabric v2.4 client libraries (fabric-gateway) became once the
gateway absorbed the transaction lifecycle: the client builds and signs
the proposal and the final envelope (signing NEVER delegates to the
gateway — the peer must not hold client keys), while endorsement
fan-out, ordering, retry, and commit tracking all happen server-side.

    gw = GatewayClient(("127.0.0.1", 7051), signer, msps, channel_id="ch")
    value = gw.evaluate("assets", "read", [b"a1"])
    code, block = gw.submit_transaction("assets", "create",
                                        [b"a1", b"owner", b"100"])
    gw.close()
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from fabric_tpu.comm import RpcError, connect
from fabric_tpu.comm.rpc import RpcClosed
from fabric_tpu.ops_plane import tracing
from fabric_tpu.endorser.proposal import (
    ProposalResponse,
    SignedProposal,
    assemble_transaction,
    signed_proposal,
)
from fabric_tpu.protocol import Endorsement, Envelope
from fabric_tpu.protocol.txflags import ValidationCode

logger = logging.getLogger("fabric_tpu.gateway")


class GatewayError(Exception):
    """A gateway verb failed (endorsement, ordering, or commit)."""

    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


class GatewayShedError(GatewayError):
    """The gateway's admission controller shed the request: a TYPED,
    RETRYABLE overload verdict (not a failure of the request itself),
    carrying the shed mode and the server's retry-after hint.  Distinct
    from queue-full backpressure, which surfaces as a plain RpcError —
    shed means "the node is overloaded, stay away for a while"."""

    def __init__(self, message: str, mode: str = "",
                 retry_after_ms: int = 0, severity: float = 0.0):
        super().__init__(message, status=429)
        self.mode = mode
        self.retry_after_ms = int(retry_after_ms)
        self.severity = float(severity)


class GatewayClient:
    """Client handle onto one peer's gateway service.

    Thread-safe: concurrent submit_transaction calls share the single
    authenticated connection (the RPC plane multiplexes by request id,
    but calls here serialize on a lock for the blocking-reply pattern).
    """

    def __init__(self, peer_addr: Tuple[str, int], signer, msps,
                 channel_id: Optional[str] = None,
                 timeout: float = 5.0, call_timeout: float = 30.0,
                 shed_retry_max: int = 2,
                 shed_backoff_cap_s: float = 2.0, seed: int = 0):
        self.peer_addr = tuple(peer_addr)
        self.signer = signer
        self.msps = msps
        self.channel_id = channel_id
        self._timeout = timeout
        self._call_timeout = call_timeout
        # shed handling: retries honor the server's retry-after hint
        # with capped jittered backoff (NEVER an immediate retry — that
        # just re-offers the load the node asked us to withhold)
        self.shed_retry_max = int(shed_retry_max)
        self.shed_backoff_cap_s = float(shed_backoff_cap_s)
        self._rand = random.Random(seed)
        self._lock = threading.Lock()
        self._conn = None
        self._stats_lock = threading.Lock()
        self._stats = {"shed_seen": 0, "shed_retries": 0,
                       "shed_exhausted": 0}

    # plumbing ----------------------------------------------------------

    def warm(self) -> None:
        """Dial the connection NOW instead of on the first call — pool
        warm-up must actually establish the socket, or "warmed" clients
        still ramp connections (and handshake latency) into the first
        measured requests."""
        with self._lock:
            if self._conn is None:
                self._conn = connect(self.peer_addr, self.signer,
                                     self.msps, timeout=self._timeout)

    def _call(self, verb: str, body: dict,
              timeout: Optional[float] = None) -> dict:
        if timeout is None:
            timeout = self._call_timeout
        # hold the lock only around dial/teardown: RpcConnection
        # multiplexes concurrent requests over one channel, so calls
        # themselves must overlap — a population of simulated clients
        # on one socket otherwise serializes into a closed loop
        with self._lock:
            conn = self._conn
            if conn is None:
                conn = connect(self.peer_addr, self.signer, self.msps,
                               timeout=self._timeout)
                self._conn = conn
        try:
            return conn.call(verb, body, timeout=timeout)
        except RpcClosed:
            # the peer went away (crash, drain+restart): drop the dead
            # channel so the NEXT call redials — a client pinned to a
            # rolling-restarted peer must recover when it returns
            with self._lock:
                if self._conn is conn:
                    self._conn = None
            try:
                conn.close()
            except Exception:
                pass
            raise
        except RpcError:
            raise
        except Exception:
            # connection damaged: drop it so the next call redials
            with self._lock:
                if self._conn is conn:
                    self._conn = None
            try:
                conn.close()
            except Exception:
                pass
            raise

    def _shed_guard(self, out: dict, what: str) -> None:
        """Raise the typed shed error when a verb answered with an
        admission shed verdict (status 429 + shed marker)."""
        if not out.get("shed"):
            return
        with self._stats_lock:
            self._stats["shed_seen"] += 1
        raise GatewayShedError(
            f"{what} shed by gateway admission "
            f"({out.get('mode', '?')}): retry after "
            f"{out.get('retry_after_ms', 0)}ms",
            mode=str(out.get("mode", "")),
            retry_after_ms=int(out.get("retry_after_ms", 0)),
            severity=int(out.get("severity_milli", 0)) / 1000.0)

    def _with_shed_retry(self, fn: Callable[[], dict]) -> dict:
        """Run a verb, honoring shed verdicts with capped jittered
        backoff seeded per client: delay = min(hint, cap) * U[0.5, 1.5)
        * 2^(attempt-1), capped — so a shed population de-synchronizes
        instead of re-stampeding in lockstep at the hint boundary."""
        attempt = 0
        while True:
            try:
                return fn()
            except GatewayShedError as exc:
                if attempt >= self.shed_retry_max:
                    with self._stats_lock:
                        self._stats["shed_exhausted"] += 1
                    raise
                attempt += 1
                with self._stats_lock:
                    self._stats["shed_retries"] += 1
                base = min(max(exc.retry_after_ms, 50) / 1000.0,
                           self.shed_backoff_cap_s)
                delay = base * (0.5 + self._rand.random()) \
                    * (2 ** (attempt - 1))
                time.sleep(min(delay, self.shed_backoff_cap_s))

    def stats(self) -> dict:
        """Client-perceived shed counters (the workload runner's view of
        admission behaviour from outside the node)."""
        with self._stats_lock:
            return dict(self._stats)

    def _channel(self, channel: Optional[str]) -> str:
        ch = channel or self.channel_id
        if not ch:
            raise GatewayError("no channel: pass channel= or set channel_id")
        return ch

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None

    # verbs -------------------------------------------------------------

    def evaluate(self, chaincode_id: str, fn: str, args: Sequence[bytes],
                 channel: Optional[str] = None) -> bytes:
        """Query: endorse on the gateway peer only, return the payload."""
        ch = self._channel(channel)
        sp = signed_proposal(ch, chaincode_id, fn, args, self.signer)

        def _once() -> dict:
            out = self._call("gateway.evaluate",
                             {"channel": ch, "proposal": sp.proposal_bytes,
                              "signature": sp.signature})
            self._shed_guard(out, "evaluate")
            return out

        out = self._with_shed_retry(_once)
        if out.get("status") != 200:
            raise GatewayError(
                f"evaluate failed: {out.get('message', '')}",
                status=int(out.get("status", 0)))
        return out["payload"]

    def endorse(self, chaincode_id: str, fn: str, args: Sequence[bytes],
                channel: Optional[str] = None
                ) -> Tuple[SignedProposal, List[ProposalResponse]]:
        """Collect endorsements via the gateway; returns the signed
        proposal plus responses ready for assemble_transaction."""
        ch = self._channel(channel)
        sp = signed_proposal(ch, chaincode_id, fn, args, self.signer)

        def _once() -> dict:
            out = self._call("gateway.endorse",
                             {"channel": ch, "proposal": sp.proposal_bytes,
                              "signature": sp.signature})
            self._shed_guard(out, "endorse")
            return out

        out = self._with_shed_retry(_once)
        if out.get("status") != 200 or not out.get("endorsements"):
            raise GatewayError(
                f"endorse failed: {out.get('message', '')}",
                status=int(out.get("status", 0)))
        responses = [
            ProposalResponse(200, "", out["payload"],
                             Endorsement(e["endorser"], e["signature"]))
            for e in out["endorsements"]]
        return sp, responses

    def submit_envelope(self, env: Envelope,
                        timeout_s: Optional[float] = None) -> dict:
        """Hand an assembled envelope to the gateway's admission queue;
        returns {"txid", "status", "info", "deduped"} once ordered."""
        body = {"envelope": env.serialize()}
        if timeout_s is not None:
            # serde is float-free by design: timeouts ride as int ms
            body["timeout_ms"] = int(timeout_s * 1000)

        def _once() -> dict:
            out = self._call("gateway.submit", body,
                             timeout=max((timeout_s or 20.0) + 10.0,
                                         self._call_timeout))
            self._shed_guard(out, "submit")
            return out

        out = self._with_shed_retry(_once)
        if out.get("status") != 200:
            raise GatewayError(
                f"submit failed ({out.get('status')}): "
                f"{out.get('info', '')}", status=int(out.get("status", 0)))
        return out

    def commit_status(self, txid: str, channel: Optional[str] = None,
                      timeout_s: float = 15.0) -> Tuple[int, int]:
        """Block until the txid commits; returns (validation code, block
        number).  Raises GatewayError if the wait times out."""
        ch = self._channel(channel)
        out = self._call("gateway.commit_status",
                         {"channel": ch, "txid": txid,
                          "timeout_ms": int(timeout_s * 1000)},
                         timeout=timeout_s + 10.0)
        if not out.get("found"):
            raise GatewayError(f"txid {txid} not committed within "
                               f"{timeout_s}s")
        return int(out["code"]), int(out["block"])

    # the full lifecycle -------------------------------------------------

    def submit_transaction(self, chaincode_id: str, fn: str,
                           args: Sequence[bytes],
                           channel: Optional[str] = None,
                           commit_timeout_s: float = 15.0
                           ) -> Tuple[int, int]:
        """endorse -> assemble -> submit -> wait for commit.

        Returns (validation code, block number); raises GatewayError if
        the tx commits with a non-VALID code.
        """
        ch = self._channel(channel)
        # one root span per lifecycle: endorse/submit/commit_status all
        # propagate this context in their RPC frames, so the whole tx
        # lands in ONE trace in the peer's flight recorder
        with tracing.tracer.start_span(
                "client.tx", attributes={"channel": ch,
                                         "chaincode": chaincode_id,
                                         "fn": fn}) as span:
            sp, responses = self.endorse(chaincode_id, fn, args, channel=ch)
            env = assemble_transaction(sp, responses, self.signer)
            txid = env.header().channel_header.txid
            span.set_attribute("txid", txid)
            # the commit budget bounds the ordering ack too: on a slow
            # verify provider the default in-flight window is too short
            self.submit_envelope(env, timeout_s=commit_timeout_s)
            code, block = self.commit_status(txid, channel=ch,
                                             timeout_s=commit_timeout_s)
        if code != int(ValidationCode.VALID):
            try:
                name = ValidationCode(code).name
            except ValueError:
                name = str(code)
            raise GatewayError(
                f"tx {txid} invalidated at commit: {name}", status=code)
        return code, block
