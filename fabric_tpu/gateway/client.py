"""GatewayClient: the thin client SDK over the gateway verbs.

What the Fabric v2.4 client libraries (fabric-gateway) became once the
gateway absorbed the transaction lifecycle: the client builds and signs
the proposal and the final envelope (signing NEVER delegates to the
gateway — the peer must not hold client keys), while endorsement
fan-out, ordering, retry, and commit tracking all happen server-side.

    gw = GatewayClient(("127.0.0.1", 7051), signer, msps, channel_id="ch")
    value = gw.evaluate("assets", "read", [b"a1"])
    code, block = gw.submit_transaction("assets", "create",
                                        [b"a1", b"owner", b"100"])
    gw.close()
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Sequence, Tuple

from fabric_tpu.comm import RpcError, connect
from fabric_tpu.ops_plane import tracing
from fabric_tpu.endorser.proposal import (
    ProposalResponse,
    SignedProposal,
    assemble_transaction,
    signed_proposal,
)
from fabric_tpu.protocol import Endorsement, Envelope
from fabric_tpu.protocol.txflags import ValidationCode

logger = logging.getLogger("fabric_tpu.gateway")


class GatewayError(Exception):
    """A gateway verb failed (endorsement, ordering, or commit)."""

    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


class GatewayClient:
    """Client handle onto one peer's gateway service.

    Thread-safe: concurrent submit_transaction calls share the single
    authenticated connection (the RPC plane multiplexes by request id,
    but calls here serialize on a lock for the blocking-reply pattern).
    """

    def __init__(self, peer_addr: Tuple[str, int], signer, msps,
                 channel_id: Optional[str] = None,
                 timeout: float = 5.0, call_timeout: float = 30.0):
        self.peer_addr = tuple(peer_addr)
        self.signer = signer
        self.msps = msps
        self.channel_id = channel_id
        self._timeout = timeout
        self._call_timeout = call_timeout
        self._lock = threading.Lock()
        self._conn = None

    # plumbing ----------------------------------------------------------

    def _call(self, verb: str, body: dict,
              timeout: Optional[float] = None) -> dict:
        if timeout is None:
            timeout = self._call_timeout
        with self._lock:
            if self._conn is None:
                self._conn = connect(self.peer_addr, self.signer, self.msps,
                                     timeout=self._timeout)
            try:
                return self._conn.call(verb, body, timeout=timeout)
            except RpcError:
                raise
            except Exception:
                # connection damaged: drop it so the next call redials
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None
                raise

    def _channel(self, channel: Optional[str]) -> str:
        ch = channel or self.channel_id
        if not ch:
            raise GatewayError("no channel: pass channel= or set channel_id")
        return ch

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None

    # verbs -------------------------------------------------------------

    def evaluate(self, chaincode_id: str, fn: str, args: Sequence[bytes],
                 channel: Optional[str] = None) -> bytes:
        """Query: endorse on the gateway peer only, return the payload."""
        ch = self._channel(channel)
        sp = signed_proposal(ch, chaincode_id, fn, args, self.signer)
        out = self._call("gateway.evaluate",
                         {"channel": ch, "proposal": sp.proposal_bytes,
                          "signature": sp.signature})
        if out.get("status") != 200:
            raise GatewayError(
                f"evaluate failed: {out.get('message', '')}",
                status=int(out.get("status", 0)))
        return out["payload"]

    def endorse(self, chaincode_id: str, fn: str, args: Sequence[bytes],
                channel: Optional[str] = None
                ) -> Tuple[SignedProposal, List[ProposalResponse]]:
        """Collect endorsements via the gateway; returns the signed
        proposal plus responses ready for assemble_transaction."""
        ch = self._channel(channel)
        sp = signed_proposal(ch, chaincode_id, fn, args, self.signer)
        out = self._call("gateway.endorse",
                         {"channel": ch, "proposal": sp.proposal_bytes,
                          "signature": sp.signature})
        if out.get("status") != 200 or not out.get("endorsements"):
            raise GatewayError(
                f"endorse failed: {out.get('message', '')}",
                status=int(out.get("status", 0)))
        responses = [
            ProposalResponse(200, "", out["payload"],
                             Endorsement(e["endorser"], e["signature"]))
            for e in out["endorsements"]]
        return sp, responses

    def submit_envelope(self, env: Envelope,
                        timeout_s: Optional[float] = None) -> dict:
        """Hand an assembled envelope to the gateway's admission queue;
        returns {"txid", "status", "info", "deduped"} once ordered."""
        body = {"envelope": env.serialize()}
        if timeout_s is not None:
            # serde is float-free by design: timeouts ride as int ms
            body["timeout_ms"] = int(timeout_s * 1000)
        out = self._call("gateway.submit", body,
                         timeout=max((timeout_s or 20.0) + 10.0,
                                     self._call_timeout))
        if out.get("status") != 200:
            raise GatewayError(
                f"submit failed ({out.get('status')}): "
                f"{out.get('info', '')}", status=int(out.get("status", 0)))
        return out

    def commit_status(self, txid: str, channel: Optional[str] = None,
                      timeout_s: float = 15.0) -> Tuple[int, int]:
        """Block until the txid commits; returns (validation code, block
        number).  Raises GatewayError if the wait times out."""
        ch = self._channel(channel)
        out = self._call("gateway.commit_status",
                         {"channel": ch, "txid": txid,
                          "timeout_ms": int(timeout_s * 1000)},
                         timeout=timeout_s + 10.0)
        if not out.get("found"):
            raise GatewayError(f"txid {txid} not committed within "
                               f"{timeout_s}s")
        return int(out["code"]), int(out["block"])

    # the full lifecycle -------------------------------------------------

    def submit_transaction(self, chaincode_id: str, fn: str,
                           args: Sequence[bytes],
                           channel: Optional[str] = None,
                           commit_timeout_s: float = 15.0
                           ) -> Tuple[int, int]:
        """endorse -> assemble -> submit -> wait for commit.

        Returns (validation code, block number); raises GatewayError if
        the tx commits with a non-VALID code.
        """
        ch = self._channel(channel)
        # one root span per lifecycle: endorse/submit/commit_status all
        # propagate this context in their RPC frames, so the whole tx
        # lands in ONE trace in the peer's flight recorder
        with tracing.tracer.start_span(
                "client.tx", attributes={"channel": ch,
                                         "chaincode": chaincode_id,
                                         "fn": fn}) as span:
            sp, responses = self.endorse(chaincode_id, fn, args, channel=ch)
            env = assemble_transaction(sp, responses, self.signer)
            txid = env.header().channel_header.txid
            span.set_attribute("txid", txid)
            # the commit budget bounds the ordering ack too: on a slow
            # verify provider the default in-flight window is too short
            self.submit_envelope(env, timeout_s=commit_timeout_s)
            code, block = self.commit_status(txid, channel=ch,
                                             timeout_s=commit_timeout_s)
        if code != int(ValidationCode.VALID):
            try:
                name = ValidationCode(code).name
            except ValueError:
                name = str(code)
            raise GatewayError(
                f"tx {txid} invalidated at commit: {name}", status=code)
        return code, block
