"""GatewayService: peer-hosted client verbs + bounded admission queue.

The receive half of the gateway.  Submissions land in a bounded queue
(full queue -> immediate backpressure error, never unbounded buffering)
and a single batcher thread coalesces them — up to `max_batch`
envelopes or `linger_s` of accumulation — into one orderer
`broadcast_batch` call, sized to feed the TPU verify lane with big
blocks instead of trickling singleton envelopes at the consenter.
A txid dedup window makes submission idempotent: a duplicate of an
in-flight txid attaches to the existing entry, a duplicate of a
recently-finished one replays its recorded outcome.

Every verb records per-verb latency; the queue depth gauge, batch-size
histogram, retry/dedup/backpressure counters land in the same
ops_plane registry the /metrics endpoint exposes.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from fabric_tpu.comm import connect
from fabric_tpu.endorser.proposal import SignedProposal
from fabric_tpu.gateway import admission as _admission
from fabric_tpu.gateway.broadcaster import BatchBroadcaster
from fabric_tpu.gateway.notifier import CommitNotifier
from fabric_tpu.ops_plane import registry, tracing
from fabric_tpu.ops_plane.logging import jlog
from fabric_tpu.protocol import Envelope
from fabric_tpu.protocol import wire
from fabric_tpu.protocol.txflags import ValidationCode

logger = logging.getLogger("fabric_tpu.gateway")


class _Pending:
    """One admitted submission.  `raw` keeps the client's wire bytes as
    received — the batcher rebroadcasts those exact bytes and the
    speculative verifier's native extractor walks them in place, so the
    covered submit path never materializes an Envelope object."""

    __slots__ = ("raw", "txid", "channel_id", "event", "status", "info",
                 "ctx", "span_queue", "t_in")

    def __init__(self, raw: bytes, txid: str, channel_id: str):
        self.raw = raw
        self.txid = txid
        self.channel_id = channel_id
        self.event = threading.Event()
        self.status = 0
        self.info = ""
        self.t_in = time.monotonic()   # gateway-sojourn start (admission)
        # tracing: the submitter's span context + its queue-wait span,
        # started on the submit thread and ended by the batcher thread
        self.ctx = tracing.tracer.current_context()
        self.span_queue = tracing.tracer.start_span(
            "gateway.queue_wait", require_parent=True,
            attributes={"txid": txid})


class GatewayService:
    """Hosts the four gateway verbs on a PeerNode's RPC server."""

    def __init__(self, node, cfg: Optional[dict] = None):
        cfg = dict(cfg or {})
        self.node = node
        self.max_queue = int(cfg.get("max_queue", 256))
        self.max_batch = int(cfg.get("max_batch", 64))
        self.linger_s = float(cfg.get("linger_s", 0.005))
        self.recent_window = int(cfg.get("dedup_window", 8192))
        self.submit_timeout_s = float(cfg.get("submit_timeout_s", 20.0))
        self.broadcaster = BatchBroadcaster(
            node.orderers, node.signer, node.msps,
            backoff_base_s=float(cfg.get("backoff_base_s", 0.05)),
            backoff_max_s=float(cfg.get("backoff_max_s", 2.0)),
            deadline_s=float(cfg.get("broadcast_deadline_s", 10.0)),
            rpc_timeout_s=float(cfg.get("rpc_timeout_s", 10.0)))
        # endorse fan-out budgets: a dropped org endorsement silently
        # weakens the policy sig-set and only surfaces at COMMIT time
        # (ENDORSEMENT_POLICY_FAILURE), so on slow verify providers these
        # must cover the authenticated handshake, not a bare TCP dial
        self.fan_dial_timeout_s = float(cfg.get(
            "fan_dial_timeout_s", max(3.0, float(cfg.get("rpc_timeout_s",
                                                         3.0)))))
        self.fan_call_timeout_s = float(cfg.get(
            "fan_call_timeout_s", max(10.0, self.fan_dial_timeout_s)))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # serving -> draining -> drained: a draining gateway refuses NEW
        # admissions (clients retry another peer) while the batcher
        # keeps flushing what was already admitted — overload shedding
        # is probabilistic and retryable, drain is absolute and orderly
        self.lifecycle = "serving"
        self._queue: List[_Pending] = []
        self._inflight: Dict[str, _Pending] = {}
        # txid -> (status, info) of finished submissions (dedup window)
        self._recent: "OrderedDict[str, tuple]" = OrderedDict()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._batch_loop, name="gateway-batcher", daemon=True)
        # metrics (ops_plane singleton registry -> /metrics exposition)
        self._m_latency = registry.histogram(
            "gateway_request_duration_seconds", "gateway verb latency")
        self._m_requests = registry.counter(
            "gateway_requests_total", "gateway verb calls")
        self._m_depth = registry.gauge(
            "gateway_queue_depth", "admission queue occupancy")
        self._m_batch = registry.histogram(
            "gateway_batch_size", "envelopes per orderer broadcast",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, float("inf")))
        self._m_dedup = registry.counter(
            "gateway_dedup_total", "duplicate txid submissions absorbed")
        self._m_backpressure = registry.counter(
            "gateway_backpressure_total",
            "submissions rejected on a full admission queue")
        # SLO-driven admission control: typed shed verdicts BEFORE the
        # queue-full cliff.  The burn source reads the node's
        # SloEvaluator lazily (peer wiring creates slo after the
        # gateway); queue occupancy reads the list length lock-free
        # (len() is atomic; the controller EWMAs it).
        self.admission = _admission.AdmissionController(
            cfg.get("admission"),
            burn_source=self._admission_burn,
            queue_source=lambda: len(self._queue) / float(
                max(1, self.max_queue)))
        # commit notifiers attach per channel as channels are touched
        for ch in getattr(node, "channels", {}).values():
            self._notifier(ch)

    # lifecycle ---------------------------------------------------------

    def register(self, rpc) -> None:
        rpc.serve("gateway.evaluate", self._rpc_evaluate)
        rpc.serve("gateway.endorse", self._rpc_endorse)
        rpc.serve("gateway.submit", self._rpc_submit)
        rpc.serve("gateway.commit_status", self._rpc_commit_status)

    def register_ops(self, ops) -> None:
        """Mount GET /gateway on the hosting node's ops server: live
        front-door state (admission queue, in-flight, dedup window,
        per-orderer breaker snapshot).  The gateway shares the node
        process, so /metrics and /slo on the same server already carry
        its registry series — this adds the structured view."""
        def _gateway(path, body):
            with self._lock:
                depth = len(self._queue)
                inflight = len(self._inflight)
                recent = len(self._recent)
            return 200, {"queue_depth": depth,
                         "lifecycle": self.lifecycle,
                         "max_queue": self.max_queue,
                         "inflight": inflight,
                         "dedup_window": recent,
                         "healthy": self.broadcaster.healthy(),
                         "admission": self.admission.snapshot(),
                         "orderers": self.broadcaster.states()}
        ops.register_route("GET", "/gateway", _gateway)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)
        self.broadcaster.close()

    def drain(self, timeout_s: float = 10.0) -> dict:
        """Stop admitting new work and flush: the batcher keeps running
        so already-admitted submissions finish against the orderer;
        drained when queue + in-flight are both empty (a lapsed deadline
        reports the remainder, nothing is dropped)."""
        self.lifecycle = "draining"
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._inflight:
                    break
            time.sleep(0.02)
        with self._lock:
            left = {"queue": len(self._queue),
                    "inflight": len(self._inflight)}
        self.lifecycle = "drained"
        return left

    # helpers -----------------------------------------------------------

    def _admission_burn(self):
        """Max short-window SLO burn from the hosting node's evaluator
        (None when the node has no SLO plane or no data yet)."""
        slo = getattr(self.node, "slo", None)
        if slo is None:
            return None
        try:
            return slo.burn_state().get("max_burn_short")
        except Exception:
            return None

    def _notifier(self, ch) -> CommitNotifier:
        with self._lock:
            n = getattr(ch, "commit_notifier", None)
            if n is None:
                n = CommitNotifier(ch.channel_id)
                ch.committer.add_commit_listener(n.on_block)
                ch.commit_notifier = n
            return n

    def _observe(self, verb: str, t0: float) -> None:
        try:
            self._m_requests.add(1, verb=verb)
            self._m_latency.observe(time.monotonic() - t0, verb=verb)
        except Exception:
            pass

    # verbs -------------------------------------------------------------

    def _rpc_evaluate(self, body: dict, peer_identity) -> dict:
        """Endorse-only: simulate on this peer and hand the result back;
        nothing reaches the orderer (read path / queries)."""
        t0 = time.monotonic()
        try:
            if self.lifecycle != "serving":
                return {"status": 503, "message":
                        "gateway draining: retry another peer",
                        "payload": b""}
            # evaluates shed FIRST under overload: queries can retry on
            # any peer, and rejecting them frees endorsement simulation
            # capacity for submits that already paid for theirs
            shed = self.admission.admit("evaluate")
            if shed is not None:
                return dict(shed.body(), status=_admission.SHED_STATUS,
                            message=f"admission shed ({shed.mode}): "
                                    "gateway overloaded, retry later",
                            payload=b"")
            ch = self.node._chan(body)
            sp = SignedProposal(body["proposal"], body["signature"])
            resp = ch.endorser.process_proposal(sp)
            return {"status": resp.status, "message": resp.message,
                    "payload": resp.payload}
        finally:
            self._observe("evaluate", t0)

    def _rpc_endorse(self, body: dict, peer_identity) -> dict:
        """Collect endorsements: this peer first, then the org peers it
        is configured with, so a client reaches every org through ONE
        gateway round trip (gateway/endorse.go's plan execution)."""
        t0 = time.monotonic()
        try:
            if self.lifecycle != "serving":
                return {"status": 503, "message":
                        "gateway draining: retry another peer",
                        "payload": b"", "endorsements": []}
            shed = self.admission.admit("endorse")
            if shed is not None:
                return dict(shed.body(), status=_admission.SHED_STATUS,
                            message=f"admission shed ({shed.mode}): "
                                    "gateway overloaded, retry later",
                            payload=b"", endorsements=[])
            ch = self.node._chan(body)
            sp = SignedProposal(body["proposal"], body["signature"])
            resp = ch.endorser.process_proposal(sp)
            if resp.status != 200 or resp.endorsement is None:
                return {"status": resp.status, "message": resp.message,
                        "payload": resp.payload, "endorsements": []}
            endorsements = [{"endorser": resp.endorsement.endorser,
                             "signature": resp.endorsement.signature}]
            errors = []
            fan_body = {"proposal": body["proposal"],
                        "signature": body["signature"],
                        "channel": ch.channel_id}
            for addr in self.node.peers:
                try:
                    conn = connect(tuple(addr[:2]), self.node.signer,
                                   ch.msps, timeout=self.fan_dial_timeout_s)
                    try:
                        out = conn.call("endorse", fan_body,
                                        timeout=self.fan_call_timeout_s)
                    finally:
                        conn.close()
                except Exception as exc:
                    errors.append(f"{addr[0]}:{addr[1]}: {exc}")
                    continue
                if out.get("status") != 200:
                    errors.append(f"{addr[0]}:{addr[1]}: "
                                  f"{out.get('message', 'endorse failed')}")
                elif out.get("payload") != resp.payload:
                    errors.append(f"{addr[0]}:{addr[1]}: divergent "
                                  "simulation payload")
                else:
                    endorsements.append({
                        "endorser": out["endorser"],
                        "signature": out["endorsement_sig"]})
            return {"status": 200, "message": "; ".join(errors),
                    "payload": resp.payload, "endorsements": endorsements}
        finally:
            self._observe("endorse", t0)

    def _rpc_submit(self, body: dict, peer_identity) -> dict:
        """Admit an assembled envelope; blocks until its batch clears the
        orderer (or the submit timeout lapses with it still queued)."""
        t0 = time.monotonic()
        try:
            raw = body["envelope"]
            # native header peek: (type, channel_id, txid) straight off
            # the wire bytes; a native reject re-runs the full Python
            # deserialize so malformed submissions fail with the same
            # exceptions as before
            summary = wire.envelope_summary(raw)
            if summary is not None:
                channel_id, txid = summary[1], summary[2]
            else:
                header = Envelope.deserialize(raw).header().channel_header
                txid = header.txid
                channel_id = header.channel_id
            if not txid:
                raise ValueError("envelope has no txid")
            ch = self.node.channels.get(channel_id)
            if ch is not None:
                self._notifier(ch)   # attach before ordering can commit it
            with self._cv:
                pending = self._inflight.get(txid)
                deduped = pending is not None
                if pending is None and txid in self._recent:
                    st, info = self._recent[txid]
                    self._m_dedup.add(1)
                    return {"txid": txid, "status": st, "info": info,
                            "deduped": True}
                if pending is None:
                    # drain check AFTER the dedup window, same rationale
                    # as shed below: a retry of an admitted txid still
                    # attaches/replays, only NEW work is refused
                    if self.lifecycle != "serving":
                        return {"txid": txid, "status": 503,
                                "info": "gateway draining: new submissions"
                                        " refused, retry another peer"}
                    # shed check AFTER the dedup window: a retry of an
                    # already-admitted txid must attach/replay, never be
                    # shed — overload control cannot break idempotency.
                    # Distinct from queue-full backpressure below: shed
                    # is a typed retryable verdict with a retry-after
                    # hint, backpressure is "lost the race this instant".
                    shed = self.admission.admit("submit")
                    if shed is not None:
                        jlog(logger, "gateway.shed",
                             level=logging.WARNING, txid=txid,
                             channel=channel_id, mode=shed.mode,
                             retry_after_ms=shed.retry_after_ms,
                             severity=round(shed.severity, 3))
                        return dict(
                            shed.body(), txid=txid,
                            status=_admission.SHED_STATUS,
                            info=f"admission shed ({shed.mode}): gateway "
                                 "overloaded, retry after "
                                 f"{shed.retry_after_ms}ms")
                    if len(self._queue) >= self.max_queue:
                        self._m_backpressure.add(1)
                        jlog(logger, "gateway.backpressure",
                             level=logging.WARNING, txid=txid,
                             channel=channel_id,
                             queue_depth=len(self._queue))
                        raise RuntimeError(
                            "gateway admission queue full "
                            f"({self.max_queue}): backpressure, retry later")
                    pending = _Pending(raw, txid, channel_id)
                    self._inflight[txid] = pending
                    self._queue.append(pending)
                    self._m_depth.set(len(self._queue))
                    self._cv.notify()
            if deduped:
                self._m_dedup.add(1)
            if "timeout_ms" in body:
                timeout = min(int(body["timeout_ms"]) / 1000.0, 120.0)
            else:
                timeout = self.submit_timeout_s
            if not pending.event.wait(timeout):
                return {"txid": txid, "status": 0,
                        "info": "submit still in flight (timeout waiting "
                                "for orderer ack)", "deduped": deduped}
            return {"txid": txid, "status": pending.status,
                    "info": pending.info, "deduped": deduped}
        finally:
            self._observe("submit", t0)

    def _rpc_commit_status(self, body: dict, peer_identity) -> dict:
        """Block until the committer records the txid's validation code
        (VALID / MVCC_READ_CONFLICT / ...), no ledger polling."""
        t0 = time.monotonic()
        try:
            ch = self.node._chan(body)
            txid = str(body["txid"])
            timeout = min(int(body.get("timeout_ms", 15000)) / 1000.0, 120.0)
            notifier = self._notifier(ch)
            with tracing.tracer.start_span(
                    "gateway.commit_wait", require_parent=True,
                    attributes={"txid": txid}) as span:
                got = notifier.peek(txid)
                if got is None:
                    # committed before this gateway attached its notifier
                    # (or long ago): the block store is authoritative
                    try:
                        if ch.ledger.blockstore.has_txid(txid):
                            code = \
                                ch.ledger.blockstore.get_tx_validation_code(
                                    txid)
                            got = (int(code), -1, None)
                    except Exception:
                        got = None
                if got is None:
                    got = notifier.wait(txid, timeout)
                if got is None:
                    span.set_attribute("found", False)
                    return {"found": False, "txid": txid}
                code, block_num, block_trace = got
                span.set_attribute("found", True)
                span.set_attribute("code", int(code))
                span.set_attribute("block", block_num)
                # stitch the request trace to the block's pipeline trace
                span.add_link(block_trace)
            try:
                name = ValidationCode(code).name
            except ValueError:
                name = str(code)
            out = {"found": True, "txid": txid, "code": int(code),
                   "code_name": name, "block": block_num}
            if block_trace:
                out["block_trace_id"] = block_trace
            return out
        finally:
            self._observe("commit_status", t0)

    # batcher -----------------------------------------------------------

    def _drain(self) -> List[_Pending]:
        with self._cv:
            while not self._queue and not self._stop.is_set():
                self._cv.wait(0.2)
            if self._stop.is_set() and not self._queue:
                return []
        # linger briefly so concurrent submitters coalesce into one
        # orderer call (the admission layer's whole point)
        if self.linger_s > 0:
            time.sleep(self.linger_s)
        with self._cv:
            batch = self._queue[:self.max_batch]
            del self._queue[:len(batch)]
            self._m_depth.set(len(self._queue))
            return batch

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            try:
                self._m_batch.observe(len(batch))
            except Exception:
                pass
            # batch coalesce point: close each tx's queue-wait span and
            # open its ordering span (parented to that tx's own trace)
            spans_order = []
            for p in batch:
                p.span_queue.set_attribute("batch_size", len(batch))
                p.span_queue.end()
                spans_order.append(tracing.tracer.start_span(
                    "gateway.order", parent=p.ctx, require_parent=True,
                    attributes={"txid": p.txid, "batch_size": len(batch)}))
            # each envelope's traceparent rides beside it in the batch
            # frame: the batcher thread has no ambient context, so this
            # is how orderer-side spans join the right per-tx trace
            tps = [tracing.format_traceparent(sp.context)
                   if sp.recording else "" for sp in spans_order]
            # verify-once plane: stamp creator verdicts at ingress (one
            # batched dispatch), queue endorsement sets for speculative
            # verification while the orderer cuts the block, and send
            # the verdict attestations alongside the envelopes so the
            # orderer can skip its own device verify
            attests = None
            spec = getattr(self.node, "speculative", None)
            if spec is not None:
                try:
                    attests = spec.stamp(
                        [p.raw for p in batch],
                        [p.channel_id for p in batch],
                        spans=spans_order)
                except Exception:
                    logger.exception("verify-plane ingress stamp failed")
                    attests = None
            try:
                results = self.broadcaster.broadcast_batch(
                    [p.raw for p in batch], tps=tps, attests=attests)
            except Exception as exc:
                logger.exception("broadcast batch failed")
                jlog(logger, "gateway.broadcast_failed",
                     level=logging.ERROR, exc=exc, batch_size=len(batch),
                     txids=[p.txid for p in batch[:8]])
                results = [(500, f"gateway broadcast error: {exc}")] \
                    * len(batch)
            with self._cv:
                for p, sp, (st, info) in zip(batch, spans_order, results):
                    p.status, p.info = int(st), str(info)
                    sp.set_attribute("status", p.status)
                    sp.end("OK" if p.status == 200 else "ERROR")
                    self._inflight.pop(p.txid, None)
                    self._recent[p.txid] = (p.status, p.info)
                while len(self._recent) > self.recent_window:
                    self._recent.popitem(last=False)
            # feed per-tx gateway sojourn (queue wait + broadcast) into
            # the admission controller's latency EWMA
            done = time.monotonic()
            for p in batch:
                try:
                    self.admission.observe_latency(done - p.t_in)
                except Exception:
                    pass
            for p in batch:
                p.event.set()
