"""Commit-status notifier: txid -> validation code, push not poll.

Rides the committer's post-commit listener hook (committer.py calls
fn(block, final_flags) after every ledger commit), decodes each
envelope's txid once, and wakes any blocked commit_status waiters.
This is the event plane the reference builds from peer/deliveryservice
block events + gateway/commit.go — here it is in-process because the
gateway is peer-co-located.

The history window is bounded: clients that ask about a txid committed
more than `window` txs ago fall back to the gateway's ledger lookup
path (blkstorage keeps the authoritative record forever).
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from fabric_tpu.ops_plane import tracing
from fabric_tpu.protocol import Envelope

logger = logging.getLogger("fabric_tpu.gateway")


class CommitNotifier:
    def __init__(self, channel_id: str, window: int = 4096):
        self.channel_id = channel_id
        self.window = int(window)
        self._lock = threading.Lock()
        # txid -> (validation code int, block number, block trace id|None)
        self._history: "OrderedDict[str, Tuple[int, int, Optional[str]]]" \
            = OrderedDict()
        self._waiters: Dict[str, List[threading.Event]] = {}

    # committer hook ----------------------------------------------------

    def on_block(self, block, flags) -> None:
        notified = []
        # listeners run inside committer.store_block's span, so the
        # ambient trace id here IS the block trace — remember it so
        # commit_status can link the request trace to the block trace
        block_trace = tracing.tracer.current_trace_id()
        with self._lock:
            for i, env_bytes in enumerate(block.data):
                try:
                    txid = Envelope.deserialize(
                        env_bytes).header().channel_header.txid
                except Exception:
                    continue
                if not txid:
                    continue
                self._history[txid] = (int(flags.flag(i)),
                                       int(block.header.number),
                                       block_trace)
                evs = self._waiters.pop(txid, None)
                if evs:
                    notified.extend(evs)
            while len(self._history) > self.window:
                self._history.popitem(last=False)
        for ev in notified:
            ev.set()

    # client side -------------------------------------------------------

    def peek(self, txid: str) -> Optional[Tuple[int, int, Optional[str]]]:
        with self._lock:
            return self._history.get(txid)

    def wait(self, txid: str,
             timeout: float) -> Optional[Tuple[int, int, Optional[str]]]:
        """Block until the txid commits or the timeout lapses."""
        ev = threading.Event()
        with self._lock:
            got = self._history.get(txid)
            if got is not None:
                return got
            self._waiters.setdefault(txid, []).append(ev)
        try:
            if not ev.wait(timeout):
                return None
            with self._lock:
                return self._history.get(txid)
        finally:
            with self._lock:
                evs = self._waiters.get(txid)
                if evs and ev in evs:
                    evs.remove(ev)
                    if not evs:
                        del self._waiters[txid]
