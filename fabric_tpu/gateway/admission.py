"""SLO-driven admission control: the gateway's graceful-degradation
state machine.

The bounded admission queue (service.py) already refuses to buffer
unboundedly — but a bare queue-full rejection is a CLIFF: the gateway
accepts 100% of offered load right up to the instant it accepts
whatever fraction happens to fit, latency for everything already
admitted blows through its SLO, and clients learn about overload only
by timing out.  The multi-window burn-rate evaluator (ops_plane/slo.py)
computes a calibrated "how much trouble are we in" signal; this module
closes the loop between that signal and the front door.

`AdmissionController` folds three normalized signals into one severity
(1.0 = at threshold):

  burn      max short-window burn rate across the node's SLO
            objectives (the sustained-overload signal)
  queue     admission-queue occupancy against `queue_high_frac`
            (the right-now signal; EWMA-smoothed)
  latency   EWMA of orderer-ack latency against `latency_slo_s`
            (the downstream-backpressure signal)

and runs a hysteretic state machine over it:

  NORMAL              admit everything
  SHED_EVALUATE       reject read-only evaluates first — queries can
                      retry anywhere, submits carry endorsement work
                      already paid for
  SHED_PROBABILISTIC  also shed submits by a SEEDED coin whose weight
                      grows with severity (deterministic under test,
                      statistically fair in production)
  SHED_HARD           reject all client verbs

Escalation is immediate (overload does not wait); recovery steps DOWN
one state at a time, only after `dwell_s` in the current state AND
severity below `recover_ratio` x the state's entry threshold — the
hysteresis that prevents shed/admit flapping at the boundary.

A shed is a TYPED, RETRYABLE verdict, not an error string: the verb
returns `{"shed": true, "mode": ..., "retry_after_ms": ...}` with a
hint that grows with severity, and GatewayClient honors it with capped
jittered backoff (client.py).  Distinct from queue-full backpressure:
backpressure means "the batcher lost the race this instant", shed
means "the NODE is overloaded — stay away for a while".
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, List, Optional

from fabric_tpu.ops_plane import registry
from fabric_tpu.ops_plane.logging import jlog

import logging

logger = logging.getLogger("fabric_tpu.gateway")

# state order IS escalation order; gauge value = index
STATES = ("NORMAL", "SHED_EVALUATE", "SHED_PROBABILISTIC", "SHED_HARD")
NORMAL, SHED_EVALUATE, SHED_PROBABILISTIC, SHED_HARD = range(4)

# the wire status a shed verdict rides under (HTTP 429 semantics)
SHED_STATUS = 429


class ShedDecision:
    """One rejected admission: what to tell the client."""

    __slots__ = ("mode", "retry_after_ms", "severity")

    def __init__(self, mode: str, retry_after_ms: int, severity: float):
        self.mode = mode
        self.retry_after_ms = int(retry_after_ms)
        self.severity = float(severity)

    def body(self) -> dict:
        # the RPC serde is float-free by design: severity rides as
        # integer thousandths
        return {"shed": True, "mode": self.mode,
                "retry_after_ms": self.retry_after_ms,
                "severity_milli": int(round(self.severity * 1000))}


class AdmissionController:
    """Severity -> state machine -> per-verb admit/shed verdicts.

    Pure host logic with injected signal sources and clock: the tests
    drive it through synthetic burn/queue/latency trajectories without
    a node, and the GatewayService wires the live ones.
    """

    def __init__(self, cfg: Optional[dict] = None, *,
                 burn_source: Optional[Callable[[], Optional[float]]] = None,
                 queue_source: Optional[Callable[[], float]] = None,
                 clock=None):
        cfg = dict(cfg or {})
        self.enabled = bool(cfg.get("enabled", False))
        # severity thresholds for entering each shed state (NORMAL has
        # none); defaults: evaluates shed at 1x threshold burn, submits
        # probabilistically from 2x, everything from 4x
        self.shed_evaluate_burn = float(cfg.get("shed_evaluate_burn", 1.0))
        self.shed_probabilistic_burn = float(
            cfg.get("shed_probabilistic_burn", 2.0))
        self.shed_hard_burn = float(cfg.get("shed_hard_burn", 4.0))
        if not (0.0 < self.shed_evaluate_burn
                <= self.shed_probabilistic_burn <= self.shed_hard_burn):
            raise ValueError("admission thresholds must satisfy 0 < "
                             "evaluate <= probabilistic <= hard")
        # queue occupancy mapping: queue_frac / queue_high_frac == 1.0
        # severity when the queue sits at the high-water mark
        self.queue_high_frac = float(cfg.get("queue_high_frac", 0.8))
        # ack-latency mapping: ewma / latency_slo_s
        self.latency_slo_s = float(cfg.get("latency_slo_s", 2.0))
        # hysteretic recovery
        self.recover_ratio = float(cfg.get("recover_ratio", 0.7))
        self.dwell_s = float(cfg.get("dwell_s", 1.0))
        # retry-after hint: base * (1 + severity), capped
        self.retry_after_base_ms = int(cfg.get("retry_after_base_ms", 200))
        self.retry_after_max_ms = int(cfg.get("retry_after_max_ms", 5000))
        # severity recompute rate limit (admit() sits on the submit path)
        self.eval_interval_s = float(cfg.get("eval_interval_s", 0.1))
        self.seed = int(cfg.get("seed", 0))

        self._burn_source = burn_source
        self._queue_source = queue_source
        self._clock = clock or time.monotonic
        self._rand = random.Random(self.seed)
        self._lock = threading.Lock()
        self._state = NORMAL
        self._since = self._clock()
        self._severity = 0.0
        self._next_eval = 0.0
        self._lat_ewma_s = 0.0
        self._lat_last = 0.0
        self._queue_ewma = 0.0
        self._transitions: List[dict] = []

        self._m_state = registry.gauge(
            "gateway_admission_state",
            "admission state (0 NORMAL .. 3 SHED_HARD)")
        self._m_severity = registry.gauge(
            "gateway_admission_severity",
            "combined admission severity (1.0 = at threshold)")
        self._m_shed = registry.counter(
            "gateway_shed_total", "admissions shed, by state and verb")
        self._m_offered = registry.counter(
            "gateway_offered_total",
            "verb calls offered to admission (admitted + shed)")
        self._m_state.set(0.0)

    # -- live signal feeds --------------------------------------------------

    def observe_latency(self, latency_s: float) -> None:
        """Feed one orderer-ack latency sample (batcher thread)."""
        with self._lock:
            self._lat_ewma_s = latency_s if self._lat_ewma_s == 0.0 else \
                0.8 * self._lat_ewma_s + 0.2 * latency_s
            self._lat_last = self._clock()

    def _signals(self) -> dict:
        burn = None
        if self._burn_source is not None:
            try:
                burn = self._burn_source()
            except Exception:
                burn = None
        qfrac = 0.0
        if self._queue_source is not None:
            try:
                qfrac = float(self._queue_source())
            except Exception:
                qfrac = 0.0
        return {"burn": burn, "queue_frac": qfrac,
                "latency_ewma_s": self._lat_ewma_s}

    # -- severity + state machine -------------------------------------------

    def _compute_severity(self, sig: dict, now: float) -> float:
        sev = 0.0
        if sig["burn"] is not None:
            sev = max(sev, float(sig["burn"]))
        if self.queue_high_frac > 0.0:
            # EWMA the queue signal: a single coalesced batch draining
            # must not read as instant recovery
            self._queue_ewma = (0.5 * self._queue_ewma
                                + 0.5 * sig["queue_frac"])
            sev = max(sev, self._queue_ewma / self.queue_high_frac)
        if self.latency_slo_s > 0.0 and sig["latency_ewma_s"] > 0.0:
            # the EWMA only refreshes when a batch completes; once shed
            # has stopped all traffic there are no more acks, and a
            # frozen overload-era reading would wedge the controller in
            # a shed state forever (no traffic -> no samples -> no
            # recovery -> no traffic).  Latency EVIDENCE goes stale:
            # halve it per dwell period since the last sample.
            half = max(self.dwell_s, 4 * self.eval_interval_s)
            age = max(0.0, now - self._lat_last)
            lat = sig["latency_ewma_s"] * 0.5 ** (age / half)
            sev = max(sev, lat / self.latency_slo_s)
        return sev

    def _target_state(self, sev: float) -> int:
        if sev >= self.shed_hard_burn:
            return SHED_HARD
        if sev >= self.shed_probabilistic_burn:
            return SHED_PROBABILISTIC
        if sev >= self.shed_evaluate_burn:
            return SHED_EVALUATE
        return NORMAL

    def _entry_threshold(self, state: int) -> float:
        return (0.0, self.shed_evaluate_burn,
                self.shed_probabilistic_burn,
                self.shed_hard_burn)[state]

    def _transition(self, new: int, now: float, sev: float) -> None:
        old, self._state = self._state, new
        self._since = now
        self._m_state.set(float(new))
        rec = {"at": time.time(), "from": STATES[old], "to": STATES[new],
               "severity": round(sev, 3)}
        self._transitions.append(rec)
        del self._transitions[:-32]
        jlog(logger, "gateway.admission_transition",
             level=logging.WARNING if new > old else logging.INFO,
             **rec)

    def evaluate_state(self, now: Optional[float] = None) -> int:
        """Recompute severity and run one state-machine step.  Called
        inline from admit() (rate-limited) and from tests directly."""
        now = self._clock() if now is None else now
        with self._lock:
            sev = self._compute_severity(self._signals(), now)
            self._severity = sev
            self._m_severity.set(sev)
            target = self._target_state(sev)
            if target > self._state:
                # escalation is immediate: overload does not dwell
                self._transition(target, now, sev)
            elif target < self._state:
                # hysteretic recovery: one step down at a time, only
                # after dwell_s AND clearly below this state's entry bar
                entry = self._entry_threshold(self._state)
                if (now - self._since >= self.dwell_s
                        and sev < entry * self.recover_ratio):
                    self._transition(self._state - 1, now, sev)
            return self._state

    def _maybe_evaluate(self, now: float) -> None:
        if now >= self._next_eval:
            self._next_eval = now + self.eval_interval_s
            self.evaluate_state(now)

    # -- the admit verdict ---------------------------------------------------

    def _retry_after_ms(self, sev: float) -> int:
        hint = self.retry_after_base_ms * (1.0 + sev)
        return int(min(hint, self.retry_after_max_ms))

    def _decision(self, state: int, sev: float) -> ShedDecision:
        return ShedDecision(STATES[state], self._retry_after_ms(sev), sev)

    def admit(self, verb: str) -> Optional[ShedDecision]:
        """None = admitted; a ShedDecision = rejected.  `verb` is
        "evaluate" | "submit" | "endorse"; endorse sheds with evaluate
        (both are pre-ordering work the client can take elsewhere)."""
        if not self.enabled:
            return None
        now = self._clock()
        self._maybe_evaluate(now)
        try:
            self._m_offered.add(1, verb=verb)
        except Exception:
            pass
        with self._lock:
            state, sev = self._state, self._severity
            if state == NORMAL:
                return None
            if state == SHED_HARD:
                decision = self._decision(state, sev)
            elif verb in ("evaluate", "endorse"):
                # evaluates shed first, in EVERY shed state
                decision = self._decision(state, sev)
            elif state == SHED_PROBABILISTIC:
                # seeded coin weighted by how far past the probabilistic
                # threshold severity has climbed: p ramps 0 -> 1 across
                # [shed_probabilistic_burn, shed_hard_burn]
                span = self.shed_hard_burn - self.shed_probabilistic_burn
                p = 1.0 if span <= 0.0 else min(
                    1.0, max(0.1, (sev - self.shed_probabilistic_burn)
                             / span))
                if self._rand.random() >= p:
                    return None
                decision = self._decision(state, sev)
            else:
                return None           # SHED_EVALUATE admits submits
        try:
            self._m_shed.add(1, mode=decision.mode, verb=verb)
        except Exception:
            pass
        return decision

    # -- test + ops surface ---------------------------------------------------

    def force_state(self, state: int) -> None:
        """Pin a state (tests/drills); the next evaluate_state() may
        move it again, so pair with a far-future eval or disabled
        sources."""
        with self._lock:
            self._transition(int(state), self._clock(), self._severity)

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    @property
    def state_name(self) -> str:
        return STATES[self.state]

    def snapshot(self) -> dict:
        with self._lock:
            sig = self._signals()
            return {"enabled": self.enabled,
                    "state": STATES[self._state],
                    "severity": round(self._severity, 4),
                    "signals": {
                        "burn": sig["burn"],
                        "queue_frac": round(sig["queue_frac"], 4),
                        "queue_ewma": round(self._queue_ewma, 4),
                        "latency_ewma_s": round(sig["latency_ewma_s"], 4)},
                    "thresholds": {
                        "shed_evaluate_burn": self.shed_evaluate_burn,
                        "shed_probabilistic_burn":
                            self.shed_probabilistic_burn,
                        "shed_hard_burn": self.shed_hard_burn,
                        "recover_ratio": self.recover_ratio,
                        "dwell_s": self.dwell_s},
                    "transitions": list(self._transitions)}
