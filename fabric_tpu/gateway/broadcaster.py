"""Batched orderer broadcast behind per-orderer circuit breakers.

The transmit half of the gateway: coalesced envelope batches go to one
orderer as a single `broadcast_batch` RPC.  Where PR 1 rotated blindly
through the orderer list under one shared backoff, each orderer now has
its own breaker + health score:

  CLOSED      normal traffic; consecutive failures are counted
  OPEN        `failure_threshold` consecutive failures tripped it; no
              traffic until `open_until` (exponential per-trip cooldown)
  HALF_OPEN   cooldown lapsed; ONE probe batch is allowed through —
              success closes the breaker, failure re-opens it with a
              longer cooldown

Selection is sticky on the current orderer while its breaker is CLOSED
(keeps one warm connection, preserves batch affinity), and otherwise
prefers the healthiest candidate: CLOSED beats HALF_OPEN beats OPEN,
ties broken by latency EWMA then failure history.  When every breaker
is OPEN the earliest-expiring one is force-probed — a fully-failed
orderer set degrades to slow retries, never to a wedge.

Failure classification uses the typed RPC errors (`RpcClosed` → the
connection died, re-dial; `RpcTimeout` → frame lost or orderer wedged)
instead of the old string matching.  Every breaker transition emits a
metric, a jlog line, and a span event on the ambient trace.

Per-envelope outcomes stay independent: a 4xx (bad envelope, unknown
channel, filter veto) is final for that envelope only, while 503s
requeue for the next attempt until the deadline lapses.

Leader hint: a follower that answers 503 includes the raft leader's id
in its response (`BroadcastResponse.leader_hint`).  The broadcaster maps
raft ids to endpoints by lazily probing each orderer's `status` RPC, and
the next rotation jumps STRAIGHT to the leader instead of walking the
list — without the hint a 5-orderer set wastes up to 4 failed attempts
(plus backoffs) per leadership change before landing on the node that
can actually order.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Sequence, Tuple

from fabric_tpu.comm import RpcClosed, RpcTimeout, connect
from fabric_tpu.ops_plane import tracing
from fabric_tpu.ops_plane.logging import jlog

logger = logging.getLogger("fabric_tpu.gateway")

CLOSED, OPEN, HALF_OPEN = "CLOSED", "OPEN", "HALF_OPEN"


class _OrdererState:
    """Breaker + health score for one orderer endpoint."""

    __slots__ = ("addr", "state", "consec_fails", "trips", "open_until",
                 "ewma_s", "ok_total", "fail_total")

    def __init__(self, addr):
        self.addr = tuple(addr)
        self.state = CLOSED
        self.consec_fails = 0
        self.trips = 0             # lifetime breaker openings
        self.open_until = 0.0
        self.ewma_s = 0.0          # smoothed broadcast latency
        self.ok_total = 0
        self.fail_total = 0

    def usable(self, now: float) -> bool:
        """May traffic be sent to this orderer right now?"""
        if self.state == CLOSED:
            return True
        return now >= self.open_until        # OPEN past cooldown => probe

    def score(self) -> Tuple:
        """Lower is better; total order over candidates."""
        rank = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}[self.state]
        return (rank, self.ewma_s, self.consec_fails, self.fail_total)

    def as_dict(self) -> dict:
        return {"addr": "%s:%s" % self.addr, "state": self.state,
                "consec_fails": self.consec_fails, "trips": self.trips,
                "ewma_ms": round(self.ewma_s * 1e3, 3),
                "ok_total": self.ok_total, "fail_total": self.fail_total}


class BatchBroadcaster:
    def __init__(self, orderers: Sequence[Tuple[str, int]], signer, msps,
                 backoff_base_s: float = 0.05, backoff_max_s: float = 2.0,
                 deadline_s: float = 10.0, rpc_timeout_s: float = 10.0,
                 failure_threshold: int = 3,
                 cooldown_base_s: float = 0.25, cooldown_max_s: float = 8.0):
        if not orderers:
            raise ValueError("gateway needs at least one orderer")
        self.orderers = [tuple(o) for o in orderers]
        self.signer = signer
        self.msps = msps
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.deadline_s = deadline_s
        self.rpc_timeout_s = rpc_timeout_s
        self.failure_threshold = int(failure_threshold)
        self.cooldown_base_s = cooldown_base_s
        self.cooldown_max_s = cooldown_max_s
        self._lock = threading.Lock()
        self._states = [_OrdererState(a) for a in self.orderers]
        self._idx = 0          # current orderer (sticky while healthy)
        self._conn = None
        self._failures = 0     # consecutive rotate count (drives backoff)
        self._raft_ids = {}    # orderer idx -> raft id (from status probes)
        self._leader_idx = None  # where the last leader hint points

    # breaker -----------------------------------------------------------

    def _set_state(self, st: _OrdererState, new: str, reason: str) -> None:
        """Caller holds self._lock.  Observability is best-effort."""
        old, st.state = st.state, new
        if old == new:
            return
        addr = "%s:%s" % st.addr
        try:
            from fabric_tpu.ops_plane import registry
            registry.counter(
                "gateway_breaker_transitions_total",
                "orderer circuit-breaker state changes").add(
                    1, orderer=addr, to=new)
            registry.gauge(
                "gateway_orderer_breaker_open",
                "1 while the orderer's breaker is open").set(
                    1.0 if new == OPEN else 0.0, orderer=addr)
            jlog(logger, "gateway.breaker", orderer=addr,
                 level=logging.WARNING if new == OPEN else logging.INFO,
                 old=old, new=new, reason=reason, trips=st.trips)
            tracing.event("breaker." + new.lower(), orderer=addr,
                          reason=reason)
        except Exception:
            pass

    def _on_success(self, idx: int, latency_s: float) -> None:
        with self._lock:
            st = self._states[idx]
            st.ok_total += 1
            st.consec_fails = 0
            st.ewma_s = latency_s if st.ewma_s == 0.0 else \
                0.8 * st.ewma_s + 0.2 * latency_s
            self._set_state(st, CLOSED, "success")
            self._failures = 0
            self._leader_idx = None   # hint consumed; stickiness takes over

    def _on_failure(self, idx: int, reason: str) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._states[idx]
            st.fail_total += 1
            st.consec_fails += 1
            if st.state == HALF_OPEN or \
                    st.consec_fails >= self.failure_threshold:
                st.trips += 1
                st.open_until = now + min(
                    self.cooldown_max_s,
                    self.cooldown_base_s * (2 ** min(st.trips - 1, 16)))
                self._set_state(st, OPEN, reason)

    def _select(self) -> int:
        """Pick the orderer for the next attempt (caller holds lock)."""
        now = time.monotonic()
        cur = self._states[self._idx]
        if cur.state == CLOSED:
            return self._idx
        candidates = []
        for i, st in enumerate(self._states):
            if st.state == OPEN and st.usable(now):
                # cooldown lapsed: promote to HALF_OPEN, allow one probe
                self._set_state(st, HALF_OPEN, "cooldown_elapsed")
            if st.usable(now) or st.state == HALF_OPEN:
                candidates.append(i)
        if candidates:
            # the last leader hint beats the health score while the
            # leader's own breaker is CLOSED — the healthiest follower
            # still answers 503 to every broadcast
            li = self._leader_idx
            if li in candidates and self._states[li].state == CLOSED:
                return li
            return min(candidates, key=lambda i: self._states[i].score())
        # everything OPEN inside cooldown: force-probe the one expiring
        # first so a total outage recovers without operator action
        return min(range(len(self._states)),
                   key=lambda i: self._states[i].open_until)

    # introspection ------------------------------------------------------

    def healthy(self) -> bool:
        """True while at least one orderer's breaker is not OPEN — the
        `/healthz` "orderer reachable" signal."""
        with self._lock:
            return any(st.state != OPEN for st in self._states)

    def states(self) -> List[dict]:
        with self._lock:
            return [st.as_dict() for st in self._states]

    def latency_ewma(self) -> float:
        """Smoothed broadcast latency (seconds) of the current orderer,
        falling back to the best-known peer — the admission plane's
        downstream-backpressure signal.  0.0 until a broadcast lands."""
        with self._lock:
            st = self._states[self._idx]
            if st.ewma_s > 0.0:
                return st.ewma_s
            vals = [s.ewma_s for s in self._states if s.ewma_s > 0.0]
            return min(vals) if vals else 0.0

    # connection management --------------------------------------------

    def _backoff(self) -> float:
        return min(self.backoff_max_s,
                   self.backoff_base_s * (2 ** min(self._failures, 16)))

    def _connection(self):
        with self._lock:
            target = self._select()
            if self._conn is not None and target == self._idx:
                return self._idx, self._conn
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None
            self._idx = target
            addr = self.orderers[self._idx]
            self._conn = connect(addr, self.signer, self.msps,
                                 timeout=min(self.rpc_timeout_s, 5.0))
            return self._idx, self._conn

    def _rotate(self, reason: str, prefer: Optional[int] = None) -> None:
        followed = False
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None
            if prefer is not None and prefer != self._idx:
                # leader hint: jump straight to the node that can order
                # instead of walking the list one failed attempt at a time
                self._idx = prefer
                followed = True
            else:
                # legacy rotation: advance off the failed orderer so the
                # next _connection() re-selects; _select may override
                self._idx = (self._idx + 1) % len(self.orderers)
            self._failures += 1
        try:
            from fabric_tpu.ops_plane import registry
            registry.counter(
                "gateway_broadcast_retries_total",
                "orderer broadcast attempts that failed over").add(
                    1, reason=reason)
            if followed:
                registry.counter(
                    "gateway_leader_follows_total",
                    "rotations that jumped to the hinted raft leader").add(
                        1, orderer="%s:%s" % self.orderers[prefer])
        except Exception:
            pass

    def _learn_leader(self, raft_id) -> Optional[int]:
        """Map a raft leader id from a broadcast response to an orderer
        index, lazily probing unprobed endpoints' `status` RPC to build
        the raft-id -> endpoint table.  Returns the index (and records
        it as the rotation preference) or None when unknown/stale."""
        try:
            raft_id = int(raft_id or 0)
        except (TypeError, ValueError):
            return None
        if raft_id <= 0:
            return None
        with self._lock:
            known = dict(self._raft_ids)
        for i, rid in known.items():
            if rid == raft_id:
                with self._lock:
                    self._leader_idx = i
                return i
        # probe outside the lock: status is a fast metadata RPC, but a
        # dead endpoint costs a dial timeout we must not serialize the
        # breaker plane behind
        for i, addr in enumerate(self.orderers):
            if i in known or self._states[i].state == OPEN:
                continue
            try:
                conn = connect(addr, self.signer, self.msps, timeout=2.0)
                try:
                    out = conn.call("status", {}, timeout=2.0)
                finally:
                    conn.close()
                rid = int(out.get("raft_id", 0))
            except Exception:
                continue
            with self._lock:
                self._raft_ids[i] = rid
            if rid == raft_id:
                with self._lock:
                    self._leader_idx = i
                return i
        return None

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None

    # broadcast ---------------------------------------------------------

    def broadcast_batch(
            self, envs: Sequence,
            deadline_s: Optional[float] = None,
            tps: Optional[Sequence[str]] = None,
            attests: Optional[Sequence[str]] = None
    ) -> List[Tuple[int, str]]:
        """Send every envelope, retrying transient failures across the
        orderer set; returns one (status, info) per envelope in order.

        `tps` (optional, aligned with envs) carries each envelope's
        traceparent so the orderer can continue per-tx traces even
        though the whole batch rides one RPC frame.  `attests` (same
        alignment) carries the verify-once plane's per-envelope verdict
        attestations; both are re-aligned by pending index on every 503
        retry so a partial requeue never shifts an attestation onto a
        different envelope."""
        results: List[Optional[Tuple[int, str]]] = [None] * len(envs)
        pending = list(enumerate(envs))
        deadline = time.monotonic() + (deadline_s if deadline_s is not None
                                       else self.deadline_s)
        while pending:
            try:
                # _connection sets self._idx to the dial target before it
                # can raise, so failure paths charge the right orderer
                idx, conn = self._connection()
                # raw wire bytes pass through untouched (zero-copy submit
                # path); Envelope objects serialize here as before
                body = {"envelopes": [
                    e if isinstance(e, (bytes, bytearray, memoryview))
                    else e.serialize() for _, e in pending]}
                if tps and any(tps):
                    body["tps"] = [tps[i] if i < len(tps) else ""
                                   for i, _ in pending]
                if attests and any(attests):
                    body["attests"] = [attests[i] if i < len(attests)
                                       else "" for i, _ in pending]
                t0 = time.monotonic()
                out = conn.call(
                    "broadcast_batch", body,
                    timeout=self.rpc_timeout_s)
                latency = time.monotonic() - t0
                statuses = [int(s) for s in out["statuses"]]
                infos = [str(s) for s in out.get(
                    "infos", [""] * len(statuses))]
            except RpcClosed as exc:
                logger.debug("broadcast: connection closed: %s", exc)
                self._on_failure(self._idx, "closed")
                self._rotate("closed")
                if time.monotonic() >= deadline:
                    break
                time.sleep(self._backoff())
                continue
            except RpcTimeout as exc:
                logger.debug("broadcast: rpc timeout: %s", exc)
                self._on_failure(self._idx, "timeout")
                self._rotate("timeout")
                if time.monotonic() >= deadline:
                    break
                time.sleep(self._backoff())
                continue
            except Exception as exc:
                logger.debug("broadcast to orderer failed: %s", exc)
                self._on_failure(self._idx, "connection")
                self._rotate("connection")
                if time.monotonic() >= deadline:
                    break
                time.sleep(self._backoff())
                continue
            retry = []
            for (i, env), st, info in zip(pending, statuses, infos):
                if st == 503:
                    retry.append((i, env))
                    results[i] = (st, info)   # stands if the deadline hits
                else:
                    results[i] = (st, info)
            if not retry:
                self._on_success(idx, latency)
                break
            pending = retry
            # the orderer answered but can't order (no leader / halted):
            # transport is fine, service is not — count against health.
            # Follow its leader hint so the retry lands on the raft
            # leader instead of the next follower in the list.
            self._on_failure(idx, "unavailable")
            self._rotate("unavailable",
                         prefer=self._learn_leader(out.get("leader")))
            if time.monotonic() >= deadline:
                break
            time.sleep(self._backoff())
        for i, _ in pending:
            if results[i] is None:
                results[i] = (503, "broadcast deadline exceeded")
        return [r if r is not None else (503, "not attempted")
                for r in results]
