"""Batched orderer broadcast with backoff + failover.

The transmit half of the gateway: coalesced envelope batches go to one
orderer as a single `broadcast_batch` RPC; connection failures and
SERVICE_UNAVAILABLE responses (no raft leader, halted chain) rotate to
the next orderer under capped exponential backoff — the same policy
the deliver plane uses in gossip/blocksprovider.py (failures counter,
min(max, base * 2^failures)).  Per-envelope outcomes come back
independently: a 4xx (bad envelope, unknown channel, filter veto) is
final for that envelope only, while 503s requeue for the next attempt
until the deadline lapses.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Sequence, Tuple

from fabric_tpu.comm import connect

logger = logging.getLogger("fabric_tpu.gateway")


class BatchBroadcaster:
    def __init__(self, orderers: Sequence[Tuple[str, int]], signer, msps,
                 backoff_base_s: float = 0.05, backoff_max_s: float = 2.0,
                 deadline_s: float = 10.0, rpc_timeout_s: float = 10.0):
        if not orderers:
            raise ValueError("gateway needs at least one orderer")
        self.orderers = [tuple(o) for o in orderers]
        self.signer = signer
        self.msps = msps
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.deadline_s = deadline_s
        self.rpc_timeout_s = rpc_timeout_s
        self._lock = threading.Lock()
        self._idx = 0          # current orderer (sticky while healthy)
        self._conn = None
        self._failures = 0

    # connection management --------------------------------------------

    def _backoff(self) -> float:
        return min(self.backoff_max_s,
                   self.backoff_base_s * (2 ** min(self._failures, 16)))

    def _connection(self):
        with self._lock:
            if self._conn is not None:
                return self._conn
            addr = self.orderers[self._idx % len(self.orderers)]
            self._conn = connect(addr, self.signer, self.msps,
                                 timeout=min(self.rpc_timeout_s, 5.0))
            return self._conn

    def _rotate(self, reason: str) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None
            self._idx = (self._idx + 1) % len(self.orderers)
            self._failures += 1
        try:
            from fabric_tpu.ops_plane import registry
            registry.counter(
                "gateway_broadcast_retries_total",
                "orderer broadcast attempts that failed over").add(
                    1, reason=reason)
        except Exception:
            pass

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None

    # broadcast ---------------------------------------------------------

    def broadcast_batch(
            self, envs: Sequence,
            deadline_s: Optional[float] = None,
            tps: Optional[Sequence[str]] = None) -> List[Tuple[int, str]]:
        """Send every envelope, retrying transient failures across the
        orderer set; returns one (status, info) per envelope in order.

        `tps` (optional, aligned with envs) carries each envelope's
        traceparent so the orderer can continue per-tx traces even
        though the whole batch rides one RPC frame."""
        results: List[Optional[Tuple[int, str]]] = [None] * len(envs)
        pending = list(enumerate(envs))
        deadline = time.monotonic() + (deadline_s if deadline_s is not None
                                       else self.deadline_s)
        while pending:
            try:
                conn = self._connection()
                body = {"envelopes": [e.serialize() for _, e in pending]}
                if tps and any(tps):
                    body["tps"] = [tps[i] if i < len(tps) else ""
                                   for i, _ in pending]
                out = conn.call(
                    "broadcast_batch", body,
                    timeout=self.rpc_timeout_s)
                statuses = [int(s) for s in out["statuses"]]
                infos = [str(s) for s in out.get(
                    "infos", [""] * len(statuses))]
            except Exception as exc:
                logger.debug("broadcast to orderer failed: %s", exc)
                self._rotate("connection")
                if time.monotonic() >= deadline:
                    break
                time.sleep(self._backoff())
                continue
            retry = []
            for (i, env), st, info in zip(pending, statuses, infos):
                if st == 503:
                    retry.append((i, env))
                    results[i] = (st, info)   # stands if the deadline hits
                else:
                    results[i] = (st, info)
            if not retry:
                with self._lock:
                    self._failures = 0
                break
            pending = retry
            self._rotate("unavailable")
            if time.monotonic() >= deadline:
                break
            time.sleep(self._backoff())
        for i, _ in pending:
            if results[i] is None:
                results[i] = (503, "broadcast deadline exceeded")
        return [r if r is not None else (503, "not attempted")
                for r in results]
