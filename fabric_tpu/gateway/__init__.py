"""Gateway: the batched, fault-tolerant client front door.

Modeled on the Fabric v2.4 Gateway service (gateway/gateway.go in the
reference lineage — which this fork predates): a peer-co-located
service that owns the client-facing transaction lifecycle so clients
stop hand-rolling endorse/broadcast/poll loops.  Four verbs ride the
authenticated RPC plane (comm/rpc.py):

  gateway.evaluate       endorse-only query, result returned, nothing
                         ordered (Evaluate in gateway.proto)
  gateway.endorse        collect endorsements from this peer + the
                         org-peers it knows, for client-side assembly
  gateway.submit         admit an assembled envelope into the bounded
                         batching queue -> coalesced orderer broadcast
  gateway.commit_status  block until the committer records the txid's
                         validation code (CommitStatus in gateway.proto)

Internals: a bounded admission queue with explicit backpressure
(service.py), batch broadcast with exponential-backoff failover across
orderers (broadcaster.py, same pattern as gossip/blocksprovider.py),
a txid dedup window for idempotent submission, a commit notifier
driven by the committer's post-validation txflags (notifier.py) so
commit_status never polls the ledger, and an SLO-driven admission
controller (admission.py) that sheds load with typed retryable
verdicts — NORMAL -> SHED_EVALUATE -> SHED_PROBABILISTIC -> SHED_HARD
with hysteretic recovery — before the queue-full cliff.
"""

from fabric_tpu.gateway.admission import AdmissionController
from fabric_tpu.gateway.broadcaster import BatchBroadcaster
from fabric_tpu.gateway.client import (
    GatewayClient,
    GatewayError,
    GatewayShedError,
)
from fabric_tpu.gateway.notifier import CommitNotifier
from fabric_tpu.gateway.service import GatewayService

__all__ = ["AdmissionController", "BatchBroadcaster", "CommitNotifier",
           "GatewayClient", "GatewayError", "GatewayShedError",
           "GatewayService"]
