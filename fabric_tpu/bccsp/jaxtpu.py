"""JAX/TPU batched BCCSP provider — the hardware slot of the framework.

Occupies the position the reference gives PKCS#11 HSMs (bccsp/pkcs11,
gated by bccsp/factory — SURVEY.md §2.1.1), but instead of one-at-a-time
HSM calls it dispatches the whole batch to the TPU kernels in
fabric_tpu.ops.  Signing and key-gen delegate to the software provider
(private keys never touch the TPU).

Host/device split per the reference's own design (msp/identities.go:178):
variable-length parsing (DER signatures, SEC1 points, RFC 8032 encodings,
SHA-512 for ed25519) happens on host; the device sees only fixed-size
word arrays.

Batching strategy: items are grouped by scheme, packed into word arrays,
and padded to power-of-two buckets so XLA compiles a small, reusable set
of programs.  Malformed items short-circuit to False on the host.
If device dispatch fails entirely, the whole batch falls back to the
software provider atomically (SURVEY.md §7 hard-part #5: fallback must be
atomic to keep determinism).

Device placement: with a mesh (parallel/mesh.py) every lane — generic
ladder, fixed-comb rows, idemix pairing — shards its flat batch across
the 1-D 'batch' axis via shard_map, buckets padded to a multiple of the
mesh size so each device holds an equal tile; verdict bitmaps and the
psum'd valid count stay on-device until resolve.  The lane-fill gauges
carry a `device` label so per-chip tile occupancy is observable live.
Independent channels can pin to disjoint sub-meshes through
parallel/placement.py (one provider per device subset).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from fabric_tpu.crypto import decode_dss_signature

from . import provider as prov
from .provider import (VerifyItem, SCHEME_P256, SCHEME_ED25519,
                       SCHEME_IDEMIX)
from .sw import SoftwareProvider

logger = logging.getLogger("fabric_tpu.bccsp.jaxtpu")

MIN_BUCKET = 128
MAX_BUCKET = 1 << 17

_ZERO32 = b"\x00" * 32

_DER_PARSE = []


def _parse_der_sigs():
    """The C batch DER parser, or None without the extension."""
    if not _DER_PARSE:
        try:
            from fabric_tpu.native import load as _load
            _DER_PARSE.append(_load("_fastcollect").parse_der_sigs)
        except Exception:       # pragma: no cover - broken toolchain
            _DER_PARSE.append(None)
    return _DER_PARSE[0]


def _bucket(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


@dataclass(frozen=True)
class ProviderStats:
    """Immutable point-in-time snapshot of a JaxTpuProvider's counters
    and effective tuning — the public observability surface."""
    dispatches: int = 0
    device_sigs: int = 0
    host_rejects: int = 0
    fallbacks: int = 0
    fast_key_sigs: int = 0        # sigs that rode the fixed-comb lane
    h2d_bytes: int = 0
    p256_table_builds: int = 0
    ed25519_table_builds: int = 0
    tuning: dict = field(default_factory=dict)


class JaxTpuProvider(prov.Provider):
    name = "jaxtpu"

    def __init__(self, require_low_s: bool = True, mesh=None,
                 fallback: Optional[SoftwareProvider] = None,
                 fast_row_c: Optional[int] = None,
                 rows_chunk: Optional[int] = None,
                 fast_key_threshold: Optional[int] = None,
                 max_cached_keys: Optional[int] = None):
        """Tuning knobs are per-instance constructor parameters (the
        public surface — no class-attribute monkeypatching needed);
        None means the FABRIC_TPU_* env default for that knob.

          fast_row_c          lanes per row in the fixed-base comb grid
          rows_chunk          soft per-dispatch row cap (pack/compute
                              overlap vs per-dispatch round-trip cost)
          fast_key_threshold  sigs/batch a key must bring to earn a
                              device-resident table slot
          max_cached_keys     table-bank slots (HBM residency cap)
        """
        import os
        self.require_low_s = require_low_s
        self.mesh = mesh
        self.fallback = fallback or SoftwareProvider(require_low_s=require_low_s)
        self._fns = {}
        self.stats = {"dispatches": 0, "device_sigs": 0, "host_rejects": 0,
                      "fallbacks": 0, "fast_key_sigs": 0, "h2d_bytes": 0}
        # per-key fixed-base fast path (ops/p256_fixed.py): keys whose comb
        # table is DEVICE-RESIDENT (ops/device_bank.py) skip the variable-
        # point ladder entirely; dispatches carry only slot indices, never
        # tables.  A table build costs ~150 ms host + one 1.4 MB upload, so
        # uncached keys only earn a slot when a single batch brings at
        # least `fast_key_threshold` signatures — repeat identities (org
        # endorsers, enrolled clients: the same assumption behind the
        # reference's msp/cache) amortize the build across blocks; true
        # one-off keys ride the generic ladder.
        from fabric_tpu.ops.device_bank import DeviceBank
        from fabric_tpu.ops import p256_tables as _pt
        from fabric_tpu.ops import ed25519_tables as _et
        import jax as _jax
        # 256 slots ~ 370 MB HBM on TPU; the CPU test backend holds the
        # bank in host RAM, so default smaller there (still above the
        # realistic ~67-hot-key block workload: pinning makes the slot
        # count a PER-BATCH fast-lane cap)
        _default_keys = "256" if _jax.default_backend() != "cpu" else "96"
        max_keys = int(max_cached_keys if max_cached_keys is not None
                       else os.environ.get("FABRIC_TPU_KEY_CACHE",
                                           _default_keys))
        self.max_cached_keys = max_keys
        # instance geometry shadows the env-derived class defaults
        self.fast_row_c = int(fast_row_c if fast_row_c is not None
                              else self.FAST_ROW_C)
        self.rows_chunk = int(rows_chunk if rows_chunk is not None
                              else self.ROWS_CHUNK)

        def _build_p256(pk: bytes):
            if len(pk) != 65 or pk[0] != 0x04:
                return None
            qx = int.from_bytes(pk[1:33], "big")
            qy = int.from_bytes(pk[33:65], "big")
            try:
                return _pt.comb_table_for_point(qx, qy)
            except ValueError:
                return None

        def _build_ed(pk: bytes):
            aff = _et.decompress_int(bytes(pk))
            if aff is None:
                return None
            ax, ay = aff
            return _et.comb_table_for_point((-ax) % _et.P, ay)  # -A

        self.key_tables = DeviceBank(
            max_keys, (_pt.COMB_WINDOWS * _pt.COMB_ENTRIES, 2 * _pt.L),
            _build_p256, mesh=mesh)
        self.ed_key_tables = DeviceBank(
            max_keys, (_et.COMB_WINDOWS * _et.COMB_ROWS, 3 * _et.L),
            _build_ed, mesh=mesh)
        self.fast_key_threshold = int(
            fast_key_threshold if fast_key_threshold is not None
            else os.environ.get("FABRIC_TPU_FAST_KEY_THRESHOLD", "64"))
        # telemetry identity of each tile: sharded dispatches lay the
        # batch out contiguously across the mesh, so slot accounting can
        # attribute real/pad slots per chip without touching the device
        if mesh is not None:
            devs = list(np.asarray(mesh.devices).flat)
        else:
            devs = [_jax.devices()[0]]
        self.device_labels = tuple(
            f"{d.platform}:{d.id}" for d in devs)

    def stats_snapshot(self) -> ProviderStats:
        """Point-in-time copy of the provider's counters plus the table
        banks' build accounting — callers observe through this instead
        of reaching into the live mutable dicts."""
        return ProviderStats(
            **self.stats,
            p256_table_builds=self.key_tables.stats.get("builds", 0),
            ed25519_table_builds=self.ed_key_tables.stats.get("builds", 0),
            tuning={"fast_row_c": self.fast_row_c,
                    "rows_chunk": self.rows_chunk,
                    "fast_key_threshold": self.fast_key_threshold,
                    "max_cached_keys": self.max_cached_keys})

    # signing / key-gen are host-side: delegate
    def key_gen(self, scheme: str):
        return self.fallback.key_gen(scheme)

    def sign(self, private_key, payload: bytes) -> bytes:
        return self.fallback.sign(private_key, payload)

    # -- device plumbing ----------------------------------------------------

    def _get_fn(self, scheme: str):
        key = scheme
        if key not in self._fns:
            import jax
            if scheme == SCHEME_P256:
                import os
                low_s = self.require_low_s
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_p256_verify(self.mesh, self.require_low_s)
                    self._fns[key] = lambda *a: f(*a)[0]
                else:
                    # round-2 windowed flat path (ops/ecp256).  On CPU the
                    # big scan bodies hit an XLA:CPU compile pathology, so
                    # run eagerly there (per-primitive jits, see flatfield).
                    from fabric_tpu.ops import ecp256
                    if jax.default_backend() == "cpu":
                        self._fns[key] = lambda *a: ecp256.verify_words_xla(
                            *a, require_low_s=low_s)
                    else:
                        from fabric_tpu.ops import bignum as _bn
                        tab = ecp256.comb_table_f32()

                        # words->limbs conversion inside the jit: eager
                        # conversion costs tunneled dispatches per call
                        def whole(qx, qy, r, s, e, _tab=tab):
                            args = [_bn.words_be_to_limbs(v)
                                    for v in (qx, qy, r, s, e)]
                            return ecp256.verify_body(
                                *args, _tab, require_low_s=low_s)
                        self._fns[key] = jax.jit(whole)
            elif scheme == "p256-rows":
                from fabric_tpu.ops import p256_fixed
                low_s = self.require_low_s
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_p256_rows_verify(
                        self.mesh, self.require_low_s)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif jax.default_backend() == "cpu":
                    self._fns[key] = (
                        lambda *a: p256_fixed.verify_words_rows(
                            *a, require_low_s=low_s))
                else:
                    self._fns[key] = jax.jit(
                        lambda *a: p256_fixed.verify_words_rows(
                            *a, require_low_s=low_s))
            elif scheme == SCHEME_ED25519:
                from fabric_tpu.ops import ed25519
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_ed25519_verify(self.mesh)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif jax.default_backend() == "cpu":
                    self._fns[key] = ed25519.verify_words
                else:
                    self._fns[key] = jax.jit(ed25519.verify_words)
            elif scheme == "idemix-pair":
                from fabric_tpu.ops import bn254_batch as bb

                def pair_fn(flags, A1, B1, A2, B2, x1, y1, x2, y2):
                    return bb.pairing_check_batch(
                        {"flags": flags, "A": A1, "B": B1},
                        {"flags": flags, "A": A2, "B": B2},
                        x1, y1, x2, y2)
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_idemix_pair_verify(self.mesh)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif jax.default_backend() == "cpu":
                    self._fns[key] = pair_fn
                else:
                    self._fns[key] = jax.jit(pair_fn)
            elif scheme == "ed25519-rows":
                from fabric_tpu.ops import ed25519
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_ed25519_rows_verify(self.mesh)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif jax.default_backend() == "cpu":
                    self._fns[key] = ed25519.verify_words_rows
                else:
                    self._fns[key] = jax.jit(ed25519.verify_words_rows)
            else:
                raise ValueError(f"unsupported scheme {scheme!r}")
        return self._fns[key]

    def _parse_p256(self, items, idxs):
        """Host-side parse: -> list of (idx, pubkey, r32, s32, e32) with
        malformed items dropped (verdict stays False).  The DER walk
        rides one C call over the whole batch when the extension is
        available (native/fastcollect.parse_der_sigs — strict DER +
        range gate, semantics mirrored by the fallback below and tested
        differentially)."""
        parse = _parse_der_sigs()
        if parse is not None:
            ok, rs = parse([items[i].signature for i in idxs])
            out = []
            for j, i in enumerate(idxs):
                it = items[i]
                pk = it.pubkey
                if (not ok[j] or len(pk) != 65 or pk[0] != 0x04
                        or len(it.payload) != 32):
                    self.stats["host_rejects"] += 1
                    continue
                out.append((i, pk, rs[64 * j:64 * j + 32],
                            rs[64 * j + 32:64 * j + 64], it.payload))
            return out
        out = []
        for i in idxs:
            it = items[i]
            try:
                pk = it.pubkey
                if len(pk) != 65 or pk[0] != 0x04:
                    raise ValueError("bad SEC1 point")
                if len(it.payload) != 32:
                    raise ValueError("p256 payload must be a 32B digest")
                ri, si = decode_dss_signature(it.signature)
                if not (0 < ri < (1 << 256) and 0 < si < (1 << 256)):
                    raise ValueError("r/s out of range")
            except Exception:
                self.stats["host_rejects"] += 1
                continue
            out.append((i, pk, ri.to_bytes(32, "big"),
                        si.to_bytes(32, "big"), it.payload))
        return out

    def _pack_p256(self, items, idxs):
        """Generic-lane packing: -> (ok_idx, [qx qy r s e] word arrays)."""
        recs = self._parse_p256(items, idxs)
        return self._pack_p256_recs(recs)

    @staticmethod
    def _pack_p256_recs(recs):
        if not recs:
            return [], None
        from fabric_tpu.ops import p256 as p256mod
        keep = [rec[0] for rec in recs]
        qx = p256mod.bytes32_to_words([rec[1][1:33] for rec in recs])
        qy = p256mod.bytes32_to_words([rec[1][33:65] for rec in recs])
        r = p256mod.bytes32_to_words([rec[2] for rec in recs])
        s = p256mod.bytes32_to_words([rec[3] for rec in recs])
        e = p256mod.bytes32_to_words([rec[4] for rec in recs])
        return keep, [qx, qy, r, s, e]

    def _pad(self, arrays, n: int):
        b = _bucket(n)
        if self.mesh is not None:
            # equal per-device tiles: the bucket must split evenly over
            # the mesh (power-of-two buckets already divide power-of-two
            # meshes; the rounding covers odd carved sub-mesh sizes)
            size = self.mesh.devices.size
            b = max(b, size)
            b += (-b) % size
        out = []
        for a in arrays:
            a = np.asarray(a)
            pad = b - a.shape[-1]
            widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
            out.append(np.pad(a, widths))
        return out

    # -- dispatch helpers ---------------------------------------------------

    # lane-fill histogram bins: how full the padded device buckets run
    _FILL_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0,
                     float("inf"))

    def _per_device_slots(self, real: int, padded: int,
                          per_device=None) -> list:
        """[(device_label, real_d, slots_d)] for one dispatch.  Sharded
        batches are laid out contiguously over the mesh, real slots
        first, so each device's real count is a clamped prefix share;
        lanes whose pad slots interleave (rows) pass explicit counts."""
        if per_device is not None:
            return per_device
        labels = self.device_labels
        tile, rem = divmod(padded, len(labels))
        if rem:        # non-mesh-divisible dispatch: charge device 0
            return [(labels[0], real, padded)]
        return [(dev, min(max(real - i * tile, 0), tile), tile)
                for i, dev in enumerate(labels)]

    def _observe_lane(self, lane: str, real: int, padded: int,
                      per_device=None) -> None:
        """Per-dispatch batching-economics telemetry: lane fill fraction
        and padded-slot waste into the ops_plane registry (the live
        counterpart of bench.py's one-shot occupancy numbers), broken
        out per device tile so a chip running empty shards is visible.
        Guarded: observability must never break the dispatch hot path."""
        try:
            from fabric_tpu.ops_plane import registry
            fill = (real / padded) if padded else 1.0
            fill_g = registry.gauge(
                "provider_lane_fill_fraction",
                "real signatures / padded device slots, last dispatch")
            pad_c = registry.counter(
                "provider_pad_slots_total",
                "padded device slots carrying no real signature")
            slot_c = registry.counter(
                "provider_lane_slots_total",
                "device slots dispatched (real + pad)")
            for dev, r_d, s_d in self._per_device_slots(
                    real, padded, per_device):
                fill_g.set((r_d / s_d) if s_d else 1.0,
                           lane=lane, device=dev)
                pad_c.add(float(s_d - r_d), lane=lane, device=dev)
                slot_c.add(float(s_d), lane=lane, device=dev)
            registry.histogram(
                "provider_lane_fill",
                "per-dispatch lane fill fraction",
                buckets=self._FILL_BUCKETS).observe(fill, lane=lane)
        except Exception:
            pass

    def _dispatch(self, fn, keep, arrays, pending, extra_args=()):
        """Pad to buckets, chunk beyond MAX_BUCKET (bounds the compiled-
        program set while arbitrarily large blocks still use the device),
        ENQUEUE the device calls (jax dispatch is async), and record
        (keep, out) pairs for the resolve step."""
        for lo in range(0, len(keep), MAX_BUCKET):
            hi = min(lo + MAX_BUCKET, len(keep))
            chunk = [a[..., lo:hi] for a in arrays]
            padded = self._pad(chunk, hi - lo)
            out = fn(*extra_args, *padded)
            self.stats["dispatches"] += 1
            self.stats["device_sigs"] += hi - lo
            self.stats["h2d_bytes"] += sum(
                np.asarray(a).nbytes for a in padded)
            self._observe_lane("generic", hi - lo,
                               int(np.asarray(padded[0]).shape[-1]))
            pending.append((keep[lo:hi], out))

    # Row-grid geometry for the fast lane (ops/p256_fixed.verify_words_
    # rows): signatures pack key-major into rows of FAST_ROW_C lanes, so
    # ANY number of cached keys rides the comb path at constant per-sig
    # cost (the round-3 joint-one-hot kernel capped NK at 4 and spilled
    # the rest to the generic ladder).  Row counts bucket in ~1.5x steps,
    # bounding the compiled-program set; the table bank is device-
    # resident with a FIXED shape (ops/device_bank.py), so it never
    # enters the program signature and row_key values are bank slot
    # indices.  Padding rows repeat real signatures and their slots are
    # dropped at resolve time.
    FAST_ROW_C = int(__import__("os").environ.get(
        "FABRIC_TPU_FAST_ROW_C", "128"))
    # deliberately coarse (~9 programs): every bucket is a multi-minute
    # cold XLA compile; padding waste at most ~2x on small dispatches
    # where the device is idle anyway.  96 exists because ~10k-sig
    # single-key-family batches land at ~80 rows (128 would pad +60%).
    ROW_BUCKETS = (4, 16, 64, 96, 128, 256, 384, 512, 1024)
    # Soft per-dispatch row cap.  Default = the top bucket (one merged
    # dispatch): on relayed/tunneled transports each dispatch costs a
    # round trip, and A/B on the axon tunnel measured splitting at
    # 128/192 rows LOSING ~40% vs one 384-row dispatch (0.60 s vs
    # 0.37 s steady on the 40k-sig block).  Co-located deployments can
    # lower it to overlap host packing with device compute.
    ROWS_CHUNK = int(__import__("os").environ.get(
        "FABRIC_TPU_ROWS_CHUNK", "1024"))

    def _verify_p256(self, items, idxs, pending):
        """Two-lane P-256 dispatch: signatures under device-resident (or
        residency-worthy) public keys take the row-grouped fixed-base
        comb kernel in ONE merged dispatch — the key-repetitive
        endorsement workload of SURVEY.md §3.2 — and the rest take the
        generic windowed-ladder kernel.  Dispatches are merged because
        relayed TPU transports charge a full round trip per dispatch.

        Lane cost model: a resident key's signatures always ride the
        comb lane (zero marginal transfer — the bank lives in HBM and
        dispatches carry slot indices only); a non-resident key earns a
        slot only when this batch brings >= fast_key_threshold
        signatures, amortizing the ~150 ms host table build + 1.4 MB
        one-time upload.

        Packing is numpy-vectorized end to end (the C DER batch parse +
        array gathers): per-signature Python work was ~60% of the
        steady-state host time at 40k sigs/block.  The rec-based path
        below remains as the no-compiler fallback and differential
        oracle."""
        parse = _parse_der_sigs()
        if parse is None:
            return self._verify_p256_recs(items, idxs, pending)
        n = len(idxs)
        sigs = [None] * n
        pays = [None] * n
        key_ids = np.empty(n, np.int64)
        pay_ok = np.empty(n, bool)
        pk_map = {}
        pks = []
        for j, i in enumerate(idxs):
            it = items[i]
            sigs[j] = it.signature
            p = it.payload
            if len(p) == 32:
                pays[j] = p
                pay_ok[j] = True
            else:
                pays[j] = _ZERO32
                pay_ok[j] = False
            gid = pk_map.get(it.pubkey)
            if gid is None:
                gid = pk_map[it.pubkey] = len(pks)
                pks.append(it.pubkey)
            key_ids[j] = gid
        ok, rs = parse(sigs)
        G = len(pks)
        pk_ok = np.empty(G, bool)
        for g, pk in enumerate(pks):
            pk_ok[g] = len(pk) == 65 and pk[0] == 0x04
        valid = (np.frombuffer(ok, np.uint8).astype(bool)
                 & pay_ok & pk_ok[key_ids])
        self.stats["host_rejects"] += n - int(valid.sum())
        if not valid.any():
            return
        rsw = np.frombuffer(rs, ">u4").reshape(n, 16).astype(np.uint32)
        ew = np.frombuffer(b"".join(pays), ">u4").reshape(n, 8).astype(
            np.uint32)
        idxs_np = np.asarray(idxs, np.int64)
        counts = np.bincount(key_ids[valid], minlength=G)
        slots = np.full(G, -1, np.int64)
        # biggest groups claim slots first; each claimed slot is PINNED
        # in the bank until the rows dispatch has captured the bank
        # array — a later build (this batch or a concurrent one on
        # another thread) must not evict it, or its rows would verify
        # against the wrong table
        pinned = set()
        try:
            for g in np.argsort(-counts, kind="stable"):
                g = int(g)
                if not pk_ok[g] or not counts[g]:
                    continue
                pk = pks[g]
                slot = self.key_tables.lookup(pk, pin=True)
                if slot is None and counts[g] >= self.fast_key_threshold:
                    slot = self.key_tables.get_or_build(pk, pin=True)
                if slot is not None:
                    pinned.add(slot)
                    slots[g] = slot
            fsel = np.nonzero(valid & (slots[key_ids] >= 0))[0]
            if fsel.size:
                self._dispatch_rows_vec(fsel, key_ids, slots, rsw, ew,
                                        idxs_np, pending)
        finally:
            self.key_tables.unpin(pinned)
        gsel = np.nonzero(valid & (slots[key_ids] < 0))[0]
        if gsel.size:
            gids = np.unique(key_ids[gsel])
            remap = np.full(G, -1, np.int64)
            remap[gids] = np.arange(gids.size)
            pkb = np.frombuffer(
                b"".join(pks[g] for g in gids), np.uint8).reshape(-1, 65)
            qxw = np.ascontiguousarray(pkb[:, 1:33]).reshape(-1).view(
                ">u4").astype(np.uint32).reshape(-1, 8)
            qyw = np.ascontiguousarray(pkb[:, 33:65]).reshape(-1).view(
                ">u4").astype(np.uint32).reshape(-1, 8)
            rows = remap[key_ids[gsel]]
            arrays = [np.ascontiguousarray(qxw[rows].T),
                      np.ascontiguousarray(qyw[rows].T),
                      np.ascontiguousarray(rsw[gsel, :8].T),
                      np.ascontiguousarray(rsw[gsel, 8:].T),
                      np.ascontiguousarray(ew[gsel].T)]
            self._dispatch(self._get_fn(SCHEME_P256), idxs_np[gsel],
                           arrays, pending)

    def _dispatch_rows_vec(self, sel, key_ids, slots, rsw, ew, idxs_np,
                           pending):
        """Vectorized rows-lane packing: key-major (R, C) grid built by
        numpy gathers over the batch word arrays; chunked by
        ROWS_CHUNK/ROW_BUCKETS like the rec path."""
        C = self.fast_row_c
        order = sel[np.argsort(key_ids[sel], kind="stable")]
        gids, starts, ngs = np.unique(key_ids[order], return_index=True,
                                      return_counts=True)
        sel_rows, slot_rows, row_key = [], [], []
        # largest groups first: keeps per-dispatch row chunks dense
        for t in np.argsort(-ngs, kind="stable"):
            g = int(gids[t])
            s0 = int(starts[t])
            ng = int(ngs[t])
            grp = order[s0:s0 + ng]
            n_rows = -(-ng // C)
            pad = n_rows * C - ng
            so = idxs_np[grp]
            if pad:
                grp = np.concatenate([grp, np.full(pad, grp[0], np.int64)])
                so = np.concatenate([so, np.full(pad, -1, np.int64)])
            sel_rows.append(grp.reshape(n_rows, C))
            slot_rows.append(so.reshape(n_rows, C))
            row_key.extend([int(slots[g])] * n_rows)
        sel_grid = np.concatenate(sel_rows)
        slot_grid = np.concatenate(slot_rows)
        row_key = np.asarray(row_key, np.int32)
        R = sel_grid.shape[0]
        fn = self._get_fn("p256-rows")
        bank = self.key_tables.array()
        max_rows = min(self.ROW_BUCKETS[-1], max(self.rows_chunk, 1))
        for lo in range(0, R, max_rows):
            hi = min(lo + max_rows, R)
            sg, rk, og = sel_grid[lo:hi], row_key[lo:hi], slot_grid[lo:hi]
            Rb = next(b for b in self.ROW_BUCKETS if b >= hi - lo)
            if self.mesh is not None:
                size = self.mesh.devices.size
                while Rb % size:
                    Rb += 1
            if Rb > hi - lo:
                padrows = Rb - (hi - lo)
                sg = np.concatenate([sg, np.repeat(sg[:1], padrows, 0)])
                rk = np.concatenate([rk, np.repeat(rk[:1], padrows)])
                og = np.concatenate(
                    [og, np.full((padrows, C), -1, np.int64)])
            flat = sg.reshape(-1)
            words = [
                np.ascontiguousarray(rsw[flat, :8].T).reshape(8, Rb, C),
                np.ascontiguousarray(rsw[flat, 8:].T).reshape(8, Rb, C),
                np.ascontiguousarray(ew[flat].T).reshape(8, Rb, C)]
            out = fn(bank, rk, *words)
            self.stats["h2d_bytes"] += (
                sum(w.nbytes for w in words) + rk.nbytes)
            self._enqueue_rows_out(out, og.reshape(-1), pending)

    def _verify_p256_recs(self, items, idxs, pending):
        """Rec-based fallback lane split (no C extension)."""
        recs = self._parse_p256(items, idxs)
        groups = {}
        for rec in recs:
            groups.setdefault(rec[1], []).append(rec)
        generic, fast = [], []
        pinned = set()
        try:
            for pk, g in sorted(groups.items(),
                                key=lambda kv: -len(kv[1])):
                slot = self.key_tables.lookup(pk, pin=True)
                if slot is None and len(g) >= self.fast_key_threshold:
                    slot = self.key_tables.get_or_build(pk, pin=True)
                if slot is None:
                    generic.extend(g)
                else:
                    pinned.add(slot)
                    fast.append((slot, g))
            # largest groups first: keeps per-dispatch row chunks dense
            fast.sort(key=lambda t: -len(t[1]))
            if fast:
                self._dispatch_rows(fast, pending)
        finally:
            self.key_tables.unpin(pinned)
        generic.sort(key=lambda rec: rec[0])
        keep, arrays = self._pack_p256_recs(generic)
        if keep:
            self._dispatch(self._get_fn(SCHEME_P256), keep, arrays, pending)

    def _row_chunks(self, fast):
        """Pack (bank_slot, group) pairs into row-grid chunks:
        [(row_key, flat_recs, slots, Rb)], each at most the top row
        bucket, row counts padded to a bucket (and to the mesh size),
        padding slots marked -1 (dropped at resolve).  row_key entries
        are device-bank slot indices — no per-chunk table list."""
        C = self.fast_row_c
        max_rows = min(self.ROW_BUCKETS[-1], max(self.rows_chunk, 1))
        chunks = []
        cur = {"row_key": [], "recs": [], "slots": []}

        def close():
            if cur["row_key"]:
                chunks.append((cur["row_key"], cur["recs"], cur["slots"]))
                cur.update(row_key=[], recs=[], slots=[])

        for bank_slot, g in fast:
            gi = 0
            while gi < len(g):
                room = max_rows - len(cur["row_key"])
                if room == 0:
                    close()
                    room = max_rows
                take = min(len(g) - gi, room * C)
                part = g[gi:gi + take]
                gi += take
                n_rows = -(-len(part) // C)
                pad = n_rows * C - len(part)
                cur["row_key"].extend([bank_slot] * n_rows)
                cur["recs"].extend(part)
                cur["recs"].extend([part[0]] * pad)   # repeat; dropped
                cur["slots"].extend([rec[0] for rec in part])
                cur["slots"].extend([-1] * pad)
        close()

        out = []
        for row_key, frecs, slots in chunks:
            R = len(row_key)
            Rb = next(b for b in self.ROW_BUCKETS if b >= R)
            if self.mesh is not None:
                size = self.mesh.devices.size
                while Rb % size:
                    Rb += 1
            if Rb > R:
                frecs = frecs + [frecs[0]] * ((Rb - R) * C)
                slots = slots + [-1] * ((Rb - R) * C)
                row_key = row_key + [row_key[0]] * (Rb - R)
            out.append((row_key, frecs, slots, Rb))
        return out

    def _enqueue_rows_out(self, out, slots, pending):
        self.stats["dispatches"] += 1
        slots_np = np.asarray(slots)
        valid = slots_np >= 0
        keep = slots_np[valid]
        self.stats["device_sigs"] += len(keep)
        self.stats["fast_key_sigs"] += len(keep)
        # rows-lane pad slots interleave (within-row pad + pad rows), so
        # the per-device split counts the valid mask over each device's
        # contiguous row range instead of assuming a real-slot prefix
        per_device = None
        n_dev = len(self.device_labels)
        if len(slots_np) % n_dev == 0:
            chunk = len(slots_np) // n_dev
            per_device = [
                (dev, int(valid[i * chunk:(i + 1) * chunk].sum()), chunk)
                for i, dev in enumerate(self.device_labels)]
        self._observe_lane("rows", len(keep), len(slots_np),
                           per_device=per_device)
        pending.append(
            (keep,
             lambda out=out, valid=valid:
                 np.asarray(out).reshape(-1)[valid]))

    def _dispatch_rows(self, fast, pending):
        """P-256 row-grid dispatches (fast: [(bank_slot, recs)], recs:
        (idx, pk, r32, s32, e32)).  The table bank is already in HBM —
        only r/s/e words and the slot vector cross host->device."""
        from fabric_tpu.ops import p256 as p256mod
        C = self.fast_row_c
        fn = self._get_fn("p256-rows")
        bank = self.key_tables.array()
        for row_key, frecs, slots, Rb in self._row_chunks(fast):
            words = [p256mod.bytes32_to_words(
                [rec[j] for rec in frecs]).reshape(8, Rb, C)
                for j in (2, 3, 4)]
            rk = np.asarray(row_key, dtype=np.int32)
            out = fn(bank, rk, *words)
            self.stats["h2d_bytes"] += (
                sum(w.nbytes for w in words) + rk.nbytes)
            self._enqueue_rows_out(out, slots, pending)

    def _dispatch_ed_rows(self, fast, pending):
        """ed25519 row-grid dispatches (fast: [(bank_slot, recs)], recs:
        (idx, pk, sig, msg))."""
        from fabric_tpu.ops import ed25519 as edmod
        C = self.fast_row_c
        fn = self._get_fn("ed25519-rows")
        bank = self.ed_key_tables.array()
        for row_key, frecs, slots, Rb in self._row_chunks(fast):
            ay, a_sign, ry, r_sign, s, k = edmod.pack_verify_inputs(
                [rec[1] for rec in frecs], [rec[2] for rec in frecs],
                [rec[3] for rec in frecs])
            rk = np.asarray(row_key, dtype=np.int32)
            args = (ry.reshape(8, Rb, C),
                    r_sign.reshape(Rb, C).astype(np.int32),
                    s.reshape(8, Rb, C), k.reshape(8, Rb, C))
            out = fn(bank, rk, *args)
            self.stats["h2d_bytes"] += (
                sum(np.asarray(a).nbytes for a in args) + rk.nbytes)
            self._enqueue_rows_out(out, slots, pending)

    def _verify_ed25519(self, items, idxs, pending):
        """Two-lane ed25519 dispatch (the P-256 design): cached-A keys
        ride the all-comb row kernel; the rest decompress A on device
        and take the comb+ladder generic kernel."""
        recs = []
        for i in idxs:
            it = items[i]
            if len(it.pubkey) != 32 or len(it.signature) != 64:
                self.stats["host_rejects"] += 1
                continue
            recs.append((i, it.pubkey, it.signature, it.payload))
        groups = {}
        for rec in recs:
            groups.setdefault(rec[1], []).append(rec)
        fast, generic = [], []
        pinned = set()
        try:
            for pk, g in sorted(groups.items(),
                                key=lambda kv: -len(kv[1])):
                slot = self.ed_key_tables.lookup(pk, pin=True)
                if slot is None and len(g) >= self.fast_key_threshold:
                    slot = self.ed_key_tables.get_or_build(pk, pin=True)
                if slot is None:
                    generic.extend(g)
                else:
                    pinned.add(slot)
                    fast.append((slot, g))
            fast.sort(key=lambda t: -len(t[1]))
            if fast:
                self._dispatch_ed_rows(fast, pending)
        finally:
            self.ed_key_tables.unpin(pinned)
        generic.sort(key=lambda rec: rec[0])
        if generic:
            from fabric_tpu.ops import ed25519 as edmod
            keep = [rec[0] for rec in generic]
            arrays = list(edmod.pack_verify_inputs(
                [rec[1] for rec in generic], [rec[2] for rec in generic],
                [rec[3] for rec in generic]))
            self._dispatch(self._get_fn(SCHEME_ED25519), keep, arrays,
                           pending)

    # -- idemix: batched BN254 pairing checks (BASELINE config 4) -----------

    IDEMIX_MIN_BUCKET = 16

    def _idemix_packed(self, ipk_bytes: bytes):
        """Per-issuer Miller-loop line precompute (w side), cached; the
        g2 side is global.  ~0.2 s host build per issuer, amortized."""
        cache = getattr(self, "_idemix_pack_cache", None)
        if cache is None:
            cache = self._idemix_pack_cache = {}
        packed = cache.get(ipk_bytes)
        if packed is None:
            from fabric_tpu.idemix import bn254 as hb
            from fabric_tpu.idemix.msp import deserialize_ipk
            from fabric_tpu.ops import bn254_batch as bb
            ipk = deserialize_ipk(ipk_bytes)
            packed = bb.pack_steps(hb.ate_precompute(ipk.w))
            cache[ipk_bytes] = packed
        return packed

    def _idemix_g2_packed(self):
        packed = getattr(self, "_idemix_g2_pack", None)
        if packed is None:
            from fabric_tpu.idemix import bn254 as hb
            from fabric_tpu.ops import bn254_batch as bb
            packed = bb.pack_steps(hb.ate_precompute(hb.G2_GEN))
            self._idemix_g2_pack = packed
        return packed

    def _verify_idemix(self, items, idxs, pending):
        """Host structural/ZK checks + ONE batched device dispatch per
        issuer for the pairing equation e(A', w) == e(Abar, g2) —
        replacing ~1.3 s of host pairing per presentation
        (/root/reference/idemix/signature.go:230 Ver's pairing check;
        the reference runs it in amcl Go loops per signature)."""
        import jax
        import os
        on_cpu = jax.default_backend() == "cpu"
        if on_cpu and os.environ.get("FABRIC_TPU_IDEMIX_DEVICE") != "1":
            # CPU backend: the eager tower-field kernel is slower than
            # host python ints — keep the host path
            idemix_items = [items[i] for i in idxs]

            def _idemix_out(its=idemix_items):
                from fabric_tpu.idemix.msp import verify_item_host
                return np.array([verify_item_host(it) for it in its],
                                dtype=bool)
            pending.append((idxs, _idemix_out))
            return

        from fabric_tpu.idemix import bn254 as hb
        from fabric_tpu.idemix.msp import collect_item_parts
        from fabric_tpu.ops import bignum as bnmod

        groups = {}
        for i in idxs:
            ok, key, pair = collect_item_parts(items[i])
            if not ok:
                continue              # verdict stays False
            groups.setdefault(key, []).append((i, pair[0], pair[1]))
        fn = self._get_fn("idemix-pair")
        packed_g2 = self._idemix_g2_packed()
        for key, g in groups.items():
            packed_w = self._idemix_packed(key)
            b = self.IDEMIX_MIN_BUCKET
            while b < len(g):
                b <<= 1
            if self.mesh is not None:
                size = int(np.asarray(self.mesh.devices).size)
                b = max(b, size)
                b += (-b) % size
            padded = g + [g[0]] * (b - len(g))
            # P2 = -Abar: the kernel checks e(P1, w) * e(P2, g2) == 1
            x1 = np.stack([bnmod.int_to_limbs(p[1][0]) for p in padded], 1)
            y1 = np.stack([bnmod.int_to_limbs(p[1][1]) for p in padded], 1)
            x2 = np.stack([bnmod.int_to_limbs(p[2][0]) for p in padded], 1)
            y2 = np.stack([bnmod.int_to_limbs((hb.P - p[2][1]) % hb.P)
                           for p in padded], 1)
            out = fn(packed_w["flags"], packed_w["A"], packed_w["B"],
                     packed_g2["A"], packed_g2["B"], x1, y1, x2, y2)
            self.stats["dispatches"] += 1
            self.stats["device_sigs"] += len(g)
            self._observe_lane("idemix", len(g), b)
            pending.append(([p[0] for p in g], out))

    def idemix_pair_probe(self, batch: int = None):
        """(fn, green_args, red_args) for the BN254 dual-pairing lane:
        green checks e(G1,g2)*e(-G1,g2)==1, red e(G1,g2)^2==1 (both
        on-curve).  One shared probe for warmup and bench — the callers
        must not each reach into the kernel privates."""
        from fabric_tpu.idemix import bn254 as hbn
        from fabric_tpu.ops import bignum as bnmod
        b = batch or self.IDEMIX_MIN_BUCKET
        fn = self._get_fn("idemix-pair")
        packed = self._idemix_g2_packed()
        g1 = hbn.G1_GEN
        x1 = np.stack([bnmod.int_to_limbs(g1[0])] * b, 1)
        y1 = np.stack([bnmod.int_to_limbs(g1[1])] * b, 1)
        y2 = np.stack([bnmod.int_to_limbs((hbn.P - g1[1]) % hbn.P)] * b, 1)
        base = (packed["flags"], packed["A"], packed["B"],
                packed["A"], packed["B"], x1, y1, x1)
        return fn, base + (y2,), base + (y1,)

    # -- the batch verbs ----------------------------------------------------

    def batch_verify_async(self, items: Sequence[VerifyItem]):
        """Enqueue device verification and return resolve() -> bool[N].

        The device work races ahead while the caller keeps collecting
        (SURVEY.md §7 hard-part #3 overlap); resolve() blocks on the
        results.  Fallback stays atomic: ANY device failure — at enqueue
        or at resolve — recomputes the whole batch on the sw provider."""
        from fabric_tpu.ops_plane import tracing
        items = list(items)
        verdicts = np.zeros(len(items), dtype=bool)
        pending = []
        # device-time bridge: one span per dispatched batch, started at
        # enqueue on the caller's trace and ended from whichever thread
        # resolves it, carrying batch size, block_until_ready wall time
        # and the cache-hit deltas from stats_snapshot()
        span = tracing.tracer.start_span(
            "bccsp.batch_verify", require_parent=True,
            attributes={"provider": self.name, "batch_size": len(items)})
        snap0 = self.stats_snapshot() if span.recording else None
        try:
            by_scheme = {}
            for i, it in enumerate(items):
                by_scheme.setdefault(it.scheme, []).append(i)
            for scheme, idxs in by_scheme.items():
                if scheme == SCHEME_P256:
                    self._verify_p256(items, idxs, pending)
                elif scheme == SCHEME_IDEMIX:
                    self._verify_idemix(items, idxs, pending)
                elif scheme == SCHEME_ED25519:
                    self._verify_ed25519(items, idxs, pending)
                else:
                    self.stats["host_rejects"] += len(idxs)
        except Exception:
            logger.exception(
                "TPU dispatch failed; falling back to sw provider")
            self.stats["fallbacks"] += 1
            span.set_attribute("fallback", "dispatch")

            def resolve_fallback():
                try:
                    return self.fallback.batch_verify(items)
                finally:
                    span.end(status="ERROR")

            return resolve_fallback

        # in-flight device work between enqueue and resolve (decremented
        # once in resolve, success or fallback)
        try:
            from fabric_tpu.ops_plane import registry as _reg
            _reg.gauge("provider_dispatch_queue_depth",
                       "device dispatches enqueued, not yet resolved"
                       ).add(float(len(pending)))
        except Exception:
            pass

        def resolve():
            import time as _time
            t0 = _time.perf_counter()
            try:
                for keep, out in pending:
                    if callable(out):
                        out = out()
                    verdicts[np.asarray(keep)] = np.asarray(out)[:len(keep)]
            except Exception:
                logger.exception(
                    "TPU resolve failed; falling back to sw provider")
                self.stats["fallbacks"] += 1
                span.set_attribute("fallback", "resolve")
                span.end(status="ERROR")
                self._drain_queue_depth(len(pending))
                return self.fallback.batch_verify(items)
            wall = _time.perf_counter() - t0
            self._drain_queue_depth(len(pending))
            if span.recording:
                snap1 = self.stats_snapshot()
                span.set_attribute("block_until_ready_s", round(wall, 6))
                span.set_attribute(
                    "dispatches", snap1.dispatches - snap0.dispatches)
                span.set_attribute(
                    "device_sigs", snap1.device_sigs - snap0.device_sigs)
                span.set_attribute(
                    "fast_key_sigs",
                    snap1.fast_key_sigs - snap0.fast_key_sigs)
                span.set_attribute(
                    "table_builds",
                    (snap1.p256_table_builds - snap0.p256_table_builds)
                    + (snap1.ed25519_table_builds
                       - snap0.ed25519_table_builds))
                span.end()
            try:
                # device-phase observability (the jax.profiler trace is
                # the deep view; these are the always-on numbers):
                # resolve wall time ~= device tail not hidden by overlap
                from fabric_tpu.ops_plane import registry
                registry.histogram(
                    "provider_resolve_seconds",
                    "batch_verify device resolve wait").observe(wall)
                registry.gauge(
                    "provider_device_sync_seconds",
                    "last batch_verify device-sync (resolve) wait"
                    ).set(wall)
                registry.counter(
                    "provider_device_sigs_total",
                    "signatures resolved on device").add(len(items))
            except Exception:
                pass
            return verdicts

        return resolve

    def _drain_queue_depth(self, n: int) -> None:
        if not n:
            return
        try:
            from fabric_tpu.ops_plane import registry
            registry.gauge("provider_dispatch_queue_depth",
                           "device dispatches enqueued, not yet resolved"
                           ).add(-float(n))
        except Exception:
            pass

    def batch_verify(self, items: Sequence[VerifyItem]) -> np.ndarray:
        return self.batch_verify_async(items)()
