"""JAX/TPU batched BCCSP provider — the hardware slot of the framework.

Occupies the position the reference gives PKCS#11 HSMs (bccsp/pkcs11,
gated by bccsp/factory — SURVEY.md §2.1.1), but instead of one-at-a-time
HSM calls it dispatches the whole batch to the TPU kernels in
fabric_tpu.ops.  Signing and key-gen delegate to the software provider
(private keys never touch the TPU).

Host/device split per the reference's own design (msp/identities.go:178):
variable-length parsing (DER signatures, SEC1 points, RFC 8032 encodings,
SHA-512 for ed25519) happens on host; the device sees only fixed-size
word arrays.

Batching strategy: items are grouped by scheme, packed into word arrays,
and padded to power-of-two buckets so XLA compiles a small, reusable set
of programs.  Malformed items short-circuit to False on the host.
If device dispatch fails entirely, the whole batch falls back to the
software provider atomically (SURVEY.md §7 hard-part #5: fallback must be
atomic to keep determinism).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np

from cryptography.hazmat.primitives.asymmetric.utils import decode_dss_signature

from . import provider as prov
from .provider import VerifyItem, SCHEME_P256, SCHEME_ED25519
from .sw import SoftwareProvider

logger = logging.getLogger("fabric_tpu.bccsp.jaxtpu")

MIN_BUCKET = 128
MAX_BUCKET = 1 << 17


def _bucket(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


class JaxTpuProvider(prov.Provider):
    name = "jaxtpu"

    def __init__(self, require_low_s: bool = True, mesh=None,
                 fallback: Optional[SoftwareProvider] = None):
        self.require_low_s = require_low_s
        self.mesh = mesh
        self.fallback = fallback or SoftwareProvider(require_low_s=require_low_s)
        self._fns = {}
        self.stats = {"dispatches": 0, "device_sigs": 0, "host_rejects": 0,
                      "fallbacks": 0}

    # signing / key-gen are host-side: delegate
    def key_gen(self, scheme: str):
        return self.fallback.key_gen(scheme)

    def sign(self, private_key, payload: bytes) -> bytes:
        return self.fallback.sign(private_key, payload)

    # -- device plumbing ----------------------------------------------------

    def _get_fn(self, scheme: str):
        key = scheme
        if key not in self._fns:
            import jax
            if scheme == SCHEME_P256:
                import os
                low_s = self.require_low_s
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_p256_verify(self.mesh, self.require_low_s)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif os.environ.get("FABRIC_TPU_PALLAS") == "1":
                    # experimental fused kernel (see ops/p256_pallas.py)
                    from fabric_tpu.ops import p256_pallas
                    self._fns[key] = lambda *a: p256_pallas.verify_words(
                        *a, require_low_s=low_s)
                else:
                    # round-2 windowed flat path (ops/ecp256).  On CPU the
                    # big scan bodies hit an XLA:CPU compile pathology, so
                    # run eagerly there (per-primitive jits, see flatfield).
                    from fabric_tpu.ops import ecp256
                    if jax.default_backend() == "cpu":
                        self._fns[key] = lambda *a: ecp256.verify_words_xla(
                            *a, require_low_s=low_s)
                    else:
                        from fabric_tpu.ops import bignum as _bn
                        tab = ecp256.comb_table_f32()

                        # words->limbs conversion inside the jit: eager
                        # conversion costs tunneled dispatches per call
                        def whole(qx, qy, r, s, e, _tab=tab):
                            args = [_bn.words_be_to_limbs(v)
                                    for v in (qx, qy, r, s, e)]
                            return ecp256.verify_body(
                                *args, _tab, require_low_s=low_s)
                        self._fns[key] = jax.jit(whole)
            elif scheme == SCHEME_ED25519:
                from fabric_tpu.ops import ed25519
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_ed25519_verify(self.mesh)
                    self._fns[key] = lambda *a: f(*a)[0]
                else:
                    self._fns[key] = jax.jit(ed25519.verify_words)
            else:
                raise ValueError(f"unsupported scheme {scheme!r}")
        return self._fns[key]

    def _pack_p256(self, items, idxs):
        """-> (ok_idx, arrays) with malformed items dropped (verdict False)."""
        qx, qy, r, s, e, keep = [], [], [], [], [], []
        for i in idxs:
            it = items[i]
            try:
                pk = it.pubkey
                if len(pk) != 65 or pk[0] != 0x04:
                    raise ValueError("bad SEC1 point")
                if len(it.payload) != 32:
                    raise ValueError("p256 payload must be a 32B digest")
                ri, si = decode_dss_signature(it.signature)
                if not (0 < ri < (1 << 256) and 0 < si < (1 << 256)):
                    raise ValueError("r/s out of range")
            except Exception:
                self.stats["host_rejects"] += 1
                continue
            qx.append(int.from_bytes(pk[1:33], "big"))
            qy.append(int.from_bytes(pk[33:65], "big"))
            r.append(ri)
            s.append(si)
            e.append(int.from_bytes(it.payload, "big"))
            keep.append(i)
        if not keep:
            return [], None
        from fabric_tpu.ops import p256 as p256mod
        arrays = [p256mod.ints_to_words(v) for v in (qx, qy, r, s, e)]
        return keep, arrays

    def _pack_ed25519(self, items, idxs):
        keep, pks, sigs, msgs = [], [], [], []
        for i in idxs:
            it = items[i]
            if len(it.pubkey) != 32 or len(it.signature) != 64:
                self.stats["host_rejects"] += 1
                continue
            keep.append(i)
            pks.append(it.pubkey)
            sigs.append(it.signature)
            msgs.append(it.payload)
        if not keep:
            return [], None
        from fabric_tpu.ops import ed25519 as edmod
        arrays = list(edmod.pack_verify_inputs(pks, sigs, msgs))
        return keep, arrays

    def _pad(self, arrays, n: int):
        b = _bucket(n)
        if self.mesh is not None:
            size = self.mesh.devices.size
            b = max(b, size)
        out = []
        for a in arrays:
            a = np.asarray(a)
            pad = b - a.shape[-1]
            widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
            out.append(np.pad(a, widths))
        return out

    # -- the batch verb -----------------------------------------------------

    def batch_verify(self, items: Sequence[VerifyItem]) -> np.ndarray:
        verdicts = np.zeros(len(items), dtype=bool)
        by_scheme = {}
        for i, it in enumerate(items):
            by_scheme.setdefault(it.scheme, []).append(i)
        try:
            for scheme, idxs in by_scheme.items():
                if scheme == SCHEME_P256:
                    keep, arrays = self._pack_p256(items, idxs)
                elif scheme == SCHEME_ED25519:
                    keep, arrays = self._pack_ed25519(items, idxs)
                else:
                    self.stats["host_rejects"] += len(idxs)
                    continue  # unknown scheme: all False
                if not keep:
                    continue
                fn = self._get_fn(scheme)
                # chunk batches beyond MAX_BUCKET so the compiled-program set
                # stays bounded while arbitrarily large blocks still use TPU
                for lo in range(0, len(keep), MAX_BUCKET):
                    hi = min(lo + MAX_BUCKET, len(keep))
                    chunk = [a[..., lo:hi] for a in arrays]
                    padded = self._pad(chunk, hi - lo)
                    out = np.asarray(fn(*padded))[:hi - lo]
                    self.stats["dispatches"] += 1
                    self.stats["device_sigs"] += hi - lo
                    verdicts[np.asarray(keep[lo:hi])] = out
        except Exception:
            # atomic fallback: recompute the WHOLE batch on the sw provider
            logger.exception("TPU dispatch failed; falling back to sw provider")
            self.stats["fallbacks"] += 1
            return self.fallback.batch_verify(items)
        return verdicts
