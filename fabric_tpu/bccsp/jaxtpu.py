"""JAX/TPU batched BCCSP provider — the hardware slot of the framework.

Occupies the position the reference gives PKCS#11 HSMs (bccsp/pkcs11,
gated by bccsp/factory — SURVEY.md §2.1.1), but instead of one-at-a-time
HSM calls it dispatches the whole batch to the TPU kernels in
fabric_tpu.ops.  Signing and key-gen delegate to the software provider
(private keys never touch the TPU).

Host/device split per the reference's own design (msp/identities.go:178):
variable-length parsing (DER signatures, SEC1 points, RFC 8032 encodings,
SHA-512 for ed25519) happens on host; the device sees only fixed-size
word arrays.

Batching strategy: items are grouped by scheme, packed into word arrays,
and padded to power-of-two buckets so XLA compiles a small, reusable set
of programs.  Malformed items short-circuit to False on the host.
If device dispatch fails entirely, the whole batch falls back to the
software provider atomically (SURVEY.md §7 hard-part #5: fallback must be
atomic to keep determinism).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np

from cryptography.hazmat.primitives.asymmetric.utils import decode_dss_signature

from . import provider as prov
from .provider import (VerifyItem, SCHEME_P256, SCHEME_ED25519,
                       SCHEME_IDEMIX)
from .sw import SoftwareProvider

logger = logging.getLogger("fabric_tpu.bccsp.jaxtpu")

MIN_BUCKET = 128
MAX_BUCKET = 1 << 17


def _bucket(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


class JaxTpuProvider(prov.Provider):
    name = "jaxtpu"

    def __init__(self, require_low_s: bool = True, mesh=None,
                 fallback: Optional[SoftwareProvider] = None):
        import os
        self.require_low_s = require_low_s
        self.mesh = mesh
        self.fallback = fallback or SoftwareProvider(require_low_s=require_low_s)
        self._fns = {}
        self.stats = {"dispatches": 0, "device_sigs": 0, "host_rejects": 0,
                      "fallbacks": 0, "fast_key_sigs": 0}
        # per-key fixed-base fast path (ops/p256_fixed.py): keys whose comb
        # table is cached skip the variable-point ladder entirely.  A table
        # build costs ~15 ms host-side, so uncached keys only earn one when
        # a single batch brings at least `fast_key_threshold` signatures
        # (endorser keys easily do; one-off client keys never will).
        from fabric_tpu.ops.p256_tables import KeyTableCache
        self.key_tables = KeyTableCache(
            max_keys=int(os.environ.get("FABRIC_TPU_KEY_CACHE", "64")))
        self.fast_key_threshold = int(
            os.environ.get("FABRIC_TPU_FAST_KEY_THRESHOLD", "1024"))

    # signing / key-gen are host-side: delegate
    def key_gen(self, scheme: str):
        return self.fallback.key_gen(scheme)

    def sign(self, private_key, payload: bytes) -> bytes:
        return self.fallback.sign(private_key, payload)

    # -- device plumbing ----------------------------------------------------

    def _get_fn(self, scheme: str):
        key = scheme
        if key not in self._fns:
            import jax
            if scheme == SCHEME_P256:
                import os
                low_s = self.require_low_s
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_p256_verify(self.mesh, self.require_low_s)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif os.environ.get("FABRIC_TPU_PALLAS") == "1":
                    # experimental fused kernel (see ops/p256_pallas.py)
                    from fabric_tpu.ops import p256_pallas
                    self._fns[key] = lambda *a: p256_pallas.verify_words(
                        *a, require_low_s=low_s)
                else:
                    # round-2 windowed flat path (ops/ecp256).  On CPU the
                    # big scan bodies hit an XLA:CPU compile pathology, so
                    # run eagerly there (per-primitive jits, see flatfield).
                    from fabric_tpu.ops import ecp256
                    if jax.default_backend() == "cpu":
                        self._fns[key] = lambda *a: ecp256.verify_words_xla(
                            *a, require_low_s=low_s)
                    else:
                        from fabric_tpu.ops import bignum as _bn
                        tab = ecp256.comb_table_f32()

                        # words->limbs conversion inside the jit: eager
                        # conversion costs tunneled dispatches per call
                        def whole(qx, qy, r, s, e, _tab=tab):
                            args = [_bn.words_be_to_limbs(v)
                                    for v in (qx, qy, r, s, e)]
                            return ecp256.verify_body(
                                *args, _tab, require_low_s=low_s)
                        self._fns[key] = jax.jit(whole)
            elif scheme == "p256-multikey":
                from fabric_tpu.ops import p256_fixed
                low_s = self.require_low_s
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_p256_multikey_verify(
                        self.mesh, self.require_low_s)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif jax.default_backend() == "cpu":
                    self._fns[key] = (
                        lambda *a: p256_fixed.verify_words_multikey(
                            *a, require_low_s=low_s))
                else:
                    self._fns[key] = jax.jit(
                        lambda *a: p256_fixed.verify_words_multikey(
                            *a, require_low_s=low_s))
            elif scheme == SCHEME_ED25519:
                from fabric_tpu.ops import ed25519
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_ed25519_verify(self.mesh)
                    self._fns[key] = lambda *a: f(*a)[0]
                else:
                    self._fns[key] = jax.jit(ed25519.verify_words)
            else:
                raise ValueError(f"unsupported scheme {scheme!r}")
        return self._fns[key]

    def _parse_p256(self, items, idxs):
        """Host-side parse: -> list of (idx, pubkey, r32, s32, e32) with
        malformed items dropped (verdict stays False)."""
        out = []
        for i in idxs:
            it = items[i]
            try:
                pk = it.pubkey
                if len(pk) != 65 or pk[0] != 0x04:
                    raise ValueError("bad SEC1 point")
                if len(it.payload) != 32:
                    raise ValueError("p256 payload must be a 32B digest")
                ri, si = decode_dss_signature(it.signature)
                if not (0 < ri < (1 << 256) and 0 < si < (1 << 256)):
                    raise ValueError("r/s out of range")
            except Exception:
                self.stats["host_rejects"] += 1
                continue
            out.append((i, pk, ri.to_bytes(32, "big"),
                        si.to_bytes(32, "big"), it.payload))
        return out

    def _pack_p256(self, items, idxs):
        """Generic-lane packing: -> (ok_idx, [qx qy r s e] word arrays)."""
        recs = self._parse_p256(items, idxs)
        return self._pack_p256_recs(recs)

    @staticmethod
    def _pack_p256_recs(recs):
        if not recs:
            return [], None
        from fabric_tpu.ops import p256 as p256mod
        keep = [rec[0] for rec in recs]
        qx = p256mod.bytes32_to_words([rec[1][1:33] for rec in recs])
        qy = p256mod.bytes32_to_words([rec[1][33:65] for rec in recs])
        r = p256mod.bytes32_to_words([rec[2] for rec in recs])
        s = p256mod.bytes32_to_words([rec[3] for rec in recs])
        e = p256mod.bytes32_to_words([rec[4] for rec in recs])
        return keep, [qx, qy, r, s, e]

    def _pack_ed25519(self, items, idxs):
        keep, pks, sigs, msgs = [], [], [], []
        for i in idxs:
            it = items[i]
            if len(it.pubkey) != 32 or len(it.signature) != 64:
                self.stats["host_rejects"] += 1
                continue
            keep.append(i)
            pks.append(it.pubkey)
            sigs.append(it.signature)
            msgs.append(it.payload)
        if not keep:
            return [], None
        from fabric_tpu.ops import ed25519 as edmod
        arrays = list(edmod.pack_verify_inputs(pks, sigs, msgs))
        return keep, arrays

    def _pad(self, arrays, n: int):
        b = _bucket(n)
        if self.mesh is not None:
            size = self.mesh.devices.size
            b = max(b, size)
        out = []
        for a in arrays:
            a = np.asarray(a)
            pad = b - a.shape[-1]
            widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
            out.append(np.pad(a, widths))
        return out

    # -- dispatch helpers ---------------------------------------------------

    def _dispatch(self, fn, keep, arrays, pending, extra_args=()):
        """Pad to buckets, chunk beyond MAX_BUCKET (bounds the compiled-
        program set while arbitrarily large blocks still use the device),
        ENQUEUE the device calls (jax dispatch is async), and record
        (keep, out) pairs for the resolve step."""
        for lo in range(0, len(keep), MAX_BUCKET):
            hi = min(lo + MAX_BUCKET, len(keep))
            chunk = [a[..., lo:hi] for a in arrays]
            padded = self._pad(chunk, hi - lo)
            out = fn(*extra_args, *padded)
            self.stats["dispatches"] += 1
            self.stats["device_sigs"] += hi - lo
            pending.append((keep[lo:hi], out))

    # fast-lane key capacity per dispatch: NK is a compiled shape, so it
    # is bucketed; beyond the largest bucket, the hottest keys win and
    # the rest spill to the generic lane (the one-hot joint lookup cost
    # scales with NK, so NK stays small)
    FAST_NK_BUCKETS = (4,)

    def _verify_p256(self, items, idxs, pending):
        """Two-lane P-256 dispatch: signatures under cached (or
        cache-worthy) public keys take the fixed-base multikey comb
        kernel in ONE merged dispatch — the key-repetitive endorsement
        workload of SURVEY.md §3.2 — and the rest take the generic
        windowed-ladder kernel.  Dispatches are merged because relayed
        TPU transports charge a full round trip per dispatch."""
        recs = self._parse_p256(items, idxs)
        groups = {}
        for rec in recs:
            groups.setdefault(rec[1], []).append(rec)
        generic, fast = [], []
        for pk, g in groups.items():
            tab = None
            if pk in self.key_tables or len(g) >= self.fast_key_threshold:
                tab = self.key_tables.get_or_build(pk)
            if tab is None:
                generic.extend(g)
            else:
                fast.append((tab, g))
        fast.sort(key=lambda t: -len(t[1]))
        max_nk = self.FAST_NK_BUCKETS[-1]
        for _, g in fast[max_nk:]:
            generic.extend(g)
        fast = fast[:max_nk]
        if fast:
            from fabric_tpu.ops import p256 as p256mod
            nk = next(b for b in self.FAST_NK_BUCKETS if b >= len(fast))
            tabs = np.stack(
                [t for t, _ in fast]
                + [fast[0][0]] * (nk - len(fast))).astype(np.float32)
            frecs, key_idx = [], []
            for ki, (_, g) in enumerate(fast):
                frecs.extend(g)
                key_idx.extend([ki] * len(g))
            keep = [rec[0] for rec in frecs]
            arrays = [np.asarray(key_idx, dtype=np.int32)] + [
                p256mod.bytes32_to_words([rec[j] for rec in frecs])
                for j in (2, 3, 4)]
            self._dispatch(self._get_fn("p256-multikey"), keep, arrays,
                           pending, extra_args=(tabs,))
            self.stats["fast_key_sigs"] += len(keep)
        generic.sort(key=lambda rec: rec[0])
        keep, arrays = self._pack_p256_recs(generic)
        if keep:
            self._dispatch(self._get_fn(SCHEME_P256), keep, arrays, pending)

    # -- the batch verbs ----------------------------------------------------

    def batch_verify_async(self, items: Sequence[VerifyItem]):
        """Enqueue device verification and return resolve() -> bool[N].

        The device work races ahead while the caller keeps collecting
        (SURVEY.md §7 hard-part #3 overlap); resolve() blocks on the
        results.  Fallback stays atomic: ANY device failure — at enqueue
        or at resolve — recomputes the whole batch on the sw provider."""
        items = list(items)
        verdicts = np.zeros(len(items), dtype=bool)
        pending = []
        try:
            by_scheme = {}
            for i, it in enumerate(items):
                by_scheme.setdefault(it.scheme, []).append(i)
            for scheme, idxs in by_scheme.items():
                if scheme == SCHEME_P256:
                    self._verify_p256(items, idxs, pending)
                elif scheme == SCHEME_IDEMIX:
                    # host-verified (BN254 pairing batch on TPU is the
                    # BASELINE config-4 target); DEFERRED to resolve()
                    # so the device lanes enqueue first and stay async
                    idemix_items = [items[i] for i in idxs]

                    def _idemix_out(its=idemix_items):
                        from fabric_tpu.idemix.msp import verify_item_host
                        return np.array([verify_item_host(it) for it in its],
                                        dtype=bool)
                    pending.append((idxs, _idemix_out))
                elif scheme == SCHEME_ED25519:
                    keep, arrays = self._pack_ed25519(items, idxs)
                    if keep:
                        self._dispatch(self._get_fn(scheme), keep, arrays,
                                       pending)
                else:
                    self.stats["host_rejects"] += len(idxs)
        except Exception:
            logger.exception(
                "TPU dispatch failed; falling back to sw provider")
            self.stats["fallbacks"] += 1
            return lambda: self.fallback.batch_verify(items)

        def resolve():
            try:
                for keep, out in pending:
                    if callable(out):
                        out = out()
                    verdicts[np.asarray(keep)] = np.asarray(out)[:len(keep)]
            except Exception:
                logger.exception(
                    "TPU resolve failed; falling back to sw provider")
                self.stats["fallbacks"] += 1
                return self.fallback.batch_verify(items)
            return verdicts

        return resolve

    def batch_verify(self, items: Sequence[VerifyItem]) -> np.ndarray:
        return self.batch_verify_async(items)()
