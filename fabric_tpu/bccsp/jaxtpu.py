"""JAX/TPU batched BCCSP provider — the hardware slot of the framework.

Occupies the position the reference gives PKCS#11 HSMs (bccsp/pkcs11,
gated by bccsp/factory — SURVEY.md §2.1.1), but instead of one-at-a-time
HSM calls it dispatches the whole batch to the TPU kernels in
fabric_tpu.ops.  Signing and key-gen delegate to the software provider
(private keys never touch the TPU).

Host/device split per the reference's own design (msp/identities.go:178):
variable-length parsing (DER signatures, SEC1 points, RFC 8032 encodings,
SHA-512 for ed25519) happens on host; the device sees only fixed-size
word arrays.

Batching strategy: items are grouped by scheme, packed into word arrays,
and padded to power-of-two buckets so XLA compiles a small, reusable set
of programs.  Malformed items short-circuit to False on the host.
If device dispatch fails entirely, the whole batch falls back to the
software provider atomically (SURVEY.md §7 hard-part #5: fallback must be
atomic to keep determinism).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np

from cryptography.hazmat.primitives.asymmetric.utils import decode_dss_signature

from . import provider as prov
from .provider import (VerifyItem, SCHEME_P256, SCHEME_ED25519,
                       SCHEME_IDEMIX)
from .sw import SoftwareProvider

logger = logging.getLogger("fabric_tpu.bccsp.jaxtpu")

MIN_BUCKET = 128
MAX_BUCKET = 1 << 17


def _bucket(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


class JaxTpuProvider(prov.Provider):
    name = "jaxtpu"

    def __init__(self, require_low_s: bool = True, mesh=None,
                 fallback: Optional[SoftwareProvider] = None):
        import os
        self.require_low_s = require_low_s
        self.mesh = mesh
        self.fallback = fallback or SoftwareProvider(require_low_s=require_low_s)
        self._fns = {}
        self.stats = {"dispatches": 0, "device_sigs": 0, "host_rejects": 0,
                      "fallbacks": 0, "fast_key_sigs": 0}
        # per-key fixed-base fast path (ops/p256_fixed.py): keys whose comb
        # table is cached skip the variable-point ladder entirely.  A table
        # build costs ~15 ms host-side, so uncached keys only earn one when
        # a single batch brings at least `fast_key_threshold` signatures —
        # repeat identities (org endorsers, enrolled clients: the same
        # assumption behind the reference's msp/cache) amortize the build
        # across blocks; true one-off keys ride the generic ladder.
        from fabric_tpu.ops.p256_tables import KeyTableCache
        self.key_tables = KeyTableCache(
            max_keys=int(os.environ.get("FABRIC_TPU_KEY_CACHE", "128")))
        from fabric_tpu.ops.ed25519_tables import Ed25519KeyTableCache
        self.ed_key_tables = Ed25519KeyTableCache(
            max_keys=int(os.environ.get("FABRIC_TPU_KEY_CACHE", "128")))
        self.fast_key_threshold = int(
            os.environ.get("FABRIC_TPU_FAST_KEY_THRESHOLD", "64"))

    # signing / key-gen are host-side: delegate
    def key_gen(self, scheme: str):
        return self.fallback.key_gen(scheme)

    def sign(self, private_key, payload: bytes) -> bytes:
        return self.fallback.sign(private_key, payload)

    # -- device plumbing ----------------------------------------------------

    def _get_fn(self, scheme: str):
        key = scheme
        if key not in self._fns:
            import jax
            if scheme == SCHEME_P256:
                import os
                low_s = self.require_low_s
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_p256_verify(self.mesh, self.require_low_s)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif os.environ.get("FABRIC_TPU_PALLAS") == "1":
                    # experimental fused kernel (see ops/p256_pallas.py)
                    from fabric_tpu.ops import p256_pallas
                    self._fns[key] = lambda *a: p256_pallas.verify_words(
                        *a, require_low_s=low_s)
                else:
                    # round-2 windowed flat path (ops/ecp256).  On CPU the
                    # big scan bodies hit an XLA:CPU compile pathology, so
                    # run eagerly there (per-primitive jits, see flatfield).
                    from fabric_tpu.ops import ecp256
                    if jax.default_backend() == "cpu":
                        self._fns[key] = lambda *a: ecp256.verify_words_xla(
                            *a, require_low_s=low_s)
                    else:
                        from fabric_tpu.ops import bignum as _bn
                        tab = ecp256.comb_table_f32()

                        # words->limbs conversion inside the jit: eager
                        # conversion costs tunneled dispatches per call
                        def whole(qx, qy, r, s, e, _tab=tab):
                            args = [_bn.words_be_to_limbs(v)
                                    for v in (qx, qy, r, s, e)]
                            return ecp256.verify_body(
                                *args, _tab, require_low_s=low_s)
                        self._fns[key] = jax.jit(whole)
            elif scheme == "p256-rows":
                from fabric_tpu.ops import p256_fixed
                low_s = self.require_low_s
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_p256_rows_verify(
                        self.mesh, self.require_low_s)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif jax.default_backend() == "cpu":
                    self._fns[key] = (
                        lambda *a: p256_fixed.verify_words_rows(
                            *a, require_low_s=low_s))
                else:
                    self._fns[key] = jax.jit(
                        lambda *a: p256_fixed.verify_words_rows(
                            *a, require_low_s=low_s))
            elif scheme == SCHEME_ED25519:
                from fabric_tpu.ops import ed25519
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_ed25519_verify(self.mesh)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif jax.default_backend() == "cpu":
                    self._fns[key] = ed25519.verify_words
                else:
                    self._fns[key] = jax.jit(ed25519.verify_words)
            elif scheme == "idemix-pair":
                from fabric_tpu.ops import bn254_batch as bb

                def pair_fn(flags, A1, B1, A2, B2, x1, y1, x2, y2):
                    return bb.pairing_check_batch(
                        {"flags": flags, "A": A1, "B": B1},
                        {"flags": flags, "A": A2, "B": B2},
                        x1, y1, x2, y2)
                if jax.default_backend() == "cpu":
                    self._fns[key] = pair_fn
                else:
                    self._fns[key] = jax.jit(pair_fn)
            elif scheme == "ed25519-rows":
                from fabric_tpu.ops import ed25519
                if self.mesh is not None:
                    from fabric_tpu.parallel import mesh as meshmod
                    f = meshmod.sharded_ed25519_rows_verify(self.mesh)
                    self._fns[key] = lambda *a: f(*a)[0]
                elif jax.default_backend() == "cpu":
                    self._fns[key] = ed25519.verify_words_rows
                else:
                    self._fns[key] = jax.jit(ed25519.verify_words_rows)
            else:
                raise ValueError(f"unsupported scheme {scheme!r}")
        return self._fns[key]

    def _parse_p256(self, items, idxs):
        """Host-side parse: -> list of (idx, pubkey, r32, s32, e32) with
        malformed items dropped (verdict stays False)."""
        out = []
        for i in idxs:
            it = items[i]
            try:
                pk = it.pubkey
                if len(pk) != 65 or pk[0] != 0x04:
                    raise ValueError("bad SEC1 point")
                if len(it.payload) != 32:
                    raise ValueError("p256 payload must be a 32B digest")
                ri, si = decode_dss_signature(it.signature)
                if not (0 < ri < (1 << 256) and 0 < si < (1 << 256)):
                    raise ValueError("r/s out of range")
            except Exception:
                self.stats["host_rejects"] += 1
                continue
            out.append((i, pk, ri.to_bytes(32, "big"),
                        si.to_bytes(32, "big"), it.payload))
        return out

    def _pack_p256(self, items, idxs):
        """Generic-lane packing: -> (ok_idx, [qx qy r s e] word arrays)."""
        recs = self._parse_p256(items, idxs)
        return self._pack_p256_recs(recs)

    @staticmethod
    def _pack_p256_recs(recs):
        if not recs:
            return [], None
        from fabric_tpu.ops import p256 as p256mod
        keep = [rec[0] for rec in recs]
        qx = p256mod.bytes32_to_words([rec[1][1:33] for rec in recs])
        qy = p256mod.bytes32_to_words([rec[1][33:65] for rec in recs])
        r = p256mod.bytes32_to_words([rec[2] for rec in recs])
        s = p256mod.bytes32_to_words([rec[3] for rec in recs])
        e = p256mod.bytes32_to_words([rec[4] for rec in recs])
        return keep, [qx, qy, r, s, e]

    def _pad(self, arrays, n: int):
        b = _bucket(n)
        if self.mesh is not None:
            size = self.mesh.devices.size
            b = max(b, size)
        out = []
        for a in arrays:
            a = np.asarray(a)
            pad = b - a.shape[-1]
            widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
            out.append(np.pad(a, widths))
        return out

    # -- dispatch helpers ---------------------------------------------------

    def _dispatch(self, fn, keep, arrays, pending, extra_args=()):
        """Pad to buckets, chunk beyond MAX_BUCKET (bounds the compiled-
        program set while arbitrarily large blocks still use the device),
        ENQUEUE the device calls (jax dispatch is async), and record
        (keep, out) pairs for the resolve step."""
        for lo in range(0, len(keep), MAX_BUCKET):
            hi = min(lo + MAX_BUCKET, len(keep))
            chunk = [a[..., lo:hi] for a in arrays]
            padded = self._pad(chunk, hi - lo)
            out = fn(*extra_args, *padded)
            self.stats["dispatches"] += 1
            self.stats["device_sigs"] += hi - lo
            pending.append((keep[lo:hi], out))

    # Row-grid geometry for the fast lane (ops/p256_fixed.verify_words_
    # rows): signatures pack key-major into rows of FAST_ROW_C lanes, so
    # ANY number of cached keys rides the comb path at constant per-sig
    # cost (the round-3 joint-one-hot kernel capped NK at 4 and spilled
    # the rest to the generic ladder).  Row counts bucket in ~1.5x steps
    # and the table bank in powers of two, bounding the compiled-program
    # set; padding rows repeat real signatures and their slots are
    # dropped at resolve time.
    FAST_ROW_C = int(__import__("os").environ.get(
        "FABRIC_TPU_FAST_ROW_C", "128"))
    ROW_BUCKETS = (4, 8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256,
                   384, 512, 768, 1024)
    BANK_BUCKETS = (4, 16, 64, 256)

    def _verify_p256(self, items, idxs, pending):
        """Two-lane P-256 dispatch: signatures under cached (or
        cache-worthy) public keys take the row-grouped fixed-base comb
        kernel in ONE merged dispatch — the key-repetitive endorsement
        workload of SURVEY.md §3.2 — and the rest take the generic
        windowed-ladder kernel.  Dispatches are merged because relayed
        TPU transports charge a full round trip per dispatch."""
        recs = self._parse_p256(items, idxs)
        groups = {}
        for rec in recs:
            groups.setdefault(rec[1], []).append(rec)
        generic, fast = [], []
        for pk, g in groups.items():
            tab = None
            if pk in self.key_tables or len(g) >= self.fast_key_threshold:
                tab = self.key_tables.get_or_build(pk)
            if tab is None:
                generic.extend(g)
            else:
                fast.append((tab, g))
        # largest groups first: keeps per-dispatch row chunks dense
        fast.sort(key=lambda t: -len(t[1]))
        if fast:
            self._dispatch_rows(fast, pending)
        generic.sort(key=lambda rec: rec[0])
        keep, arrays = self._pack_p256_recs(generic)
        if keep:
            self._dispatch(self._get_fn(SCHEME_P256), keep, arrays, pending)

    def _row_chunks(self, fast):
        """Pack (table, group) pairs into row-grid chunks:
        [(tabs, row_key, flat_recs, slots, Rb)], each at most the top
        row bucket, row counts padded to a bucket (and to the mesh
        size), padding slots marked -1 (dropped at resolve)."""
        C = self.FAST_ROW_C
        max_rows = self.ROW_BUCKETS[-1]
        chunks = []
        cur = {"tabs": [], "row_key": [], "recs": [], "slots": []}

        def close():
            if cur["row_key"]:
                chunks.append((cur["tabs"], cur["row_key"], cur["recs"],
                               cur["slots"]))
                cur.update(tabs=[], row_key=[], recs=[], slots=[])

        for tab, g in fast:
            gi = 0
            while gi < len(g):
                room = max_rows - len(cur["row_key"])
                if room == 0 or len(cur["tabs"]) >= self.BANK_BUCKETS[-1]:
                    close()
                    room = max_rows
                take = min(len(g) - gi, room * C)
                part = g[gi:gi + take]
                gi += take
                ki = len(cur["tabs"])
                cur["tabs"].append(tab)
                n_rows = -(-len(part) // C)
                pad = n_rows * C - len(part)
                cur["row_key"].extend([ki] * n_rows)
                cur["recs"].extend(part)
                cur["recs"].extend([part[0]] * pad)   # repeat; dropped
                cur["slots"].extend([rec[0] for rec in part])
                cur["slots"].extend([-1] * pad)
        close()

        out = []
        for tabs, row_key, frecs, slots in chunks:
            R = len(row_key)
            Rb = next(b for b in self.ROW_BUCKETS if b >= R)
            if self.mesh is not None:
                size = self.mesh.devices.size
                while Rb % size:
                    Rb += 1
            if Rb > R:
                frecs = frecs + [frecs[0]] * ((Rb - R) * C)
                slots = slots + [-1] * ((Rb - R) * C)
                row_key = row_key + [0] * (Rb - R)
            out.append((tabs, row_key, frecs, slots, Rb))
        return out

    def _enqueue_rows_out(self, out, slots, pending):
        self.stats["dispatches"] += 1
        slots_np = np.asarray(slots)
        valid = slots_np >= 0
        keep = slots_np[valid]
        self.stats["device_sigs"] += len(keep)
        self.stats["fast_key_sigs"] += len(keep)
        pending.append(
            (keep,
             lambda out=out, valid=valid:
                 np.asarray(out).reshape(-1)[valid]))

    def _dispatch_rows(self, fast, pending):
        """P-256 row-grid dispatches (recs: (idx, pk, r32, s32, e32))."""
        from fabric_tpu.ops import p256 as p256mod
        C = self.FAST_ROW_C
        fn = self._get_fn("p256-rows")
        for tabs, row_key, frecs, slots, Rb in self._row_chunks(fast):
            K = len(tabs)
            Kb = next(b for b in self.BANK_BUCKETS if b >= K)
            bank = np.stack(tabs + [tabs[0]] * (Kb - K)).astype(np.float32)
            words = [p256mod.bytes32_to_words(
                [rec[j] for rec in frecs]).reshape(8, Rb, C)
                for j in (2, 3, 4)]
            out = fn(bank, np.asarray(row_key, dtype=np.int32), *words)
            self._enqueue_rows_out(out, slots, pending)

    def _dispatch_ed_rows(self, fast, pending):
        """ed25519 row-grid dispatches (recs: (idx, pk, sig, msg))."""
        from fabric_tpu.ops import ed25519 as edmod
        C = self.FAST_ROW_C
        fn = self._get_fn("ed25519-rows")
        for tabs, row_key, frecs, slots, Rb in self._row_chunks(fast):
            K = len(tabs)
            Kb = next(b for b in self.BANK_BUCKETS if b >= K)
            bank = np.stack(tabs + [tabs[0]] * (Kb - K)).astype(np.float32)
            ay, a_sign, ry, r_sign, s, k = edmod.pack_verify_inputs(
                [rec[1] for rec in frecs], [rec[2] for rec in frecs],
                [rec[3] for rec in frecs])
            out = fn(bank, np.asarray(row_key, dtype=np.int32),
                     ry.reshape(8, Rb, C),
                     r_sign.reshape(Rb, C).astype(np.int32),
                     s.reshape(8, Rb, C), k.reshape(8, Rb, C))
            self._enqueue_rows_out(out, slots, pending)

    def _verify_ed25519(self, items, idxs, pending):
        """Two-lane ed25519 dispatch (the P-256 design): cached-A keys
        ride the all-comb row kernel; the rest decompress A on device
        and take the comb+ladder generic kernel."""
        recs = []
        for i in idxs:
            it = items[i]
            if len(it.pubkey) != 32 or len(it.signature) != 64:
                self.stats["host_rejects"] += 1
                continue
            recs.append((i, it.pubkey, it.signature, it.payload))
        groups = {}
        for rec in recs:
            groups.setdefault(rec[1], []).append(rec)
        fast, generic = [], []
        for pk, g in groups.items():
            tab = None
            if (pk in self.ed_key_tables
                    or len(g) >= self.fast_key_threshold):
                tab = self.ed_key_tables.get_or_build(pk)
            if tab is None:
                generic.extend(g)
            else:
                fast.append((tab, g))
        fast.sort(key=lambda t: -len(t[1]))
        if fast:
            self._dispatch_ed_rows(fast, pending)
        generic.sort(key=lambda rec: rec[0])
        if generic:
            from fabric_tpu.ops import ed25519 as edmod
            keep = [rec[0] for rec in generic]
            arrays = list(edmod.pack_verify_inputs(
                [rec[1] for rec in generic], [rec[2] for rec in generic],
                [rec[3] for rec in generic]))
            self._dispatch(self._get_fn(SCHEME_ED25519), keep, arrays,
                           pending)

    # -- idemix: batched BN254 pairing checks (BASELINE config 4) -----------

    IDEMIX_MIN_BUCKET = 16

    def _idemix_packed(self, ipk_bytes: bytes):
        """Per-issuer Miller-loop line precompute (w side), cached; the
        g2 side is global.  ~0.2 s host build per issuer, amortized."""
        cache = getattr(self, "_idemix_pack_cache", None)
        if cache is None:
            cache = self._idemix_pack_cache = {}
        packed = cache.get(ipk_bytes)
        if packed is None:
            from fabric_tpu.idemix import bn254 as hb
            from fabric_tpu.idemix.msp import deserialize_ipk
            from fabric_tpu.ops import bn254_batch as bb
            ipk = deserialize_ipk(ipk_bytes)
            packed = bb.pack_steps(hb.ate_precompute(ipk.w))
            cache[ipk_bytes] = packed
        return packed

    def _idemix_g2_packed(self):
        packed = getattr(self, "_idemix_g2_pack", None)
        if packed is None:
            from fabric_tpu.idemix import bn254 as hb
            from fabric_tpu.ops import bn254_batch as bb
            packed = bb.pack_steps(hb.ate_precompute(hb.G2_GEN))
            self._idemix_g2_pack = packed
        return packed

    def _verify_idemix(self, items, idxs, pending):
        """Host structural/ZK checks + ONE batched device dispatch per
        issuer for the pairing equation e(A', w) == e(Abar, g2) —
        replacing ~1.3 s of host pairing per presentation
        (/root/reference/idemix/signature.go:230 Ver's pairing check;
        the reference runs it in amcl Go loops per signature)."""
        import jax
        import os
        on_cpu = jax.default_backend() == "cpu"
        if on_cpu and os.environ.get("FABRIC_TPU_IDEMIX_DEVICE") != "1":
            # CPU backend: the eager tower-field kernel is slower than
            # host python ints — keep the host path
            idemix_items = [items[i] for i in idxs]

            def _idemix_out(its=idemix_items):
                from fabric_tpu.idemix.msp import verify_item_host
                return np.array([verify_item_host(it) for it in its],
                                dtype=bool)
            pending.append((idxs, _idemix_out))
            return

        from fabric_tpu.idemix import bn254 as hb
        from fabric_tpu.idemix.msp import collect_item_parts
        from fabric_tpu.ops import bignum as bnmod

        groups = {}
        for i in idxs:
            ok, key, pair = collect_item_parts(items[i])
            if not ok:
                continue              # verdict stays False
            groups.setdefault(key, []).append((i, pair[0], pair[1]))
        fn = self._get_fn("idemix-pair")
        packed_g2 = self._idemix_g2_packed()
        for key, g in groups.items():
            packed_w = self._idemix_packed(key)
            b = self.IDEMIX_MIN_BUCKET
            while b < len(g):
                b <<= 1
            padded = g + [g[0]] * (b - len(g))
            # P2 = -Abar: the kernel checks e(P1, w) * e(P2, g2) == 1
            x1 = np.stack([bnmod.int_to_limbs(p[1][0]) for p in padded], 1)
            y1 = np.stack([bnmod.int_to_limbs(p[1][1]) for p in padded], 1)
            x2 = np.stack([bnmod.int_to_limbs(p[2][0]) for p in padded], 1)
            y2 = np.stack([bnmod.int_to_limbs((hb.P - p[2][1]) % hb.P)
                           for p in padded], 1)
            out = fn(packed_w["flags"], packed_w["A"], packed_w["B"],
                     packed_g2["A"], packed_g2["B"], x1, y1, x2, y2)
            self.stats["dispatches"] += 1
            self.stats["device_sigs"] += len(g)
            pending.append(([p[0] for p in g], out))

    # -- the batch verbs ----------------------------------------------------

    def batch_verify_async(self, items: Sequence[VerifyItem]):
        """Enqueue device verification and return resolve() -> bool[N].

        The device work races ahead while the caller keeps collecting
        (SURVEY.md §7 hard-part #3 overlap); resolve() blocks on the
        results.  Fallback stays atomic: ANY device failure — at enqueue
        or at resolve — recomputes the whole batch on the sw provider."""
        items = list(items)
        verdicts = np.zeros(len(items), dtype=bool)
        pending = []
        try:
            by_scheme = {}
            for i, it in enumerate(items):
                by_scheme.setdefault(it.scheme, []).append(i)
            for scheme, idxs in by_scheme.items():
                if scheme == SCHEME_P256:
                    self._verify_p256(items, idxs, pending)
                elif scheme == SCHEME_IDEMIX:
                    self._verify_idemix(items, idxs, pending)
                elif scheme == SCHEME_ED25519:
                    self._verify_ed25519(items, idxs, pending)
                else:
                    self.stats["host_rejects"] += len(idxs)
        except Exception:
            logger.exception(
                "TPU dispatch failed; falling back to sw provider")
            self.stats["fallbacks"] += 1
            return lambda: self.fallback.batch_verify(items)

        def resolve():
            import time as _time
            t0 = _time.perf_counter()
            try:
                for keep, out in pending:
                    if callable(out):
                        out = out()
                    verdicts[np.asarray(keep)] = np.asarray(out)[:len(keep)]
            except Exception:
                logger.exception(
                    "TPU resolve failed; falling back to sw provider")
                self.stats["fallbacks"] += 1
                return self.fallback.batch_verify(items)
            try:
                # device-phase observability (the jax.profiler trace is
                # the deep view; these are the always-on numbers):
                # resolve wall time ~= device tail not hidden by overlap
                from fabric_tpu.ops_plane import registry
                registry.histogram(
                    "provider_resolve_seconds",
                    "batch_verify device resolve wait").observe(
                        _time.perf_counter() - t0)
                registry.counter(
                    "provider_device_sigs_total",
                    "signatures resolved on device").add(len(items))
            except Exception:
                pass
            return verdicts

        return resolve

    def batch_verify(self, items: Sequence[VerifyItem]) -> np.ndarray:
        return self.batch_verify_async(items)()
