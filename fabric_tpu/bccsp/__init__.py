"""BCCSP: the pluggable crypto provider plane (batch-first, TPU-gated).

Re-design of the reference's Blockchain Crypto Service Provider
(/root/reference/bccsp/bccsp.go:121-133, factory at bccsp/factory/factory.go:42):
same role — every signature creation/verification in the framework flows
through a provider selected by config — but the interface is *batch-first*:
the primary verb is `batch_verify(items) -> bool[N]`, because the whole point
of the TPU-native design is verify-then-gate over an entire block
(SURVEY.md §7) instead of per-tx serial verifies.

Providers:
- sw      : CPU/OpenSSL provider — fallback and correctness oracle
            (the reference's bccsp/sw equivalent)
- jaxtpu  : JAX/TPU batched provider (the reference's PKCS#11 "hardware
            slot" — SURVEY.md §2.1.1 — occupied by the TPU)
"""

from .provider import (VerifyItem, SCHEME_P256, SCHEME_ED25519,
                       SCHEME_IDEMIX)
from .factory import get_default, init_factories, FactoryOpts

__all__ = [
    "VerifyItem", "SCHEME_P256", "SCHEME_ED25519", "SCHEME_IDEMIX",
    "get_default", "init_factories", "FactoryOpts",
]
