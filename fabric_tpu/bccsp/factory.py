"""BCCSP factory: config-gated provider selection.

Mirror of the reference's bccsp/factory (factory.go:42 GetDefault,
nopkcs11.go:19-28 FactoryOpts / InitFactories, selected by the BCCSP
section of core.yaml — sampleconfig/core.yaml:287-303).  Here the options
are `SW` and `JAXTPU` (the latter replacing the PKCS11 hardware slot).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from .provider import Provider
from .sw import SoftwareProvider

logger = logging.getLogger("fabric_tpu.bccsp.factory")

_default: Optional[Provider] = None


@dataclass
class FactoryOpts:
    """The BCCSP config block (core.yaml `bccsp:` equivalent)."""
    default: str = "JAXTPU"          # "SW" | "JAXTPU"
    require_low_s: bool = True
    use_mesh: bool = False           # shard batches over all visible devices
    placement: bool = False          # per-channel device placement: carve
    #                                  the mesh into sub-meshes sized by
    #                                  channel queue depth (parallel/placement)
    mesh_devices: Optional[int] = None   # cap the device count the mesh /
    #                                  placement scheduler may use (None: all)
    degrade: Optional[bool] = None   # wrap in DegradingProvider (breaker
    #                                  + SW fallback on device sickness).
    #                                  None = auto: ON for JAXTPU (a node
    #                                  that loses its accelerator keeps
    #                                  committing on SW, healthz flags it),
    #                                  OFF for SW.  Explicit False is the
    #                                  fail-stop escape hatch.
    compile_cache_dir: Optional[str] = None   # persistent XLA cache dir
    #                                  (node config "compile_cache_dir" /
    #                                  FABRIC_TPU_<ROLE>_COMPILE_CACHE_DIR)


def default_cache_dir() -> str:
    import os
    return os.environ.get("JAX_COMPILATION_CACHE_DIR",
                          os.path.expanduser("~/.cache/fabric_tpu_xla"))


def enable_compile_cache(cache_dir: Optional[str] = None) -> None:
    """Point jax at the persistent compilation cache so node cold-starts
    reuse every previously-compiled kernel (round-2 flagged 200s+ cold
    compiles; the cache survives across processes on one host).  Must go
    through jax.config — the env var alone is too late on images whose
    sitecustomize imports jax at interpreter start.

    Precedence: explicit `cache_dir` (node config / warmup --cache-dir)
    > JAX_COMPILATION_CACHE_DIR > ~/.cache/fabric_tpu_xla.  Prebake with
    `python -m fabric_tpu.node.warmup --cache-dir <dir>` at provisioning
    time, then start nodes against the same dir."""
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          cache_dir or default_cache_dir())
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        logger.debug("persistent compile cache unavailable", exc_info=True)


# written by node.warmup when a prebake COMPLETES; its presence is what
# makes a cache dir count as a warmup artifact
WARMUP_MANIFEST = "fabric_tpu_warmup.json"


def compile_cache_is_warm(cache_dir: Optional[str] = None,
                          min_entries: int = 4) -> bool:
    """True when the cache dir holds a COMPLETED warmup artifact: the
    manifest `node.warmup` writes after prebaking, plus at least
    `min_entries` compiled kernels.  Incidental cache entries left by an
    ordinary test run do NOT count — the slow-marked kernel test
    modules rejoin the quick gate off this check, so it must flip only
    on an explicit prebake, never as a side effect of running tests.
    Also used by ops checks."""
    import os
    d = cache_dir or default_cache_dir()
    if not os.path.isfile(os.path.join(d, WARMUP_MANIFEST)):
        return False
    try:
        names = os.listdir(d)
    except OSError:
        return False
    return sum(1 for n in names if not n.startswith(".")
               and n != WARMUP_MANIFEST) >= min_entries


_placement = None                # PlacementScheduler when opts.placement


def init_factories(opts: Optional[FactoryOpts] = None) -> Provider:
    """Initialize the default provider (InitFactories equivalent)."""
    global _default, _placement
    opts = opts or FactoryOpts()
    kind = opts.default.upper()
    degrade = (kind == "JAXTPU") if opts.degrade is None else \
        bool(opts.degrade)
    _placement = None
    if kind == "SW":
        _default = SoftwareProvider(require_low_s=opts.require_low_s)
    elif kind == "JAXTPU":
        enable_compile_cache(opts.compile_cache_dir)
        from .jaxtpu import JaxTpuProvider
        import jax
        devices = jax.devices()
        if opts.mesh_devices:
            devices = devices[:opts.mesh_devices]
        mesh = None
        if opts.use_mesh and len(devices) > 1:
            from fabric_tpu.parallel import mesh as meshmod
            mesh = meshmod.make_mesh(devices)
        _default = JaxTpuProvider(require_low_s=opts.require_low_s, mesh=mesh)
        if opts.placement and len(devices) > 1:
            from fabric_tpu.parallel.placement import PlacementScheduler
            wrap = None
            if degrade:
                from .degrade import DegradingProvider
                low_s = opts.require_low_s

                def wrap(p):
                    return DegradingProvider(
                        p, SoftwareProvider(require_low_s=low_s))
            _placement = PlacementScheduler(
                devices=devices,
                provider_factory=lambda m: JaxTpuProvider(
                    require_low_s=opts.require_low_s, mesh=m),
                wrap=wrap)
    else:
        raise ValueError(f"unknown BCCSP provider {opts.default!r}")
    if degrade:
        from .degrade import DegradingProvider
        _default = DegradingProvider(
            _default, SoftwareProvider(require_low_s=opts.require_low_s))
    logger.info("BCCSP default provider: %s", _default.name)
    return _default


def get_placement():
    """The PlacementScheduler, or None when placement is off / SW."""
    return _placement


def provider_for_channel(channel_id: str,
                         demand: Optional[int] = None) -> Optional[Provider]:
    """Per-channel provider from the placement scheduler, or None when
    placement is disabled (callers fall back to the default provider).
    `demand` is the caller's current queue depth — it sizes the
    channel's device span on the next carve."""
    if _placement is None:
        return None
    return _placement.provider_for(channel_id, demand=demand)


def get_default() -> Provider:
    """GetDefault equivalent: lazily initializes a JAXTPU provider."""
    global _default
    if _default is None:
        init_factories()
    return _default


def set_default(p: Provider) -> None:
    global _default
    _default = p
