"""Provider interface and item types for the batch-first BCCSP plane.

Reference parity: bccsp.BCCSP (bccsp/bccsp.go:121-133) exposes KeyGen /
KeyImport / Hash / Sign / Verify.  Here the same verbs exist, plus the
batch verb that the verify-then-gate pipeline (SURVEY.md §7) is built on.
Signing always stays on the host CPU — private keys never touch the TPU.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import NamedTuple, Optional, Sequence

import numpy as np

SCHEME_P256 = "ecdsa-p256"
SCHEME_ED25519 = "ed25519"
SCHEME_IDEMIX = "idemix-bbs"

HASH_SHA256 = "sha256"
HASH_SHA384 = "sha384"
HASH_SHA3_256 = "sha3_256"
HASH_SHA3_384 = "sha3_384"


class VerifyItem(NamedTuple):
    """One signature-verification work item.

    scheme  : SCHEME_P256 | SCHEME_ED25519
    pubkey  : SEC1 uncompressed point (65B, 0x04||X||Y) for p256;
              raw 32B for ed25519
    signature: ASN.1/DER (r,s) for p256; raw 64B (R||S) for ed25519
    payload : the 32-byte *digest* for p256 (hashing happened upstream,
              mirroring msp/identities.go:178); the full *message* for
              ed25519 (RFC 8032 signs the message itself)

    A NamedTuple on purpose: items are created and hashed 4x per tx on
    the validator's pass-1 hot loop (they ARE their own dedup keys —
    Verify is a pure function of these four fields), and C-level tuple
    construction/hash measurably beats the frozen-dataclass forms.
    """
    scheme: str
    pubkey: bytes
    signature: bytes
    payload: bytes


def hash_payload(data: bytes, algo: str = HASH_SHA256) -> bytes:
    """The provider Hash verb (bccsp.Hash equivalent)."""
    try:
        return hashlib.new(algo, data).digest()
    except ValueError as e:
        raise ValueError(f"unsupported hash {algo!r}") from e


class Provider:
    """Abstract BCCSP provider. Concrete: sw.SoftwareProvider, jaxtpu.JaxTpuProvider."""

    name = "abstract"

    # -- keys / signing (host-side in every provider) -----------------------

    def key_gen(self, scheme: str):
        raise NotImplementedError

    def sign(self, private_key, payload: bytes) -> bytes:
        raise NotImplementedError

    # -- verification -------------------------------------------------------

    def verify(self, item: VerifyItem) -> bool:
        return bool(self.batch_verify([item])[0])

    def batch_verify(self, items: Sequence[VerifyItem]) -> np.ndarray:
        """Verify a batch; returns bool[N] aligned to `items`.

        Malformed items (bad lengths, undecodable DER/points) yield False —
        they never raise, so one bad signature cannot fail a whole block
        (policy.go:390-393 semantics)."""
        raise NotImplementedError

    def batch_verify_async(self, items: Sequence[VerifyItem]):
        """Start verifying a batch; returns resolve() -> bool[N].

        Device providers override this to ENQUEUE the work and return
        immediately, letting the caller overlap further host-side
        collection with device compute (SURVEY.md §7 hard-part #3).  The
        default is lazy-but-correct: work happens at resolve()."""
        from fabric_tpu.ops_plane import tracing
        items = list(items)
        span = tracing.tracer.start_span(
            "bccsp.batch_verify", require_parent=True,
            attributes={"provider": self.name, "batch_size": len(items)})

        def resolve():
            import time as _t
            t0 = _t.perf_counter()
            try:
                out = self.batch_verify(items)
            except BaseException as exc:
                span.set_attribute("error", repr(exc))
                span.end(status="ERROR")
                raise
            span.set_attribute("block_until_ready_s",
                               round(_t.perf_counter() - t0, 6))
            span.end()
            return out

        return resolve

    def hash(self, data: bytes, algo: str = HASH_SHA256) -> bytes:
        return hash_payload(data, algo)
