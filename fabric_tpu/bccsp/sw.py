"""Software (CPU/OpenSSL) BCCSP provider — fallback and correctness oracle.

Equivalent of the reference's bccsp/sw (pure-Go CSP, bccsp/sw/impl.go:247):
ECDSA-P256 with low-S enforcement on sign AND verify
(bccsp/sw/ecdsa.go:27-58), plus ed25519 (new capability).  Backed by the
`cryptography` package (OpenSSL), which is faster than Go's crypto/ecdsa —
so using it as the benchmark baseline is conservative.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from fabric_tpu.crypto import InvalidSignature
from fabric_tpu.crypto import hashes
from fabric_tpu.crypto import ec
from fabric_tpu.crypto import (
    Ed25519PrivateKey, Ed25519PublicKey)
from fabric_tpu.crypto import (
    Prehashed, decode_dss_signature, encode_dss_signature)
from fabric_tpu.crypto import serialization

from . import provider as prov
from .provider import (VerifyItem, SCHEME_P256, SCHEME_ED25519,
                       SCHEME_IDEMIX)

P256_N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
P256_HALF_N = (P256_N - 1) // 2


class SigningKey:
    """A host-side private key (scheme + cryptography key object)."""

    def __init__(self, scheme: str, key):
        self.scheme = scheme
        self._key = key

    def public_bytes(self) -> bytes:
        """Provider wire format: SEC1 uncompressed for p256, raw for ed25519."""
        pub = self._key.public_key()
        if self.scheme == SCHEME_P256:
            return pub.public_bytes(
                serialization.Encoding.X962,
                serialization.PublicFormat.UncompressedPoint)
        return pub.public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)

    @property
    def key(self):
        return self._key


def parse_p256_pubkey(pubkey: bytes):
    """SEC1 uncompressed 65B -> EllipticCurvePublicKey (raises on bad input)."""
    return ec.EllipticCurvePublicKey.from_encoded_point(ec.SECP256R1(), pubkey)


def low_s(r: int, s: int) -> tuple:
    """Normalize an ECDSA-P256 signature to low-S (bccsp/utils ToLowS)."""
    if s > P256_HALF_N:
        s = P256_N - s
    return r, s


class SoftwareProvider(prov.Provider):
    name = "sw"

    def __init__(self, require_low_s: bool = True):
        self.require_low_s = require_low_s

    def key_gen(self, scheme: str) -> SigningKey:
        if scheme == SCHEME_P256:
            return SigningKey(scheme, ec.generate_private_key(ec.SECP256R1()))
        if scheme == SCHEME_ED25519:
            return SigningKey(scheme, Ed25519PrivateKey.generate())
        raise ValueError(f"unsupported scheme {scheme!r}")

    def sign(self, private_key: SigningKey, payload: bytes) -> bytes:
        """p256: payload is the 32B digest; ed25519: payload is the message."""
        if private_key.scheme == SCHEME_P256:
            der = private_key.key.sign(
                payload, ec.ECDSA(Prehashed(hashes.SHA256())))
            r, s = low_s(*decode_dss_signature(der))
            return encode_dss_signature(r, s)
        if private_key.scheme == SCHEME_ED25519:
            return private_key.key.sign(payload)
        raise ValueError(f"unsupported scheme {private_key.scheme!r}")

    def _verify_one(self, it: VerifyItem) -> bool:
        try:
            if it.scheme == SCHEME_P256:
                # same wire checks as the jaxtpu packer (_pack_p256) so the
                # two providers reject the exact same malformed inputs —
                # required for the atomic-fallback determinism invariant
                if len(it.pubkey) != 65 or it.pubkey[0] != 0x04:
                    return False
                if len(it.payload) != 32:
                    return False
                r, s = decode_dss_signature(it.signature)
                if self.require_low_s and s > P256_HALF_N:
                    return False
                pub = parse_p256_pubkey(it.pubkey)
                pub.verify(it.signature, it.payload,
                           ec.ECDSA(Prehashed(hashes.SHA256())))
                return True
            if it.scheme == SCHEME_ED25519:
                Ed25519PublicKey.from_public_bytes(it.pubkey).verify(
                    it.signature, it.payload)
                return True
            if it.scheme == SCHEME_IDEMIX:
                from fabric_tpu.idemix.msp import verify_item_host
                return verify_item_host(it)
            return False
        except (InvalidSignature, ValueError, TypeError):
            return False

    def batch_verify(self, items: Sequence[VerifyItem]) -> np.ndarray:
        return np.array([self._verify_one(it) for it in items], dtype=bool)
