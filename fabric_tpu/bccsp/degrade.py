"""Graceful degradation wrapper for the BCCSP plane.

`DegradingProvider` fronts a primary (device) provider with a circuit
breaker and a software fallback that is guaranteed to produce identical
validation flags (both implement the same malformed-item-is-False
batch_verify contract, and the chaos suite asserts flag identity):

  HEALTHY    batches go to the primary; exceptions from enqueue or
             resolve — AND silent per-batch fallbacks the JAXTPU
             provider performs internally (its `fallbacks` counter
             moving) — count against the breaker
  DEGRADED   the breaker tripped: batches route straight to the SW
             fallback, skipping the cost of a doomed device attempt;
             a cooldown timer (exponential per trip) arms a probe
  PROBE      first batch after cooldown goes to the primary again —
             success restores HEALTHY, failure re-trips with a longer
             cooldown

Every transition emits `bccsp_degraded` (gauge), a
`bccsp_breaker_transitions_total` count, a jlog line, and a span event
on the ambient trace.  Signing, key-gen, and hashing are host-side in
every provider and always delegate to the primary.

The ops plane reads `.backend` — "jaxtpu" while healthy,
"sw(degraded)" while tripped — which the peer's `/healthz` bccsp
checker surfaces.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional, Sequence

import numpy as np

from fabric_tpu.ops_plane import tracing
from fabric_tpu.ops_plane.logging import jlog

from .provider import Provider, VerifyItem

logger = logging.getLogger("fabric_tpu.bccsp.degrade")


class DegradingProvider(Provider):
    def __init__(self, primary: Provider, fallback: Provider,
                 failure_threshold: int = 2,
                 cooldown_base_s: float = 1.0,
                 cooldown_max_s: float = 30.0,
                 watch_silent_fallbacks: bool = True):
        self.primary = primary
        self.sw = fallback
        self.name = primary.name
        self.failure_threshold = int(failure_threshold)
        self.cooldown_base_s = cooldown_base_s
        self.cooldown_max_s = cooldown_max_s
        # the JAXTPU provider absorbs device errors per batch by running
        # the batch on ITS OWN sw fallback without raising; watching its
        # `fallbacks` counter lets the breaker see that sickness too
        self.watch_silent_fallbacks = bool(watch_silent_fallbacks)
        self._lock = threading.Lock()
        self._degraded = False
        self._consec_fails = 0
        self._trips = 0
        self._probe_at = 0.0

    # -- breaker --------------------------------------------------------

    @property
    def degraded(self) -> bool:
        return self._degraded

    @property
    def backend(self) -> str:
        return (f"{self.sw.name}(degraded)" if self._degraded
                else self.primary.name)

    def _use_primary(self) -> bool:
        """Route the next batch to the primary?  True also arms the
        post-cooldown probe."""
        if not self._degraded:
            return True
        with self._lock:
            if self._degraded and time.monotonic() >= self._probe_at:
                # push the next probe out so concurrent batches don't
                # stampede a sick device; success clears everything
                self._probe_at = time.monotonic() + self.cooldown_base_s
                return True
            return False

    def _on_success(self) -> None:
        with self._lock:
            self._consec_fails = 0
            if not self._degraded:
                return
            self._degraded = False
            self._trips_observe("restored")

    def _on_failure(self, why: str) -> None:
        with self._lock:
            self._consec_fails += 1
            if self._degraded:
                # failed probe: back off harder
                self._trips += 1
                self._probe_at = time.monotonic() + self._cooldown()
                return
            if self._consec_fails < self.failure_threshold:
                return
            self._degraded = True
            self._trips += 1
            self._probe_at = time.monotonic() + self._cooldown()
            self._trips_observe(why)

    def _cooldown(self) -> float:
        return min(self.cooldown_max_s,
                   self.cooldown_base_s * (2 ** min(self._trips - 1, 16)))

    def _trips_observe(self, reason: str) -> None:
        """Caller holds self._lock; everything here is best-effort."""
        state = "degraded" if self._degraded else "healthy"
        try:
            from fabric_tpu.ops_plane import registry
            registry.gauge(
                "bccsp_degraded",
                "1 while the crypto provider runs on the SW fallback"
            ).set(1.0 if self._degraded else 0.0)
            registry.counter(
                "bccsp_breaker_transitions_total",
                "crypto-provider breaker state changes").add(
                    1, to=state, reason=reason)
            jlog(logger, "bccsp.breaker",
                 level=logging.WARNING if self._degraded else logging.INFO,
                 state=state, reason=reason, trips=self._trips,
                 backend=self.backend)
            tracing.event("bccsp." + state, reason=reason,
                          backend=self.backend)
        except Exception:
            pass

    # -- verification ---------------------------------------------------

    def _silent_fallbacks(self) -> int:
        if not self.watch_silent_fallbacks:
            return 0
        stats = getattr(self.primary, "stats", None)
        if isinstance(stats, dict):
            return int(stats.get("fallbacks", 0))
        return 0

    def batch_verify_async(self, items: Sequence[VerifyItem]):
        items = list(items)
        if not self._use_primary():
            return self.sw.batch_verify_async(items)
        fb0 = self._silent_fallbacks()
        try:
            resolve = self.primary.batch_verify_async(items)
        except Exception as exc:
            self._on_failure("enqueue:" + type(exc).__name__)
            logger.warning("primary bccsp enqueue failed (%r); "
                           "falling back to %s", exc, self.sw.name)
            return self.sw.batch_verify_async(items)

        def _resolve():
            try:
                out = resolve()
            except Exception as exc:
                self._on_failure("resolve:" + type(exc).__name__)
                logger.warning("primary bccsp resolve failed (%r); "
                               "re-verifying %d items on %s",
                               exc, len(items), self.sw.name)
                return self.sw.batch_verify(items)
            if self._silent_fallbacks() > fb0:
                # results are correct (primary already re-ran on its own
                # sw path) but the device is sick: tell the breaker
                self._on_failure("silent_fallback")
            else:
                self._on_success()
            return out

        return _resolve

    def batch_verify(self, items: Sequence[VerifyItem]) -> np.ndarray:
        return self.batch_verify_async(items)()

    # -- host-side verbs ------------------------------------------------

    def key_gen(self, scheme: str):
        return self.primary.key_gen(scheme)

    def sign(self, private_key, payload: bytes) -> bytes:
        return self.primary.sign(private_key, payload)

    def hash(self, data: bytes, algo: str = "sha256") -> bytes:
        return self.primary.hash(data, algo)

    def stats_snapshot(self):
        snap = getattr(self.primary, "stats_snapshot", None)
        return snap() if callable(snap) else None

    def __getattr__(self, name):
        # anything this wrapper doesn't own (stats, idemix probes,
        # device labels, ...) belongs to the primary — callers must not
        # have to care whether the provider is breaker-fronted
        return getattr(self.primary, name)
