"""Channel-config plane: typed config, live bundles, config transactions.

Re-design of /root/reference/common/{channelconfig,configtx,capabilities}
(VERDICT.md missing #1): config-as-consensus-state with atomic bundle
swap on committed config blocks.
"""

from .channelconfig import (
    Bundle,
    BundleSource,
    BatchConfig,
    CAP_KEY_LEVEL_ENDORSEMENT,
    CAP_V2_0,
    ChannelConfig,
    ConfigError,
    OrgConfig,
    default_policies,
)
from .configtx import (
    apply_config_block,
    build_config_envelope,
    parse_config_envelope,
    validate_config_update,
    validate_parsed_config_update,
    config_envelope_of,
)

__all__ = [
    "Bundle", "BundleSource", "BatchConfig", "ChannelConfig", "ConfigError",
    "OrgConfig", "default_policies", "CAP_V2_0", "CAP_KEY_LEVEL_ENDORSEMENT",
    "apply_config_block", "build_config_envelope", "parse_config_envelope",
    "validate_config_update", "validate_parsed_config_update", "config_envelope_of",
]
