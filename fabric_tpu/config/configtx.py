"""Config transaction construction, validation, and application.

Reference parity: common/configtx/validator.go (ProposeConfigUpdate /
Validate), orderer/common/msgprocessor ProcessConfigUpdateMsg, and the
peer-side config-block consumption in core/peer (channel config updates
take effect at commit).

A config envelope's payload carries:
  {"config": <ChannelConfig serialized>, "last_update_sigs": [SignedData]}
The signatures are over the serialized new config (binding them to the
channel id and sequence inside it) and must satisfy the CURRENT bundle's
Admins policy; the sequence must be exactly current+1
(configtx/validator.go:1 sequence rule).
"""

from __future__ import annotations

import time
from typing import List, Optional

from fabric_tpu.policy import SignedData
from fabric_tpu.protocol.build import compute_txid
from fabric_tpu.protocol.types import (
    ChannelHeader,
    Envelope,
    Header,
    SignatureHeader,
    TX_CONFIG,
)
from fabric_tpu.utils import serde

from .channelconfig import Bundle, ChannelConfig, ConfigError


def build_config_envelope(new_config: ChannelConfig, signers,
                          nonce: bytes = b"") -> Envelope:
    """Create a signed config envelope.

    signers: list of objects with .serialize() -> identity bytes and
    .sign(data) -> signature (msp SigningIdentity surface).  Every signer
    signs the serialized new config; their signatures ride in the payload
    for Admins-policy evaluation at validation time.
    """
    cfg_bytes = new_config.serialize()
    sigs = []
    for s in signers:
        sigs.append({"identity": s.serialize(), "signature": s.sign(cfg_bytes)})
    creator = signers[0].serialize() if signers else b""
    nonce = nonce or str(time.time_ns()).encode()
    txid = compute_txid(nonce, creator)
    header = Header(
        channel_header=ChannelHeader(TX_CONFIG, new_config.channel_id, txid,
                                     timestamp=int(time.time())),
        signature_header=SignatureHeader(creator=creator, nonce=nonce),
    )
    payload = {
        "header": header.to_dict(),
        "data": serde.encode({"config": cfg_bytes, "sigs": sigs}),
    }
    payload_bytes = serde.encode(payload)
    signature = signers[0].sign(payload_bytes) if signers else b""
    return Envelope(payload=payload_bytes, signature=signature)


def parse_config_envelope(env: Envelope) -> tuple:
    """-> (ChannelConfig, List[SignedData over the config bytes])."""
    body = serde.decode(env.payload_dict()["data"])
    cfg_bytes = body["config"]
    cfg = ChannelConfig.deserialize(cfg_bytes)
    sds = [SignedData(data=cfg_bytes, identity=s["identity"],
                      signature=s["signature"]) for s in body["sigs"]]
    return cfg, sds


def config_envelope_of(block) -> Optional[Envelope]:
    """The single config envelope of a config block, else None.

    THE definition of "is a config block": config blocks are always cut as
    single-envelope blocks (the chain's configure() isolates them; a config
    tx smuggled into a multi-tx block is flagged invalid by the validator).
    Shared by the committer and apply_config_block so the rule cannot
    drift.
    """
    from fabric_tpu.protocol.wire import n_txs
    if n_txs(block) != 1:
        return None
    try:
        env = Envelope.deserialize(block.data[0])
    except Exception:
        return None          # malformed envelope: flagged by the validator
    try:
        is_config = env.header().channel_header.type == TX_CONFIG
    except Exception:
        return None
    return env if is_config else None


def validate_config_update(bundle: Bundle, env: Envelope, provider) -> ChannelConfig:
    """Admission + commit-time validation of a config envelope against the
    CURRENT bundle.  Returns the new ChannelConfig or raises ConfigError.

    Rules (configtx/validator.go):
      - channel id must match,
      - sequence must be exactly bundle.sequence + 1,
      - signature set must satisfy the current Admins policy,
      - the new config must build into a Bundle (MSPs must parse).
    """
    try:
        cfg, sds = parse_config_envelope(env)
    except Exception as exc:
        raise ConfigError(f"malformed config envelope: {exc}") from exc
    return validate_parsed_config_update(bundle, cfg, sds, provider)


def validate_parsed_config_update(bundle: Bundle, cfg: ChannelConfig,
                                  sds: List[SignedData],
                                  provider) -> ChannelConfig:
    """validate_config_update on an already-parsed envelope body."""
    if cfg.channel_id != bundle.channel_id:
        raise ConfigError(
            f"config for channel {cfg.channel_id!r} on {bundle.channel_id!r}")
    if cfg.sequence != bundle.sequence + 1:
        raise ConfigError(
            f"config sequence {cfg.sequence}, expected {bundle.sequence + 1}")
    if not bundle.evaluate_policy("Admins", sds, provider):
        raise ConfigError("config update not authorized by Admins policy")
    try:
        Bundle(cfg)
    except Exception as exc:
        raise ConfigError(f"config does not materialize: {exc}") from exc
    return cfg


def apply_config_block(source, block, provider) -> Optional[Bundle]:
    """Peer-side consumption: if the block carries a (valid) config tx,
    re-validate against the current bundle and swap the source.

    Returns the new Bundle when applied, else None.  Mirrors
    core/peer/peer.go channel-config update at commit: validation happened
    at ordering admission too, but commit-side re-validation keeps peers
    that weren't the ordering node honest.
    """
    env = config_envelope_of(block)
    if env is None:
        return None
    cfg = validate_config_update(source.current(), env, provider)
    new_bundle = Bundle(cfg)
    source.update(new_bundle)
    return new_bundle
