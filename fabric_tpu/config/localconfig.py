"""Node-config loading with an environment-variable override tier.

Reference parity: common/viperutil/config_util.go plus the CORE_* /
ORDERER_* env tiers the reference binaries install at startup
(/root/reference/cmd/peer/main.go:31-34, orderer/common/localconfig).
Precedence, low to high:

  1. the node's JSON config file,
  2. ``FABRIC_TPU_<ROLE>_...`` environment variables.

Naming: the env suffix is the upper-cased config key; ``__`` (double
underscore) descends into nested objects — a single ``_`` stays part of
the key, so keys like ``ops_port`` are unambiguous (viper's single-'_'
nesting cannot express them):

  FABRIC_TPU_PEER_PORT=9443            ->  cfg["port"] = 9443
  FABRIC_TPU_PEER_OPS_PORT=9444        ->  cfg["ops_port"] = 9444
  FABRIC_TPU_ORDERER_RAFT__TICK_MS=50  ->  cfg["raft"]["tick_ms"] = 50

Values parse as JSON when possible (numbers, booleans, lists, objects)
and fall back to the raw string — ``FABRIC_TPU_PEER_HOST=0.0.0.0``
needs no quoting.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

logger = logging.getLogger("fabric_tpu.config.localconfig")


def apply_env_overrides(cfg: dict, role: str,
                        environ: Optional[dict] = None) -> dict:
    """Layer FABRIC_TPU_<ROLE>_* overrides onto cfg (mutated + returned)."""
    env = os.environ if environ is None else environ
    prefix = f"FABRIC_TPU_{role.upper()}_"
    for name in sorted(env):
        if not name.startswith(prefix) or name == prefix:
            continue
        path = name[len(prefix):].lower().split("__")
        raw = env[name]
        try:
            value = json.loads(raw)
        except ValueError:
            value = raw
        node = cfg
        ok = True
        for part in path[:-1]:
            nxt = node.get(part)
            if nxt is None:
                nxt = node[part] = {}
            elif not isinstance(nxt, dict):
                logger.warning("env override %s: %r is not an object; "
                               "ignored", name, part)
                ok = False
                break
            node = nxt
        if ok:
            node[path[-1]] = value
            logger.info("config override from env: %s", name)
    return cfg


def load_node_config(path: str, role: str,
                     environ: Optional[dict] = None) -> dict:
    """Read a node JSON config and apply the env override tier."""
    with open(path) as f:
        cfg = json.load(f)
    return apply_env_overrides(cfg, role, environ=environ)
