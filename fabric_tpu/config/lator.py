"""configtxlator: config <-> JSON translation + update computation.

Reference parity: /root/reference/internal/configtxlator — the ops tool
that turns opaque serialized channel config into reviewable JSON
(`proto_decode`), back (`proto_encode`), and computes the delta between
an original and an updated config (`compute_update`).  Here the wire
form is the framework's canonical serde of ChannelConfig; bytes fields
render as {"$base64": ...} so the JSON is lossless.

CLI:
  python -m fabric_tpu.config.lator decode  <config.bin>  > config.json
  python -m fabric_tpu.config.lator encode  <config.json> > config.bin
  python -m fabric_tpu.config.lator compute-update <orig.bin> <new.json>
      > update.bin    (re-sequenced updated config + human diff on stderr)
"""

from __future__ import annotations

import base64
import json
import sys
from typing import Any, List

from .channelconfig import ChannelConfig


def jsonify(v: Any) -> Any:
    if isinstance(v, (bytes, bytearray)):
        return {"$base64": base64.b64encode(bytes(v)).decode()}
    if isinstance(v, dict):
        return {k: jsonify(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [jsonify(x) for x in v]
    return v


def dejsonify(v: Any) -> Any:
    if isinstance(v, dict):
        if set(v) == {"$base64"}:
            return base64.b64decode(v["$base64"])
        return {k: dejsonify(x) for k, x in v.items()}
    if isinstance(v, list):
        return [dejsonify(x) for x in v]
    return v


def decode_config(raw: bytes) -> str:
    cfg = ChannelConfig.deserialize(raw)
    return json.dumps(jsonify(cfg.to_dict()), indent=2, sort_keys=True)


def encode_config(json_text: str) -> bytes:
    d = dejsonify(json.loads(json_text))
    return ChannelConfig.from_dict(d).serialize()


def compute_update(original_raw: bytes, updated_json: str):
    """-> (updated config bytes with sequence = original+1, diff lines).

    The reference emits a ConfigUpdate proto (read/write set delta); this
    framework's config plane replaces whole configs at commit
    (config/configtx.py), so the 'update' is the re-sequenced new config
    plus a reviewable diff of what changed.
    """
    orig = ChannelConfig.deserialize(original_raw)
    new = ChannelConfig.from_dict(dejsonify(json.loads(updated_json)))
    if new.channel_id != orig.channel_id:
        raise ValueError(
            f"channel mismatch: {new.channel_id!r} vs {orig.channel_id!r}")
    import dataclasses
    new = dataclasses.replace(new, sequence=orig.sequence + 1)

    diff: List[str] = []
    o_orgs = {o.mspid: o for o in orig.orgs}
    n_orgs = {o.mspid: o for o in new.orgs}
    for mspid in sorted(set(n_orgs) - set(o_orgs)):
        diff.append(f"+ org {mspid}")
    for mspid in sorted(set(o_orgs) - set(n_orgs)):
        diff.append(f"- org {mspid}")
    for mspid in sorted(set(o_orgs) & set(n_orgs)):
        if o_orgs[mspid] != n_orgs[mspid]:
            diff.append(f"~ org {mspid} (MSP material changed)")
    for name in sorted(set(orig.policies) | set(new.policies)):
        a, b = orig.policies.get(name), new.policies.get(name)
        if a != b:
            tag = "+" if a is None else ("-" if b is None else "~")
            diff.append(f"{tag} policy {name}")
    if tuple(orig.capabilities) != tuple(new.capabilities):
        diff.append(f"~ capabilities {sorted(orig.capabilities)} -> "
                    f"{sorted(new.capabilities)}")
    if orig.batch != new.batch:
        diff.append("~ batch config")
    if tuple(orig.consenters) != tuple(new.consenters):
        diff.append(f"~ consenters {list(orig.consenters)} -> "
                    f"{list(new.consenters)}")
    diff.append(f"sequence {orig.sequence} -> {new.sequence}")
    return new.serialize(), diff


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    cmd = argv[0]
    if cmd == "decode" and len(argv) == 2:
        with open(argv[1], "rb") as f:
            sys.stdout.write(decode_config(f.read()))
        return 0
    if cmd == "encode" and len(argv) == 2:
        with open(argv[1]) as f:
            sys.stdout.buffer.write(encode_config(f.read()))
        return 0
    if cmd == "compute-update" and len(argv) == 3:
        with open(argv[1], "rb") as f:
            orig = f.read()
        with open(argv[2]) as f:
            raw, diff = compute_update(orig, f.read())
        sys.stdout.buffer.write(raw)
        for line in diff:
            print(line, file=sys.stderr)
        return 0
    print(__doc__, file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
