"""Channel configuration as consensus state: typed config + live Bundle.

Reference parity (VERDICT.md missing #1):
  common/channelconfig/bundle.go     — immutable typed view of the config
  common/channelconfig/application.go, orderer.go, organization.go
  common/capabilities/*.go           — feature gating per channel
  common/configtx/validator.go       — config-tx validation & sequencing

Design (TPU-first framework, host-side control plane): a channel's
configuration is a serializable `ChannelConfig` value committed through
the ordering service like any transaction; every consumer (msgprocessor
writers filter, deliver readers ACL, txvalidator MSPs/policies, block
cutter batch limits) reads the *current* immutable `Bundle` through a
shared `BundleSource` and picks up the new bundle atomically when a
config block commits — mirroring how the reference resolves resources
through the bundle at each use (channelconfig/bundlesource.go).

Deviation from the reference, documented: config updates here carry the
full next ChannelConfig plus the expected sequence number, not a
read-set/write-set delta (configtx/update.go).  Validation still enforces
the two invariants that matter for safety: monotonic sequence (exactly
current+1) and authorization by the current Admins policy.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from fabric_tpu.msp import MSP, MSPConfig, Principal
from fabric_tpu.msp.cache import CachedMSP
from fabric_tpu.policy import (
    PolicyEvaluator,
    SignaturePolicy,
    SignedData,
    n_out_of,
    signed_by,
)
from fabric_tpu.utils import serde


class ConfigError(Exception):
    """Config transaction rejected."""


# Capability names (common/capabilities/application.go flags, reduced to
# the ones this framework gates behavior on).
CAP_V2_0 = "V2_0"
CAP_KEY_LEVEL_ENDORSEMENT = "V1_3_KeyLevelEndorsement"


@dataclass(frozen=True)
class OrgConfig:
    """One organization: MSP material + org-scoped policy expressions."""
    mspid: str
    root_certs: tuple            # PEM bytes
    admins: tuple = ()           # PEM bytes of admin certs (by-identity role)
    intermediate_certs: tuple = ()
    crls: tuple = ()

    def to_dict(self) -> dict:
        return {"mspid": self.mspid, "root_certs": list(self.root_certs),
                "admins": list(self.admins),
                "intermediate_certs": list(self.intermediate_certs),
                "crls": list(self.crls)}

    @staticmethod
    def from_dict(d: dict) -> "OrgConfig":
        return OrgConfig(d["mspid"], tuple(d["root_certs"]),
                         tuple(d.get("admins", ())),
                         tuple(d.get("intermediate_certs", ())),
                         tuple(d.get("crls", ())))


@dataclass(frozen=True)
class BatchConfig:
    """orderer.BatchSize/BatchTimeout (orderer/common/localconfig)."""
    max_message_count: int = 500
    absolute_max_bytes: int = 10 * 1024 * 1024
    preferred_max_bytes: int = 2 * 1024 * 1024
    timeout_s: float = 2.0

    def to_dict(self) -> dict:
        return {"max_message_count": self.max_message_count,
                "absolute_max_bytes": self.absolute_max_bytes,
                "preferred_max_bytes": self.preferred_max_bytes,
                "timeout_ms": int(self.timeout_s * 1000)}

    @staticmethod
    def from_dict(d: dict) -> "BatchConfig":
        return BatchConfig(d["max_message_count"], d["absolute_max_bytes"],
                           d["preferred_max_bytes"], d["timeout_ms"] / 1000.0)


@dataclass(frozen=True)
class ChannelConfig:
    """The full channel configuration value (a config block's payload).

    policies: name -> SignaturePolicy for the channel-level policies the
    stack consults ("Readers", "Writers", "Admins", plus application
    defaults like "Endorsement").  acls: resource name -> policy name
    (core/aclmgmt resource map).
    """
    channel_id: str
    sequence: int
    orgs: tuple                       # tuple[OrgConfig]
    policies: Dict[str, SignaturePolicy]
    batch: BatchConfig = BatchConfig()
    capabilities: tuple = (CAP_V2_0, CAP_KEY_LEVEL_ENDORSEMENT)
    acls: Dict[str, str] = field(default_factory=dict)
    consenters: tuple = ()            # raft node ids, informational

    def to_dict(self) -> dict:
        return {
            "channel_id": self.channel_id,
            "sequence": self.sequence,
            "orgs": [o.to_dict() for o in self.orgs],
            "policies": {k: v.to_dict() for k, v in self.policies.items()},
            "batch": self.batch.to_dict(),
            "capabilities": list(self.capabilities),
            "acls": dict(self.acls),
            "consenters": list(self.consenters),
        }

    def serialize(self) -> bytes:
        return serde.encode(self.to_dict())

    @staticmethod
    def from_dict(d: dict) -> "ChannelConfig":
        return ChannelConfig(
            channel_id=d["channel_id"],
            sequence=d["sequence"],
            orgs=tuple(OrgConfig.from_dict(o) for o in d["orgs"]),
            policies={k: SignaturePolicy.from_dict(v)
                      for k, v in d["policies"].items()},
            batch=BatchConfig.from_dict(d["batch"]),
            capabilities=tuple(d.get("capabilities", ())),
            acls=dict(d.get("acls", {})),
            consenters=tuple(d.get("consenters", ())),
        )

    @staticmethod
    def deserialize(data: bytes) -> "ChannelConfig":
        return ChannelConfig.from_dict(serde.decode(data))


def default_policies(mspids: List[str]) -> Dict[str, SignaturePolicy]:
    """The implicit-meta defaults: Readers/Writers = any member,
    Admins = majority of org admins (policies/implicitmeta.go semantics,
    compiled down to explicit NOutOf over org principals)."""
    members = [signed_by(Principal.member(m)) for m in mspids]
    admins = [signed_by(Principal.admin(m)) for m in mspids]
    majority = len(mspids) // 2 + 1
    return {
        "Readers": n_out_of(1, members),
        "Writers": n_out_of(1, members),
        "Admins": n_out_of(majority, admins),
        "Endorsement": n_out_of(majority, members),
    }


class Bundle:
    """Immutable materialization of a ChannelConfig: live MSPs + policy
    evaluator + batch/capability accessors (channelconfig/bundle.go)."""

    def __init__(self, config: ChannelConfig):
        self.config = config
        self.msps: Dict[str, CachedMSP] = {}
        for org in config.orgs:
            self.msps[org.mspid] = CachedMSP(MSP(MSPConfig(
                mspid=org.mspid,
                root_certs_pem=list(org.root_certs),
                intermediate_certs_pem=list(org.intermediate_certs),
                admin_certs_pem=list(org.admins),
                crls_pem=list(org.crls),
            )))

    @property
    def channel_id(self) -> str:
        return self.config.channel_id

    @property
    def sequence(self) -> int:
        return self.config.sequence

    @property
    def batch(self) -> BatchConfig:
        return self.config.batch

    def has_capability(self, cap: str) -> bool:
        return cap in self.config.capabilities

    def policy(self, name: str) -> Optional[SignaturePolicy]:
        return self.config.policies.get(name)

    def acl_policy_name(self, resource: str, default: str = "Writers") -> str:
        return self.config.acls.get(resource, default)

    def evaluator(self, provider) -> PolicyEvaluator:
        return PolicyEvaluator(self.msps, provider)

    def evaluate_policy(self, name: str, signed_data, provider) -> bool:
        """Control-plane policy evaluation (batched through the provider
        like every other signature set)."""
        pol = self.policy(name)
        if pol is None:
            return False
        return PolicyEvaluator(self.msps, provider).evaluate_signed_data(
            pol, signed_data)


class BundleSource:
    """Thread-safe holder of the current Bundle; consumers call current()
    at each use so a committed config block takes effect atomically
    (channelconfig/bundlesource.go)."""

    def __init__(self, bundle: Bundle, config_height: int = 0):
        self._lock = threading.Lock()
        self._bundle = bundle
        self._listeners: List = []
        # block number at/below which config txs are genuine catch-up
        # replay: the height of the block that carried the bootstrap
        # config (0 for a genesis bootstrap).  The committer advances it
        # as config blocks are applied, and uses it to tell historical
        # replay apart from a fresh block carrying a stale-sequence
        # config tx (which must be flagged INVALID, configtx semantics).
        self.config_height = int(config_height)

    def current(self) -> Bundle:
        with self._lock:
            return self._bundle

    def update(self, bundle: Bundle, config_height: int = None) -> None:
        with self._lock:
            # check-and-swap under one lock: concurrent appliers must not
            # be able to install an older bundle over a newer one
            if bundle.sequence <= self._bundle.sequence:
                raise ConfigError(
                    f"config sequence regression: {bundle.sequence} <= "
                    f"{self._bundle.sequence}")
            self._bundle = bundle
            if config_height is not None:
                # advanced atomically with the bundle so on_update
                # listeners (e.g. the peer's config persistence) observe
                # a consistent (bundle, height) pair
                self.config_height = max(self.config_height,
                                         int(config_height))
            listeners = list(self._listeners)
        for cb in listeners:
            cb(bundle)

    def on_update(self, cb) -> None:
        """Register callback(bundle) invoked after each update."""
        with self._lock:
            self._listeners.append(cb)
