"""Batched ECDSA-P256 verification on TPU.

TPU-native equivalent of the reference's verifyECDSA
(/root/reference/bccsp/sw/ecdsa.go:41-58): same semantics — the message is
already hashed upstream (msp/identities.go:178), r/s must be in [1, n-1],
and high-S signatures are REJECTED (ecdsa.go:47-53, bccsp/utils/ecdsa.go:84)
— but evaluated for an entire block's worth of signatures in one jitted
data-parallel dispatch instead of one goroutine per transaction
(core/committer/txvalidator/v20/validator.go:194-209).

Inputs are (8, B) uint32 big-endian words (SEC1 byte order); output is a
(B,) bool verdict bitmap.  No hashing, parsing, or variable-length data on
device.  The final x-coordinate comparison is done projectively
(X == r*Z^2), avoiding any field inversion; only one Fermat inversion mod n
(for s^-1) remains.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import bignum as bn
from .weierstrass import ShortCurve

P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
HALF_N = (N - 1) // 2

curve = ShortCurve(P, A, B, GX, GY, N, name="p256")


def verify_words(qx, qy, r, s, e, require_low_s: bool = True) -> jnp.ndarray:
    """Batched ECDSA-P256 verify over big-endian uint32 words.

    qx, qy, r, s, e: (8, B) uint32 — public key affine coords, signature
    (r, s), and the 32-byte message digest interpreted as a big-endian
    integer (SEC1 bits2int for SHA-256 is the identity).
    Returns (B,) bool.
    """
    fp, fn = curve.fp, curve.fn
    qx_l = bn.words_be_to_limbs(qx)
    qy_l = bn.words_be_to_limbs(qy)
    r_l = bn.words_be_to_limbs(r)
    s_l = bn.words_be_to_limbs(s)
    e_l = bn.words_be_to_limbs(e)

    # --- scalar-range and key validity preconditions (all batched) ---
    r_ok = bn.limbs_lt_const(r_l, N) & ~bn.limbs_is_zero(r_l)
    s_ok = bn.limbs_lt_const(s_l, N) & ~bn.limbs_is_zero(s_l)
    if require_low_s:
        s_ok = s_ok & bn.limbs_lt_const(s_l, HALF_N + 1)
    q_range_ok = bn.limbs_lt_const(qx_l, P) & bn.limbs_lt_const(qy_l, P)

    qx_m = fp.to_mont(qx_l)
    qy_m = fp.to_mont(qy_l)
    q_ok = q_range_ok & curve.on_curve_affine(qx_m, qy_m)
    # affine input cannot encode infinity; (0, +-sqrt(b)) is on-curve but is
    # a valid finite point on P-256 (cofactor 1), so no extra subgroup check.

    # --- u1 = e/s, u2 = r/s (mod n) ---
    s_mn = fn.to_mont(s_l)
    e_mn = fn.to_mont(e_l)  # to_mont reduces e mod n implicitly
    r_mn = fn.to_mont(r_l)
    w = fn.inv(s_mn)
    u1 = fn.from_mont(fn.mul(e_mn, w))   # canonical integer limbs in [0, n)
    u2 = fn.from_mont(fn.mul(r_mn, w))

    # --- R = u1*G + u2*Q ---
    Q = curve.to_jacobian(qx_m, qy_m)
    X, Y, Z = curve.shamir(u1, u2, Q, n_bits=256)
    nonzero = ~fp.is_zero(Z)

    # --- projective check: X == (r mod p adjustments) * Z^2 ---
    z2 = fp.sqr(Z)
    r_mp = fp.to_mont(r_l)
    eq1 = fp.eq(X, fp.mul(r_mp, z2))
    # r + n may also be a valid x-coordinate when r + n < p
    rn_l = bn.carry_prop(r_l + jnp.asarray(bn.int_to_limbs(N).reshape(bn.N_LIMBS, 1)),
                         bn.N_LIMBS)
    rn_lt_p = bn.limbs_lt_const(rn_l, P)
    eq2 = rn_lt_p & fp.eq(X, fp.mul(fp.to_mont(rn_l), z2))

    return r_ok & s_ok & q_ok & nonzero & (eq1 | eq2)


# ---------------------------------------------------------------------------
# Host-side packing helpers (numpy; byte-level, used by the provider layer)
# ---------------------------------------------------------------------------

def bytes32_to_words(vals: list) -> np.ndarray:
    """list of B 32-byte big-endian bytestrings -> (8, B) uint32."""
    for v in vals:
        if len(v) != 32:
            raise ValueError("expected 32-byte value")
    if not vals:
        return np.zeros((8, 0), dtype=np.uint32)
    flat = np.frombuffer(b"".join(vals), dtype=">u4")
    return np.ascontiguousarray(
        flat.reshape(len(vals), 8).T).astype(np.uint32)


def ints_to_words(vals: list) -> np.ndarray:
    """list of B python ints (< 2^256) -> (8, B) uint32 big-endian words."""
    return bytes32_to_words([int(v).to_bytes(32, "big") for v in vals])
