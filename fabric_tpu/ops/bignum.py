"""256-bit modular arithmetic on TPU via 12-bit limbs in int32 lanes.

This is the foundation of the TPU crypto plane: every scalar-field and
base-field operation used by batched ECDSA-P256 / ed25519 verification
(the reference's hot path: /root/reference/bccsp/sw/ecdsa.go:41,
msp/identities.go:169) runs on arrays shaped (N_LIMBS, B) where B is the
signature batch dimension.

Design notes (TPU-first):
- 12-bit limbs stored in int32: schoolbook partial products are <= 2^24 and
  a full 22-term column sum stays < 2^31, so everything fits int32 lanes —
  no int64 emulation, no float tricks.
- limbs-first layout (L, B): the batch axis is minor, so the VPU vectorizes
  across signatures; limb indexing is static leading-axis slicing.
- Montgomery (CIOS) multiplication, generic over any odd modulus <= 2^256:
  the same machinery serves the P-256 base field, the P-256 group order,
  the curve25519 field, and the ed25519 group order.
- Limb iteration uses lax.scan so the traced graph stays small (a full
  ECDSA verify compiles to a few thousand HLO ops, not millions); all
  shapes are static and there is no data-dependent control flow.

int32 overflow analysis for the CIOS accumulator: each scan step adds
a_i*b_j + m*p_j <= 2*(2^12-1)^2 ~ 3.36e7 to a limb; a limb lives through at
most N_LIMBS=22 steps before being shifted out, so its magnitude stays
below 22*3.36e7 + carry ~ 7.4e8 < 2^31.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

LIMB_BITS = 12
LIMB_MASK = (1 << LIMB_BITS) - 1
N_LIMBS = 22  # 264 bits capacity: holds any value < 2*p for p < 2^256


# ---------------------------------------------------------------------------
# Host-side conversions (numpy, used for constants and tests)
# ---------------------------------------------------------------------------

def int_to_limbs(x: int, n_limbs: int = N_LIMBS) -> np.ndarray:
    """Little-endian 12-bit limb decomposition of a python int."""
    if x < 0:
        raise ValueError("negative")
    out = np.zeros((n_limbs,), dtype=np.int32)
    for i in range(n_limbs):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    if x:
        raise ValueError("value does not fit in %d limbs" % n_limbs)
    return out


def limbs_to_int(a) -> int:
    """Inverse of int_to_limbs (host-side; accepts (L,) or (L, 1))."""
    arr = np.asarray(a).reshape(np.asarray(a).shape[0], -1)
    if arr.shape[1] != 1:
        raise ValueError("limbs_to_int expects a single element")
    return limbs_to_ints(arr)[0]


def limbs_to_ints(a) -> list:
    """Batch version: (L, B) -> list of B python ints."""
    arr = np.asarray(a)
    out = []
    for b in range(arr.shape[1]):
        x = 0
        for i in reversed(range(arr.shape[0])):
            x = (x << LIMB_BITS) | int(arr[i, b])
        out.append(x)
    return out


def ints_to_limbs(vals) -> np.ndarray:
    """list of B python ints -> (N_LIMBS, B) int32."""
    return np.stack([int_to_limbs(v) for v in vals], axis=1)


# ---------------------------------------------------------------------------
# Device-side primitives. All arrays are int32 (L, B); ops return new arrays.
# ---------------------------------------------------------------------------

def words_be_to_limbs(words) -> jnp.ndarray:
    """(8, B) uint32 big-endian words -> (N_LIMBS, B) int32 12-bit limbs.

    words[0] is the most significant 32 bits (matches SEC1/RFC8032 byte
    order after packing bytes big-endian into uint32 words).
    """
    w = jnp.asarray(words, dtype=jnp.uint32)
    wle = w[::-1]  # little-endian word order
    limbs = []
    for j in range(N_LIMBS):
        bitpos = j * LIMB_BITS
        wi = bitpos // 32
        shift = bitpos % 32
        if wi >= 8:
            limbs.append(jnp.zeros_like(wle[0]))
            continue
        val = wle[wi] >> shift
        if shift > 32 - LIMB_BITS and wi + 1 < 8:
            val = val | (wle[wi + 1] << (32 - shift))
        limbs.append(val & LIMB_MASK)
    return jnp.stack(limbs).astype(jnp.int32)


def limbs_to_words_be(a) -> jnp.ndarray:
    """(N_LIMBS, B) canonical limbs -> (8, B) uint32 big-endian words."""
    a = jnp.asarray(a, dtype=jnp.uint32)
    words = []
    for wi in range(8):  # little-endian word index
        lo_bit = wi * 32
        acc = jnp.zeros_like(a[0])
        for j in range(N_LIMBS):
            bitpos = j * LIMB_BITS
            if bitpos + LIMB_BITS <= lo_bit or bitpos >= lo_bit + 32:
                continue
            sh = bitpos - lo_bit
            if sh >= 0:
                acc = acc | (a[j] << sh)
            else:
                acc = acc | (a[j] >> (-sh))
        words.append(acc)
    return jnp.stack(words[::-1])


def carry_prop(x: jnp.ndarray, n_out: int) -> jnp.ndarray:
    """Signed carry propagation: (L, B) int32 -> (n_out, B) canonical limbs.

    Accepts limbs with magnitude up to ~2^30 (positive or negative); output
    limbs are in [0, 2^LIMB_BITS). The total value must be representable in
    n_out limbs and non-negative.
    """
    L = x.shape[0]
    if L < n_out:
        pad = jnp.zeros((n_out - L,) + x.shape[1:], dtype=x.dtype)
        x = jnp.concatenate([x, pad], axis=0)
    elif L > n_out:
        raise ValueError("carry_prop cannot drop limbs")

    def body(c, xi):
        v = xi + c
        return v >> LIMB_BITS, v & LIMB_MASK

    _, out = lax.scan(body, x[0] * 0, x)
    return out


def cond_sub(x: jnp.ndarray, c_limbs: np.ndarray) -> jnp.ndarray:
    """If x >= c then x - c else x.  x: (L, B) canonical limbs, c: (L,) const."""
    c = jnp.asarray(np.asarray(c_limbs, dtype=np.int32).reshape(-1, *([1] * (x.ndim - 1))))

    def body(borrow, args):
        xi, ci = args
        v = xi - ci + borrow
        return v >> LIMB_BITS, v & LIMB_MASK

    borrow, t = lax.scan(body, x[0] * 0, (x, jnp.broadcast_to(c, x.shape)))
    return jnp.where(borrow == 0, t, x)


def limbs_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(L, B) x (L, B) -> (B,) bool, exact limb equality."""
    return jnp.all(a == b, axis=0)


def limbs_is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == 0, axis=0)


def limbs_lt_const(x: jnp.ndarray, c: int) -> jnp.ndarray:
    """(L, B) canonical limbs < python-int constant -> (B,) bool."""
    c_l = jnp.asarray(int_to_limbs(c, x.shape[0]).reshape(-1, *([1] * (x.ndim - 1))))

    def body(borrow, args):
        xi, ci = args
        v = xi - ci + borrow
        return v >> LIMB_BITS, None

    borrow, _ = lax.scan(body, x[0] * 0, (x, jnp.broadcast_to(c_l, x.shape)))
    return borrow < 0


def bit(a: jnp.ndarray, i: int) -> jnp.ndarray:
    """Static bit extraction from canonical limbs: (L, B) -> (B,) int32 0/1."""
    return (a[i // LIMB_BITS] >> (i % LIMB_BITS)) & 1


def bits_window(a: jnp.ndarray, lo: int, width: int) -> jnp.ndarray:
    """Static extraction of bits [lo, lo+width) as a (B,) int32 value."""
    acc = jnp.zeros_like(a[0])
    for k in range(width):
        acc = acc | (bit(a, lo + k) << k)
    return acc


def to_bits(a: jnp.ndarray, n_bits: int) -> jnp.ndarray:
    """(L, B) canonical limbs -> (n_bits, B) int32 bits, LSB first.

    Vectorized: expands each limb into LIMB_BITS rows, then trims.
    """
    L = a.shape[0]
    shifts = jnp.arange(LIMB_BITS, dtype=jnp.int32).reshape(1, LIMB_BITS, *([1] * (a.ndim - 1)))
    expanded = (a[:, None] >> shifts) & 1  # (L, LIMB_BITS, B)
    flat = expanded.reshape((L * LIMB_BITS,) + a.shape[1:])
    return flat[:n_bits]


# ---------------------------------------------------------------------------
# Montgomery context
# ---------------------------------------------------------------------------

class Mont:
    """Montgomery arithmetic mod an odd prime p <= 2^256, R = 2^264.

    Domain invariant: all "Montgomery-form" values are canonical-limbed
    integers in [0, 2p).  mul/add/sub preserve the invariant; canon()
    produces the unique representative in [0, p).
    """

    def __init__(self, modulus: int, name: str = ""):
        if modulus % 2 == 0 or modulus >= (1 << 256):
            raise ValueError("modulus must be odd and < 2^256")
        self.p = modulus
        self.name = name
        self.R = 1 << (N_LIMBS * LIMB_BITS)
        self.n0inv = (-pow(modulus, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)
        self.p_limbs = int_to_limbs(modulus)
        self.p2_limbs = int_to_limbs(2 * modulus)
        self.r2_np = int_to_limbs((self.R * self.R) % modulus)
        self.one_np = int_to_limbs(self.R % modulus)  # 1 in Montgomery form

    # -- constant helpers ---------------------------------------------------

    def const(self, x: int) -> np.ndarray:
        """Montgomery form of python int x as a (L, 1) numpy constant
        (broadcasts against (L, B) arrays)."""
        m = (x % self.p) * self.R % self.p
        return int_to_limbs(m).reshape(N_LIMBS, 1)

    def one(self) -> np.ndarray:
        return self.one_np.reshape(N_LIMBS, 1).copy()

    def zero(self) -> np.ndarray:
        return np.zeros((N_LIMBS, 1), dtype=np.int32)

    def one_bc(self, bshape) -> jnp.ndarray:
        """Montgomery 1 broadcast to (N_LIMBS,) + bshape."""
        return jnp.broadcast_to(
            jnp.asarray(self.one_np.reshape(N_LIMBS, *([1] * len(bshape)))),
            (N_LIMBS,) + tuple(bshape))

    # -- core ops -----------------------------------------------------------

    def mul(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """CIOS Montgomery multiplication: returns a*b*R^-1 mod p, < 2p.

        Inputs must be canonical-limbed and < 2p (one operand may be any
        value < R).  Implemented as a lax.scan over a's limbs.
        """
        a = jnp.asarray(a, dtype=jnp.int32)
        b = jnp.asarray(b, dtype=jnp.int32)
        bshape = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
        b = jnp.broadcast_to(b, (N_LIMBS,) + bshape)
        p_col = jnp.asarray(
            self.p_limbs.reshape(N_LIMBS, *([1] * len(bshape))))
        n0inv = np.int32(self.n0inv)

        def body(acc, ai):
            # acc: (N_LIMBS, B); ai: broadcastable to (B,).  Every partial
            # product a_t*b_j lands at a final (shifted) index <= N_LIMBS-1,
            # so no extra top row is needed.
            acc = acc + ai * b
            m = (acc[0] * n0inv) & LIMB_MASK
            acc = acc + m * p_col
            c0 = acc[0] >> LIMB_BITS
            top = jnp.zeros((1,) + acc.shape[1:], dtype=acc.dtype)
            acc = jnp.concatenate([acc[1:2] + c0, acc[2:], top], axis=0)
            return acc, None

        a_b = jnp.broadcast_to(a, (N_LIMBS,) + bshape)
        # init as a zero-multiple of the operands so it inherits their
        # varying-manual-axes type under shard_map (fresh zeros would not)
        init = a_b * 0 + b * 0
        acc, _ = lax.scan(body, init, a_b)
        return carry_prop(acc, N_LIMBS)

    def sqr(self, a):
        return self.mul(a, a)

    def add(self, a, b):
        s = carry_prop(jnp.asarray(a) + jnp.asarray(b), N_LIMBS)
        return cond_sub(s, self.p2_limbs)

    def sub(self, a, b):
        p2 = jnp.asarray(self.p2_limbs.reshape(N_LIMBS, *([1] * (jnp.asarray(a).ndim - 1))))
        s = carry_prop(jnp.asarray(a) + p2 - jnp.asarray(b), N_LIMBS)
        return cond_sub(s, self.p2_limbs)

    def neg(self, a):
        """-a mod p, kept strictly < 2p."""
        a = jnp.asarray(a)
        p2 = jnp.asarray(self.p2_limbs.reshape(N_LIMBS, *([1] * (a.ndim - 1))))
        s = carry_prop(p2 - a, N_LIMBS)
        return cond_sub(s, self.p2_limbs)

    def mul_small(self, a, k: int):
        """a * k for small non-negative int k (k <= 8)."""
        if not 0 <= k <= 8:
            raise ValueError("k out of range")
        s = carry_prop(jnp.asarray(a) * k, N_LIMBS)
        # value < k * 2p; k-1 conditional subtractions of 2p guarantee < 2p
        for _ in range(max(0, k - 1)):
            s = cond_sub(s, self.p2_limbs)
        return s

    def to_mont(self, a):
        """Canonical integer limbs (< R) -> Montgomery form (< 2p)."""
        return self.mul(a, jnp.asarray(self.r2_np.reshape(N_LIMBS, 1)))

    def from_mont(self, a):
        """Montgomery form -> canonical integer in [0, p)."""
        a = jnp.asarray(a)
        one = np.zeros((N_LIMBS, 1), dtype=np.int32)
        one[0, 0] = 1
        out = self.mul(a, jnp.asarray(one))
        return cond_sub(out, self.p_limbs)

    def canon(self, a):
        """Reduce a Montgomery-form value from [0,2p) to [0,p)."""
        return cond_sub(a, self.p_limbs)

    def eq(self, a, b):
        return limbs_eq(self.canon(a), self.canon(b))

    def is_zero(self, a):
        return limbs_is_zero(self.canon(a))

    def select(self, cond, a, b):
        """Elementwise (B,) bool select between two (L, B) values."""
        return jnp.where(cond[None, :], a, b)

    def pow_const(self, a, e: int):
        """a^e mod p for a fixed python-int exponent.

        Square-and-multiply as a scan over the exponent's bits (MSB first)
        so the traced graph stays small regardless of exponent size.
        """
        if e < 0:
            raise ValueError("negative exponent")
        a = jnp.asarray(a)
        one = self.one_bc(a.shape[1:])
        if e == 0:
            return one
        bits = np.array([int(c) for c in bin(e)[2:]], dtype=np.int32)

        def body(res, eb):
            res = self.sqr(res)
            res = jnp.where(eb != 0, self.mul(res, a), res)
            return res, None

        # first bit is always 1: start from a (skips one sqr+mul)
        res, _ = lax.scan(body, jnp.broadcast_to(a, one.shape), jnp.asarray(bits[1:]))
        return res

    def inv(self, a):
        """Modular inverse via Fermat (p must be prime). inv(0) = 0."""
        return self.pow_const(a, self.p - 2)
