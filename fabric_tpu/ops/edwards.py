"""Batched twisted-Edwards (a=-1) extended-coordinate arithmetic for ed25519.

The ed25519 capability is NEW relative to the reference (verified in
SURVEY.md §2: no ed25519 anywhere in /root/reference — BCCSP is ECDSA-only);
it exists because BASELINE.json configs 2-3 call for ed25519 and mixed-curve
batch verification on TPU.

Extended homogeneous coordinates (X : Y : Z : T) with x = X/Z, y = Y/Z,
T = XY/Z.  The unified addition law (add-2008-hwcd-3) is COMPLETE for
a = -1 with non-square d, so there are no degenerate cases at all — ideal
for a branchless batched TPU ladder.  Identity is (0 : 1 : 1 : 0).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from . import bignum as bn

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, -1, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p
BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
BY = 46316835694926478169428394003475163141307993866256225615783033603165251855960

fp = bn.Mont(P, "ed25519.p")
fl = bn.Mont(L, "ed25519.l")

D_M = fp.const(D)
D2_M = fp.const(2 * D % P)
SQRT_M1_M = fp.const(SQRT_M1)
B_AFF = (fp.const(BX), fp.const(BY))


def identity(bshape) -> tuple:
    one = fp.one_bc(bshape)
    zero = jnp.zeros((bn.N_LIMBS,) + tuple(bshape), dtype=jnp.int32)
    return zero, one, one, zero


def from_affine(x_m, y_m) -> tuple:
    one = fp.one_bc(jnp.asarray(x_m).shape[1:])
    return jnp.asarray(x_m), jnp.asarray(y_m), one, fp.mul(x_m, y_m)


def neg(Pt) -> tuple:
    X, Y, Z, T = Pt
    return fp.neg(X), Y, Z, fp.neg(T)


def select(cond, A, Bp) -> tuple:
    return tuple(fp.select(cond, a, b) for a, b in zip(A, Bp))


def add(Pt, Qt) -> tuple:
    """Complete unified addition (add-2008-hwcd-3, a=-1, k=2d)."""
    X1, Y1, Z1, T1 = Pt
    X2, Y2, Z2, T2 = Qt
    A = fp.mul(fp.sub(Y1, X1), fp.sub(Y2, X2))
    Bv = fp.mul(fp.add(Y1, X1), fp.add(Y2, X2))
    C = fp.mul(fp.mul(T1, jnp.asarray(D2_M)), T2)
    Dv = fp.mul_small(fp.mul(Z1, Z2), 2)
    E = fp.sub(Bv, A)
    F = fp.sub(Dv, C)
    G = fp.add(Dv, C)
    H = fp.add(Bv, A)
    return fp.mul(E, F), fp.mul(G, H), fp.mul(F, G), fp.mul(E, H)


def dbl(Pt) -> tuple:
    """Doubling (dbl-2008-hwcd, a=-1); also complete."""
    X1, Y1, Z1, _ = Pt
    A = fp.sqr(X1)
    Bv = fp.sqr(Y1)
    C = fp.mul_small(fp.sqr(Z1), 2)
    H = fp.add(A, Bv)
    E = fp.sub(H, fp.sqr(fp.add(X1, Y1)))
    G = fp.sub(A, Bv)
    F = fp.add(C, G)
    return fp.mul(E, F), fp.mul(G, H), fp.mul(F, G), fp.mul(E, H)


def shamir(u1_limbs, u2_limbs, Q, n_bits: int = 253) -> tuple:
    """u1*B + u2*Q, interleaved double-and-add over the basepoint B and Q.

    Scalars as canonical little-endian limbs (L, Bsz); returns extended point.
    """
    bshape = jnp.asarray(u1_limbs).shape[1:]
    Bpt = from_affine(
        jnp.broadcast_to(jnp.asarray(B_AFF[0]), (bn.N_LIMBS,) + tuple(bshape)),
        jnp.broadcast_to(jnp.asarray(B_AFF[1]), (bn.N_LIMBS,) + tuple(bshape)))
    BQ = add(Bpt, Q)
    u1b = bn.to_bits(u1_limbs, n_bits)[::-1]
    u2b = bn.to_bits(u2_limbs, n_bits)[::-1]

    def body(acc, bits):
        b1, b2 = bits
        acc = dbl(acc)
        t = select(b1 != 0, Bpt, identity(bshape))
        t = select((b1 == 0) & (b2 != 0), Q, t)
        t = select((b1 != 0) & (b2 != 0), BQ, t)
        acc = add(acc, t)
        return acc, None

    # tie the init to the scalars so its shard_map variance matches
    init = tuple(c + jnp.asarray(u1_limbs) * 0 for c in identity(bshape))
    acc, _ = lax.scan(body, init, (u1b, u2b))
    return acc


def decompress(y_limbs, sign_bit) -> tuple:
    """RFC 8032 §5.1.3 point decompression, batched & branchless.

    y_limbs: (L, B) canonical integer limbs of the y coordinate (< 2^255);
    sign_bit: (B,) int32 0/1 (the x parity bit from the encoding MSB).
    Returns ((x_m, y_m), ok): affine Montgomery coords and validity mask.
    Callers must reject when y >= p (checked here) or when no sqrt exists.
    """
    y_ok = bn.limbs_lt_const(y_limbs, P)
    y_m = fp.to_mont(y_limbs)
    y2 = fp.sqr(y_m)
    one = jnp.asarray(fp.one_np.reshape(bn.N_LIMBS, 1))
    u = fp.sub(y2, one)                      # y^2 - 1
    v = fp.add(fp.mul(y2, jnp.asarray(D_M)), one)  # d*y^2 + 1
    # candidate root: x = u * v^3 * (u * v^7)^((p-5)/8)
    v3 = fp.mul(fp.sqr(v), v)
    v7 = fp.mul(fp.sqr(v3), v)
    x = fp.mul(fp.mul(u, v3), fp.pow_const(fp.mul(u, v7), (P - 5) // 8))
    vx2 = fp.mul(v, fp.sqr(x))
    root_ok = fp.eq(vx2, u)
    root_neg = fp.eq(vx2, fp.neg(u))
    x = fp.select(root_neg, fp.mul(x, jnp.asarray(SQRT_M1_M)), x)
    ok = y_ok & (root_ok | root_neg)
    # sign handling: if x == 0 and sign==1 -> invalid; else negate to match
    x_can = fp.from_mont(x)  # already canonical in [0, p)
    x_is_zero = bn.limbs_is_zero(x_can)
    x_parity = bn.bit(x_can, 0)
    ok = ok & ~(x_is_zero & (sign_bit == 1))
    x = fp.select((x_parity != sign_bit) & ~x_is_zero, fp.neg(x), x)
    return (x, y_m), ok


def eq_points(Pt, Qt) -> jnp.ndarray:
    """Projective equality: X1*Z2 == X2*Z1 and Y1*Z2 == Y2*Z1."""
    X1, Y1, Z1, _ = Pt
    X2, Y2, Z2, _ = Qt
    return (fp.eq(fp.mul(X1, Z2), fp.mul(X2, Z1)) &
            fp.eq(fp.mul(Y1, Z2), fp.mul(Y2, Z1)))
