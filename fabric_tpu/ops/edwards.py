"""Batched twisted-Edwards (a=-1) arithmetic for ed25519 on the flat field.

The ed25519 capability is NEW relative to the reference (verified in
SURVEY.md §2: no ed25519 anywhere in /root/reference — BCCSP is
ECDSA-only); it exists because BASELINE.json configs 2-3 call for
ed25519 and mixed-curve batch verification on TPU.

Round-4 rework: the round-1 module ran a 253-iteration bit ladder on the
scan-heavy bignum.Mont layer; this one runs on the lazy-reduction flat
field (ops/flatfield.py, the P-256 hot-path layer) with fixed-base COMB
scalar multiplication:

  * extended homogeneous coordinates (X : Y : Z : T), x = X/Z, y = Y/Z,
    T = XY/Z; the unified add (add-2008-hwcd-3) and dbl (dbl-2008-hwcd)
    are COMPLETE for a = -1 with non-square d — no degenerate cases, no
    infinity flags, ideal for branchless batched kernels;
  * table entries in "niels" form (y-x, y+x, 2d*x*y): the mixed add
    costs 7 muls (vs 11 for the P-256 Jacobian mixed add), the identity
    (1, 1, 0) is handled by the formulas natively (digit-0 rows need no
    masking), and negation is a coordinate swap + one negate — so
    SIGNED comb digits are free, which completeness makes safe (the
    P-256 comb must stay unsigned because its incomplete mixed add
    would need reachability analysis per window);
  * signed 7-bit comb: 37 windows of |digit| <= 64 over a 65-row
    one-hot lookup per window (row 0 = identity).

Lazy bounds (operand values < 16p keep the CIOS contract; tracked
inline): all point coordinates stay < 2p out of every mul; sums/diffs
peak at 8p inside the formulas.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from . import bignum as bn
from . import flatfield as ff
from .flatfield import FlatMod, L as NL, LB

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, -1, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p
BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
BY = 46316835694926478169428394003475163141307993866256225615783033603165251855960

COMB_W = 7
COMB_WINDOWS = 37            # 37*7 = 259 >= 253 (+ signed carry headroom)
COMB_ROWS = 65               # |digit| in 0..64; row 0 = identity niels

fp = FlatMod(P, "ed25519.p")
fl = FlatMod(L, "ed25519.l")

_D_M = fp.const_mont(D)
_D2_M = fp.const_mont(2 * D % P)
_SQRT_M1_M = fp.const_mont(SQRT_M1)


def _c(np_col, ndim):
    return ff.const_col(np_col, ndim)


# ---------------------------------------------------------------------------
# Extended-coordinate point ops (values lazily bounded, coords < 2p)
# ---------------------------------------------------------------------------

def identity(bshape) -> tuple:
    one = fp.one_bc(bshape)
    zero = fp.zero_bc(bshape)
    return zero, one, one, zero


def from_affine(x_m, y_m) -> tuple:
    one = fp.one_bc(jnp.asarray(x_m).shape[1:])
    return (jnp.asarray(x_m), jnp.asarray(y_m), one, fp.mul(x_m, y_m))


def neg(Pt) -> tuple:
    X, Y, Z, T = Pt
    z = fp.zero_bc(jnp.asarray(X).shape[1:])
    return fp.subl(z, X, 2), Y, Z, fp.subl(z, T, 2)


def select(cond, A, Bp) -> tuple:
    return tuple(fp.select(cond, a, b) for a, b in zip(A, Bp))


def add(Pt, Qt) -> tuple:
    """Complete unified addition (add-2008-hwcd-3, a=-1, k=2d).
    Inputs < 2p -> outputs < 2p; 9 muls, no conditional subtractions."""
    X1, Y1, Z1, T1 = Pt
    X2, Y2, Z2, T2 = Qt
    ndim = jnp.asarray(X1).ndim
    A = fp.mul(fp.subl(Y1, X1, 2), fp.subl(Y2, X2, 2))    # 16p^2 -> <2p
    Bv = fp.mul(fp.addl(Y1, X1), fp.addl(Y2, X2))          # <2p
    C = fp.mul(fp.mul(T1, _c(_D2_M, ndim)), T2)            # <2p
    Dv = fp.smalll(fp.mul(Z1, Z2), 2)                      # <4p
    E = fp.subl(Bv, A, 2)                                  # <4p
    F = fp.subl(Dv, C, 2)                                  # <6p
    G = fp.addl(Dv, C)                                     # <6p
    H = fp.addl(Bv, A)                                     # <4p
    return fp.mul(E, F), fp.mul(G, H), fp.mul(F, G), fp.mul(E, H)


def dbl(Pt) -> tuple:
    """Doubling (dbl-2008-hwcd, a=-1); also complete.  <2p out; 7 muls."""
    X1, Y1, Z1, _ = Pt
    A = fp.sqr(X1)                                         # <2p
    Bv = fp.sqr(Y1)                                        # <2p
    C = fp.smalll(fp.sqr(Z1), 2)                           # <4p
    H = fp.addl(A, Bv)                                     # <4p
    E = fp.subl(H, fp.sqr(fp.addl(X1, Y1)), 2)             # <6p
    G = fp.subl(A, Bv, 2)                                  # <4p
    F = fp.addl(C, G)                                      # <8p
    return fp.mul(E, F), fp.mul(G, H), fp.mul(F, G), fp.mul(E, H)


def add_niels(Pt, e0, e1, e2) -> tuple:
    """Mixed add of a niels-form table entry (y-x, y+x, 2dxy), each
    canonical < p Montgomery.  The identity entry (1, 1, 0) flows
    through the formulas natively — no digit-0 masking.  7 muls."""
    X1, Y1, Z1, T1 = Pt
    A = fp.mul(fp.subl(Y1, X1, 2), e0)                     # <2p
    Bv = fp.mul(fp.addl(Y1, X1), e1)                       # <2p
    C = fp.mul(T1, e2)                                     # <2p
    Dv = fp.smalll(Z1, 2)                                  # <4p
    E = fp.subl(Bv, A, 2)                                  # <4p
    F = fp.subl(Dv, C, 2)                                  # <6p
    G = fp.addl(Dv, C)                                     # <6p
    H = fp.addl(Bv, A)                                     # <4p
    return fp.mul(E, F), fp.mul(G, H), fp.mul(F, G), fp.mul(E, H)


# ---------------------------------------------------------------------------
# Signed-digit comb
# ---------------------------------------------------------------------------

def comb_digits_signed(u_can):
    """(NL, B) canonical limbs (< 2^253) -> (37, B) int32 signed digits
    d_j in [-64, 64], u = sum d_j * 2^(7j).  LSB-first."""
    raw = []
    for j in range(COMB_WINDOWS):
        bitpos = COMB_W * j
        limb = bitpos // LB
        off = bitpos % LB
        if limb >= NL:
            raw.append(jnp.zeros_like(u_can[0]))
            continue
        v = u_can[limb] >> off
        if off > LB - COMB_W and limb + 1 < NL:
            v = v | (u_can[limb + 1] << (LB - off))
        raw.append(v & ((1 << COMB_W) - 1))
    out = []
    carry = jnp.zeros_like(raw[0])
    for j in range(COMB_WINDOWS):
        v = raw[j] + carry
        hi = v >= (1 << (COMB_W - 1))                      # v in [64, 128]
        out.append(jnp.where(hi, v - (1 << COMB_W), v))
        carry = hi.astype(v.dtype)
    return jnp.stack(out)


def comb_accumulate(tab_f32, u_can, bshape):
    """u * T against a niels comb table (COMB_WINDOWS*COMB_ROWS, 3*NL):
    row j*COMB_ROWS + k = niels(k * 2^(7j) * T), row j*COMB_ROWS + 0 =
    identity.  Signed digits: negative selects swap (y-x)/(y+x) and
    negate the 2dxy coordinate AFTER the one-hot lookup."""
    eager = ff._is_concrete(u_can)
    sd = comb_digits_signed(u_can)                         # (37, B)
    mag = jnp.abs(sd)
    neg_d = sd < 0
    tab = jnp.asarray(tab_f32).reshape(COMB_WINDOWS, COMB_ROWS, 3 * NL)
    iota = jnp.arange(COMB_ROWS, dtype=jnp.int32).reshape(1, COMB_ROWS, 1)

    def entry(sel, negb):
        e0, e1, e2 = sel[:NL], sel[NL:2 * NL], sel[2 * NL:]
        z = fp.zero_bc(negb.shape)
        e2n = fp.subl(z, e2, 1)                            # p - e2 < 2p
        return (fp.select(negb, e1, e0), fp.select(negb, e0, e1),
                fp.select(negb, e2n, e2))

    if eager:
        acc = identity(bshape)
        for j in range(COMB_WINDOWS):
            onehot = (iota[0] == mag[j][None]).astype(jnp.float32)
            sel = jnp.tensordot(
                tab[j].T, onehot, axes=1,
                precision=lax.Precision.HIGHEST).astype(jnp.int32)
            acc = add_niels(acc, *entry(sel, neg_d[j]))
        return acc

    onehot = (iota == mag[:, None, :]).astype(jnp.float32)  # (37, 65, B)
    sel = lax.dot_general(
        tab, onehot,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        precision=lax.Precision.HIGHEST).astype(jnp.int32)  # (37, 3NL, B)

    def body(acc, xs):
        s, nb = xs
        return add_niels(acc, *entry(s, nb)), None

    init = tuple(c + u_can[0] * 0 for c in identity(bshape))
    acc, _ = lax.scan(body, init, (sel, neg_d))
    return acc


def comb_accumulate_rows(bank_f32, row_key, u_can, bshape):
    """Row-grouped multikey niels comb over a (R, C) grid (the ed25519
    analogue of ecp256.comb_accumulate_rows; same packing contract)."""
    eager = ff._is_concrete(u_can)
    R, C = bshape
    sd = comb_digits_signed(u_can)                         # (37, R, C)
    mag = jnp.abs(sd)
    neg_d = sd < 0
    bank = jnp.asarray(bank_f32, jnp.float32)
    rows = bank[row_key].reshape(R, COMB_WINDOWS, COMB_ROWS, 3 * NL)
    rows = rows.transpose(1, 0, 3, 2)                      # (37, R, 3NL, 65)
    iota = jnp.arange(COMB_ROWS, dtype=jnp.int32).reshape(
        1, 1, COMB_ROWS, 1)

    def entry(sel, negb):
        e0, e1, e2 = sel[:NL], sel[NL:2 * NL], sel[2 * NL:]
        z = fp.zero_bc(negb.shape)
        e2n = fp.subl(z, e2, 1)
        return (fp.select(negb, e1, e0), fp.select(negb, e0, e1),
                fp.select(negb, e2n, e2))

    if eager:
        acc = identity(bshape)
        for j in range(COMB_WINDOWS):
            onehot = (iota[0] == mag[j][:, None, :]).astype(jnp.float32)
            sel = lax.dot_general(
                rows[j], onehot,
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                precision=lax.Precision.HIGHEST).astype(jnp.int32)
            sel = sel.transpose(1, 0, 2)                   # (3NL, R, C)
            acc = add_niels(acc, *entry(sel, neg_d[j]))
        return acc

    onehot = (iota == mag[:, :, None, :]).astype(jnp.float32)
    sel = lax.dot_general(
        rows, onehot,
        dimension_numbers=(((3,), (2,)), ((0, 1), (0, 1))),
        precision=lax.Precision.HIGHEST)                   # (37, R, 3NL, C)
    sel = sel.transpose(0, 2, 1, 3).astype(jnp.int32)      # (37, 3NL, R, C)

    def body(acc, xs):
        s, nb = xs
        return add_niels(acc, *entry(s, nb)), None

    init = tuple(c + u_can[0] * 0 for c in identity(bshape))
    acc, _ = lax.scan(body, init, (sel, neg_d))
    return acc


# ---------------------------------------------------------------------------
# Variable-point windowed ladder (uncached keys)
# ---------------------------------------------------------------------------

LADDER_W = 4
LADDER_WINDOWS = 64          # scalars < L < 2^253


def ladder_digits(u_can):
    """(NL, B) canonical -> (64, B) 4-bit digits, MSB-first."""
    digits = []
    for w in range(LADDER_WINDOWS):
        limb = w // 3
        shift = (w % 3) * 4
        digits.append((u_can[limb] >> shift) & 0xF)
    return jnp.stack(digits[::-1])


def windowed_mul(u_can, Q, bshape):
    """u * Q for a variable point by a 4-bit windowed ladder: one scan
    builds the 16-entry table (complete adds — safe for ANY input), one
    scan runs 64 windows of 4 dbl + 1 unified add."""
    eager = ff._is_concrete(u_can)
    T0 = identity(bshape)
    if not eager:
        T0 = tuple(c + u_can[0] * 0 for c in T0)
    T2 = dbl(Q)
    if eager:
        T = [T0, Q, T2]
        for k in range(3, 16):
            T.append(add(T[k - 1], Q))
        TX, TY, TZ, TT = (jnp.stack([t[i] for t in T]) for i in range(4))
    else:
        def tab_body(acc, _):
            nxt = add(acc, Q)
            return nxt, nxt
        _, rest = lax.scan(tab_body, T2, None, length=13)
        TX, TY, TZ, TT = (
            jnp.concatenate([jnp.stack([a, b, c]), r], axis=0)
            for a, b, c, r in zip(T0, Q, T2, rest))

    ld = ladder_digits(u_can)

    def ladder_body(acc, d):
        if eager:
            for _ in range(LADDER_W):
                acc = dbl(acc)
        else:
            acc = lax.fori_loop(0, LADDER_W, lambda _, a: dbl(a), acc)
        ent = (TX[0], TY[0], TZ[0], TT[0])
        for k in range(1, 16):
            ent = select(d == k, (TX[k], TY[k], TZ[k], TT[k]), ent)
        return add(acc, ent), None

    if eager:
        acc = T0
        for i in range(LADDER_WINDOWS):
            acc, _ = ladder_body(acc, ld[i])
    else:
        acc, _ = lax.scan(ladder_body, T0, ld)
    return acc


# ---------------------------------------------------------------------------
# Decompression & recompression
# ---------------------------------------------------------------------------

def decompress(y_limbs, sign_bit) -> tuple:
    """RFC 8032 §5.1.3 point decompression, batched & branchless.

    y_limbs: (NL, B) canonical integer limbs of y (< 2^255); sign_bit:
    (B,) int32 0/1.  Returns ((x_m, y_m), ok) with x_m, y_m < 2p
    Montgomery.  ok=False for y >= p, non-residues, or x=0 with sign=1.
    """
    ndim = jnp.asarray(y_limbs).ndim
    y_ok = ff.lt_const(y_limbs, P)
    y_m = fp.to_mont(y_limbs)
    y2 = fp.sqr(y_m)
    one = fp.one_bc(jnp.asarray(y_limbs).shape[1:])
    u = fp.subl(y2, one, 2)                                # y^2 - 1, <4p
    v = fp.addl(fp.mul(y2, _c(_D_M, ndim)), one)           # d y^2 + 1, <4p
    # candidate root: x = u * v^3 * (u*v^7)^((p-5)/8)
    v2 = fp.sqr(v)
    v3 = fp.mul(v2, v)
    v7 = fp.mul(fp.sqr(v3), v)
    pw = fp.pow_const_scan(fp.mul(u, v7), (P - 5) // 8)
    x = fp.mul(fp.mul(u, v3), pw)                          # <2p
    vx2 = fp.mul(v, fp.sqr(x))                             # <2p
    root_ok = fp.eq_k(vx2, u, 4, 6)
    neg_u = fp.subl(fp.zero_bc(u.shape[1:]), u, 4)         # <4p
    root_neg = fp.eq_k(vx2, neg_u, 4, 6)
    x = fp.select(root_neg, fp.mul(x, _c(_SQRT_M1_M, ndim)), x)
    ok = y_ok & (root_ok | root_neg)
    x_can = fp.from_mont(x)
    x_is_zero = ff.is_zero_limbs(x_can)
    x_parity = (x_can[0] & 1)
    ok = ok & ~(x_is_zero & (sign_bit == 1))
    flip = (x_parity != sign_bit) & ~x_is_zero
    x = fp.select(flip, fp.subl(fp.zero_bc(x.shape[1:]), x, 2), x)
    return (x, y_m), ok


def batch_zinv(Z, gate):
    """Batch inverse of the Z coordinates via the product tree.

    gate: (B,) bool — elements already known invalid (their Z may be
    garbage/zero and must not poison the tree; their inverse is never
    consumed).  Falls back to the Fermat chain for odd shapes."""
    bshape = jnp.asarray(Z).shape[1:]
    z_zero = fp.is_zero_k(Z, 2) | ~gate
    z_safe = fp.select(z_zero, fp.one_bc(bshape), Z)
    if (not ff._is_concrete(Z) and len(bshape) == 1
            and bshape[0] >= 128 and bshape[0] % 2 == 0):
        return fp.inv_tree(z_safe)
    return fp.inv(z_safe)


def compressed_equals(Pt, y_limbs, sign_bit, zinv):
    """Does the extended point equal the ENCODED point (y, sign)?

    Recompression check: replaces per-signature decompression of R (a
    ~250-squaring sqrt chain) with one batch-amortized inversion —
    y(P) == y and parity(x(P)) == sign.  `zinv` comes from batch_zinv.
    Non-canonical encodings (y >= p) are rejected, and a sign bit of 1
    with x == 0 cannot match (parity(0) == 0), per RFC 8032.
    """
    X, Y, Z, _ = Pt
    y_ok = ff.lt_const(y_limbs, P)
    # coords are Montgomery forms: (X*R)(Z^-1*R)*R^-1 = (X/Z)*R stays
    # Montgomery; from_mont strips the factor and canonicalizes.
    x_aff = fp.from_mont(fp.mul(X, zinv))
    y_aff = fp.from_mont(fp.mul(Y, zinv))
    y_match = jnp.all(y_aff == jnp.asarray(y_limbs), axis=0)
    x_parity = (x_aff[0] & 1)
    return y_ok & y_match & (x_parity == sign_bit)
